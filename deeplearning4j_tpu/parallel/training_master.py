"""Distributed-training control plane: TrainingMaster / TrainingWorker.

Capability mirror of the reference Spark training contract (SURVEY.md
sections 2.3 and 3.3):
  - TrainingMaster/TrainingWorker pluggable contract
    (dl4j-spark/.../spark/api/TrainingMaster.java:24-93, TrainingWorker.java)
    with WorkerConfiguration and Repartition strategy;
  - ParameterAveragingTrainingMaster
    (.../impl/paramavg/ParameterAveragingTrainingMaster.java:47): splits the
    incoming data so each split is numWorkers x batchSizePerWorker x
    averagingFrequency examples (:148), runs workers, averages params (+
    updater state), repeats; builder defaults batchSizePerWorker=16,
    averagingFrequency=5 (:463-471);
  - distributed evaluation (SparkDl4jMultiLayer.evaluate ->
    EvaluateFlatMapFunction + EvaluationReduceFunction.java:18-19 merging
    Evaluation objects);
  - training stats collection per phase (stats.py).

TPU-native mapping: "executors" are mesh devices. The data plane
(broadcast params out / aggregate params in) becomes the
ParameterAveragingTrainer's shard_map + pmean over ICI; this module is the
HOST control plane — data splitting, retries, stats, evaluation merge —
exactly the part of the reference that stays on the driver JVM.
"""

from __future__ import annotations

import contextlib
import logging
import time
from dataclasses import dataclass
from typing import Iterable, List, Optional, Sequence

logger = logging.getLogger("deeplearning4j_tpu")

import numpy as np

from deeplearning4j_tpu.datasets.iterator import DataSet
from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.parallel.data_parallel import (
    ParallelWrapper,
    ParameterAveragingTrainer,
)
from deeplearning4j_tpu.parallel.stats import TrainingStats


@dataclass
class WorkerConfiguration:
    """Reference api/WorkerConfiguration.java."""

    batch_size_per_worker: int = 16
    averaging_frequency: int = 5
    prefetch_num_batches: int = 2
    collect_training_stats: bool = False


class Repartition:
    """Reference api/Repartition enum."""

    ALWAYS = "always"
    NEVER = "never"
    NUM_PARTITIONS_WORKERS_DIFFERS = "num_partitions_workers_differs"


def balanced_splits(n: int, k: int) -> List[slice]:
    """Exact balanced partitioning (reference BalancedPartitioner +
    AssignIndexFunction semantics): first n%k parts get one extra element."""
    base, extra = divmod(n, k)
    out, start = [], 0
    for i in range(k):
        size = base + (1 if i < extra else 0)
        out.append(slice(start, start + size))
        start += size
    return out


class TrainingMaster:
    """Abstract contract (TrainingMaster.java): executeTraining + stats."""

    def execute_training(self, net, iterator) -> None:
        raise NotImplementedError

    def get_training_stats(self) -> Optional[TrainingStats]:
        return None


# -- exported-dataset plane (RDDTrainingApproach.Export role) ---------------

_EXPORT_PREFIX = "dataset_"


def export_datasets(iterator_or_datasets, dest: str,
                    prefix: str = _EXPORT_PREFIX) -> List[str]:
    """Serialize each DataSet minibatch to its own file — the reference's
    export plumbing (ParameterAveragingTrainingMaster split/export,
    :148-168, writing objects a later fit(String path) consumes,
    SparkDl4jMultiLayer.fit:217). One npz per DataSet (the DataSet.save
    role), named {prefix}{i:05d}.npz; dest is a local directory or a
    gs:// prefix (staged locally, pushed via GcsUploader). Returns the
    written paths/URIs."""
    import os
    import shutil
    import tempfile

    datasets = (list(iterator_or_datasets)
                if not isinstance(iterator_or_datasets, (list, tuple))
                else iterator_or_datasets)
    is_gs = dest.startswith("gs://")
    uploader = None
    if is_gs:
        from deeplearning4j_tpu.provision.gcs import GcsUploader

        uploader = GcsUploader()
        stage = tempfile.mkdtemp(prefix="dl4j_export_")
    else:
        stage = dest
        os.makedirs(dest, exist_ok=True)
    paths = []
    try:
        for i, ds in enumerate(datasets):
            arrays = {"features": np.asarray(ds.features),
                      "labels": np.asarray(ds.labels)}
            if getattr(ds, "features_mask", None) is not None:
                arrays["features_mask"] = np.asarray(ds.features_mask)
            if getattr(ds, "labels_mask", None) is not None:
                arrays["labels_mask"] = np.asarray(ds.labels_mask)
            local = os.path.join(stage, f"{prefix}{i:05d}.npz")
            np.savez(local, **arrays)
            if is_gs:
                uri = f"{dest.rstrip('/')}/{prefix}{i:05d}.npz"
                uploader.upload(local, uri)
                os.unlink(local)  # bound staging disk to one minibatch
                paths.append(uri)
            else:
                paths.append(local)
    finally:
        if is_gs:
            shutil.rmtree(stage, ignore_errors=True)
    return paths


def load_exported_datasets(path,
                           prefix: str = _EXPORT_PREFIX) -> Iterable[DataSet]:
    """Read DataSets back from an export location (the sc.binaryFiles +
    deserialize step of fit(String path), SparkDl4jMultiLayer.java:217-221):
    a local directory, an explicit list of files, or a gs:// prefix
    (fetched through GcsDownloader's idempotent cache). Directory reads
    match `prefix` so two exports into one directory under different
    prefixes stay separate runs; files sort by name so the split order is
    deterministic."""
    import glob
    import os
    import tempfile

    if isinstance(path, (list, tuple)):
        files = sorted(path)
    elif path.startswith("gs://"):
        from deeplearning4j_tpu.provision.gcs import (
            BucketIterator,
            GcsDownloader,
        )

        dl = GcsDownloader(tempfile.mkdtemp(prefix="dl4j_fitpath_"))
        # same prefix/.npz filter as the local branch — co-located exports
        # (or a checkpoint object under the prefix) must not leak in
        uris = [u for u in BucketIterator(path)
                if u.rsplit("/", 1)[-1].startswith(prefix)
                and u.endswith(".npz")]
        files = sorted(dl.fetch(uri) for uri in uris)
    else:
        files = sorted(glob.glob(os.path.join(path, f"{prefix}*.npz")))
    if not files:
        raise ValueError(f"no exported datasets under {path!r}")
    # native ordered prefetch: a background C thread parses file i+1..i+k
    # while the device trains on file i (AsyncDataSetIterator ring buffer
    # applied to the exported feed; np.load fallback inside iter_npz)
    from deeplearning4j_tpu.native import iter_npz

    for z in iter_npz(files):
        yield DataSet(
            z["features"], z["labels"],
            z.get("features_mask"),
            z.get("labels_mask"),
        )


class ParameterAveragingTrainingMaster(TrainingMaster):
    """Host control plane over the device-side ParameterAveragingTrainer."""

    def __init__(
        self,
        num_workers: Optional[int] = None,
        batch_size_per_worker: int = 16,
        averaging_frequency: int = 5,
        save_updater: bool = True,
        repartition: str = Repartition.ALWAYS,
        collect_training_stats: bool = False,
        max_retries: int = 2,
        rng_seed: int = 12345,
    ):
        # worker count defaults to the device count, resolved LAZILY at
        # first use (the num_workers property): len(jax.devices()) here
        # would initialize the axon TPU plugin at construction time and
        # hang forever on a dead tunnel (the CLAUDE.md stale-tunnel rule)
        # even for a master that is only being configured/serialized
        self._num_workers = int(num_workers) if num_workers else None
        self.batch_size_per_worker = batch_size_per_worker
        self.averaging_frequency = max(1, averaging_frequency)
        self.save_updater = save_updater
        self.repartition = repartition
        self.collect_training_stats = collect_training_stats
        self.max_retries = max_retries
        self.rng_seed = rng_seed
        self.stats = TrainingStats() if collect_training_stats else None
        self._trainer: Optional[ParameterAveragingTrainer] = None
        self._trainer_net = None
        self._round = 0

    @property
    def num_workers(self) -> int:
        if self._num_workers is None:
            import jax

            self._num_workers = len(jax.devices())
        return self._num_workers

    @num_workers.setter
    def num_workers(self, value: int) -> None:
        self._num_workers = int(value)

    # -- data plane helpers -----------------------------------------------
    def _examples_per_split(self) -> int:
        # reference :148 — one split feeds every worker for `freq` minibatches
        return self.num_workers * self.batch_size_per_worker * self.averaging_frequency

    def _collect(self, iterator) -> List[DataSet]:
        if isinstance(iterator, (list, tuple)):
            return list(iterator)
        out = list(iterator)
        if hasattr(iterator, "reset"):
            iterator.reset()
        return out

    def _splits(self, datasets: List[DataSet]):
        """Concatenate and re-split so each split is exactly
        workers x batch x freq examples (repartition=Always; the reference's
        Balanced repartition becomes an exact reshape here). Features/labels
        may be per-component LISTS (multi-input/multi-output
        ComputationGraph — the reference's MultiDataSet); every component is
        permuted and sliced with the same index set."""

        def cat(get):
            first = get(datasets[0])
            if isinstance(first, (list, tuple)):
                return [
                    np.concatenate([np.asarray(get(d)[i]) for d in datasets])
                    for i in range(len(first))
                ]
            return np.concatenate([np.asarray(get(d)) for d in datasets])

        # DataSet carries arrays (or component lists); MultiDataSet carries
        # features_list/labels_list — normalize the accessors
        def accessor(multi_attr, single_attr):
            def get(d):
                comp = getattr(d, multi_attr, None)
                if comp is not None:
                    if not comp:
                        raise ValueError(
                            f"{type(d).__name__}.{multi_attr} is empty")
                    return comp
                return getattr(d, single_attr)

            return get

        x = cat(accessor("features_list", "features"))
        y = cat(accessor("labels_list", "labels"))
        take = lambda comp, idx: (
            [c[idx] for c in comp] if isinstance(comp, list) else comp[idx]
        )
        n = (x[0] if isinstance(x, list) else x).shape[0]
        if self.repartition == Repartition.ALWAYS:
            # vary the shuffle per call (the reference repartitions each fit)
            rng = np.random.default_rng(self.rng_seed + self._round)
            self._round += 1
            order = rng.permutation(n)
            x, y = take(x, order), take(y, order)
        per = self._examples_per_split()
        n_full = n // per
        dropped = n - n_full * per
        if dropped:
            # static shard_map shapes require whole averaging rounds; the
            # shuffle rotates which examples land in the tail across rounds
            logger.warning(
                "parameter averaging: dropping %d tail examples "
                "(< one %d-example round)", dropped, per,
            )
        for s in range(n_full):
            sl = slice(s * per, (s + 1) * per)
            yield take(x, sl), take(y, sl)

    # -- TrainingMaster contract ------------------------------------------
    def execute_training(self, net, iterator) -> None:
        """fit(JavaRDD<DataSet>) analog (SparkDl4jMultiLayer.fit:194-230 →
        executeTraining:163; SparkComputationGraph.fit:68 for graphs): per
        split, one averaging round on the mesh. Drives BOTH containers —
        the trainer dispatches on MultiLayerNetwork vs ComputationGraph."""
        if self._trainer is None or self._trainer_net is not net:
            self._trainer = ParameterAveragingTrainer(
                net,
                num_workers=self.num_workers,
                averaging_frequency=self.averaging_frequency,
                save_updater=self.save_updater,
            )
            self._trainer_net = net
        datasets = self._collect(iterator)
        stats = self.stats
        with stats.timed("split") if stats else contextlib.nullcontext():
            splits = list(self._splits(datasets))
        if not splits:
            raise ValueError(
                f"not enough examples for one averaging round "
                f"(need {self._examples_per_split()})"
            )
        for x, y in splits:
            # x may be a per-component LIST (multi-input graph): the example
            # count is the leading dim of a component, not the list length
            n_examples = (x[0] if isinstance(x, list) else x).shape[0]
            attempt = 0
            while True:
                try:
                    t0 = stats.time_source.current_time_millis() if stats else 0
                    p0 = time.perf_counter()
                    self._trainer.fit(x, y)
                    if stats:  # record successful attempts only
                        stats.record(
                            "fit", t0, (time.perf_counter() - p0) * 1000.0,
                            example_count=n_examples,
                        )
                    break
                except Exception:
                    # Spark retries failed tasks natively (SURVEY.md section 5
                    # failure detection); parameter averaging is idempotent
                    # per split, so a bounded retry reproduces that behavior.
                    attempt += 1
                    if attempt > self.max_retries:
                        raise

    def get_training_stats(self) -> Optional[TrainingStats]:
        return self.stats

    def execute_training_paths(self, net, path) -> None:
        """Fit from a previously exported location (the reference's
        executeTraining(JavaPairRDD<String, PortableDataStream>) branch,
        ParameterAveragingTrainingMaster.java:189-210, fed by
        SparkDl4jMultiLayer.fit(String path) :217): deserialize the
        exported DataSets, then run the same split/average loop."""
        self.execute_training(net, load_exported_datasets(path))


class ElasticParameterAveragingTrainingMaster(ParameterAveragingTrainingMaster):
    """The averaging master over the ELASTIC fleet (ISSUE 6): identical
    split/average control plane, but each split executes through
    parallel/fleet.ElasticParameterAveragingTrainer — workers join and
    leave mid-run (every round re-forms over the live membership, the
    split count tracking the survivor set), a dead member's in-flight
    work is reclaimed, and SIGTERM'd OS-process members announce
    departure. ``num_workers`` here sizes the SPLITS (examples per round
    = workers x batch x freq, reference :148) and the initial in-process
    fleet; the live round fan-out is the membership's business.

    Pick ``batch_size_per_worker * averaging_frequency * num_workers``
    divisible by every membership size the run may shrink/grow through —
    an indivisible round raises loudly (multihost.local_batch_slice
    rule) instead of silently truncating the tail."""

    def __init__(self, *args, fleet_kwargs: Optional[dict] = None, **kw):
        super().__init__(*args, **kw)
        self.fleet_kwargs = dict(fleet_kwargs or {})

    def execute_training(self, net, iterator) -> None:
        from deeplearning4j_tpu.parallel.fleet import (
            ElasticParameterAveragingTrainer,
        )

        if self._trainer is None or self._trainer_net is not net:
            if self._trainer is not None:
                # the old fleet's worker threads must not outlive the
                # trainer swap (they would keep polling the old tracker
                # on the shared core forever)
                self._trainer.close()
            self._trainer = ElasticParameterAveragingTrainer(
                net,
                num_workers=self.num_workers,
                averaging_frequency=self.averaging_frequency,
                save_updater=self.save_updater,
                **self.fleet_kwargs,
            )
            self._trainer_net = net
        # the split/retry/stats loop is inherited verbatim: the parent
        # only drives self._trainer through .fit(x, y), a contract the
        # fleet trainer implements, and it rebuilds the trainer only when
        # _trainer_net is not net — which we just pinned
        super().execute_training(net, iterator)

    @property
    def fleet(self):
        """The live ElasticParameterAveragingTrainer (None before the
        first execute_training) — membership surface for admit/evict."""
        return self._trainer

    def close(self) -> None:
        """Stop the fleet this master spawned (worker threads + any
        tracker server) — the master owns the trainer lifecycle, so the
        caller that used it like the base master must not be left with
        daemon threads polling the job queue forever."""
        if self._trainer is not None:
            self._trainer.close()
            self._trainer = None
            self._trainer_net = None

    def __enter__(self) -> "ElasticParameterAveragingTrainingMaster":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class DistributedEvaluator:
    """Map-reduce evaluation (EvaluateFlatMapFunction +
    EvaluationReduceFunction): evaluate shards independently, merge."""

    def __init__(self, num_shards: Optional[int] = None):
        # same lazy rule as ParameterAveragingTrainingMaster.num_workers:
        # never touch jax.devices() before work actually arrives
        self._num_shards = int(num_shards) if num_shards else None

    @property
    def num_shards(self) -> int:
        if self._num_shards is None:
            import jax

            self._num_shards = len(jax.devices())
        return self._num_shards

    @num_shards.setter
    def num_shards(self, value: int) -> None:
        self._num_shards = int(value)

    def evaluate(self, net, datasets: Iterable[DataSet]) -> Evaluation:
        datasets = list(datasets)
        shards = balanced_splits(len(datasets), self.num_shards)
        partials: List[Evaluation] = []
        for sl in shards:
            ev = Evaluation()
            for ds in datasets[sl]:
                out = net.output(ds.features)
                out0 = out[0] if isinstance(out, (list, tuple)) else out
                ev.eval(np.asarray(ds.labels), np.asarray(out0),
                        mask=ds.labels_mask)
            partials.append(ev)
        merged = partials[0]
        for ev in partials[1:]:
            merged.merge(ev)
        return merged


class SparkStyleNetwork:
    """User-facing wrapper pairing a net with a TrainingMaster
    (SparkDl4jMultiLayer / SparkComputationGraph role — both containers
    train under the averaging master)."""

    def __init__(self, net, training_master: TrainingMaster):
        self.net = net
        self.training_master = training_master

    def fit(self, iterator_or_datasets) -> "SparkStyleNetwork":
        self.training_master.execute_training(self.net, iterator_or_datasets)
        return self

    def fit_paths(self, path) -> "SparkStyleNetwork":
        """Fit from exported DataSet files — a directory, file list, or
        gs:// prefix (SparkDl4jMultiLayer.fit(String path) :217)."""
        self.training_master.execute_training_paths(self.net, path)
        return self

    def evaluate(self, datasets) -> Evaluation:
        return DistributedEvaluator().evaluate(self.net, datasets)

    def score_examples(self, datasets) -> np.ndarray:
        """Per-example scores (SparkDl4jMultiLayer.scoreExamples): one loss
        value per example, concatenated over all datasets. Computed by
        scoring batch-1 slices (one extra XLA compile at batch 1)."""
        scores = []
        for ds in datasets:
            f = np.asarray(ds.features)
            l = np.asarray(ds.labels)
            for i in range(f.shape[0]):
                scores.append(self.net.score(f[i : i + 1], l[i : i + 1]))
        return np.asarray(scores)
