"""Sequence/context parallelism: ring attention over the device mesh.

The reference's only long-sequence mechanism is truncated BPTT (SURVEY.md
section 5 "Long-context": no ring attention / CP / Ulysses existed in 2016).
This framework treats long-context as first-class: sequences too long for
one chip's HBM are sharded over the mesh's sequence axis and attention runs
as a RING — each device holds its Q shard permanently, while K/V shards
rotate around the ring via `ppermute` over ICI; softmax is accumulated
online (running max + denominator, flash-attention style) so the result is
EXACTLY full attention, never an approximation.

Pieces:
  - `multi_head_attention(...)`: the single-device reference math;
  - `ring_attention(...)`: per-shard body (runs inside shard_map);
  - `ring_attention_sharded(...)`: user entry — builds the shard_map over a
    ('seq',) mesh axis and returns the full attention output;
  - `ulysses_attention_sharded(...)`: the all-to-all alternative (swap the
    sharded axis seq->heads, attend locally, swap back) for when heads
    divide the mesh and per-device [T, T] blocks fit memory;
  - causal masking is exact across shards via global position indexing.

Design notes (scaling-book recipe): the ring overlaps compute of block t
with the DCN/ICI transfer of block t+1 when XLA schedules the ppermute
asynchronously; per-device memory is O(T_local * T_local) per block pair
instead of O(T^2).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.parallel.mesh import shard_map

SEQ_AXIS = "seq"


# ---------------------------------------------------------------------------
# Reference single-device attention
# ---------------------------------------------------------------------------


def multi_head_attention(q, k, v, *, causal: bool = False,
                         q_offset: int = 0, k_offset: int = 0,
                         key_mask=None):
    """q,k,v: [N, T, H, D] -> [N, T, H, D]; plain softmax attention.
    Offsets give global positions for causal masking of shards.
    key_mask: optional [N, Tk] 0/1 — padded keys are excluded from the
    softmax (variable-length batches)."""
    d = q.shape[-1]
    s = jnp.einsum("nqhd,nkhd->nhqk", q, k) / jnp.sqrt(jnp.asarray(d, q.dtype))
    # softmax in AT LEAST f32 (ops/dtypes.softmax_dtype): a bf16 exp/sum
    # over thousands of keys loses mass (every other attention path —
    # serial _attention, the ring body, the flash kernel — already
    # upcasts); f64 inputs stay f64 so the x64 gradcheck substrate keeps
    # its resolution
    from deeplearning4j_tpu.ops.dtypes import softmax_dtype

    s = s.astype(softmax_dtype(s.dtype))
    if causal:
        qi = q_offset + jnp.arange(q.shape[1])
        ki = k_offset + jnp.arange(k.shape[1])
        mask = qi[:, None] >= ki[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    if key_mask is not None:
        km = jnp.asarray(key_mask, bool)[:, None, None, :]  # [N,1,1,Tk]
        s = jnp.where(km, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows (causal shard with no visible keys) -> zeros not NaN
    p = jnp.where(jnp.isfinite(s).any(axis=-1, keepdims=True), p, 0.0)
    return jnp.einsum("nhqk,nkhd->nqhd", p.astype(q.dtype), v)


# ---------------------------------------------------------------------------
# Ring attention (runs inside shard_map over the sequence axis)
# ---------------------------------------------------------------------------


def _ring_attention_body(q, k, v, key_mask=None, *, causal: bool,
                         t_local: int, axis_name: str = SEQ_AXIS):
    """Per-device body. q,k,v: [N, T_local, H, D] shards; key_mask an
    optional [N, T_local] 0/1 shard that rotates with its K/V block. Exact
    full attention via online softmax over rotating K/V blocks."""
    n_dev = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    n, tq, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    q32 = q.astype(jnp.float32)

    # accumulators: running max m, denominator l, numerator o
    m = jnp.full((n, h, tq), -jnp.inf, jnp.float32)
    l = jnp.zeros((n, h, tq), jnp.float32)
    o = jnp.zeros((n, tq, h, d), jnp.float32)

    q_pos = my * t_local + jnp.arange(tq)
    # the mask shard (when present) travels around the ring WITH its K/V
    # block; the mask-free hot path carries (and ppermutes) nothing extra
    km0 = () if key_mask is None else (jnp.asarray(key_mask, bool),)

    def step_fn(carry, step):
        m, l, o, k_blk, v_blk, km_blk = carry
        # the block currently held arrived from device (my - step) mod n_dev
        src = (my - step) % n_dev
        s = jnp.einsum("nqhd,nkhd->nhqk", q32, k_blk.astype(jnp.float32))
        s = s * scale
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        if key_mask is not None:
            s = jnp.where(km_blk[0][:, None, None, :], s, -jnp.inf)
        blk_max = jnp.max(s, axis=-1)  # [N,H,Tq]
        m_new = jnp.maximum(m, blk_max)
        # guard -inf - -inf
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l = l * corr + p.sum(axis=-1)
        o = o * jnp.moveaxis(corr, 1, 2)[..., None] + jnp.einsum(
            "nhqk,nkhd->nqhd", p, v_blk.astype(jnp.float32)
        )
        # rotate K/V (and the mask that travels with them) around the ring
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        km_blk = tuple(lax.ppermute(km, axis_name, perm) for km in km_blk)
        return (m_new, l, o, k_blk, v_blk, km_blk), None

    (m, l, o, _, _, _), _ = lax.scan(
        step_fn, (m, l, o, k, v, km0), jnp.arange(n_dev)
    )
    # where-based safe denominator, NOT maximum(l, 1e-30): the division
    # backward computes -o/denom^2 and (1e-30)^2 underflows f32 to 0,
    # turning all-masked rows (l = 0, o = 0) into 0/0 = NaN grads
    denom = jnp.moveaxis(jnp.where(l > 0, l, 1.0), 1, 2)[..., None]
    return (o / denom).astype(q.dtype)


def _ring_attention_body_flash(q, k, v, key_mask=None, *, causal: bool,
                               t_local: int, axis_name: str = SEQ_AXIS,
                               interpret: bool = False):
    """Ring body with the LOCAL block product running through the pallas
    flash kernel (ops/pallas_attention.flash_attention_block — the
    composition that module's header promises): per ring step the kernel
    returns (block_out, lse) and the shard results are combined exactly in
    log space. The kernel's TRACED visibility offset (qi + off >= ki with
    off = (my - src) * t_local) expresses shard-level causality, so one
    compiled kernel serves every step of the lax.scan ring."""
    from deeplearning4j_tpu.ops.pallas_attention import (
        _fold_heads,
        _unfold_heads,
        flash_attention_block,
    )

    n_dev = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    n, tq, h, d = q.shape

    qf, kf, vf = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    # mask shard travels with its K/V block; mask-free path carries nothing
    km0 = () if key_mask is None else (jnp.asarray(key_mask, bool),)

    # combined accumulators over ring steps: running max M of the lse,
    # denominator l (in M scale), numerator o (in M scale)
    M = jnp.full((n * h, tq), -jnp.inf, jnp.float32)
    l = jnp.zeros((n * h, tq), jnp.float32)
    o = jnp.zeros((n * h, tq, d), jnp.float32)

    def step_fn(carry, step):
        M, l, o, k_blk, v_blk, km_blk = carry
        src = (my - step) % n_dev
        # visible iff my*t+qi >= src*t+ki  <=>  qi + (my-src)*t >= ki;
        # non-causal: off = t_local*n_dev makes every key visible
        off = ((my - src) * t_local) if causal else t_local * n_dev
        o_b, lse_b = flash_attention_block(
            qf, k_blk, v_blk, offset=off,
            key_mask=(jnp.repeat(km_blk[0], h, axis=0) if km_blk else None),
            interpret=interpret)
        M_new = jnp.maximum(M, lse_b)
        M_safe = jnp.where(jnp.isfinite(M_new), M_new, 0.0)
        corr = jnp.where(jnp.isfinite(M), jnp.exp(M - M_safe), 0.0)
        w = jnp.exp(lse_b - M_safe)
        l = l * corr + w
        o = o * corr[..., None] + w[..., None] * o_b.astype(jnp.float32)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        km_blk = tuple(lax.ppermute(km, axis_name, perm) for km in km_blk)
        return (M_new, l, o, k_blk, v_blk, km_blk), None

    (M, l, o, _, _, _), _ = lax.scan(
        step_fn, (M, l, o, kf, vf, km0), jnp.arange(n_dev))
    # where-based safe denominator (see _ring_attention_body): with the
    # kernel's lse = -inf for all-masked rows, l = 0 here, and a
    # maximum(l, 1e-30) denominator NaNs the backward via (1e-30)^2
    # f32 underflow in -o/denom^2
    out = o / jnp.where(l > 0, l, 1.0)[..., None]
    return _unfold_heads(out, n, h).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, *, causal: bool = False,
                           key_mask=None, use_flash: Optional[bool] = None,
                           interpret: bool = False,
                           batch_axis: Optional[str] = None):
    """Full exact attention with the SEQUENCE dimension sharded over
    mesh axis 'seq'. q,k,v: [N, T, H, D] with T divisible by the axis size.
    key_mask: optional [N, T] 0/1, sharded with the keys (padded timesteps
    excluded exactly — the mask shard rotates with its K/V block).
    use_flash: run the local block product through the pallas flash kernel
    (ops/pallas_attention.py); default auto — on when pallas is enabled and
    the local shard fits the kernel's block/VMEM constraints.
    batch_axis: optional second mesh axis sharding the BATCH dim (DP x SP
    composition) — without it a ('data','seq') caller would all-gather the
    batch and compute every data slice's attention redundantly."""
    from deeplearning4j_tpu.ops.pallas_attention import (
        ext_fits,
        pallas_enabled,
    )

    n_dev = mesh.shape[SEQ_AXIS]
    t = q.shape[1]
    if t % n_dev != 0:
        raise ValueError(f"sequence length {t} not divisible by {n_dev} devices")
    t_local = t // n_dev
    if use_flash is None:
        # default-on needs BOTH the fit check and a committed on-chip win
        # (kernel_gate rent rule); explicit use_flash=True bypasses only
        # the win check
        from deeplearning4j_tpu.ops.kernel_gate import measured_win

        use_flash = (pallas_enabled()
                     and ext_fits(t_local, t_local, q.shape[-1])
                     and measured_win("attention", "ring_local_flash"))
    elif use_flash and not ext_fits(t_local, t_local, q.shape[-1]):
        raise ValueError(
            f"use_flash=True but the local shard (T_local={t_local}, "
            f"D={q.shape[-1]}) does not fit the kernel's block/VMEM "
            "constraints (ops/pallas_attention.ext_fits); use more/fewer "
            "'seq' devices or use_flash=False")
    body = (_ring_attention_body_flash if use_flash
            else _ring_attention_body)
    kwargs = dict(causal=causal, t_local=t_local)
    if use_flash:
        kwargs["interpret"] = interpret
    spec = P(batch_axis, SEQ_AXIS, None, None)
    args = (q, k, v)
    in_specs = (spec, spec, spec)
    if key_mask is not None:
        args += (key_mask,)
        in_specs += (P(batch_axis, SEQ_AXIS),)
    fn = shard_map(
        partial(body, **kwargs),
        mesh=mesh,
        in_specs=in_specs,
        out_specs=spec,
        check_vma=False,
    )
    return fn(*args)


# ---------------------------------------------------------------------------
# Ulysses: all-to-all sequence parallelism (the ring's sibling strategy)
# ---------------------------------------------------------------------------


def _ulysses_body(q, k, v, *, causal: bool, axis_name: str = SEQ_AXIS):
    """Per-device body. q,k,v: [N, T_local, H, D] sequence shards.

    Two all_to_alls instead of T/T_local ppermutes: swap the sharded axis
    from sequence to heads (each device then holds ALL timesteps for H/p
    heads), run plain dense attention locally, and swap back. Cheaper in
    collective count than the ring when the full [T, T] block fits memory;
    the ring wins when T is too long for any single device to hold T x T.
    """
    qh = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    att = multi_head_attention(qh, kh, vh, causal=causal)
    return lax.all_to_all(att, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention_sharded(q, k, v, mesh: Mesh, *, causal: bool = False,
                              batch_axis: Optional[str] = None):
    """Exact full attention with the sequence dim sharded over mesh axis
    'seq' via head<->sequence all_to_alls (DeepSpeed-Ulysses strategy).
    q,k,v: [N, T, H, D]; T and H must both divide by the axis size.
    batch_axis: optional second mesh axis sharding the batch (DP x SP)."""
    n_dev = mesh.shape[SEQ_AXIS]
    t, h = q.shape[1], q.shape[2]
    if t % n_dev != 0:
        raise ValueError(f"sequence length {t} not divisible by {n_dev}")
    if h % n_dev != 0:
        raise ValueError(f"num heads {h} not divisible by {n_dev} devices "
                         "(Ulysses shards heads; use ring attention instead)")
    spec = P(batch_axis, SEQ_AXIS, None, None)
    fn = shard_map(
        partial(_ulysses_body, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Layer-zoo integration: MultiHeadAttention for [N, T, F] activations
# ---------------------------------------------------------------------------


def mha_apply(params, x, num_heads: int, *, causal: bool = False,
              mesh: Optional[Mesh] = None, key_mask=None):
    """x: [N, T, F] -> [N, T, F]; runs ring attention when a mesh with a
    'seq' axis is supplied, single-device attention otherwise. key_mask
    ([N, T] 0/1) excludes padded timesteps from attention (single-device
    path; the ring path shards full sequences)."""
    n, t, f = x.shape
    proj = params["Wq"].shape[1]
    head_dim = proj // num_heads

    def split(w):
        return (x @ w).reshape(n, t, num_heads, head_dim)

    q, k, v = split(params["Wq"]), split(params["Wk"]), split(params["Wv"])
    if mesh is not None and SEQ_AXIS in mesh.shape:
        # the mask shard rotates with its K/V block through the ring, so
        # padded timesteps are excluded exactly even across shards
        att = ring_attention_sharded(q, k, v, mesh, causal=causal,
                                     key_mask=key_mask)
    else:
        # single-device path: ONE dispatch policy (attention_auto) — flash
        # pallas kernel when on TPU and the shape fits VMEM (masked batches
        # ride the extended kernel's key bias), dense XLA otherwise
        from deeplearning4j_tpu.ops.pallas_attention import attention_auto

        att = attention_auto(q, k, v, causal=causal, key_mask=key_mask)
    return att.reshape(n, t, proj) @ params["Wo"]
