"""Distributed / data-parallel training engine.

Replaces all three of the reference's data-parallel flavors (SURVEY.md
section 2.7) with ONE mesh-based engine:

  reference mechanism                          -> here
  --------------------------------------------------------------------
  ParallelWrapper (threads + param averaging,   ParallelWrapper: batch
    core/.../parallelism/ParallelWrapper.java)    sharded over a Mesh, jit
                                                  auto-partitions (GSPMD),
                                                  gradients pmean over ICI
  ParameterAveragingTrainingMaster (Spark       ParameterAveragingTrainer:
    broadcast + RDD.aggregate,                    shard_map local steps +
    dl4j-spark/.../ParameterAveragingTraining-    param/updater pmean every
    Master.java:402-434)                          k minibatches (exact
                                                  reference semantics)
  Akka/Hazelcast Hogwild (legacy)               statetracker.py job/heartbeat
                                                  plane, promoted to the
                                                  elastic fleet's membership
                                                  authority (fleet.py)
  Spark cluster fault tolerance (lineage +      ElasticParameterAveraging-
    heartbeat-tracked workers, job reclaim)       Trainer: preemption-tolerant
                                                  N-worker averaging, rounds
                                                  re-form over survivors,
                                                  bit-exact vs a replay of
                                                  the membership schedule

Multi-host: the same Mesh spans hosts via jax.distributed; collectives ride
ICI within a slice and DCN across slices — the ELASTIC control plane
(fleet membership, split reclaim) rides the statetracker transports.
"""

from deeplearning4j_tpu.parallel.mesh import device_mesh
from deeplearning4j_tpu.parallel.data_parallel import (
    ParallelWrapper,
    ParameterAveragingTrainer,
)
from deeplearning4j_tpu.parallel.fleet import (  # noqa: F401
    ElasticParameterAveragingTrainer,
    FileMembershipBoard,
)
