"""Distributed / data-parallel training engine.

Replaces all three of the reference's data-parallel flavors (SURVEY.md
section 2.7) with ONE mesh-based engine:

  reference mechanism                          -> here
  --------------------------------------------------------------------
  ParallelWrapper (threads + param averaging,   ParallelWrapper: batch
    core/.../parallelism/ParallelWrapper.java)    sharded over a Mesh, jit
                                                  auto-partitions (GSPMD),
                                                  gradients pmean over ICI
  ParameterAveragingTrainingMaster (Spark       ParameterAveragingTrainer:
    broadcast + RDD.aggregate,                    shard_map local steps +
    dl4j-spark/.../ParameterAveragingTraining-    param/updater pmean every
    Master.java:402-434)                          k minibatches (exact
                                                  reference semantics)
  Akka/Hazelcast Hogwild (legacy)               not reproduced (superseded)

Multi-host: the same Mesh spans hosts via jax.distributed; collectives ride
ICI within a slice and DCN across slices — no Spark/Akka control plane.
"""

from deeplearning4j_tpu.parallel.mesh import device_mesh
from deeplearning4j_tpu.parallel.data_parallel import (
    ParallelWrapper,
    ParameterAveragingTrainer,
)
