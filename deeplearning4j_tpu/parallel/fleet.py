"""Elastic fleet runtime: preemption-tolerant N-worker parameter averaging.

The reference's headline scale story is Spark parameter averaging across a
fault-prone cluster (dl4j-spark/.../paramavg/ParameterAveragingTrainingMaster
.java:402-434) with worker liveness and job reclaim delegated to the
Hazelcast/ZooKeeper state tracker (BaseHazelCastStateTracker.java:49 —
heartbeats, job re-queue on dead members; reproduced in
parallel/statetracker.py). Spark re-executes a lost executor's partition
through lineage; membership changes re-form the next stage over the
survivors. This module is that story made elastic and DETERMINISTIC:

  coordinator       :class:`ElasticParameterAveragingTrainer` — one
                    averaging round per ``fit`` call: poll the membership
                    authority (the promoted StateTracker — in-process,
                    over its TCP transport, or a :class:`FileMembershipBoard`
                    shared directory), partition the round's global batch
                    into one split per LIVE worker (sorted, balanced,
                    loud ValueError when not divisible — the
                    multihost.local_batch_slice rule), enqueue the splits
                    as fenced jobs, wait, average the results in SPLIT
                    ORDER on the host.
  workers           in-process threads (:class:`_InProcessWorker`) or
                    other OS processes (:func:`run_worker` over
                    RemoteStateTracker + the file data plane): each pulls
                    a split, runs ``averaging_frequency`` independent
                    train steps from the broadcast params
                    (data_parallel.local_round_scan — the exact
                    ExecuteWorkerFlatMap.java:35-100 semantics), and
                    completes the job with the attempt-fenced protocol.
  failure handling  a worker that dies holding a split is detected by
                    heartbeat expiry; the split is RECLAIMED and
                    re-executed by a survivor (no batch dropped); a
                    zombie whose heartbeat merely stalled gets its late
                    completion FENCED OUT (no batch double-counted) and
                    re-registers. A SIGTERM'd worker process announces
                    departure (deregister + immediate job re-queue)
                    before dying. The NEXT round re-forms over the
                    survivor set (membership epoch bump), which also
                    re-partitions any attached ETL pipelines
                    (etl/pipeline.InputPipeline.reshard).

Determinism is structural, not incidental: a split's result is a pure
function of (broadcast params, split data, round RNGs) — executor
identity never enters — and the host average runs in split-index order.
A run that loses worker k at round s and re-admits a replacement at round
s+m is therefore BIT-exact against a deterministic replay of the same
membership schedule (scripted evict/admit at the same rounds), and at
``averaging_frequency=1`` with SGD it matches the serial big-batch run to
1e-5 (TestCompareParameterAveragingSparkVsSingleMachine.java:115-262 bar,
extended across membership changes — tests/test_fleet.py and the elastic
legs of ``__graft_entry__.dryrun_multichip``).

The authoritative training state lives with the COORDINATOR: wrap the
trainer in resilience.ResilientTrainer with a CheckpointManager and the
coordinator owns the single checkpoint (workers are stateless between
splits — their goodbye is the departure announcement, not a state dump).

Env knobs: ``DL4J_TPU_FLEET_HEARTBEAT_S`` (failure-detection timeout,
default 5.0), ``DL4J_TPU_FLEET_MIN_WORKERS`` (a round blocks until this
many members are live, default 1), ``DL4J_TPU_FLEET_DIR`` (when set, the
default shared directory for the file membership/data planes).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.obs import journal as obs_journal
from deeplearning4j_tpu.obs import registry as obs_registry
from deeplearning4j_tpu.obs import trace as obs_trace
from deeplearning4j_tpu.ops import env as envknob

logger = logging.getLogger("deeplearning4j_tpu")

HEARTBEAT_ENV = "DL4J_TPU_FLEET_HEARTBEAT_S"
MIN_WORKERS_ENV = "DL4J_TPU_FLEET_MIN_WORKERS"
FLEET_DIR_ENV = "DL4J_TPU_FLEET_DIR"

_MANIFEST = "fleet"  # FileServiceRegistry entry for cross-process workers


def _env_float(name: str, default: float) -> float:
    return envknob.get_float(name, default)


def _env_int(name: str, default: int) -> int:
    return envknob.get_int(name, default)


def shard_for(worker_id: str, live: List[str]) -> Optional[Tuple[int, int]]:
    """(rank, count) of ``worker_id`` in the SORTED live set — the ETL
    plane's shard selection under elastic membership (every member
    computes the same answer from the same membership snapshot). None
    when the worker is not (any longer) a member."""
    ordered = sorted(live)
    if worker_id not in ordered:
        return None
    return ordered.index(worker_id), len(ordered)


# ---------------------------------------------------------------------------
# File membership board (shared-directory transport)
# ---------------------------------------------------------------------------


class FileMembershipBoard:
    """Membership authority over a shared directory (the file half of the
    ISSUE-6 "file/socket transport": NFS/GCS-fuse deployments where the
    TCP tracker port cannot be reached; same znode-as-json-file idiom as
    statetracker.FileServiceRegistry). Join writes a heartbeat file,
    every beat rewrites it with a fresh sequence payload, leave removes
    it — so announced departure and heartbeat expiry look identical to
    the coordinator's poll, exactly like the tracker authority.

    Liveness is CLOCK-SKEW-FREE: the reader never compares a writer
    timestamp (or server mtime) against its own wall clock — unsynced
    hosts and coarse GCS-fuse mtimes would falsely expel live members.
    Instead each poll records, on the reader's MONOTONIC clock, when a
    member's payload was last observed to CHANGE; a member whose file
    stops changing for `heartbeat_timeout` of reader-time is dead."""

    def __init__(self, root: str, heartbeat_timeout: float = 5.0):
        self.root = os.path.abspath(root)
        self.heartbeat_timeout = heartbeat_timeout
        # worker -> (last payload seen, reader-monotonic time it changed)
        self._seen: Dict[str, Tuple[str, float]] = {}
        self._beats = 0
        os.makedirs(self.root, exist_ok=True)

    def _path(self, worker_id: str) -> str:
        return os.path.join(self.root, f"member-{worker_id}.hb")

    def register_worker(self, worker_id: str) -> int:
        self.heartbeat(worker_id)
        return 0  # epoch accounting is coordinator-side (set-change scan)

    def heartbeat(self, worker_id: str) -> None:
        self._beats += 1
        tmp = self._path(worker_id) + f".tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            # payload only needs to CHANGE every beat (a per-writer
            # sequence); the wall time rides along for human debugging
            f.write(f"{os.getpid()}:{self._beats}:{time.time()}\n")
        os.replace(tmp, self._path(worker_id))  # atomic publish

    def deregister_worker(self, worker_id: str) -> int:
        try:
            os.remove(self._path(worker_id))
        except FileNotFoundError:
            pass
        self._seen.pop(worker_id, None)
        return 0

    def live_workers(self) -> List[str]:
        now = time.monotonic()
        out = []
        try:
            names = os.listdir(self.root)
        except OSError as e:
            # a shared-mount blip must read as a PARTITION (the
            # coordinator's retry/fallback path), not as "fleet empty" —
            # an empty answer would run the round-timeout clock out
            raise ConnectionError(
                f"membership board unreadable at {self.root!r}: {e}"
            ) from e
        present = set()
        for name in names:
            if not (name.startswith("member-") and name.endswith(".hb")):
                continue
            wid = name[len("member-"):-len(".hb")]
            try:
                with open(os.path.join(self.root, name),
                          encoding="utf-8") as f:
                    payload = f.read()
            except OSError:
                continue  # removed between listdir and read
            present.add(wid)
            last = self._seen.get(wid)
            if last is None or last[0] != payload:
                self._seen[wid] = (payload, now)  # observed a fresh beat
                out.append(wid)
            elif now - last[1] <= self.heartbeat_timeout:
                out.append(wid)
        # forget removed files so a re-join starts a fresh observation
        for wid in list(self._seen):
            if wid not in present:
                del self._seen[wid]
        return sorted(out)


# ---------------------------------------------------------------------------
# npz tree plumbing (the file data plane: tensors never ride the JSON RPC)
# ---------------------------------------------------------------------------


def _atomic_savez(path: str, **arrays) -> None:
    """Crash-safe npz publish: tmp + rename (a member reading a
    half-written file would poison a round)."""
    tmp = f"{path}.tmp-{os.getpid()}.npz"  # .npz suffix: savez appends none
    np.savez(tmp, **arrays)
    os.replace(tmp, path)


def _save_trees(path: str, losses=None, extra: Optional[dict] = None,
                **trees) -> None:
    """Atomic npz of several pytrees' leaves ({prefix}{i} keys, leaf
    order = tree_flatten order, reproducible from the same conf), plus
    optional flat `extra` arrays — the ONE writer both the coordinator's
    round-state/result files and the workers' readers agree on."""
    import jax

    arrays: Dict[str, np.ndarray] = {}
    for prefix, tree in trees.items():
        for i, leaf in enumerate(jax.tree_util.tree_leaves(tree)):
            arrays[f"{prefix}{i}"] = np.asarray(leaf)
    if losses is not None:
        arrays["losses"] = np.asarray(losses)
    for key, val in (extra or {}).items():
        arrays[key] = np.asarray(val)
    _atomic_savez(path, **arrays)


def _load_tree(npz, prefix: str, template):
    """Leaves {prefix}{i} back into `template`'s structure."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(template)
    out = [npz[f"{prefix}{i}"] for i in range(len(leaves))]
    return jax.tree_util.tree_unflatten(treedef, out)


# ---------------------------------------------------------------------------
# Workers
# ---------------------------------------------------------------------------


class _Heartbeater:
    """Heartbeat from a side thread while a member computes: a split's
    first execution traces (seconds of XLA compile on this host), far
    past any sane failure-detection timeout — liveness and compute are
    separate planes, as with the reference's Hazelcast heartbeat thread
    next to the worker's training thread."""

    def __init__(self, worker_id: str, tracker, board, heartbeat_s: float,
                 enabled: bool = True):
        self.worker_id = worker_id
        self.tracker = tracker
        self.board = board
        self.interval = max(0.01, min(0.25, heartbeat_s / 4.0))
        self.enabled = enabled
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def __enter__(self):
        if self.enabled:
            self._thread = threading.Thread(
                target=self._beat, daemon=True,
                name=f"hb-{self.worker_id}")
            self._thread.start()
        return self

    def _beat(self):
        while not self._stop.wait(self.interval):
            try:
                self.tracker.heartbeat(self.worker_id)
                if self.board is not None:
                    self.board.heartbeat(self.worker_id)
            except Exception:  # noqa: BLE001 — a dying transport ends beats
                return

    def __exit__(self, *exc):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        return False


class _InProcessWorker(threading.Thread):
    """One fleet member as a daemon thread: poll the tracker for split
    jobs, execute them through the coordinator's jitted local scan, and
    complete with the fenced protocol. The thread analogue of the
    reference's worker JVM (ExecuteWorkerFlatMap) — the cross-process
    twin is :func:`run_worker`."""

    def __init__(self, fleet: "ElasticParameterAveragingTrainer",
                 worker_id: str, chaos=None, poll_s: float = 0.005):
        super().__init__(name=f"fleet-{worker_id}", daemon=True)
        self.fleet = fleet
        self.worker_id = worker_id
        self.chaos = chaos
        self.poll_s = poll_s
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:  # noqa: C901 — one worker loop, kept whole
        fleet, wid = self.fleet, self.worker_id
        tracker = fleet.tracker
        board = fleet.membership_board
        tracker.register_worker(wid)
        if board is not None:
            board.register_worker(wid)
        try:
            while not self._stop.is_set():
                rnd = fleet.round_index
                if self.chaos is not None and self.chaos.kill_on_poll(
                        wid, rnd):
                    return  # dies silently: no goodbye, no deregister
                job = tracker.request_job(wid)
                if board is not None:
                    board.heartbeat(wid)
                if job is None:
                    self._stop.wait(self.poll_s)
                    continue
                split = int(job.payload["split"])
                jrnd = int(job.payload["round"])
                if self.chaos is not None and self.chaos.kill_on_job(
                        wid, jrnd, split):
                    return  # dies HOLDING the job -> reclaim path
                stall = (self.chaos.stall_on_job(wid, jrnd, split)
                         if self.chaos is not None else None)
                try:
                    # side-thread heartbeats while computing: the first
                    # execution of a split TRACES (seconds of XLA compile),
                    # and a silent member mid-compile must not read as dead
                    # — except the chaos zombie, whose silence is the fault
                    with _Heartbeater(
                            wid, tracker, board, self.fleet.heartbeat_s,
                            enabled=stall is None):
                        result = fleet._execute_split(job.payload)
                except Exception as e:  # noqa: BLE001 — JobFailed protocol
                    logger.warning("fleet worker %s failed split %d of "
                                   "round %d: %s", wid, split, jrnd, e)
                    tracker.fail_job(job.job_id, attempt=job.attempts)
                    continue
                if stall is not None:
                    # zombie: computed, then went silent past the
                    # heartbeat timeout — the split is reclaimed and
                    # re-executed underneath; the completion below MUST
                    # be fenced out or the round double-counts it
                    time.sleep(stall)
                accepted = tracker.complete_job(
                    job.job_id, result, attempt=job.attempts)
                if not accepted and not self._stop.is_set():
                    # fenced out: the split was reclaimed and re-assigned
                    # underneath this zombie — rejoin at a fresh epoch.
                    # NOT when evicted: a stopped worker re-registering
                    # would resurrect a ghost member for heartbeat_s and
                    # skew the next round's split count
                    logger.warning(
                        "fleet worker %s: completion of split %d round %d "
                        "fenced out (job reclaimed while stalled); "
                        "re-registering", wid, split, jrnd)
                    tracker.register_worker(wid)
                    if board is not None:
                        board.register_worker(wid)
        finally:
            if self._stop.is_set():
                # EVICTED (scripted/announced departure): re-remove any
                # membership trace a still-beating heartbeater recreated
                # after evict_worker's deregister — a ghost member file
                # would skew the next round's split count. A chaos-killed
                # worker must NOT clean up: its death is meant to be
                # detected by heartbeat expiry.
                tracker.deregister_worker(wid)
                if board is not None:
                    board.deregister_worker(wid)


def run_worker(address: str, worker_id: str, spool_dir: str, *,
               poll_s: float = 0.02, handle_signals: bool = True,
               stop_after_idle_s: Optional[float] = None) -> None:
    """Cross-process fleet member: the reference's worker JVM over our
    transports — control plane on the tracker's TCP JSON RPC
    (RemoteStateTracker), data plane on the spool directory (split /
    round-state / result npz files; tensors never ride the RPC —
    statetracker.StateTrackerServer contract). Builds its own net from
    the fleet manifest the coordinator registered (FileServiceRegistry
    role), so the jitted local scan is the same XLA program on every
    member.

    Preemption: SIGTERM -> fail the in-flight job back to the queue,
    deregister (announced departure — the survivors rebalance without
    waiting out the heartbeat timeout), exit. The coordinator owns the
    authoritative checkpoint; a worker's goodbye is its announcement."""
    import signal
    import sys

    from deeplearning4j_tpu.parallel.statetracker import (
        FileServiceRegistry,
        RemoteStateTracker,
    )

    # per-worker flight-recorder path (unless the operator pinned one):
    # N workers sharing the coordinator's cwd must not last-writer-wins
    # clobber the coordinator's checkpoint/membership/preempt timeline
    os.environ.setdefault(
        "DL4J_TPU_OBS_JOURNAL",
        os.path.join(spool_dir, f".obs_journal.{worker_id}.jsonl"))

    manifest = FileServiceRegistry(spool_dir).retrieve(_MANIFEST)
    if manifest is None:
        raise RuntimeError(f"no fleet manifest under {spool_dir!r}")
    net = _net_from_manifest(manifest)
    freq = int(manifest["averaging_frequency"])
    from deeplearning4j_tpu.parallel.data_parallel import (
        container_calls,
        local_round_scan,
    )
    from deeplearning4j_tpu.ops import dispatch

    loss_call, update_call, _ = container_calls(net)
    local = dispatch.instrumented_jit(
        local_round_scan(net, loss_call, update_call),
        "fleet_worker", net.dispatch_stats, step=True)

    tracker = RemoteStateTracker.from_address(address)
    tracker.register_worker(worker_id)
    state = {"job": None, "preempted": False}

    def on_sigterm(signum, frame):
        state["preempted"] = True

    if handle_signals:
        signal.signal(signal.SIGTERM, on_sigterm)

    round_cache: Dict[int, tuple] = {}
    last_work = time.monotonic()
    try:
        while True:
            if state["preempted"]:
                # announced departure: in-flight job back to the queue,
                # membership epoch bumps NOW, not at heartbeat expiry
                job = state["job"]
                if job is not None:
                    tracker.fail_job(job.job_id, attempt=job.attempts)
                tracker.deregister_worker(worker_id)
                print(f"FLEET_WORKER_PREEMPTED {worker_id}", flush=True)
                sys.exit(143)
            job = tracker.request_job(worker_id)
            if job is None:
                if (stop_after_idle_s is not None
                        and time.monotonic() - last_work > stop_after_idle_s):
                    tracker.deregister_worker(worker_id)
                    return
                time.sleep(poll_s)
                continue
            state["job"] = job
            p = job.payload
            rnd, split = int(p["round"]), int(p["split"])
            try:
                # the whole split execution is JobFailed-protected, like
                # _InProcessWorker: a stale round's deleted spool file, a
                # corrupt npz, or ENOSPC must fail the JOB back to the
                # queue (toward the dead-letter cap), not kill the member
                if rnd not in round_cache:
                    round_cache.clear()  # old rounds never come back
                    with np.load(p["state"]) as z:
                        round_cache[rnd] = (
                            _load_tree(z, "p", net.params),
                            _load_tree(z, "s", net.states),
                            _load_tree(z, "u", net.updater_state),
                            int(z["iteration"]),
                            z["rngs"].copy(),
                        )
                params, states, upd, iteration, rngs = round_cache[rnd]
                with np.load(p["data"]) as z:
                    xs, ys = z["xs"], z["ys"]
                    ms = z["ms"] if "ms" in z.files else None
                    lms = z["lms"] if "lms" in z.files else None
                import jax.numpy as jnp

                with _Heartbeater(worker_id, tracker, None,
                                  float(manifest.get(
                                      "heartbeat_s",
                                      _env_float(HEARTBEAT_ENV, 5.0)))):
                    (o_params, o_states, o_upd, _), losses = local(
                        params, states, upd, xs, ys, ms, lms,
                        jnp.asarray(iteration, jnp.int32), rngs)
                result_path = os.path.join(
                    spool_dir, f"result-{rnd}-{split}-{worker_id}.npz")
                _save_trees(result_path, losses=np.asarray(losses),
                            p=o_params, s=o_states, u=o_upd)
            except Exception as e:  # noqa: BLE001 — JobFailed protocol
                logger.warning("fleet worker %s failed split %d of round "
                               "%d: %s", worker_id, split, rnd, e)
                tracker.fail_job(job.job_id, attempt=job.attempts)
                state["job"] = None
                continue
            accepted = tracker.complete_job(
                job.job_id, {"split": split, "path": result_path},
                attempt=job.attempts)
            if not accepted:
                # fenced out: the split was reclaimed (this member read
                # as dead) and re-assigned — rejoin at a fresh epoch,
                # same as the in-process worker
                print(f"FLEET_WORKER_FENCED {worker_id} r{rnd}s{split}",
                      flush=True)
                tracker.register_worker(worker_id)
            state["job"] = None
            last_work = time.monotonic()
    finally:
        tracker.close()


def _net_from_manifest(manifest: dict):
    model_class = manifest.get("model_class", "MultiLayerNetwork")
    if model_class == "ComputationGraph":
        from deeplearning4j_tpu.nn.conf.graph import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        return ComputationGraph(
            ComputationGraphConfiguration.from_json(manifest["conf"])).init()
    from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    return MultiLayerNetwork(
        MultiLayerConfiguration.from_json(manifest["conf"])).init()


# ---------------------------------------------------------------------------
# Coordinator
# ---------------------------------------------------------------------------


class ElasticParameterAveragingTrainer:
    """Elastic ParameterAveragingTrainer (see module docstring). One
    ``fit(features, labels)`` call = one averaging round over the live
    membership. Carries the container fit contract, so ResilientTrainer
    and ParameterAveragingTrainingMaster drive it unchanged."""

    def __init__(
        self,
        net,
        num_workers: int = 2,
        averaging_frequency: int = 1,
        save_updater: bool = True,
        *,
        tracker=None,
        membership_board=None,
        heartbeat_s: Optional[float] = None,
        min_workers: Optional[int] = None,
        chaos=None,
        spool_dir: Optional[str] = None,
        round_timeout_s: float = 120.0,
        job_max_attempts: int = 5,
    ):
        from deeplearning4j_tpu.parallel.statetracker import StateTracker

        self.net = net
        self.averaging_frequency = max(1, int(averaging_frequency))
        self.save_updater = save_updater
        self.heartbeat_s = (heartbeat_s if heartbeat_s is not None
                            else _env_float(HEARTBEAT_ENV, 5.0))
        self.min_workers = (min_workers if min_workers is not None
                            else _env_int(MIN_WORKERS_ENV, 1))
        self.tracker = tracker if tracker is not None else StateTracker(
            heartbeat_timeout=self.heartbeat_s,
            max_attempts=job_max_attempts)
        self.membership_board = membership_board
        self.chaos = chaos
        self.spool_dir = spool_dir or envknob.get_str(FLEET_DIR_ENV)
        self.round_timeout_s = float(round_timeout_s)
        self.round_index = 0  # 1-based during a round; 0 before the first
        self.resilience_stats: Dict[str, Any] = {
            "retries": 0, "reclaims": 0, "backoff_seconds": 0.0,
            "rounds": 0, "membership_retries": 0, "membership_fallbacks": 0,
            "epoch": 0, "stale_completions": 0,
        }
        net.resilience_stats = self.resilience_stats
        # join the central MetricsRegistry: the fleet's rounds/epoch/
        # reclaim counters become one more view beside dispatch/memory
        obs_registry.register_net(net)
        self._workers: Dict[str, _InProcessWorker] = {}
        self._pending_spawn = [f"w{i}" for i in range(int(num_workers))]
        self._worker_seq = int(num_workers)  # next generated member id
        self._server = None
        self._round_state: Optional[dict] = None
        self._step_fns: Dict[tuple, Callable] = {}
        self._step_build_lock = threading.Lock()
        self._epoch = 0
        self._last_live: Optional[List[str]] = None
        self._listeners: List[Callable[[int, List[str]], None]] = []
        self._pipelines: List[tuple] = []
        self._is_graph = hasattr(net, "_as_inputs")
        if self._is_graph:
            raise NotImplementedError(
                "ElasticParameterAveragingTrainer drives MultiLayerNetwork; "
                "ComputationGraph stays on the shard_map "
                "ParameterAveragingTrainer (SparkComputationGraph mode)")

    # -- membership surface -------------------------------------------------
    @property
    def membership_authority(self):
        return (self.membership_board if self.membership_board is not None
                else self.tracker)

    @property
    def epoch(self) -> int:
        return self._epoch

    def add_membership_listener(self, fn: Callable[[int, List[str]], None]):
        """``fn(epoch, sorted_live)`` fired whenever the live set the
        coordinator plans rounds over changes."""
        self._listeners.append(fn)

    def attach_pipeline(self, pipeline, worker_id: str,
                        boundary_fn: Callable[[], int]) -> None:
        """Live ETL resharding: on every membership change, re-partition
        `pipeline`'s shard selection to ``shard_for(worker_id, live)`` at
        the absolute batch boundary ``boundary_fn()`` (the control plane
        must agree on one boundary fleet-wide — typically the first
        global batch index of the next epoch/round)."""
        self._pipelines.append((pipeline, worker_id, boundary_fn))

    def admit_worker(self, worker_id: Optional[str] = None) -> str:
        """Grow the fleet: spawn (or re-admit) an in-process member. The
        next round re-forms over the enlarged set. Generated ids come
        from a monotone counter — len()-based naming would collide with
        a live member after an eviction (silently orphaning its thread
        and making the admit a membership no-op)."""
        if worker_id is None:
            worker_id = f"w{self._worker_seq}"
            self._worker_seq += 1
        self._spawn(worker_id)
        return worker_id

    def evict_worker(self, worker_id: str) -> None:
        """Scripted/announced departure (the deterministic-replay twin of
        a chaos kill): stop the member and deregister it — its in-flight
        jobs re-queue immediately."""
        w = self._workers.pop(worker_id, None)
        if w is not None:
            w.stop()
        self.tracker.deregister_worker(worker_id)
        if self.membership_board is not None:
            self.membership_board.deregister_worker(worker_id)

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> str:
        """Expose this coordinator's tracker over TCP for OS-process
        members (:func:`run_worker`); registers the fleet manifest in the
        spool dir so workers can build the identical net. Returns the
        address to hand to workers."""
        from deeplearning4j_tpu.parallel.statetracker import (
            FileServiceRegistry,
            StateTrackerServer,
        )

        if self.spool_dir is None:
            raise ValueError("cross-process fleet needs spool_dir (the "
                             "file data plane; DL4J_TPU_FLEET_DIR)")
        os.makedirs(self.spool_dir, exist_ok=True)
        if self.net.params is None:
            self.net.init()
        FileServiceRegistry(self.spool_dir).register(_MANIFEST, {
            "model_class": type(self.net).__name__,
            "conf": self.net.conf.to_json(),
            "averaging_frequency": self.averaging_frequency,
            "save_updater": bool(self.save_updater),
            "heartbeat_s": self.heartbeat_s,
        })
        self._server = StateTrackerServer(self.tracker, host, port).start()
        return self._server.address

    def close(self) -> None:
        for wid in list(self._workers):
            self.evict_worker(wid)
        if self._server is not None:
            self._server.stop()
            self._server = None

    def _spawn(self, wid: str) -> None:
        old = self._workers.get(wid)
        if old is not None and old.is_alive():
            raise ValueError(
                f"worker id {wid!r} is already a live member — evict it "
                "first or admit under a fresh id")
        w = _InProcessWorker(self, wid, chaos=self.chaos)
        self._workers[wid] = w
        w.start()
        # registration barrier: the membership a round forms over must be
        # deterministic, so a spawn/admit returns only once the member is
        # visible to the authority (otherwise the first round would race
        # the workers' registrations and the split count would flap)
        deadline = time.monotonic() + 10.0
        while wid not in self.membership_authority.live_workers():
            if time.monotonic() > deadline:
                raise RuntimeError(f"worker {wid} never registered")
            time.sleep(0.001)

    def _ensure_workers(self) -> None:
        pending, self._pending_spawn = self._pending_spawn, []
        for wid in pending:
            self._spawn(wid)

    # -- membership poll ----------------------------------------------------
    def _poll_membership(self) -> List[str]:
        """Sorted live member set, >= min_workers, with partition
        tolerance: a failed poll retries with backoff and ultimately
        falls back to the last-known set (LOUDLY) rather than killing
        training — the coordinator analogue of Spark surviving a
        transient ZooKeeper session loss."""
        stats = self.resilience_stats
        deadline = time.monotonic() + self.round_timeout_s
        backoff = 0.01
        while True:
            try:
                if self.chaos is not None:
                    self.chaos.on_membership_poll(self.round_index)
                # expire silent members FIRST: their in-flight jobs
                # re-queue and the epoch bumps before the round forms
                reclaimed = self.tracker.reclaim_dead_jobs()
                if reclaimed:
                    stats["reclaims"] += reclaimed
                live = sorted(self.membership_authority.live_workers())
            except (ConnectionError, TimeoutError) as e:
                # TimeoutError too: the FIRST slow RPC on a
                # RemoteStateTracker raises the socket timeout (only the
                # poisoned connection's LATER calls raise ConnectionError)
                stats["membership_retries"] += 1
                if time.monotonic() > deadline:
                    if self._last_live:
                        stats["membership_fallbacks"] += 1
                        logger.warning(
                            "membership authority unreachable (%s); falling "
                            "back to last-known membership %s", e,
                            self._last_live)
                        return list(self._last_live)
                    raise
                time.sleep(backoff)
                backoff = min(0.2, backoff * 2)
                continue
            if len(live) >= self.min_workers:
                self._note_membership(live)
                return live
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"fleet below min_workers={self.min_workers} for "
                    f"{self.round_timeout_s:.0f}s (live: {live})")
            time.sleep(0.01)

    def _note_membership(self, live: List[str]) -> None:
        if self._last_live == live:
            return
        self._epoch += 1
        self.resilience_stats["epoch"] = self._epoch
        obs_journal.event("membership", epoch=self._epoch,
                          live=list(live), was=self._last_live,
                          round=self.round_index)
        logger.info("fleet membership epoch %d: %s (was %s) — rounds "
                    "re-form over %d workers", self._epoch, live,
                    self._last_live, len(live))
        self._last_live = list(live)
        for fn in self._listeners:
            fn(self._epoch, list(live))
        from deeplearning4j_tpu.etl.pipeline import DROP_SHARD

        for pipeline, wid, boundary_fn in self._pipelines:
            # a DEPARTED member owns nothing (reshard(None) would mean
            # "own everything" and double-feed the survivors' batches)
            shard = shard_for(wid, live)
            pipeline.reshard(DROP_SHARD if shard is None else shard,
                             at_seq=boundary_fn())

    # -- the round ----------------------------------------------------------
    def _to_rounds(self, a):
        from deeplearning4j_tpu.parallel.data_parallel import stack_rounds

        return stack_rounds(a, self.averaging_frequency)

    def _local_step(self):
        key = ("local",)
        # built under a lock: N worker threads race here on round 1, and
        # an unsynchronized check would hand each its OWN jit instance —
        # the identical scan traced/compiled num_workers times on the
        # shared core (and inflated dispatch_stats trace counts)
        with self._step_build_lock:
            if key not in self._step_fns:
                from deeplearning4j_tpu.ops import dispatch
                from deeplearning4j_tpu.parallel.data_parallel import (
                    container_calls,
                    local_round_scan,
                )

                loss_call, update_call, _ = container_calls(self.net)
                # NO donation: every split of a round re-reads the same
                # broadcast params/states/updater trees
                self._step_fns[key] = dispatch.instrumented_jit(
                    local_round_scan(self.net, loss_call, update_call),
                    "fleet_worker", self.net.dispatch_stats, step=True)
        return self._step_fns[key]

    def _execute_split(self, payload: dict):
        """Run one split's local scan (in-process data plane). A
        reclaimed job re-executes here with the SAME round state — the
        result is identical no matter which worker runs it."""
        rs = self._round_state
        if rs is None or payload["round"] != rs["round"]:
            raise RuntimeError(
                f"split for round {payload['round']} but round "
                f"{None if rs is None else rs['round']} is current")
        import jax.numpy as jnp

        with obs_trace.span("fleet.split", round=int(payload["round"]),
                            split=int(payload["split"]),
                            membership_epoch=self._epoch):
            xs, ys, ms, lms = rs["splits"][payload["split"]]
            (params, states, upd, _), losses = self._local_step()(
                rs["params"], rs["states"], rs["upd"], xs, ys, ms, lms,
                jnp.asarray(rs["iteration"], jnp.int32), rs["rngs"])
        return {"split": int(payload["split"]),
                "arrays": (params, states, upd, np.asarray(losses))}

    def _step_rngs(self):
        from deeplearning4j_tpu.parallel.data_parallel import round_step_rngs

        return round_step_rngs(self.net, self.averaging_frequency)

    def _publish_round(self, rnd: int, splits: List[tuple]) -> List[dict]:
        """Round state for the workers; returns per-split payloads. With
        a spool dir the state/split arrays also land as npz files for
        OS-process members (the file data plane)."""
        net = self.net
        self._round_state = {
            "round": rnd,
            "params": net.params,
            "states": net.states,
            "upd": net.updater_state,
            "iteration": int(net.iteration),
            "rngs": self._step_rngs(),
            "splits": splits,
        }
        payloads = [{"round": rnd, "split": i} for i in range(len(splits))]
        if self.spool_dir:
            os.makedirs(self.spool_dir, exist_ok=True)
            state_path = os.path.join(self.spool_dir, f"state-{rnd}.npz")
            rs = self._round_state
            _save_trees(state_path,
                        extra={"iteration": rs["iteration"],
                               "rngs": rs["rngs"]},
                        p=rs["params"], s=rs["states"], u=rs["upd"])
            for i, (xs, ys, ms, lms) in enumerate(splits):
                sp = os.path.join(self.spool_dir, f"split-{rnd}-{i}.npz")
                arrs = {"xs": np.asarray(xs), "ys": np.asarray(ys)}
                if ms is not None:
                    arrs["ms"] = np.asarray(ms)
                if lms is not None:
                    arrs["lms"] = np.asarray(lms)
                _atomic_savez(sp, **arrs)
                payloads[i].update(state=state_path, data=sp)
            self._gc_spool(rnd)
        return payloads

    def _gc_spool(self, rnd: int) -> None:
        """Bound spool disk to the live round plus one (a reclaimed job
        of the previous round must still find its files)."""
        try:
            names = os.listdir(self.spool_dir)
        except OSError:
            return
        for name in names:
            for prefix in ("state-", "split-", "result-"):
                if name.startswith(prefix):
                    try:
                        r = int(name[len(prefix):].split("-")[0].split(".")[0])
                    except ValueError:
                        continue
                    if r < rnd - 1:
                        try:
                            os.remove(os.path.join(self.spool_dir, name))
                        except OSError:
                            pass

    def fit(self, features, labels, mask=None, label_mask=None) -> float:
        """One elastic averaging round: re-form over the live membership,
        split, dispatch, reclaim as needed, average in split order. The
        round span carries the membership epoch so a flight-recorder
        timeline correlates rounds with chaos-injected kills and the
        elastic_dp bench leg (ISSUE 7)."""
        with obs_trace.span("fleet.round") as sp:
            loss = self._fit_round(features, labels, mask, label_mask)
            sp.set_attr("round", self.round_index)
            sp.set_attr("membership_epoch", self._epoch)
            sp.set_attr("workers", len(self._last_live or ()))
        return loss

    def _fit_round(self, features, labels, mask=None,
                   label_mask=None) -> float:
        net = self.net
        if net.params is None:
            net.init()
        self._ensure_workers()
        self.round_index += 1
        rnd = self.round_index
        live = self._poll_membership()
        n = len(live)
        x = self._to_rounds(features)
        y = self._to_rounds(labels)
        m = self._to_rounds(mask)
        lm = self._to_rounds(label_mask)
        gb = x.shape[1]
        if gb % n != 0:
            raise ValueError(
                f"global batch {gb} not divisible by {n} live workers — "
                "pad or trim so every member trains an equal split "
                "(silent tail truncation would drop examples; the "
                "multihost.local_batch_slice rule)")
        per = gb // n
        take = lambda a, sl: None if a is None else a[:, sl]
        splits = [
            (take(x, slice(i * per, (i + 1) * per)),
             take(y, slice(i * per, (i + 1) * per)),
             take(m, slice(i * per, (i + 1) * per)),
             take(lm, slice(i * per, (i + 1) * per)))
            for i in range(n)
        ]
        if hasattr(net, "_reset_rnn_states"):
            net._reset_rnn_states(per)
        payloads = self._publish_round(rnd, splits)
        from deeplearning4j_tpu.parallel.statetracker import Job

        job_ids = [f"r{rnd}-s{i}" for i in range(n)]
        for jid, payload in zip(job_ids, payloads):
            self.tracker.add_job(Job(jid, payload))
        results = self._await_round(job_ids)
        loss = self._apply_average(results, n)
        self.resilience_stats["rounds"] += 1
        if hasattr(self.tracker, "stale_completion_count"):
            # RPC-safe accessor: works for in-process AND remote trackers
            self.resilience_stats["stale_completions"] = (
                self.tracker.stale_completion_count())
        net.iteration += self.averaging_frequency
        net.score_value = loss
        return loss

    def _await_round(self, job_ids: List[str]) -> Dict[int, tuple]:
        """Wait until every split of this round is DONE, reclaiming dead
        members' in-flight splits along the way. No early exit: a round
        completes over whatever membership survives it (no batch dropped),
        or fails loudly (poisoned split / timeout / fleet extinct)."""
        stats = self.resilience_stats
        deadline = time.monotonic() + self.round_timeout_s
        last_expire = time.monotonic()
        want = set(job_ids)
        while True:
            done = self.tracker.results()
            if want <= set(done):
                break
            now = time.monotonic()
            # failure detection AND dead-letter checks at heartbeat
            # granularity, not every completion poll — the coordinator
            # shares the core with the worker threads doing the compute
            # (and each check copies a tracker dict / costs an RPC)
            if now - last_expire >= max(0.05, self.heartbeat_s / 2):
                last_expire = now
                reclaimed = self.tracker.reclaim_dead_jobs()
                if reclaimed:
                    stats["reclaims"] += reclaimed
                    logger.warning(
                        "fleet round %d: reclaimed %d in-flight split(s) "
                        "from dead worker(s); re-executing on survivors",
                        self.round_index, reclaimed)
                    live = self.membership_authority.live_workers()
                    if not live and not any(
                            t.is_alive() for t in self._workers.values()):
                        raise RuntimeError(
                            "fleet extinct: every worker died holding "
                            "splits and none can re-execute them")
                poisoned = self.tracker.poisoned_jobs() if hasattr(
                    self.tracker, "poisoned_jobs") else {}
                bad = want & set(poisoned)
                if bad:
                    raise RuntimeError(
                        f"split job(s) {sorted(bad)} poisoned after "
                        f"{max(poisoned[b] for b in bad)} attempts — a "
                        "batch may not be silently dropped; fix the fault "
                        "and rerun")
            if now > deadline:
                raise RuntimeError(
                    f"fleet round {self.round_index} timed out waiting for "
                    f"{sorted(want - set(done))}")
            time.sleep(0.005)
        drained = self.tracker.drain_results()
        out: Dict[int, tuple] = {}
        for jid in job_ids:
            res = drained[jid]
            if isinstance(res, dict) and "arrays" in res:
                out[int(res["split"])] = res["arrays"]
            else:  # file data plane (cross-process member)
                import jax

                with np.load(res["path"]) as z:
                    out[int(res["split"])] = (
                        _load_tree(z, "p", self.net.params),
                        _load_tree(z, "s", self.net.states),
                        _load_tree(z, "u", self.net.updater_state),
                        z["losses"].copy(),
                    )
        return out

    def _apply_average(self, results: Dict[int, tuple], n: int) -> float:
        """Host-side averaging round, in SPLIT-INDEX order (deterministic
        regardless of executor identity or completion order): params (and
        updater state, reference saveUpdater :416-434) averaged; batch-
        statistics states averaged; recurrent stream states keep the
        coordinator's (workers rebuild from broadcast each split)."""
        import jax

        from deeplearning4j_tpu.nn.layers.factory import STATEFUL_RNN_CONFS

        net = self.net
        ordered = [results[i] for i in range(n)]

        def mean_trees(trees):
            flat = [jax.tree_util.tree_flatten(t) for t in trees]
            treedef = flat[0][1]
            leaves = [f[0] for f in flat]
            out = []
            for li in range(len(leaves[0])):
                acc = np.asarray(leaves[0][li])
                for wi in range(1, n):  # fixed order: split 0,1,...,n-1
                    acc = acc + np.asarray(leaves[wi][li])
                out.append(acc / np.asarray(n, dtype=acc.dtype))
            return jax.tree_util.tree_unflatten(treedef, out)

        net.params = mean_trees([r[0] for r in ordered])
        net.states = [
            (net.states[i]  # recurrent stream state: local, not averaged
             if isinstance(net.conf.layers[i], STATEFUL_RNN_CONFS)
             else mean_trees([r[1][i] for r in ordered]))
            for i in range(len(net.states))
        ]
        if self.save_updater:
            net.updater_state = mean_trees([r[2] for r in ordered])
        else:
            net.updater_state = ordered[0][2]
        losses = np.stack([np.asarray(r[3], np.float32) for r in ordered])
        return float(np.mean(np.mean(losses, axis=1)))
