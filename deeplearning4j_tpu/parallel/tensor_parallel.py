"""Tensor (model) parallelism: Megatron-style sharded dense/attention.

The reference has NO tensor parallelism (SURVEY.md section 2.7: "Absent in
reference ... tensor parallelism, pipeline parallelism"; model scale in 2016
did not require it). This framework treats model parallelism as first-class:
weight matrices too large for one chip's HBM are sharded over the mesh's
'model' axis and the forward/backward run as SPMD programs with exactly one
collective per block boundary.

The layout is the classic column-then-row pairing:

  column-parallel dense:  W [F, H] sharded on H  -> each device computes its
                          slice of the output; NO collective (output stays
                          feature-sharded).
  row-parallel dense:     W [H, F] sharded on H with the input feature-
                          sharded -> partial products are summed with ONE
                          psum over ICI; output is replicated again.

A transformer block needs exactly two psums (one after attention's output
projection, one after the MLP's second matmul) — the same schedule XLA's
GSPMD derives for Megatron shardings, written here explicitly with
`shard_map` so tests can assert the collective structure and the dryrun can
validate it on a virtual mesh.

Gradients: `shard_map` is differentiable; the transpose of psum is identity
(cotangent already replicated) and the transpose of the implicit slice is a
psum, so `jax.grad` through these functions yields mathematically-correct
full gradients with the matching reverse collectives.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.parallel.mesh import shard_map

from deeplearning4j_tpu.parallel.mesh import MODEL_AXIS

Params = Dict[str, jax.Array]


# ---------------------------------------------------------------------------
# Param init + sharding placement
# ---------------------------------------------------------------------------


def init_tp_block_params(key, d_model: int, d_ff: int, num_heads: int,
                         dtype=jnp.float32) -> Params:
    """Standard transformer block params, laid out for column/row sharding.

    Shapes are GLOBAL; `shard_tp_params` places them on the mesh. Weight
    init delegates to the framework's WeightInit.XAVIER
    (nn/weights.init_weights — reference WeightInitUtil.java:93-123)."""
    from deeplearning4j_tpu.nn.weights import init_weights

    ks = jax.random.split(key, 6)

    def xavier(k, shape):
        return init_weights(k, shape, "xavier", shape[0], shape[-1],
                            None).astype(dtype)

    return {
        "ln1_g": jnp.ones((d_model,), dtype),
        "ln1_b": jnp.zeros((d_model,), dtype),
        "Wq": xavier(ks[0], (d_model, d_model)),
        "Wk": xavier(ks[1], (d_model, d_model)),
        "Wv": xavier(ks[2], (d_model, d_model)),
        "Wo": xavier(ks[3], (d_model, d_model)),
        "ln2_g": jnp.ones((d_model,), dtype),
        "ln2_b": jnp.zeros((d_model,), dtype),
        "W1": xavier(ks[4], (d_model, d_ff)),
        "b1": jnp.zeros((d_ff,), dtype),
        "W2": xavier(ks[5], (d_ff, d_model)),
        "b2": jnp.zeros((d_model,), dtype),
    }


# PartitionSpecs per param name: column-parallel weights shard their OUTPUT
# dim, row-parallel weights their INPUT dim; layernorm + output-side biases
# are replicated.
TP_BLOCK_SPECS: Dict[str, P] = {
    "ln1_g": P(), "ln1_b": P(),
    "Wq": P(None, MODEL_AXIS), "Wk": P(None, MODEL_AXIS),
    "Wv": P(None, MODEL_AXIS), "Wo": P(MODEL_AXIS, None),
    "ln2_g": P(), "ln2_b": P(),
    "W1": P(None, MODEL_AXIS), "b1": P(MODEL_AXIS),
    "W2": P(MODEL_AXIS, None), "b2": P(),
}


def shard_tp_params(params: Params, mesh: Mesh) -> Params:
    """Place block params on the mesh with Megatron shardings (device_put
    with NamedSharding — each chip holds 1/p of every sharded matrix)."""
    return {
        k: jax.device_put(v, NamedSharding(mesh, TP_BLOCK_SPECS[k]))
        for k, v in params.items()
    }


# ---------------------------------------------------------------------------
# Per-device bodies (run inside shard_map over the 'model' axis)
# ---------------------------------------------------------------------------


def _layer_norm(x, g, b, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * g + b


def _tp_block_body(p: Params, x, *, num_heads_local: int, causal: bool,
                   axis: str):
    """One transformer block on one device. x: [N, T, F] REPLICATED;
    sharded params arrive as local shards ([F, H/p] etc.)."""
    h = _layer_norm(x, p["ln1_g"], p["ln1_b"])
    n, t, _ = h.shape
    # column-parallel QKV: local heads only, no collective
    q = (h @ p["Wq"]).reshape(n, t, num_heads_local, -1)
    k = (h @ p["Wk"]).reshape(n, t, num_heads_local, -1)
    v = (h @ p["Wv"]).reshape(n, t, num_heads_local, -1)
    d = q.shape[-1]
    s = jnp.einsum("nqhd,nkhd->nhqk", q, k) / jnp.sqrt(jnp.asarray(d, q.dtype))
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    att = jnp.einsum("nhqk,nkhd->nqhd", jax.nn.softmax(s, axis=-1), v)
    att = att.reshape(n, t, -1)
    # row-parallel output projection: psum #1 restores replication
    x = x + lax.psum(att @ p["Wo"], axis)
    h = _layer_norm(x, p["ln2_g"], p["ln2_b"])
    # column-parallel W1 (+ sharded bias), row-parallel W2: psum #2
    inner = jax.nn.gelu(h @ p["W1"] + p["b1"])
    x = x + lax.psum(inner @ p["W2"], axis) + p["b2"]
    return x


def tp_block_apply(params: Params, x, mesh: Mesh, *, num_heads: int,
                   causal: bool = True, axis: str = MODEL_AXIS):
    """Apply one tensor-parallel transformer block.

    x: [N, T, F] replicated; params sharded per TP_BLOCK_SPECS (global
    shapes — shard_map hands each device its shard). Output replicated."""
    p_size = mesh.shape[axis]
    if num_heads % p_size != 0:
        raise ValueError(f"num_heads {num_heads} not divisible by "
                         f"model-axis size {p_size}")
    in_specs = ({k: TP_BLOCK_SPECS[k] for k in params}, P())
    fn = shard_map(
        partial(_tp_block_body, num_heads_local=num_heads // p_size,
                causal=causal, axis=axis),
        mesh=mesh, in_specs=in_specs, out_specs=P(),
        check_vma=False,
    )
    return fn(params, x)


def tp_block_reference(params: Params, x, *, num_heads: int,
                       causal: bool = True):
    """Single-device reference math for equivalence tests: identical block
    with unsharded params (the TP result must match this exactly up to
    reduction-order float noise)."""
    h = _layer_norm(x, params["ln1_g"], params["ln1_b"])
    n, t, f = h.shape
    q = (h @ params["Wq"]).reshape(n, t, num_heads, -1)
    k = (h @ params["Wk"]).reshape(n, t, num_heads, -1)
    v = (h @ params["Wv"]).reshape(n, t, num_heads, -1)
    d = q.shape[-1]
    s = jnp.einsum("nqhd,nkhd->nhqk", q, k) / jnp.sqrt(jnp.asarray(d, q.dtype))
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    att = jnp.einsum("nhqk,nkhd->nqhd", jax.nn.softmax(s, axis=-1), v)
    x = x + att.reshape(n, t, f) @ params["Wo"]
    h = _layer_norm(x, params["ln2_g"], params["ln2_b"])
    inner = jax.nn.gelu(h @ params["W1"] + params["b1"])
    return x + inner @ params["W2"] + params["b2"]


# ---------------------------------------------------------------------------
# Standalone column/row-parallel dense (building blocks for other models)
# ---------------------------------------------------------------------------


def column_parallel_dense(W, b, x, mesh: Mesh, *, axis: str = MODEL_AXIS,
                          gather: bool = True):
    """y = x @ W + b with W [F, H] sharded on H. gather=True all_gathers the
    output back to full H (use gather=False to feed a row-parallel dense)."""
    def body(Wl, bl, xl):
        y = xl @ Wl + bl
        if gather:
            y = lax.all_gather(y, axis, axis=y.ndim - 1, tiled=True)
        return y

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(None, axis), P(axis), P()),
        out_specs=P() if gather else P(*(None,) * (x.ndim - 1), axis),
        check_vma=False,
    )(W, b, x)


def local_head_columns(W, *, num_heads: int, head_dim: int,
                       n_devices: int, axis: str = MODEL_AXIS):
    """This device's head-columns of a REPLICATED projection W [F, H*hd]
    — the column-parallel partition of :data:`TP_BLOCK_SPECS` in its
    BYTEWISE form, for use inside a shard_map body (serving/mesh.py's
    decode tick).

    Column-parallel QKV is exact, not approximate: every output column
    of ``x @ W`` is an independent dot product, so
    ``(x @ W)[:, cols] == x @ W[:, cols]`` element-for-element — no
    float reduction is split or reordered. Slicing the replicated W at
    trace time by ``lax.axis_index`` keeps one params copy per device
    (no resharded second tree) while the compute still runs only the
    local ``num_heads / n_devices`` heads' columns. The serving tick
    needs this form (rather than `shard_tp_params` + row-parallel Wo)
    because its acceptance bar is BYTE-identity with the single-device
    program: a Megatron psum after Wo would reorder the output
    contraction's float sum."""
    cols = (num_heads // n_devices) * head_dim
    idx = lax.axis_index(axis)
    return lax.dynamic_slice_in_dim(W, idx * cols, cols, axis=1)


def row_parallel_dense(W, b, x_sharded, mesh: Mesh, *, axis: str = MODEL_AXIS):
    """y = x @ W + b with W [H, F] sharded on H and x [..., H] sharded on its
    last dim; ONE psum replicates the output."""
    def body(Wl, bl, xl):
        return lax.psum(xl @ Wl, axis) + bl

    return shard_map(
        body, mesh=mesh,
        in_specs=(P(axis, None), P(), P(*(None,) * (x_sharded.ndim - 1), axis)),
        out_specs=P(),
        check_vma=False,
    )(W, b, x_sharded)
