"""Distributed-training instrumentation.

Capability mirror of the reference Spark stats stack (SURVEY.md section 2.3
"stats + time"): timestamped per-phase EventStats collected worker-side
(dl4j-spark/.../spark/stats/{BaseEventStats,ExampleCountEventStats}.java +
api/stats/StatsCalculationHelper.java), aggregated into
ParameterAveragingTrainingMasterStats, exportable as an HTML timeline
(StatsUtils.exportStatsAsHtml — spark/stats/StatsUtils.java:65), with a
pluggable TimeSource (spark/time/{TimeSource,NTPTimeSource,
SystemClockTimeSource}.java — NTP is used in the reference to align clocks
ACROSS JVMs; in a single-controller TPU pod the host clock is already the
common reference, so SystemClockTimeSource is the default and the NTP
variant is a no-network stub hook).
"""

from __future__ import annotations

import html
import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class TimeSource:
    """spark/time/TimeSource.java: currentTimeMillis()."""

    def current_time_millis(self) -> int:
        return int(time.time() * 1000)


class SystemClockTimeSource(TimeSource):
    pass


class NTPTimeSource(TimeSource):
    """Reference NTPTimeSource queries 0.pool.ntp.org for a cross-node clock
    offset. This environment has no network egress; the offset hook is kept
    so a deployment can inject one (e.g. from chrony) without touching
    callers."""

    def __init__(self, offset_millis: int = 0):
        self.offset_millis = offset_millis

    def current_time_millis(self) -> int:
        return int(time.time() * 1000) + self.offset_millis


@dataclass
class EventStats:
    """BaseEventStats: machine/worker ids + start time + duration."""

    event_type: str
    start_time_ms: int
    duration_ms: float
    worker_id: str = "worker-0"
    example_count: int = 0


@dataclass
class TrainingStats:
    """ParameterAveragingTrainingMasterStats-equivalent collection."""

    events: List[EventStats] = field(default_factory=list)
    time_source: TimeSource = field(default_factory=SystemClockTimeSource)

    def record(self, event_type: str, start_ms: int, duration_ms: float,
               worker_id: str = "worker-0", example_count: int = 0) -> None:
        self.events.append(
            EventStats(event_type, start_ms, duration_ms, worker_id, example_count)
        )

    class _Timer:
        def __init__(self, stats: "TrainingStats", event_type: str,
                     worker_id: str, example_count: int):
            self.stats = stats
            self.event_type = event_type
            self.worker_id = worker_id
            self.example_count = example_count

        def __enter__(self):
            self.t0 = self.stats.time_source.current_time_millis()
            self.p0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            dur = (time.perf_counter() - self.p0) * 1000.0
            self.stats.record(self.event_type, self.t0, dur,
                              self.worker_id, self.example_count)
            return False

    def timed(self, event_type: str, worker_id: str = "worker-0",
              example_count: int = 0) -> "TrainingStats._Timer":
        return TrainingStats._Timer(self, event_type, worker_id, example_count)

    # -- aggregation ------------------------------------------------------
    def durations(self, event_type: str) -> List[float]:
        return [e.duration_ms for e in self.events if e.event_type == event_type]

    def summary(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for e in self.events:
            s = out.setdefault(
                e.event_type, {"count": 0, "total_ms": 0.0, "max_ms": 0.0}
            )
            s["count"] += 1
            s["total_ms"] += e.duration_ms
            s["max_ms"] = max(s["max_ms"], e.duration_ms)
        for s in out.values():
            s["mean_ms"] = s["total_ms"] / max(1, s["count"])
        return out

    # -- export (StatsUtils.exportStatsAsHtml) -----------------------------
    def export_html(self, path: str, title: str = "Training stats") -> None:
        """Self-contained HTML timeline + summary table."""
        if self.events:
            t0 = min(e.start_time_ms for e in self.events)
        else:
            t0 = 0
        rows = []
        lanes = sorted({e.worker_id for e in self.events})
        colors = ["#4c78a8", "#f58518", "#54a24b", "#e45756", "#72b7b2",
                  "#b279a2", "#ff9da6", "#9d755d"]
        types = sorted({e.event_type for e in self.events})
        color_of = {t: colors[i % len(colors)] for i, t in enumerate(types)}
        total_span = max(
            (e.start_time_ms - t0 + e.duration_ms for e in self.events),
            default=1.0,
        )
        total_span = max(total_span, 1e-6)  # all-zero-duration guard
        for e in self.events:
            left = 100.0 * (e.start_time_ms - t0) / total_span
            width = max(0.2, 100.0 * e.duration_ms / total_span)
            lane = lanes.index(e.worker_id)
            rows.append(
                f'<div class="ev" style="left:{left:.2f}%;width:{width:.2f}%;'
                f"top:{lane * 28}px;background:{color_of[e.event_type]}\" "
                f'title="{html.escape(e.event_type)} {e.duration_ms:.1f}ms '
                f'({html.escape(e.worker_id)})"></div>'
            )
        summary_rows = "".join(
            f"<tr><td>{html.escape(k)}</td><td>{v['count']:.0f}</td>"
            f"<td>{v['mean_ms']:.2f}</td><td>{v['max_ms']:.2f}</td>"
            f"<td>{v['total_ms']:.2f}</td></tr>"
            for k, v in sorted(self.summary().items())
        )
        legend = "".join(
            f'<span class="lg"><span class="sw" style="background:'
            f'{color_of[t]}"></span>{html.escape(t)}</span>'
            for t in types
        )
        doc = f"""<!doctype html><html><head><meta charset="utf-8">
<title>{html.escape(title)}</title><style>
body{{font-family:sans-serif;margin:2em}}
.timeline{{position:relative;height:{max(1, len(lanes)) * 28 + 10}px;
border:1px solid #ccc;background:#fafafa}}
.ev{{position:absolute;height:22px;border-radius:3px;opacity:.85}}
table{{border-collapse:collapse;margin-top:1.5em}}
td,th{{border:1px solid #ccc;padding:4px 10px;text-align:right}}
th{{background:#eee}}.lg{{margin-right:1em}}
.sw{{display:inline-block;width:12px;height:12px;margin-right:4px;
border-radius:2px;vertical-align:middle}}</style></head><body>
<h2>{html.escape(title)}</h2><div>{legend}</div>
<div class="timeline">{''.join(rows)}</div>
<table><tr><th>event</th><th>count</th><th>mean ms</th><th>max ms</th>
<th>total ms</th></tr>{summary_rows}</table>
</body></html>"""
        with open(path, "w", encoding="utf-8") as f:
            f.write(doc)

    def export_json(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(
                [e.__dict__ for e in self.events], f, indent=1, sort_keys=True
            )
