"""Device-mesh helpers.

The TPU equivalent of the reference's cluster topology plumbing (Spark
master/executor layout; Akka ActorSystem + ZooKeeper discovery): a
``jax.sharding.Mesh`` over the chips, with named axes that parallel
strategies refer to (data / model / pipeline / sequence / expert).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPELINE_AXIS = "pipe"
SEQUENCE_AXIS = "seq"
EXPERT_AXIS = "expert"

# --------------------------------------------------------------------------
# shard_map compatibility: newer jax exports it at top level with a
# `check_vma` flag; this environment's jax (0.4.x) has it under
# jax.experimental with the older `check_rep` spelling. Every parallel
# module imports THIS symbol so the whole stack tracks one shim.
# --------------------------------------------------------------------------
try:
    from jax import shard_map as _jax_shard_map  # jax >= 0.6

    _CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover - exercised on the 0.4.x image
    from jax.experimental.shard_map import shard_map as _jax_shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """jax.shard_map with the replication-check flag translated to whatever
    this jax version calls it (check_vma in new jax, check_rep before)."""
    if check_vma is not None:
        kwargs[_CHECK_KW] = check_vma
    return _jax_shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)


def virtual_cpu_devices(n: int = 8) -> None:
    """Force a virtual n-device CPU platform BEFORE first backend use —
    the standalone-script version of the tests/conftest.py discipline
    (Spark local[N] role, BaseSparkTest.java:90).

    jax >= 0.5 spells it ``jax_num_cpu_devices``; this environment's
    0.4.x only honors the XLA_FLAGS host-platform flag, which the CPU
    client reads at backend creation — so it must land in the env before
    the first device query. Any inherited count flag is REPLACED (a
    leftover =2 from a multihost worker env would otherwise silently win
    and break every 8-device mesh). The `-m examples` smoke tier exists
    precisely because examples carried a bare ``jax_num_cpu_devices``
    update that this image's jax rejects at line one."""
    import os

    jax.config.update("jax_platforms", "cpu")
    # strip any inherited count flag FIRST, on both branches: even where
    # jax_num_cpu_devices exists, a leftover XLA_FLAGS count could still
    # win at CPU-client creation (conftest applies the same discipline)
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        flags.append(f"--xla_force_host_platform_device_count={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)


def device_mesh(
    num_devices: Optional[int] = None,
    shape: Optional[Sequence[int]] = None,
    axis_names: Tuple[str, ...] = (DATA_AXIS,),
    devices=None,
) -> Mesh:
    """Build a Mesh. Default: 1-D data axis over all (or first n) devices."""
    devs = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devs)} available"
            )
        devs = devs[:num_devices]
    if shape is None:
        shape = (len(devs),)
    arr = np.asarray(devs).reshape(tuple(shape))
    return Mesh(arr, axis_names)


def data_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Batch-axis sharding: [B, ...] split over the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
