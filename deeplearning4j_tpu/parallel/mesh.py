"""Device-mesh helpers.

The TPU equivalent of the reference's cluster topology plumbing (Spark
master/executor layout; Akka ActorSystem + ZooKeeper discovery): a
``jax.sharding.Mesh`` over the chips, with named axes that parallel
strategies refer to (data / model / pipeline / sequence / expert).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPELINE_AXIS = "pipe"
SEQUENCE_AXIS = "seq"
EXPERT_AXIS = "expert"


def device_mesh(
    num_devices: Optional[int] = None,
    shape: Optional[Sequence[int]] = None,
    axis_names: Tuple[str, ...] = (DATA_AXIS,),
    devices=None,
) -> Mesh:
    """Build a Mesh. Default: 1-D data axis over all (or first n) devices."""
    devs = list(devices if devices is not None else jax.devices())
    if num_devices is not None:
        if num_devices > len(devs):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devs)} available"
            )
        devs = devs[:num_devices]
    if shape is None:
        shape = (len(devs),)
    arr = np.asarray(devs).reshape(tuple(shape))
    return Mesh(arr, axis_names)


def data_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Batch-axis sharding: [B, ...] split over the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
