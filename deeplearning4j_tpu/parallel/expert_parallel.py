"""Expert parallelism: mixture-of-experts FFN sharded over an 'expert' axis.

The reference has NO MoE / expert parallelism (SURVEY.md section 2.7 —
absent; 2016). Here it is first-class: E expert MLPs live sharded over the
mesh's 'expert' axis (each chip holds E/p experts), tokens are routed by a
learned top-k gate, and the dispatch/combine are exact einsum contractions
with ONE psum over ICI on the combine — the GShard/Switch formulation, which
keeps every shape static (capacity-bounded) so the whole layer jits into a
fixed SPMD program.

Routing math (capacity C per expert per device-batch):
  gate logits [T, E] -> softmax -> top-k (values renormalized to sum 1);
  slot-j one-hots are assigned positions by a running per-expert cumsum
  (earlier slots get priority, matching GShard); tokens past capacity are
  DROPPED (their combine weight is zero — the residual connection carries
  them, standard MoE semantics).
  dispatch [T, E, C] one-hot  : token t -> (expert e, slot c)
  combine  [T, E, C] weights  : gate mass for the same assignment
  expert inputs  = einsum('tec,tf->ecf', dispatch, x)   (sharded on e)
  expert outputs = per-expert MLP on [C, F]
  y              = psum_e einsum('tec,ecf->tf', combine, out)

Differentiable end-to-end (top_k indices are constant under grad; gate
values flow through combine), so `jax.grad` gives exact MoE gradients with
the reverse all-reduce inserted automatically.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from deeplearning4j_tpu.parallel.mesh import shard_map

from deeplearning4j_tpu.parallel.mesh import EXPERT_AXIS

Params = Dict[str, jax.Array]


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int,
                    dtype=jnp.float32) -> Params:
    """Gate + E expert MLPs (leading expert dim on expert leaves)."""
    kg, k1, k2 = jax.random.split(key, 3)

    def xavier(k, shape, fan_in, fan_out):
        return (jax.random.normal(k, shape)
                * jnp.sqrt(2.0 / (fan_in + fan_out))).astype(dtype)

    return {
        "Wg": xavier(kg, (d_model, n_experts), d_model, n_experts),
        "W1": xavier(k1, (n_experts, d_model, d_ff), d_model, d_ff),
        "b1": jnp.zeros((n_experts, d_ff), dtype),
        "W2": xavier(k2, (n_experts, d_ff, d_model), d_ff, d_model),
        "b2": jnp.zeros((n_experts, d_model), dtype),
    }


MOE_SPECS: Dict[str, P] = {
    "Wg": P(),
    "W1": P(EXPERT_AXIS, None, None),
    "b1": P(EXPERT_AXIS, None),
    "W2": P(EXPERT_AXIS, None, None),
    "b2": P(EXPERT_AXIS, None),
}


def shard_moe_params(params: Params, mesh: Mesh) -> Params:
    return {
        k: jax.device_put(v, NamedSharding(mesh, MOE_SPECS[k]))
        for k, v in params.items()
    }


def _routing(gates: jax.Array, top_k: int, capacity: int
             ) -> Tuple[jax.Array, jax.Array]:
    """gates [T, E] -> (dispatch [T, E, C] 0/1, combine [T, E, C])."""
    t, e = gates.shape
    topv, topi = lax.top_k(gates, top_k)          # [T, k]
    topv = topv / jnp.maximum(topv.sum(-1, keepdims=True), 1e-9)
    dispatch = jnp.zeros((t, e, capacity), gates.dtype)
    combine = jnp.zeros((t, e, capacity), gates.dtype)
    prior = jnp.zeros((e,), jnp.int32)            # slots used per expert
    for j in range(top_k):                        # static small loop
        onehot = jax.nn.one_hot(topi[:, j], e, dtype=jnp.int32)   # [T, E]
        pos = jnp.cumsum(onehot, axis=0) - 1 + prior[None, :]      # [T, E]
        prior = prior + onehot.sum(0)
        in_cap = (pos < capacity) & (onehot > 0)
        pos_oh = jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                                dtype=gates.dtype)                 # [T,E,C]
        slot = jnp.where(in_cap[..., None], pos_oh, 0.0)
        dispatch = dispatch + slot
        combine = combine + topv[:, j, None, None] * slot
    return dispatch, combine


def expert_mlp(W1, b1, W2, b2, dispatch, combine, x):
    """The GShard dispatch -> per-expert MLP -> combine einsum chain on
    [T(, E, C)] tensors (shared by the shard_map body, the serial
    reference, and the transformer flagship's inline MoE blocks)."""
    ex_in = jnp.einsum("tec,tf->ecf", dispatch, x)          # [E, C, F]
    h = jax.nn.gelu(jnp.einsum("ecf,efh->ech", ex_in, W1) + b1[:, None, :])
    out = jnp.einsum("ech,ehf->ecf", h, W2) + b2[:, None, :]
    return jnp.einsum("tec,ecf->tf", combine, out)


def aux_loss_from_gates(gates: jax.Array) -> jax.Array:
    """Switch-style load-balance loss from softmax gates [T, E]:
    E * sum_e f_e * P_e (f_e = argmax-count fraction, P_e = mean prob)."""
    e = gates.shape[-1]
    hard = jax.nn.one_hot(jnp.argmax(gates, -1), e, dtype=gates.dtype)
    return e * jnp.sum(hard.mean(0) * gates.mean(0))


def _moe_body(p: Params, dispatch, combine, x, *, axis: str):
    """Per-device body: local experts only. dispatch/combine arrive sliced
    on the expert dim ([T, E/p, C]); x replicated [T, F]."""
    y = expert_mlp(p["W1"], p["b1"], p["W2"], p["b2"], dispatch, combine, x)
    return lax.psum(y, axis)


def moe_apply(params: Params, x: jax.Array, mesh: Mesh, *, top_k: int = 2,
              capacity_factor: float = 1.25,
              axis: str = EXPERT_AXIS) -> jax.Array:
    """Apply the expert-parallel MoE FFN. x: [N, T, F] (or [T, F])
    replicated; returns same shape, replicated. Gate runs replicated (it is
    tiny); expert compute is sharded over the expert axis."""
    orig_shape = x.shape
    xt = x.reshape(-1, orig_shape[-1])
    n_tokens = xt.shape[0]
    n_experts = params["Wg"].shape[1]
    p_size = mesh.shape[axis]
    if n_experts % p_size != 0:
        raise ValueError(f"{n_experts} experts not divisible by "
                         f"expert-axis size {p_size}")
    capacity = max(1, int(capacity_factor * n_tokens * top_k / n_experts))
    gates = jax.nn.softmax(xt @ params["Wg"], axis=-1)
    dispatch, combine = _routing(gates, top_k, capacity)
    body_params = {k: v for k, v in params.items() if k != "Wg"}
    fn = shard_map(
        partial(_moe_body, axis=axis),
        mesh=mesh,
        in_specs=({k: MOE_SPECS[k] for k in body_params},
                  P(None, axis, None), P(None, axis, None), P()),
        out_specs=P(),
        check_vma=False,
    )
    y = fn(body_params, dispatch, combine, xt)
    return y.reshape(orig_shape)


def moe_reference(params: Params, x: jax.Array, *, top_k: int = 2,
                  capacity_factor: float = 1.25) -> jax.Array:
    """Single-device reference with identical routing (equivalence oracle)."""
    orig_shape = x.shape
    xt = x.reshape(-1, orig_shape[-1])
    n_tokens = xt.shape[0]
    n_experts = params["Wg"].shape[1]
    capacity = max(1, int(capacity_factor * n_tokens * top_k / n_experts))
    gates = jax.nn.softmax(xt @ params["Wg"], axis=-1)
    dispatch, combine = _routing(gates, top_k, capacity)
    y = expert_mlp(params["W1"], params["b1"], params["W2"], params["b2"],
                   dispatch, combine, xt)
    return y.reshape(orig_shape)


def load_balancing_loss(x: jax.Array, Wg: jax.Array) -> jax.Array:
    """Auxiliary load-balance loss over raw activations (see
    aux_loss_from_gates). Add to the task loss with a small coefficient."""
    xt = x.reshape(-1, x.shape[-1])
    return aux_loss_from_gates(jax.nn.softmax(xt @ Wg, axis=-1))
