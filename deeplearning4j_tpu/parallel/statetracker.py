"""Job distribution + state tracking (legacy scaleout stack parity).

Capability mirror of the reference's second distributed backend (SURVEY.md
sections 2.4, 5): the Akka/Hazelcast/ZooKeeper plane —
  - StateTracker (deeplearning4j-scaleout-api/.../statetracker/
    StateTracker.java: job queue, parameter storage, worker heartbeats,
    job reclaim on dead workers);
  - work routers (deeplearning4j-scaleout-akka/.../workrouter/: HogWild —
    async lock-free dispatch — vs IterativeReduce — barrier rounds with
    aggregation);
  - service discovery (zookeeper ZooKeeperConfigurationRegister/Retriever —
    registering the master address + conf for workers to find).

TPU-native reading: in a single-controller TPU pod these roles collapse
into process-local coordination (the controller IS the master), so the
implementation is an in-process, thread-safe tracker with REAL heartbeat
expiry + job-reclaim semantics (the failure-detection behavior the
reference gets from Hazelcast), and a file-based registry standing in for
znodes. Multi-controller deployments point the registry at a shared
filesystem and the semantics carry over.

SCOPE NOTE (revised round 4): the tracker's core is in-process, and the
tensor data plane stays XLA collectives over ICI
(parallel/{data,tensor,…}_parallel.py) with jax.distributed as multi-host
control (parallel/multihost.py) — a host-side distributed KV store would
duplicate what the runtime provides. But the reference's
BaseHazelCastStateTracker.java:49 plane is genuinely CROSS-PROCESS
(Hazelcast members over TCP), so the control protocol is too:
StateTrackerServer hosts a tracker on a TCP port and RemoteStateTracker
drives the job-queue/heartbeat/reclaim protocol from other OS processes
(exercised by a real multi-subprocess kill-and-reclaim test).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


@dataclass
class Job:
    """Reference scaleout/api/Job.java: work id + payload (+ worker)."""

    job_id: str
    payload: Any
    worker_id: Optional[str] = None
    attempts: int = 0
    done: bool = False
    result: Any = None


class StateTracker:
    """In-process job queue + heartbeats + reclaim + fleet membership
    (BaseHazelCastStateTracker.java:49 capability surface, promoted to the
    elastic fleet's membership authority — ISSUE 6: `register_worker` /
    `deregister_worker` / `live_workers` and a `membership_epoch` that
    bumps on every join, announced departure, and heartbeat-expiry death,
    so averaging rounds can re-form over the survivor set).

    Delivery guarantees for the fleet's no-drop/no-double-count contract:
      * a reclaimed job is RE-QUEUED, never lost (no batch dropped);
      * `complete_job` is FENCED by the assignment's attempt number — a
        zombie executor (stalled heartbeat, job reclaimed and re-assigned
        underneath it) gets its late completion rejected, so a split is
        counted exactly once (`stale_completions` audits the rejections);
      * a job failing `max_attempts` times routes to a dead-letter list
        (`poisoned_jobs`) instead of cycling forever.
    """

    def __init__(self, heartbeat_timeout: float = 5.0,
                 max_attempts: Optional[int] = None):
        self.heartbeat_timeout = heartbeat_timeout
        self.max_attempts = max_attempts
        self._lock = threading.Lock()
        self._pending: List[Job] = []
        self._assigned: Dict[str, Job] = {}  # job_id -> job
        self._done: Dict[str, Job] = {}
        self._poisoned: Dict[str, Job] = {}  # dead-letter list
        self._heartbeats: Dict[str, float] = {}
        self._registered: List[str] = []  # fleet members, join order
        self._epoch = 0
        self._params: Dict[str, Any] = {}  # replicated-map role
        self.stale_completions = 0  # fenced-out zombie completions

    # -- job lifecycle ----------------------------------------------------
    def add_job(self, job: Job) -> None:
        with self._lock:
            self._pending.append(job)

    def request_job(self, worker_id: str) -> Optional[Job]:
        """Worker asks for work (GiveMeMyJob protocol message). Returns a
        SNAPSHOT of the job, not the tracked object: the delivered
        attempt number must stay frozen in the worker's hands (the fence
        token), exactly as it does over the wire transport — a shared
        mutable Job would let a later re-assignment retroactively update
        a zombie's attempt and slip past the completion fence."""
        import copy

        with self._lock:
            self._heartbeats[worker_id] = time.monotonic()
            if not self._pending:
                return None
            job = self._pending.pop(0)
            job.worker_id = worker_id
            job.attempts += 1
            self._assigned[job.job_id] = job
            return copy.copy(job)

    def complete_job(self, job_id: str, result: Any = None,
                     attempt: Optional[int] = None) -> bool:
        """Record a finished job. When `attempt` is given, the completion
        is FENCED: it is accepted only while the job is still assigned at
        that attempt number. A worker whose job was reclaimed (stalled
        heartbeat) and re-assigned holds a stale attempt — its late
        completion is rejected so the split cannot be double-counted.
        Returns whether the completion was accepted."""
        with self._lock:
            job = self._assigned.get(job_id)
            if job is None or (attempt is not None
                               and job.attempts != attempt):
                if attempt is not None:
                    # audit FENCED rejections only: an unfenced legacy
                    # duplicate-complete is not a zombie event and must
                    # not pollute the double-count telemetry
                    self.stale_completions += 1
                return False
            del self._assigned[job_id]
            job.done = True
            job.result = result
            self._done[job_id] = job
            return True

    def fail_job(self, job_id: str, attempt: Optional[int] = None) -> bool:
        """JobFailed message: back to the queue — unless the job has
        already burned `max_attempts` deliveries, in which case it routes
        to the dead-letter list (a poison job must not cycle forever).
        FENCED like complete_job when `attempt` is given: a zombie whose
        job was reclaimed and re-assigned must not yank the survivor's
        live assignment back to pending (a third execution that burns
        attempts toward the poison cap). Returns True when re-queued,
        False when fenced/poisoned/unknown."""
        with self._lock:
            job = self._assigned.get(job_id)
            if job is None or (attempt is not None
                               and job.attempts != attempt):
                return False
            del self._assigned[job_id]
            return self._requeue_or_poison_locked(job)

    def _requeue_or_poison_locked(self, job: Job) -> bool:
        """Shared by every re-queue path (JobFailed, heartbeat-expiry
        reclaim, announced departure): a job that already burned
        `max_attempts` deliveries routes to the dead-letter list — a
        split whose executor keeps DYING (not just raising) must hit the
        same cap as one that keeps failing, or it cycles until the round
        timeout instead of surfacing in poisoned_jobs()."""
        if (self.max_attempts is not None
                and job.attempts >= self.max_attempts):
            self._poisoned[job.job_id] = job
            return False
        job.worker_id = None
        self._pending.append(job)
        return True

    def poisoned_jobs(self) -> Dict[str, int]:
        """Dead-letter list: job_id -> attempts burned before giving up."""
        with self._lock:
            return {k: j.attempts for k, j in self._poisoned.items()}

    def stale_completion_count(self) -> int:
        """Fenced-out zombie completions (RPC-safe accessor: the fleet's
        telemetry must see the counter through the wire transport too)."""
        with self._lock:
            return self.stale_completions

    # -- fleet membership --------------------------------------------------
    def register_worker(self, worker_id: str) -> int:
        """Worker joins the fleet; returns the new membership epoch."""
        with self._lock:
            self._heartbeats[worker_id] = time.monotonic()
            if worker_id not in self._registered:
                self._registered.append(worker_id)
                self._epoch += 1
            epoch = self._epoch
        from deeplearning4j_tpu.obs import journal as obs_journal

        obs_journal.event("fleet.worker", action="register",
                          worker=worker_id, epoch=epoch)
        return epoch

    def deregister_worker(self, worker_id: str) -> int:
        """Announced departure (the SIGTERM'd worker's goodbye): drop the
        member, RE-QUEUE its in-flight jobs immediately (no heartbeat
        expiry to wait out), bump the epoch."""
        with self._lock:
            self._heartbeats.pop(worker_id, None)
            if worker_id in self._registered:
                self._registered.remove(worker_id)
                self._epoch += 1
            for job_id in list(self._assigned):
                job = self._assigned[job_id]
                if job.worker_id == worker_id:
                    del self._assigned[job_id]
                    self._requeue_or_poison_locked(job)
            epoch = self._epoch
        from deeplearning4j_tpu.obs import journal as obs_journal

        obs_journal.event("fleet.worker", action="deregister",
                          worker=worker_id, epoch=epoch)
        return epoch

    def live_workers(self) -> List[str]:
        """Registered members with a fresh heartbeat, in join order."""
        now = time.monotonic()
        with self._lock:
            return [
                w for w in self._registered
                if now - self._heartbeats.get(w, 0.0)
                <= self.heartbeat_timeout
            ]

    def membership(self) -> Dict[str, Any]:
        with self._lock:
            return {"epoch": self._epoch, "workers": list(self._registered)}

    @property
    def membership_epoch(self) -> int:
        with self._lock:
            return self._epoch

    # -- heartbeats / failure detection -----------------------------------
    def heartbeat(self, worker_id: str) -> None:
        with self._lock:
            self._heartbeats[worker_id] = time.monotonic()

    def dead_workers(self) -> List[str]:
        now = time.monotonic()
        with self._lock:
            return [
                w for w, t in self._heartbeats.items()
                if now - t > self.heartbeat_timeout
            ]

    def reclaim_dead_jobs(self) -> int:
        """Re-queue jobs assigned to workers that stopped heartbeating
        (the ClearWorker/job-reclaim protocol). Dead workers are also
        DEREGISTERED from the fleet membership (epoch bump), so the next
        averaging round re-forms over the survivor set."""
        dead = set(self.dead_workers())
        reclaimed = 0
        with self._lock:
            for job_id in list(self._assigned):
                job = self._assigned[job_id]
                if job.worker_id in dead:
                    del self._assigned[job_id]
                    self._requeue_or_poison_locked(job)
                    reclaimed += 1
            for w in dead:
                self._heartbeats.pop(w, None)
                if w in self._registered:
                    self._registered.remove(w)
                    self._epoch += 1
        return reclaimed

    # -- shared parameter storage (replicated-map role) --------------------
    def set_params(self, key: str, value: Any) -> None:
        with self._lock:
            self._params[key] = value

    def get_params(self, key: str) -> Any:
        with self._lock:
            return self._params.get(key)

    # -- introspection ----------------------------------------------------
    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {
                "pending": len(self._pending),
                "assigned": len(self._assigned),
                "done": len(self._done),
            }

    def results(self) -> Dict[str, Any]:
        with self._lock:
            return {k: j.result for k, j in self._done.items()}

    def drain_results(self) -> Dict[str, Any]:
        """Snapshot AND clear completed jobs (per-round aggregation must not
        see previous rounds' results)."""
        with self._lock:
            out = {k: j.result for k, j in self._done.items()}
            self._done.clear()
            return out


# ---------------------------------------------------------------------------
# Work routers
# ---------------------------------------------------------------------------


class HogwildWorkRouter:
    """Async dispatch, no synchronization between workers
    (HogWildWorkRouter.java): every idle worker immediately gets the next
    job; results apply in completion order."""

    def __init__(self, tracker: StateTracker, num_workers: int):
        self.tracker = tracker
        self.num_workers = num_workers

    def run(self, work_fn: Callable[[Any], Any]) -> Dict[str, Any]:
        def worker(wid: str):
            while True:
                job = self.tracker.request_job(wid)
                if job is None:
                    return
                try:
                    result = work_fn(job.payload)
                    self.tracker.complete_job(job.job_id, result)
                except Exception:  # noqa: BLE001 — JobFailed protocol
                    if job.attempts >= 3:
                        # poison job: record as done-with-None while still
                        # assigned, so it can't cycle forever
                        self.tracker.complete_job(job.job_id, None)
                    else:
                        self.tracker.fail_job(job.job_id)

        threads = [
            threading.Thread(target=worker, args=(f"worker-{i}",), daemon=True)
            for i in range(self.num_workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return self.tracker.results()


class IterativeReduceWorkRouter:
    """Barrier rounds with aggregation (IterativeReduceWorkRouter.java):
    all workers finish the round, then `reduce_fn` merges results before
    the next round starts."""

    def __init__(self, tracker: StateTracker, num_workers: int):
        self.tracker = tracker
        self.num_workers = num_workers

    def run_round(self, work_fn: Callable[[Any], Any],
                  reduce_fn: Callable[[List[Any]], Any]) -> Any:
        HogwildWorkRouter(self.tracker, self.num_workers).run(work_fn)
        round_results = self.tracker.drain_results()  # this round only
        results = [r for r in round_results.values() if r is not None]
        merged = reduce_fn(results)
        self.tracker.set_params("merged", merged)
        return merged


# ---------------------------------------------------------------------------
# Cross-process transport (the Hazelcast TCP member plane)
# ---------------------------------------------------------------------------


_RPC_METHODS = frozenset({
    "request_job", "complete_job", "fail_job", "heartbeat", "add_job",
    "dead_workers", "reclaim_dead_jobs", "set_params", "get_params",
    "counts", "results", "drain_results",
    # fleet membership + dead-letter surface (ISSUE 6)
    "register_worker", "deregister_worker", "live_workers", "membership",
    "poisoned_jobs", "stale_completion_count",
})


class StateTrackerServer:
    """TCP host for a StateTracker — the part of
    BaseHazelCastStateTracker.java:49 that is genuinely cross-process: the
    master binds a port (the reference's Hazelcast member on :5701/:2181)
    and workers in OTHER OS processes drive the job-queue/heartbeat/reclaim
    protocol over it. Newline-delimited JSON RPC
    ({"method": m, "args": [...]} -> {"ok": result} | {"err": msg});
    payloads/results must be JSON values — tensors never ride this plane
    (they move over ICI via the parallel/*_parallel.py data plane).

    Publish the address for workers with FileServiceRegistry (the
    zookeeper role), as the reference registers the Hazelcast host
    (ZooKeeperConfigurationRegister)."""

    def __init__(self, tracker: StateTracker, host: str = "127.0.0.1",
                 port: int = 0):
        import socketserver

        self.tracker = tracker
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    try:
                        req = json.loads(line)
                        method = req["method"]
                        if method not in _RPC_METHODS:
                            raise ValueError(f"unknown method {method!r}")
                        args = req.get("args", [])
                        if method == "add_job":
                            outer.tracker.add_job(Job(args[0], args[1]))
                            resp = {"ok": None}
                        elif method == "request_job":
                            job = outer.tracker.request_job(args[0])
                            resp = {"ok": None if job is None else
                                    {"job_id": job.job_id,
                                     "payload": job.payload,
                                     "attempts": job.attempts}}
                        else:
                            resp = {"ok": getattr(outer.tracker, method)(
                                *args)}
                    except Exception as e:  # noqa: BLE001 — protocol error reply
                        resp = {"err": f"{type(e).__name__}: {e}"}
                    try:
                        wire = json.dumps(resp)
                    except TypeError as e:
                        # non-JSON result (e.g. an ndarray set_params by an
                        # in-process router): an error REPLY, not a dead
                        # connection — tensors don't ride this plane
                        wire = json.dumps(
                            {"err": f"result not JSON-serializable: {e}"})
                    self.wfile.write((wire + "\n").encode("utf-8"))
                    self.wfile.flush()

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "StateTrackerServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)


class RemoteStateTracker:
    """Worker-side proxy: same surface as StateTracker, each call one JSON
    RPC round trip to the master's StateTrackerServer (the reference
    worker's Hazelcast client role). One persistent connection per proxy;
    construct per process/thread."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        import socket

        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._rfile = self._sock.makefile("rb")
        self._lock = threading.Lock()
        self._broken = False

    @classmethod
    def from_address(cls, address: str, **kw) -> "RemoteStateTracker":
        host, port = address.rsplit(":", 1)
        return cls(host, int(port), **kw)

    def _call(self, method: str, *args):
        with self._lock:
            if self._broken:
                raise ConnectionError(
                    "state tracker connection is broken (an earlier call "
                    "timed out mid-reply; request/reply pairing is lost — "
                    "reconnect with a new RemoteStateTracker)")
            try:
                self._sock.sendall(
                    (json.dumps({"method": method, "args": list(args)})
                     + "\n").encode("utf-8"))
                line = self._rfile.readline()
            except Exception:
                # a timeout/partial read leaves the late reply queued on the
                # socket: a retry would read the PREVIOUS call's reply and
                # silently desync every later call — poison the connection
                self._broken = True
                self._sock.close()
                raise
        if not line:
            raise ConnectionError("state tracker server closed connection")
        resp = json.loads(line)
        if "err" in resp:
            raise RuntimeError(f"remote state tracker: {resp['err']}")
        return resp["ok"]

    def close(self) -> None:
        self._sock.close()

    # -- StateTracker surface over the wire --------------------------------
    def add_job(self, job: Job) -> None:
        self._call("add_job", job.job_id, job.payload)

    def request_job(self, worker_id: str) -> Optional[Job]:
        d = self._call("request_job", worker_id)
        if d is None:
            return None
        return Job(d["job_id"], d["payload"], worker_id=worker_id,
                   attempts=d["attempts"])

    def complete_job(self, job_id: str, result: Any = None,
                     attempt: Optional[int] = None) -> bool:
        return self._call("complete_job", job_id, result, attempt)

    def fail_job(self, job_id: str, attempt: Optional[int] = None) -> bool:
        return self._call("fail_job", job_id, attempt)

    def heartbeat(self, worker_id: str) -> None:
        self._call("heartbeat", worker_id)

    def register_worker(self, worker_id: str) -> int:
        return self._call("register_worker", worker_id)

    def deregister_worker(self, worker_id: str) -> int:
        return self._call("deregister_worker", worker_id)

    def live_workers(self) -> List[str]:
        return self._call("live_workers")

    def membership(self) -> Dict[str, Any]:
        return self._call("membership")

    def poisoned_jobs(self) -> Dict[str, int]:
        return self._call("poisoned_jobs")

    def stale_completion_count(self) -> int:
        return self._call("stale_completion_count")

    def dead_workers(self) -> List[str]:
        return self._call("dead_workers")

    def reclaim_dead_jobs(self) -> int:
        return self._call("reclaim_dead_jobs")

    def set_params(self, key: str, value: Any) -> None:
        self._call("set_params", key, value)

    def get_params(self, key: str) -> Any:
        return self._call("get_params", key)

    def counts(self) -> Dict[str, int]:
        return self._call("counts")

    def results(self) -> Dict[str, Any]:
        return self._call("results")

    def drain_results(self) -> Dict[str, Any]:
        return self._call("drain_results")


# ---------------------------------------------------------------------------
# Service registry (zookeeper role)
# ---------------------------------------------------------------------------


class FileServiceRegistry:
    """Register/retrieve service addresses + configs through a shared
    directory (ZooKeeperConfigurationRegister/Retriever role: the znode is
    a json file; multi-host deployments point this at NFS/GCS-fuse)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.root, f"{name}.json")

    def register(self, name: str, value: Dict[str, Any]) -> None:
        tmp = self._path(name) + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(value, f)
        os.replace(tmp, self._path(name))  # atomic publish

    def retrieve(self, name: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._path(name), "r", encoding="utf-8") as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def unregister(self, name: str) -> None:
        try:
            os.remove(self._path(name))
        except FileNotFoundError:
            pass

    def list_services(self) -> List[str]:
        return sorted(
            os.path.splitext(n)[0]
            for n in os.listdir(self.root)
            if n.endswith(".json")
        )
