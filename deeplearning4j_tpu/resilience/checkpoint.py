"""Async atomic checkpoint manager with retention, digests, and fallback.

The reference persists models through ModelSerializer and the
early-stopping savers (LocalFileModelSaver.java writes bestModel.bin /
latestModel.bin with a bare FileOutputStream — a crash mid-write leaves a
torn file, and nothing ever verifies a checkpoint before trusting it).
This module is the production-grade replacement the ROADMAP's
"handles as many scenarios as you can imagine" bar demands:

  * **Async**: ``save()`` snapshots the training state to HOST numpy
    synchronously (mandatory — under buffer donation the next train step
    CONSUMES the device buffers a lazy writer would still be reading) and
    hands serialization + IO to a background worker, so the train loop
    stalls for the snapshot only, not the zip/fsync.
  * **Atomic**: payload is written into ``ckpt-<step>.tmp/``, fsync'd,
    manifested, then committed with one directory rename — a preemption
    at any instant leaves either the previous checkpoint or the new one,
    never a torn mix (same discipline as utils/sharded_checkpoint.py's
    pointer-file flip).
  * **Verified**: MANIFEST.json records a sha256 per payload file;
    ``latest_intact()`` re-hashes before trusting, logs and SKIPS a
    corrupt checkpoint, and falls back to the newest intact one — a
    bit-flip or truncation can cost retained history, never a silent
    garbage restore.
  * **Retention**: keep-last-k plus keep-every-n anchors
    (``DL4J_TPU_CKPT_KEEP``), pruned only after a successful commit.
  * **Layered**: the payload is either the single-host ModelSerializer
    zip (utils/serialization.py — now with the training-state section) or
    the orbax sharded layout (utils/sharded_checkpoint.py) for
    mesh-sharded state, behind one manifest/retention/fallback plane.

Scheduling: ``should_save(step)`` implements step-cadence
(``every_steps`` / ``DL4J_TPU_CKPT_EVERY``) and wall-clock cadence
(``every_seconds``). Multi-process runs write from the primary process
only (parallel/multihost.is_primary); every process restores from the
shared directory.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue
import shutil
import threading
import time
import zipfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.obs import journal as obs_journal
from deeplearning4j_tpu.obs import trace as obs_trace
from deeplearning4j_tpu.ops import env as envknob

logger = logging.getLogger("deeplearning4j_tpu")

ENV_EVERY = "DL4J_TPU_CKPT_EVERY"
ENV_KEEP = "DL4J_TPU_CKPT_KEEP"
ENV_ASYNC = "DL4J_TPU_CKPT_ASYNC"

MANIFEST = "MANIFEST.json"
MANIFEST_FORMAT = 1
_CKPT_PREFIX = "ckpt-"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed digest/structure verification (raised only by
    the explicit single-checkpoint restore path; the scanning restore
    logs and falls back instead)."""


# --------------------------------------------------------------------- utils
def fsync_file(path: str) -> None:
    with open(path, "rb") as f:
        os.fsync(f.fileno())


def fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def atomic_replace(path: str, data: bytes) -> None:
    """Crash-safe single-file write: tmp + fsync + rename (the
    early-stopping savers route their bestModel/latestModel zips through
    this so a preemption mid-save can no longer tear them)."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _host_tree(tree):
    """Numpy copies of every leaf — the synchronous part of an async save.
    np.asarray on a jax array devices-to-host copies; doing it HERE (not
    in the worker) is what makes async checkpointing sound under buffer
    donation: by the time the next train step consumes the donated
    buffers, the snapshot no longer references them."""
    import jax

    return jax.tree_util.tree_map(lambda a: np.asarray(a), tree)


def _env_int(name: str, default: int) -> int:
    return envknob.get_int(name, default)


@dataclass
class _SaveJob:
    step: int
    model_class: str
    conf_json: str
    params: Any
    states: Any
    updater_state: Any
    meta: Dict[str, Any]
    training_state: Dict[str, Any]
    done: threading.Event = field(default_factory=threading.Event)
    error: Optional[BaseException] = None
    path: Optional[str] = None


class CheckpointManager:
    """See module docstring. One manager owns one checkpoint directory."""

    def __init__(
        self,
        directory: str,
        *,
        every_steps: Optional[int] = None,
        every_seconds: Optional[float] = None,
        keep_last: Optional[int] = None,
        keep_every: Optional[int] = None,
        async_save: Optional[bool] = None,
        backend: str = "zip",
        compression: int = zipfile.ZIP_STORED,
        primary: Optional[bool] = None,
        chaos=None,
    ):
        if backend not in ("zip", "sharded"):
            raise ValueError(f"unknown checkpoint backend {backend!r}")
        self.directory = os.path.abspath(directory)
        self.every_steps = (_env_int(ENV_EVERY, 0) if every_steps is None
                            else int(every_steps))
        self.every_seconds = every_seconds
        self.keep_last = (_env_int(ENV_KEEP, 3) if keep_last is None
                          else int(keep_last))
        self.keep_every = keep_every
        self.async_save = (envknob.raw(ENV_ASYNC, "1") != "0"
                           if async_save is None else bool(async_save))
        self.backend = backend
        self.compression = compression
        self._primary = primary
        self.chaos = chaos
        self._last_save_t: Optional[float] = None
        self._queue: "queue.Queue[_SaveJob]" = queue.Queue(maxsize=2)
        self._worker: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # serializes the actual fs writes: a BLOCKING save (preemption)
        # may run on the caller thread while the async worker is mid-job
        self._write_lock = threading.Lock()
        # telemetry, mirroring ops/dispatch.DispatchStats' role: the bench
        # leg and tests read these instead of re-deriving from the fs
        self.stats = {"saves": 0, "skipped_busy": 0, "bytes": 0,
                      "write_s": 0.0, "pruned": 0, "errors": 0}
        self.errors: List[BaseException] = []
        os.makedirs(self.directory, exist_ok=True)

    # ---------------------------------------------------------------- policy
    def is_primary(self) -> bool:
        if self._primary is None:
            from deeplearning4j_tpu.parallel.multihost import is_primary

            self._primary = is_primary()
        return self._primary

    def should_save(self, step: int) -> bool:
        """Step/time cadence (both opt-in; the trainer additionally saves
        on preemption and at fit() exit regardless of cadence)."""
        if self.every_steps and step % self.every_steps == 0:
            return True
        if self.every_seconds is not None:
            now = time.monotonic()
            if (self._last_save_t is None
                    or now - self._last_save_t >= self.every_seconds):
                return True
        return False

    # ----------------------------------------------------------------- save
    def save(self, net, *, step: int, epoch: int = 0,
             iterator_state: Optional[dict] = None,
             block: Optional[bool] = None) -> Optional[str]:
        """Checkpoint `net` (MultiLayerNetwork or ComputationGraph) as
        step `step`. Synchronous part: host snapshot of
        params/states/updater + training state. Async part (unless
        ``block`` or sync mode): zip/fsync/manifest/commit/retention in
        the worker thread. Returns the committed path when blocking, else
        None (the commit is observable via flush()/checkpoints()).

        A non-blocking save while the previous write is still in flight
        is SKIPPED (counted in stats["skipped_busy"]) rather than queued
        without bound — checkpoint cadence must never grow an unbounded
        snapshot backlog in host RAM. Blocking saves (preemption,
        fit-exit) always wait for a slot instead."""
        if not self.is_primary():
            return None
        block = (not self.async_save) if block is None else block
        training_state = dict(net.training_state()) if hasattr(
            net, "training_state") else {"iteration": int(net.iteration)}
        training_state.update({
            "step": int(step),
            "epoch": int(epoch),
            "iterator_state": iterator_state,
        })
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        # the synchronous half of an async save — the only stall the
        # train loop pays; the span makes that stall visible next to the
        # dispatch spans it interleaves with
        with obs_trace.span("ckpt.snapshot", step=int(step)):
            job = _SaveJob(
                step=int(step),
                model_class=type(net).__name__,
                conf_json=net.conf.to_json(),
                params=_host_tree(net.params),
                states=_host_tree(net.states),
                updater_state=_host_tree(net.updater_state),
                meta=ModelSerializer._container_meta(net),
                training_state=training_state,
            )
        self._last_save_t = time.monotonic()
        if block:
            self._write(job)
            if job.error is not None:
                # raised HERE means handled here: drop it from the list
                # flush() reports, or the next flush would re-raise an
                # error the caller already dealt with
                try:
                    self.errors.remove(job.error)
                except ValueError:
                    pass
                raise job.error
            return job.path
        self._ensure_worker()
        try:
            self._queue.put_nowait(job)
        except queue.Full:
            self.stats["skipped_busy"] += 1
            logger.warning(
                "checkpoint step %d skipped: previous write still in "
                "flight (next cadence point will retry)", step)
            return None
        return None

    def _ensure_worker(self) -> None:
        with self._lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._worker_loop, daemon=True,
                    name="ckpt-writer")
                self._worker.start()

    def _worker_loop(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:  # close() sentinel
                # the sentinel must be task_done'd too: a manager reused
                # after close() (ensure_worker restarts the thread) would
                # otherwise deadlock every later flush()'s queue.join()
                self._queue.task_done()
                return
            self._write(job)
            self._queue.task_done()

    def flush(self) -> None:
        """Wait until every enqueued save has committed; re-raise the
        first writer error (a failed checkpoint must not stay silent)."""
        if self._worker is not None:
            self._queue.join()
        if self.errors:
            err, self.errors = self.errors[0], []
            raise err

    def close(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            self._queue.join()
            self._queue.put(None)
            self._worker.join(timeout=10.0)
        self._worker = None

    # ---------------------------------------------------------------- write
    def _ckpt_path(self, step: int) -> str:
        return os.path.join(self.directory, f"{_CKPT_PREFIX}{step:08d}")

    def _write(self, job: _SaveJob) -> None:
        with self._write_lock:
            self._write_locked(job)

    def _write_locked(self, job: _SaveJob) -> None:
        t0 = time.perf_counter()
        final = self._ckpt_path(job.step)
        tmp = final + ".tmp"
        try:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            with obs_trace.span("ckpt.write", step=job.step,
                                backend=self.backend):
                files = (self._write_zip_payload(tmp, job)
                         if self.backend == "zip"
                         else self._write_sharded_payload(tmp, job))
            manifest = {
                "format": MANIFEST_FORMAT,
                "backend": self.backend,
                "step": job.step,
                "epoch": job.training_state.get("epoch", 0),
                "iteration": job.training_state.get("iteration"),
                "model_class": job.model_class,
                "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
                "files": files,
                "iterator_state": job.training_state.get("iterator_state"),
            }
            mpath = os.path.join(tmp, MANIFEST)
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            # the commit: one directory rename; a crash before this line
            # leaves only a .tmp dir that the next write sweeps away.
            # A re-save of an existing step (save_on_exit, restart of a
            # finished run) renames the old dir ASIDE first — an rmtree
            # here would open a whole-tree-wide window with NO checkpoint
            # for the step; .old dirs don't parse as checkpoints, so the
            # scan never sees the intermediate state
            with obs_trace.span("ckpt.commit", step=job.step):
                old = None
                if os.path.isdir(final):
                    old = final + ".old"
                    if os.path.isdir(old):
                        shutil.rmtree(old)
                    os.replace(final, old)
                os.replace(tmp, final)
                if old is not None:
                    shutil.rmtree(old, ignore_errors=True)
                fsync_dir(self.directory)
            job.path = final
            total_bytes = sum(f["bytes"] for f in files.values())
            self.stats["saves"] += 1
            self.stats["bytes"] += total_bytes
            self.stats["write_s"] += time.perf_counter() - t0
            # flight-recorder marker: a post-mortem timeline can line the
            # last committed checkpoint up against spans/preemption events
            obs_journal.event(
                "checkpoint", step=job.step, path=final,
                epoch=job.training_state.get("epoch", 0),
                bytes=total_bytes)
            if self.chaos is not None:
                self.chaos.on_checkpoint_written(final, job.step)
            self._retain()
        except BaseException as e:  # noqa: BLE001 — surfaced via flush()
            job.error = e
            self.stats["errors"] += 1
            self.errors.append(e)
            logger.error("checkpoint step %d failed: %s", job.step, e)
            shutil.rmtree(tmp, ignore_errors=True)
        finally:
            job.done.set()

    def _write_zip_payload(self, tmp: str, job: _SaveJob) -> Dict[str, dict]:
        from deeplearning4j_tpu.utils.serialization import write_model_parts

        zpath = os.path.join(tmp, "model.zip")
        write_model_parts(
            zpath,
            model_class=job.model_class,
            conf_json=job.conf_json,
            params=job.params,
            states=job.states,
            updater_state=job.updater_state,
            meta=job.meta,
            training_state=job.training_state,
            compression=self.compression,
        )
        fsync_file(zpath)
        return {"model.zip": {"sha256": file_sha256(zpath),
                              "bytes": os.path.getsize(zpath)}}

    def _write_sharded_payload(self, tmp: str, job: _SaveJob) -> Dict[str, dict]:
        """Orbax layout for mesh-sharded state (utils/sharded_checkpoint):
        the pytrees stream through orbax's per-shard writers; config and
        training state ride as plain JSON files; the manifest digests the
        whole tree so verification covers every shard file."""
        from deeplearning4j_tpu.utils import sharded_checkpoint as sc

        sc.save_pytree(os.path.join(tmp, "state"), {
            "params": job.params,
            "states": job.states,
            "updater": job.updater_state,
        })
        from deeplearning4j_tpu.utils.serialization import (
            _jsonable_training_state,
        )

        with open(os.path.join(tmp, "configuration.json"), "w") as f:
            f.write(job.conf_json)
        with open(os.path.join(tmp, "training_state.json"), "w") as f:
            json.dump(_jsonable_training_state(job.training_state), f)
        with open(os.path.join(tmp, "metadata.json"), "w") as f:
            json.dump({"model_class": job.model_class,
                       "format": "orbax-dir", **job.meta}, f)
        files = {}
        for root, _, names in os.walk(tmp):
            for name in names:
                p = os.path.join(root, name)
                fsync_file(p)
                rel = os.path.relpath(p, tmp)
                files[rel] = {"sha256": file_sha256(p),
                              "bytes": os.path.getsize(p)}
        return files

    # ------------------------------------------------------------- retention
    def _retain(self) -> None:
        """keep-last-k + keep-every-n anchors; prune the rest. Runs after
        every successful commit (never deletes the checkpoint it just
        wrote: it is always within the last k >= 1)."""
        entries = self.checkpoints()
        if not entries:
            return
        keep = {s for s, _ in entries[-max(1, self.keep_last):]}
        if self.keep_every:
            keep |= {s for s, _ in entries if s % self.keep_every == 0}
        for step, path in entries:
            if step not in keep:
                shutil.rmtree(path, ignore_errors=True)
                self.stats["pruned"] += 1

    # ----------------------------------------------------------------- scan
    def checkpoints(self) -> List[Tuple[int, str]]:
        """Committed checkpoints, sorted ascending by step."""
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            if not name.startswith(_CKPT_PREFIX) or name.endswith(".tmp"):
                continue
            try:
                step = int(name[len(_CKPT_PREFIX):])
            except ValueError:
                continue
            out.append((step, os.path.join(self.directory, name)))
        return sorted(out)

    def verify(self, path: str) -> Tuple[bool, str]:
        """Re-hash every manifested file. (ok, reason)."""
        mpath = os.path.join(path, MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            return False, f"manifest unreadable: {e}"
        for rel, info in manifest.get("files", {}).items():
            p = os.path.join(path, rel)
            if not os.path.isfile(p):
                return False, f"missing payload file {rel}"
            if os.path.getsize(p) != info["bytes"]:
                return False, (f"{rel}: size {os.path.getsize(p)} != "
                               f"manifested {info['bytes']}")
            if file_sha256(p) != info["sha256"]:
                return False, f"{rel}: sha256 mismatch"
        return True, "ok"

    def read_manifest(self, path: str) -> Dict[str, Any]:
        with open(os.path.join(path, MANIFEST)) as f:
            return json.load(f)

    def latest_intact(self) -> Optional[Tuple[str, Dict[str, Any]]]:
        """Newest checkpoint that passes verification, scanning backwards
        with a LOUD warning per corrupt candidate — fallback may cost
        history, silence may cost correctness."""
        for step, path in reversed(self.checkpoints()):
            ok, reason = self.verify(path)
            if ok:
                return path, self.read_manifest(path)
            logger.warning(
                "checkpoint %s is corrupt (%s); falling back to the "
                "previous retained checkpoint", path, reason)
        return None

    # -------------------------------------------------------------- restore
    def restore(self, path: str, net) -> Dict[str, Any]:
        """Restore checkpoint dir `path` into the existing `net` (must be
        built from the same configuration). Verifies first — an explicit
        restore of a corrupt checkpoint raises :class:`CheckpointCorrupt`
        rather than loading garbage."""
        ok, reason = self.verify(path)
        if not ok:
            raise CheckpointCorrupt(f"{path}: {reason}")
        manifest = self.read_manifest(path)
        if manifest.get("backend", "zip") == "zip":
            from deeplearning4j_tpu.utils.serialization import ModelSerializer

            ts = ModelSerializer.load_into(
                net, os.path.join(path, "model.zip"))
        else:
            ts = self._restore_sharded(path, net)
        return {
            "step": int(manifest.get("step", ts.get("step", 0) or 0)),
            "epoch": int(ts.get("epoch", manifest.get("epoch", 0)) or 0),
            "iterator_state": ts.get("iterator_state",
                                     manifest.get("iterator_state")),
            "path": path,
        }

    def _restore_sharded(self, path: str, net) -> Dict[str, Any]:
        from deeplearning4j_tpu.utils import sharded_checkpoint as sc

        if net.params is None:
            net.init()
        state = sc.restore_pytree(os.path.join(path, "state"), {
            "params": net.params,
            "states": net.states,
            "updater": net.updater_state,
        })
        net.params = state["params"]
        net.states = state["states"]
        net.updater_state = state["updater"]
        with open(os.path.join(path, "training_state.json")) as f:
            ts = json.load(f)
        if hasattr(net, "restore_training_state"):
            net.restore_training_state(ts)
        return ts

    def restore_latest(self, net) -> Optional[Dict[str, Any]]:
        """Restore the newest intact checkpoint into `net`; None when the
        directory holds nothing restorable (fresh run)."""
        found = self.latest_intact()
        if found is None:
            return None
        path, _ = found
        return self.restore(path, net)
