"""ResilientTrainer: preemptible, exactly-resumable fit loops.

Drives the fit loop of any trainee with the container fit contract —
``MultiLayerNetwork``, ``ComputationGraph``, or the parallel trainers
(``ParameterAveragingTrainer`` / ``ParallelWrapper``, whose ``.net``
holds the state; one iterator batch = one averaging round for the
former) — adding the fault plane the reference delegates to Spark
lineage (SURVEY.md §2.3: a lost executor recomputes its partition;
here a lost PROCESS resumes the exact step stream):

  * cadence checkpointing through :class:`CheckpointManager` (async by
    default: the loop stalls for the host snapshot only);
  * SIGTERM preemption -> checkpoint-before-death at the next batch
    boundary, then :class:`Preempted` (a TPU pod eviction or scheduler
    kill loses AT MOST the in-flight batch, which the resume replays);
  * restore-and-continue: a fresh process pointed at the same manager
    directory reloads params/updater/step counters/RNG key AND the data
    iterator cursor (datasets/iterator.py resumable protocol), so the
    resumed run consumes the exact remaining batch stream —
    interrupted-and-resumed training is bit-identical to uninterrupted
    training (the resilience analogue of the repo's distributed==serial
    convention; tests/test_resilience.py proves it for MLN, CG, and the
    DP trainer);
  * transient-fault retry with exponential backoff (a flaky device /
    tunnel hiccup re-runs the step; chaos.TransientDeviceError injects
    it deterministically in tests).

With no manager and no chaos config this class is a plain fit loop —
bit-identical to ``for ds in it: net.fit(...)`` — so wrapping costs
nothing (the zero-behavior-change contract).
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import List, Optional

from deeplearning4j_tpu.obs import journal as obs_journal
from deeplearning4j_tpu.obs import registry as obs_registry
from deeplearning4j_tpu.resilience.chaos import ChaosMonkey, TransientDeviceError
from deeplearning4j_tpu.resilience.checkpoint import CheckpointManager

logger = logging.getLogger("deeplearning4j_tpu")


class Preempted(RuntimeError):
    """Raised after a preemption signal once the goodbye checkpoint has
    committed; carries the checkpoint step so drivers can log it."""

    def __init__(self, step: int, path: Optional[str]):
        super().__init__(
            f"preempted after step {step}; checkpoint at {path}")
        self.step = step
        self.path = path


class ResilientTrainer:
    def __init__(
        self,
        trainee,
        manager: Optional[CheckpointManager] = None,
        *,
        chaos: Optional[ChaosMonkey] = None,
        resume: bool = True,
        save_on_exit: bool = True,
        handle_signals: bool = True,
        preempt_signals=(signal.SIGTERM,),
        max_step_retries: int = 0,
        retry_backoff_s: float = 0.05,
        retry_backoff_max_s: float = 2.0,
        retry_jitter: float = 0.25,
        retry_salt: Optional[int] = None,
    ):
        self.trainee = trainee
        # parallel trainers carry the state-owning container on .net
        self.net = trainee.net if hasattr(trainee, "net") else trainee
        self.manager = manager
        self.chaos = chaos
        self.resume = resume
        self.save_on_exit = save_on_exit
        self.handle_signals = handle_signals
        self.preempt_signals = tuple(preempt_signals)
        self.max_step_retries = int(max_step_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_backoff_max_s = float(retry_backoff_max_s)
        self.retry_jitter = float(retry_jitter)
        # per-process salt: N peers hitting the same fault at the same
        # step must NOT sleep identical jittered backoffs (they would
        # re-collide on every attempt — the multihost env contract gives
        # a stable per-process value without touching jax; pid covers
        # the unconfigured case). Overridable for reproducible tests.
        if retry_salt is None:
            import os

            from deeplearning4j_tpu.parallel.multihost import _int_env, \
                PROCESS_ID_ENV

            pid = _int_env(PROCESS_ID_ENV)
            retry_salt = pid if pid is not None else os.getpid()
        self.retry_salt = int(retry_salt)
        self._preempt_requested = False
        self._old_handlers = {}
        self.losses: List[float] = []
        self.resumed_step: Optional[int] = None  # set when a restore ran
        self.step = 0  # completed batches (trainer steps), incl. restored
        # fault-plane telemetry beside dispatch_stats/memory_stats: a
        # fleet trainee (parallel/fleet.py) already carries the dict
        # (reclaims/membership counters) — share it rather than shadow it
        self.resilience_stats = getattr(trainee, "resilience_stats", None)
        if self.resilience_stats is None:
            self.resilience_stats = {}
        for key, zero in (("retries", 0), ("reclaims", 0),
                          ("backoff_seconds", 0.0), ("preemptions", 0),
                          ("resumes", 0),
                          # checkpoint correlation (ISSUE 7): the id of
                          # the last checkpoint this trainer saved, so a
                          # flight-recorder timeline / elastic_dp bench
                          # row can be joined against checkpoints on disk
                          ("last_checkpoint_step", -1)):
            self.resilience_stats.setdefault(key, zero)
        self.net.resilience_stats = self.resilience_stats
        # the fault-plane ledger joins the central MetricsRegistry beside
        # the net's own dispatch/memory ledgers (obs/registry.py)
        obs_registry.register_net(self.net)

    # ---------------------------------------------------------------- signals
    def _install_handlers(self) -> None:
        if not self.handle_signals:
            return
        if threading.current_thread() is not threading.main_thread():
            logger.warning(
                "ResilientTrainer: not on the main thread; preemption "
                "signal handling disabled for this fit")
            return
        for sig in self.preempt_signals:
            self._old_handlers[sig] = signal.signal(sig, self._on_signal)

    def _restore_handlers(self) -> None:
        for sig, old in self._old_handlers.items():
            signal.signal(sig, old)
        self._old_handlers = {}

    def _on_signal(self, signum, frame) -> None:
        # handler does the MINIMUM: flag it. The loop checkpoints at the
        # next batch boundary — saving from inside a signal handler could
        # interrupt an in-flight step's own bookkeeping.
        logger.warning(
            "preemption signal %s received: checkpoint-before-death at "
            "the next batch boundary", signum)
        self._preempt_requested = True

    # ------------------------------------------------------------------- fit
    def fit(self, iterator, num_epochs: int = 1):
        """The reference fit(DataSetIterator) loop (MultiLayerNetwork
        .java:1017) under the fault plane. Returns the trained net."""
        net = self.net
        if net.params is None and not (self.manager and self.resume):
            net.init()
        start_epoch, pending_iter_state = 0, None
        if self.manager is not None and self.resume:
            restored = self.manager.restore_latest(net)
            if restored is not None:
                self.step = int(restored["step"])
                self.resumed_step = self.step
                self.resilience_stats["resumes"] += 1
                self.resilience_stats["last_checkpoint_step"] = self.step
                start_epoch = int(restored["epoch"])
                pending_iter_state = restored.get("iterator_state")
                obs_journal.event(
                    "resume", step=self.step, epoch=start_epoch,
                    path=restored["path"],
                    membership_epoch=self.resilience_stats.get("epoch"))
                logger.info(
                    "resumed from %s (step %d, epoch %d)",
                    restored["path"], self.step, start_epoch)
                # (start_epoch == num_epochs is the designed happy path —
                # the end-of-fit checkpoint resumes PAST the loop, so no
                # epoch replays and no warning is due)
                if (pending_iter_state is None and self.step > 0
                        and start_epoch < num_epochs):
                    logger.warning(
                        "resume checkpoint has no iterator cursor: the "
                        "epoch restarts from its first batch (exact "
                        "resume needs a resumable iterator — "
                        "datasets/iterator.py state()/restore_state())")
        if net.params is None:
            net.init()
        self._preempt_requested = False
        self._install_handlers()
        try:
            for epoch in range(start_epoch, num_epochs):
                if pending_iter_state is not None:
                    iterator.restore_state(pending_iter_state)
                    pending_iter_state = None
                for ds in iterator:
                    # NOTE: no preemption check before the step — the
                    # iterator cursor already counts the in-hand batch, so
                    # a checkpoint here would skip it on resume
                    loss = self._step_with_retry(ds)
                    self.step += 1
                    self.losses.append(float(loss))
                    if (self.manager is not None
                            and self.manager.should_save(self.step)):
                        self.manager.save(
                            net, step=self.step, epoch=epoch,
                            iterator_state=self._iter_state(iterator))
                        self.resilience_stats["last_checkpoint_step"] = \
                            self.step
                    if self.chaos is not None:
                        self.chaos.after_step(self.step)
                    self._check_preempt(epoch, iterator)
                if hasattr(iterator, "reset"):
                    iterator.reset()
            if self.manager is not None and self.save_on_exit:
                # end-of-fit checkpoint: epoch == num_epochs with a fresh
                # cursor, so a restart of the SAME command resumes past
                # the loop instead of re-training the last epoch
                self.manager.save(net, step=self.step, epoch=num_epochs,
                                  iterator_state=None, block=True)
        finally:
            self._restore_handlers()
            if self.manager is not None:
                self.manager.flush()
        return net

    # ----------------------------------------------------------------- steps
    def _retry_backoff(self, attempts: int) -> float:
        """Exponential backoff with a cap and DETERMINISTIC jitter:
        uncapped doubling can sleep past the preemption budget, and
        jitterless retries from N workers re-collide on every attempt
        (thundering herd). The jitter fraction derives from (step,
        attempt, per-process salt) via a Weyl-style integer mix — no RNG
        state, so the bit-exact resume contract is untouched (sleep
        never enters the numerics), while peers hitting the same fault
        at the same step still sleep DIFFERENT amounts (the salt is what
        actually decorrelates the herd)."""
        base = min(self.retry_backoff_max_s,
                   self.retry_backoff_s * (2 ** (attempts - 1)))
        mix = ((self.step + 1) * 2654435761 + attempts * 40503
               + (self.retry_salt + 1) * 83492791) % (2 ** 32)
        return base * (1.0 + self.retry_jitter * (mix / 2.0 ** 32))

    def _step_with_retry(self, ds) -> float:
        attempts = 0
        while True:
            try:
                if self.chaos is not None:
                    self.chaos.before_step(self.step + 1)
                return self._fit_one(ds)
            except TransientDeviceError as e:
                attempts += 1
                if attempts > self.max_step_retries:
                    raise
                backoff = self._retry_backoff(attempts)
                self.resilience_stats["retries"] += 1
                self.resilience_stats["backoff_seconds"] += backoff
                logger.warning(
                    "transient device error at step %d (attempt %d/%d): "
                    "%s — retrying in %.2fs", self.step + 1, attempts,
                    self.max_step_retries, e, backoff)
                time.sleep(backoff)

    def _fit_one(self, ds) -> float:
        # MLN fit(features, labels, mask, label_mask) / CG fit(features,
        # labels, masks, label_masks) / both parallel trainers share the
        # positional contract, so one call drives all trainees
        return self.trainee.fit(ds.features, ds.labels,
                                ds.features_mask, ds.labels_mask)

    @staticmethod
    def _iter_state(iterator) -> Optional[dict]:
        return iterator.state() if hasattr(iterator, "state") else None

    def _check_preempt(self, epoch: int, iterator) -> None:
        if not self._preempt_requested:
            return
        self.resilience_stats["preemptions"] += 1
        path = None
        if self.manager is not None:
            path = self.manager.save(
                self.net, step=self.step, epoch=epoch,
                iterator_state=self._iter_state(iterator), block=True)
            self.manager.flush()
            self.resilience_stats["last_checkpoint_step"] = self.step
        # fsync-on-preemption: the goodbye checkpoint just committed; the
        # flight recorder's timeline (spans, checkpoint commits, this
        # marker) must survive the kill the same way (obs/journal.py —
        # no-op unless DL4J_TPU_OBS is on)
        obs_journal.event(
            "preempt", step=self.step, epoch=epoch, path=path,
            membership_epoch=self.resilience_stats.get("epoch"))
        obs_journal.flush(fsync=True)
        raise Preempted(self.step, path)
