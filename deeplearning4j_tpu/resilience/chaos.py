"""Deterministic fault injection for the fault-tolerant training runtime.

The reference survives worker loss through Spark lineage plus the
StateTracker heartbeat/reclaim plane (ConnectionStateTracker heartbeats,
reproduced in parallel/statetracker.py) — but it has no way to *provoke*
those failures deterministically, so its resilience paths were exercised
only by real cluster flakiness. This module is the missing test
instrument: every fault the resilience/ subsystem claims to survive
(process kill at a known step, SIGTERM preemption, a stalled feed, a
truncated or bit-flipped checkpoint, a transient device error) can be
injected at an exact, reproducible point, driven ONLY by an explicit
:class:`ChaosConfig` — there is no ambient/env activation, so a run
without a configured monkey is bit-identical to a run without this
module imported (the zero-behavior-change contract in
tests/test_resilience.py).

Faults and where they fire:

  kill_at_step        — after step k completes: raise :class:`InjectedKill`
                        (``kill_mode="exception"``, a hard crash with NO
                        goodbye checkpoint) or deliver a real SIGTERM to
                        this process (``kill_mode="sigterm"``, exercising
                        the trainer's checkpoint-before-death path).
  stall_at_step       — before step k: sleep ``stall_seconds`` (a wedged
                        feed/tunnel; liveness, not correctness).
  transient_error_at_step — before step k: raise
                        :class:`TransientDeviceError` the first
                        ``transient_error_count`` times, then succeed
                        (the retry/backoff path in ResilientTrainer).
  corrupt_checkpoint  — after the manager commits checkpoint step k:
                        truncate or bit-flip its payload on disk
                        (the corruption-detection/fallback path in
                        CheckpointManager.latest_intact).
"""

from __future__ import annotations

import os
import signal
import time
from dataclasses import dataclass
from typing import Optional


class InjectedKill(RuntimeError):
    """A chaos-injected hard crash (no cleanup, no goodbye checkpoint)."""


class TransientDeviceError(RuntimeError):
    """A chaos-injected transient accelerator failure (retryable)."""


@dataclass
class ChaosConfig:
    """Declarative fault plan. Steps are 1-based counts of COMPLETED
    trainer steps (kill_at_step=k dies after the k-th step's update has
    been applied; stall/transient fire before the step runs)."""

    kill_at_step: Optional[int] = None
    kill_mode: str = "exception"  # "exception" | "sigterm"
    stall_at_step: Optional[int] = None
    stall_seconds: float = 0.0
    transient_error_at_step: Optional[int] = None
    transient_error_count: int = 1
    # {"at_step": int, "mode": "truncate"|"bitflip"} applied to the
    # checkpoint the manager just committed for that step
    corrupt_checkpoint: Optional[dict] = None

    def __post_init__(self):
        if self.kill_mode not in ("exception", "sigterm"):
            raise ValueError(f"unknown kill_mode {self.kill_mode!r}")
        if self.corrupt_checkpoint is not None:
            mode = self.corrupt_checkpoint.get("mode", "truncate")
            if mode not in ("truncate", "bitflip"):
                raise ValueError(f"unknown corruption mode {mode!r}")


class ChaosMonkey:
    """Stateful executor of a :class:`ChaosConfig`, consulted by
    ResilientTrainer (before/after each step) and CheckpointManager
    (after each committed checkpoint). Deterministic: the same config
    against the same step sequence injects the same faults."""

    def __init__(self, config: ChaosConfig):
        if isinstance(config, dict):
            config = ChaosConfig(**config)
        self.config = config
        self._transient_left = int(config.transient_error_count)
        self.log: list = []  # (step, fault) audit trail for tests

    # ------------------------------------------------------------ step hooks
    def before_step(self, step: int) -> None:
        """`step` is the 1-based index of the step ABOUT to run."""
        c = self.config
        if c.stall_at_step is not None and step == c.stall_at_step:
            self.log.append((step, "stall"))
            time.sleep(c.stall_seconds)
        if (c.transient_error_at_step is not None
                and step == c.transient_error_at_step
                and self._transient_left > 0):
            self._transient_left -= 1
            self.log.append((step, "transient_error"))
            raise TransientDeviceError(
                f"injected transient device error at step {step} "
                f"({self._transient_left} more before recovery)")

    def after_step(self, step: int) -> None:
        """`step` is the 1-based count of COMPLETED steps."""
        c = self.config
        if c.kill_at_step is not None and step == c.kill_at_step:
            self.log.append((step, f"kill:{c.kill_mode}"))
            if c.kill_mode == "sigterm":
                # a REAL signal, exactly like a preempting scheduler: the
                # trainer's handler sets the flag and the loop performs
                # checkpoint-before-death at the next boundary
                os.kill(os.getpid(), signal.SIGTERM)
                return
            raise InjectedKill(f"injected kill after step {step}")

    # ------------------------------------------------- checkpoint corruption
    def on_checkpoint_written(self, path: str, step: int) -> None:
        """Called by CheckpointManager after committing `path` for `step`."""
        c = self.config.corrupt_checkpoint
        if c is None or int(c.get("at_step", -1)) != step:
            return
        target = os.path.join(path, "model.zip")
        if not os.path.exists(target):  # sharded layout: hit any payload
            for root, _, files in os.walk(path):
                for f in files:
                    if f != "MANIFEST.json":
                        target = os.path.join(root, f)
                        break
        mode = c.get("mode", "truncate")
        self.log.append((step, f"corrupt:{mode}"))
        if mode == "truncate":
            truncate_file(target, keep=int(c.get("keep_bytes", 16)))
        else:
            bitflip_file(target, offset=c.get("at_byte"))


def truncate_file(path: str, keep: int = 16) -> None:
    """Write-then-truncate fault: keep only the first `keep` bytes (a
    crash mid-write that an atomic rename would normally prevent —
    simulates torn storage underneath the checkpoint)."""
    with open(path, "r+b") as f:
        f.truncate(keep)


def bitflip_file(path: str, offset: Optional[int] = None) -> None:
    """Flip one bit of `path` in place (silent media corruption). With no
    offset the middle byte is flipped — deterministic, no RNG."""
    size = os.path.getsize(path)
    if size == 0:
        return
    off = size // 2 if offset is None else int(offset) % size
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0x01]))
