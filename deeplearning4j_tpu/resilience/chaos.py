"""Deterministic fault injection for the fault-tolerant training runtime.

The reference survives worker loss through Spark lineage plus the
StateTracker heartbeat/reclaim plane (ConnectionStateTracker heartbeats,
reproduced in parallel/statetracker.py) — but it has no way to *provoke*
those failures deterministically, so its resilience paths were exercised
only by real cluster flakiness. This module is the missing test
instrument: every fault the resilience/ subsystem claims to survive
(process kill at a known step, SIGTERM preemption, a stalled feed, a
truncated or bit-flipped checkpoint, a transient device error) can be
injected at an exact, reproducible point, driven ONLY by an explicit
:class:`ChaosConfig` — there is no ambient/env activation, so a run
without a configured monkey is bit-identical to a run without this
module imported (the zero-behavior-change contract in
tests/test_resilience.py).

Faults and where they fire:

  kill_at_step        — after step k completes: raise :class:`InjectedKill`
                        (``kill_mode="exception"``, a hard crash with NO
                        goodbye checkpoint) or deliver a real SIGTERM to
                        this process (``kill_mode="sigterm"``, exercising
                        the trainer's checkpoint-before-death path).
  stall_at_step       — before step k: sleep ``stall_seconds`` (a wedged
                        feed/tunnel; liveness, not correctness).
  transient_error_at_step — before step k: raise
                        :class:`TransientDeviceError` the first
                        ``transient_error_count`` times, then succeed
                        (the retry/backoff path in ResilientTrainer).
  corrupt_checkpoint  — after the manager commits checkpoint step k:
                        truncate or bit-flip its payload on disk
                        (the corruption-detection/fallback path in
                        CheckpointManager.latest_intact).
"""

from __future__ import annotations

import os
import signal
import threading
import time
from dataclasses import dataclass
from typing import Optional


class InjectedKill(RuntimeError):
    """A chaos-injected hard crash (no cleanup, no goodbye checkpoint)."""


class TransientDeviceError(RuntimeError):
    """A chaos-injected transient accelerator failure (retryable)."""


@dataclass
class ChaosConfig:
    """Declarative fault plan. Steps are 1-based counts of COMPLETED
    trainer steps (kill_at_step=k dies after the k-th step's update has
    been applied; stall/transient fire before the step runs)."""

    kill_at_step: Optional[int] = None
    kill_mode: str = "exception"  # "exception" | "sigterm"
    stall_at_step: Optional[int] = None
    stall_seconds: float = 0.0
    transient_error_at_step: Optional[int] = None
    transient_error_count: int = 1
    # {"at_step": int, "mode": "truncate"|"bitflip"} applied to the
    # checkpoint the manager just committed for that step
    corrupt_checkpoint: Optional[dict] = None

    def __post_init__(self):
        if self.kill_mode not in ("exception", "sigterm"):
            raise ValueError(f"unknown kill_mode {self.kill_mode!r}")
        if self.corrupt_checkpoint is not None:
            mode = self.corrupt_checkpoint.get("mode", "truncate")
            if mode not in ("truncate", "bitflip"):
                raise ValueError(f"unknown corruption mode {mode!r}")


class ChaosMonkey:
    """Stateful executor of a :class:`ChaosConfig`, consulted by
    ResilientTrainer (before/after each step) and CheckpointManager
    (after each committed checkpoint). Deterministic: the same config
    against the same step sequence injects the same faults."""

    def __init__(self, config: ChaosConfig):
        if isinstance(config, dict):
            config = ChaosConfig(**config)
        self.config = config
        self._transient_left = int(config.transient_error_count)
        self.log: list = []  # (step, fault) audit trail for tests

    # ------------------------------------------------------------ step hooks
    def before_step(self, step: int) -> None:
        """`step` is the 1-based index of the step ABOUT to run."""
        c = self.config
        if c.stall_at_step is not None and step == c.stall_at_step:
            self.log.append((step, "stall"))
            time.sleep(c.stall_seconds)
        if (c.transient_error_at_step is not None
                and step == c.transient_error_at_step
                and self._transient_left > 0):
            self._transient_left -= 1
            self.log.append((step, "transient_error"))
            raise TransientDeviceError(
                f"injected transient device error at step {step} "
                f"({self._transient_left} more before recovery)")

    def after_step(self, step: int) -> None:
        """`step` is the 1-based count of COMPLETED steps."""
        c = self.config
        if c.kill_at_step is not None and step == c.kill_at_step:
            self.log.append((step, f"kill:{c.kill_mode}"))
            if c.kill_mode == "sigterm":
                # a REAL signal, exactly like a preempting scheduler: the
                # trainer's handler sets the flag and the loop performs
                # checkpoint-before-death at the next boundary
                os.kill(os.getpid(), signal.SIGTERM)
                return
            raise InjectedKill(f"injected kill after step {step}")

    # ------------------------------------------------- checkpoint corruption
    def on_checkpoint_written(self, path: str, step: int) -> None:
        """Called by CheckpointManager after committing `path` for `step`."""
        c = self.config.corrupt_checkpoint
        if c is None or int(c.get("at_step", -1)) != step:
            return
        target = os.path.join(path, "model.zip")
        if not os.path.exists(target):  # sharded layout: hit any payload
            for root, _, files in os.walk(path):
                for f in files:
                    if f != "MANIFEST.json":
                        target = os.path.join(root, f)
                        break
        mode = c.get("mode", "truncate")
        self.log.append((step, f"corrupt:{mode}"))
        if mode == "truncate":
            truncate_file(target, keep=int(c.get("keep_bytes", 16)))
        else:
            bitflip_file(target, offset=c.get("at_byte"))


# ---------------------------------------------------------------------------
# Fleet faults (ISSUE 6): deterministic failures for the elastic fleet
# runtime (parallel/fleet.py) — worker loss mid-round, stalled heartbeats
# (the zombie-executor double-count hazard), and a partitioned coordinator.
# ---------------------------------------------------------------------------


class CoordinatorPartitioned(ConnectionError):
    """A chaos-injected membership-plane partition: the coordinator's
    poll of the membership authority fails (the Hazelcast split-brain /
    ZooKeeper session-loss failure the reference inherits from its
    cluster substrate)."""


@dataclass
class FleetChaosConfig:
    """Declarative fleet fault plan. Rounds are 1-based averaging rounds;
    faults key on the ROUND (and, where executor identity is racy, on the
    SPLIT — whichever worker holds that split is the victim, which keeps
    the fault deterministic under free-for-all job scheduling while the
    round's numerics stay executor-independent by construction).

      kill_worker       — {"worker": id, "in_round": r}: the worker dies
                          at its first job poll of round r (holding its
                          job, if it got one) — heartbeat expiry detects
                          it, its split is reclaimed, the NEXT round
                          re-forms over the survivors.
      kill_split        — {"round": r, "split": s}: whoever takes split s
                          of round r dies HOLDING it (guaranteed reclaim
                          + re-execution path).
      stall_heartbeat   — {"round": r, "split": s, "sleep_s": x}: the
                          holder of split s goes silent for x seconds
                          (> the heartbeat timeout) while still alive —
                          the job is reclaimed and re-executed; the
                          zombie's late completion must be FENCED out
                          (StateTracker attempt fencing), after which the
                          zombie re-registers (rejoin).
      partition_coordinator — {"at_round": r, "polls": k}: the first k
                          membership polls of round r raise
                          :class:`CoordinatorPartitioned`; the
                          coordinator must retry / fall back to the
                          last-known membership instead of dying.
    """

    kill_worker: Optional[dict] = None
    kill_split: Optional[dict] = None
    stall_heartbeat: Optional[dict] = None
    partition_coordinator: Optional[dict] = None


class FleetChaos:
    """Stateful executor of a :class:`FleetChaosConfig`, consulted by the
    fleet coordinator (membership polls) and its workers (job polls /
    job receipt). Deterministic: the same config against the same round
    sequence injects the same faults exactly once each."""

    def __init__(self, config: FleetChaosConfig):
        if isinstance(config, dict):
            config = FleetChaosConfig(**config)
        self.config = config
        c = config.partition_coordinator or {}
        self._partition_polls_left = int(c.get("polls", 0))
        self._killed_worker = False
        self._killed_split = False
        self._stalled = False
        self.log: list = []  # (round, fault) audit trail for tests

    def kill_on_poll(self, worker_id: str, rnd: int) -> bool:
        """Worker-side, at each job poll: True -> the worker dies now."""
        c = self.config.kill_worker
        if (c is not None and not self._killed_worker
                and worker_id == c["worker"] and rnd >= int(c["in_round"])):
            self._killed_worker = True
            self.log.append((rnd, f"kill_worker:{worker_id}"))
            return True
        return False

    def kill_on_job(self, worker_id: str, rnd: int, split: int) -> bool:
        """Worker-side, after TAKING a job: True -> die holding it."""
        c = self.config.kill_split
        if (c is not None and not self._killed_split
                and rnd == int(c["round"]) and split == int(c["split"])):
            self._killed_split = True
            self.log.append((rnd, f"kill_split:{split}:{worker_id}"))
            return True
        return False

    def stall_on_job(self, worker_id: str, rnd: int,
                     split: int) -> Optional[float]:
        """Worker-side, after taking a job: seconds to go silent for
        (heartbeats suppressed by the silence itself), or None."""
        c = self.config.stall_heartbeat
        if (c is not None and not self._stalled
                and rnd == int(c["round"]) and split == int(c["split"])):
            self._stalled = True
            self.log.append((rnd, f"stall_heartbeat:{split}:{worker_id}"))
            return float(c.get("sleep_s", 1.0))
        return None

    def on_membership_poll(self, rnd: int) -> None:
        """Coordinator-side, before each membership poll."""
        c = self.config.partition_coordinator
        if (c is not None and rnd == int(c.get("at_round", -1))
                and self._partition_polls_left > 0):
            self._partition_polls_left -= 1
            self.log.append((rnd, "partition"))
            raise CoordinatorPartitioned(
                f"injected membership-plane partition at round {rnd} "
                f"({self._partition_polls_left} polls left)")


# ---------------------------------------------------------------------------
# Serving faults (ISSUE 8): deterministic failures for the serving
# resilience plane (serving/resilience.py + engine/batcher/registry/decode
# surgery) — a raising model, a hung device call (the documented
# stale-tunnel wedge: ~0 CPU, no error), a slow dispatch, a bad rollout
# (load/warmup raising), and a crashing decode-slot admission. Same
# contract as ChaosConfig/FleetChaosConfig: config-driven only, never
# ambient — an engine without a configured ServingChaos is byte-identical
# to one built before this module existed.
# ---------------------------------------------------------------------------


class InjectedServingFault(RuntimeError):
    """A chaos-injected serving failure (inference / load / warmup /
    decode admission)."""


@dataclass
class ServingChaosConfig:
    """Declarative serving fault plan. Indices are 1-based counts of the
    engine-side event they key on — batcher DISPATCHES for the infer
    faults (deterministic under coalescing: the k-th batch the worker
    dispatches, regardless of which requests rode in it), decode
    ADMISSIONS for admit_raise_at.

      infer_raise_at    — dispatches [k, k+infer_raise_count) raise
                          :class:`InjectedServingFault` (the flaky-model
                          path: consecutive failures walk the breaker
                          SERVING -> DEGRADED -> BROKEN).
      infer_hang_at     — dispatch k blocks for ``infer_hang_s`` seconds
                          (or until :meth:`ServingChaos.release_hangs`)
                          with no error and ~0 CPU — the stale-tunnel
                          signature the watchdog must detect. The hung
                          call eventually RETURNS (a test must not leak a
                          forever-thread), but by then the watchdog has
                          failed its futures and fenced its worker, so
                          the late completion must be a no-op.
      slow_infer_at     — dispatch k sleeps ``slow_infer_s`` then
                          succeeds (latency degradation WITHOUT failure:
                          the breaker must NOT open; drain must wait).
      load_fail_name    — registry.load(name) raises (bad rollout: the
                          record lands BROKEN, prior serving version
                          keeps live).
      warmup_fail_name  — registry.warmup(name) raises (same isolation).
      admit_raise_at    — the k-th continuous-decode slot admission
                          raises (the crashed slot is evicted + its
                          future failed without poisoning co-residents).
    """

    infer_raise_at: Optional[int] = None
    infer_raise_count: int = 1
    infer_hang_at: Optional[int] = None
    infer_hang_s: float = 3600.0
    slow_infer_at: Optional[int] = None
    slow_infer_s: float = 0.0
    load_fail_name: Optional[str] = None
    warmup_fail_name: Optional[str] = None
    admit_raise_at: Optional[int] = None


class ServingChaos:
    """Stateful executor of a :class:`ServingChaosConfig`, consulted by
    the engine's batcher infer closure (per dispatch), the registry
    (load/warmup) and the continuous decoder (slot admission).
    Deterministic: the same config against the same dispatch/admission
    sequence injects the same faults."""

    def __init__(self, config: ServingChaosConfig):
        if isinstance(config, dict):
            config = ServingChaosConfig(**config)
        self.config = config
        self._dispatches = 0
        self._admits = 0
        self._lock = threading.Lock()
        # a test can release an injected hang at teardown instead of
        # leaking a sleeping daemon thread for infer_hang_s
        self._hang_release = threading.Event()
        self.log: list = []  # (index, fault) audit trail for tests

    def release_hangs(self) -> None:
        self._hang_release.set()

    def on_infer(self) -> None:
        """Engine-side, at each batcher dispatch, BEFORE the model call."""
        c = self.config
        with self._lock:
            self._dispatches += 1
            k = self._dispatches
        if c.slow_infer_at is not None and k == c.slow_infer_at:
            self.log.append((k, "slow_infer"))
            time.sleep(c.slow_infer_s)
        if c.infer_hang_at is not None and k == c.infer_hang_at:
            self.log.append((k, "infer_hang"))
            # the wedge: block quietly (~0 CPU, no error) — exactly the
            # stale-tunnel failure mode; returns when released or after
            # infer_hang_s so tests never leak a forever-thread
            self._hang_release.wait(timeout=c.infer_hang_s)
            return
        if (c.infer_raise_at is not None
                and c.infer_raise_at <= k
                < c.infer_raise_at + c.infer_raise_count):
            self.log.append((k, "infer_raise"))
            raise InjectedServingFault(
                f"injected inference failure at dispatch {k}")

    def on_load(self, name: str) -> None:
        """Registry-side, inside load() before the record is installed."""
        if (self.config.load_fail_name is not None
                and name == self.config.load_fail_name):
            self.log.append((name, "load_fail"))
            raise InjectedServingFault(f"injected load failure for {name!r}")

    def on_warmup(self, name: str) -> None:
        """Registry-side, at the head of warmup()."""
        if (self.config.warmup_fail_name is not None
                and name == self.config.warmup_fail_name):
            self.log.append((name, "warmup_fail"))
            raise InjectedServingFault(
                f"injected warmup failure for {name!r}")

    def on_admit(self) -> None:
        """Decoder-side, per slot admission, BEFORE the prefill."""
        c = self.config
        with self._lock:
            self._admits += 1
            k = self._admits
        if c.admit_raise_at is not None and k == c.admit_raise_at:
            self.log.append((k, "admit_raise"))
            raise InjectedServingFault(
                f"injected decode-slot crash at admission {k}")


# ---------------------------------------------------------------------------
# Serving-fleet faults (ISSUE 12): deterministic failures for the
# replicated serving tier (serving/fleet.py + serving/router.py) — a
# replica killed mid-request-stream (the observed dominant failure mode on
# this host: process death, BENCH_r02–r05) and a router-side partition to
# one replica (connect failures without any process dying — the breaker
# ejection/half-open-readmission path). Same contract as the other
# configs: config-driven only, never ambient — a router without a
# configured RouterChaos is byte-identical to one built before this
# existed.
# ---------------------------------------------------------------------------


class ReplicaPartitioned(ConnectionError):
    """A chaos-injected router->replica partition: the router's HTTP call
    fails at connect time exactly as if the replica's port went away —
    the replica-breaker vote path, without any process actually dying."""


@dataclass
class RouterChaosConfig:
    """Declarative fleet-serving fault plan. Counts are 1-based over the
    router-side event they key on — PROXIED requests for kill_replica
    (deterministic under concurrency: the k-th request the router
    completes, whichever replica served it), per-replica CALL attempts
    for partition_replica.

      kill_replica      — {"replica": id, "after_proxied": k}: once the
                          router has completed k requests, replica `id`
                          is killed HARD (no drain, no goodbye — the
                          router's kill hook enacts it via
                          ServingFleet.kill_replica). Heartbeat expiry
                          and connect errors must between them detect
                          the death; every already-admitted /predict
                          must be answered by a survivor.
      partition_replica — {"replica": id, "calls": k}: the first k
                          router->replica calls addressed to `id` raise
                          :class:`ReplicaPartitioned` before any bytes
                          are sent; the breaker walks the replica to
                          ejection, then half-open probes re-admit it
                          once the partition heals (calls exhausted).
    """

    kill_replica: Optional[dict] = None
    partition_replica: Optional[dict] = None


class RouterChaos:
    """Stateful executor of a :class:`RouterChaosConfig`, consulted by
    the FleetRouter (per replica call and per completed proxy). The
    router never owns replica processes, so :meth:`kill_due` only
    RETURNS the victim id — the fleet's kill hook enacts it (the same
    decide-vs-enact split as FleetChaos.kill_on_poll). Deterministic:
    the same config against the same request sequence injects the same
    faults exactly once each."""

    def __init__(self, config: RouterChaosConfig):
        if isinstance(config, dict):
            config = RouterChaosConfig(**config)
        self.config = config
        c = config.partition_replica or {}
        self._partition_calls_left = int(c.get("calls", 0))
        self._killed = False
        self._proxied = 0
        self._lock = threading.Lock()
        self.log: list = []  # (count, fault) audit trail for tests

    def on_replica_call(self, replica_id: str) -> None:
        """Router-side, before each HTTP call to `replica_id`."""
        c = self.config.partition_replica
        if c is None or replica_id != c.get("replica"):
            return
        with self._lock:
            if self._partition_calls_left <= 0:
                return
            self._partition_calls_left -= 1
            left = self._partition_calls_left
            self.log.append((replica_id, "partition"))
        raise ReplicaPartitioned(
            f"injected router partition to {replica_id!r} "
            f"({left} calls left)")

    def kill_due(self) -> Optional[str]:
        """Router-side, after each COMPLETED proxy: the replica id to
        kill now, or None. Fires at most once."""
        c = self.config.kill_replica
        with self._lock:
            self._proxied += 1
            if (c is None or self._killed
                    or self._proxied < int(c.get("after_proxied", 1))):
                return None
            self._killed = True
            self.log.append((self._proxied, f"kill_replica:{c['replica']}"))
            return str(c["replica"])


@dataclass
class LowPrecChaosConfig:
    """Declarative overflow plan for the bf16 loss-scaling contract
    (ops/lowprec.py): poison the FEATURES of step ``overflow_at_step``
    (1-based) so the backward pass produces non-finite grads and the
    dynamic loss scale must halve-and-skip. Config-driven, never ambient
    — the test loop calls :meth:`LowPrecChaos.poison` explicitly."""

    overflow_at_step: Optional[int] = None
    mode: str = "inf"  # "inf" | "nan"
    count: int = 1     # consecutive poisoned steps from overflow_at_step

    def __post_init__(self):
        if self.mode not in ("inf", "nan"):
            raise ValueError(f"unknown overflow mode {self.mode!r}")
        if self.count < 1:
            raise ValueError("count must be >= 1")


class LowPrecChaos:
    """Stateful executor of a :class:`LowPrecChaosConfig` (the ChaosMonkey
    shape). Deterministic: poisons element [0, ...] of the feature batch
    for the configured step window, leaves every other step untouched."""

    def __init__(self, config: LowPrecChaosConfig):
        if isinstance(config, dict):
            config = LowPrecChaosConfig(**config)
        self.config = config
        self.log: list = []  # (step, fault) audit trail for tests

    def poison(self, step: int, features):
        """`step` is the 1-based index of the step about to run. Returns
        the features to feed it (a poisoned COPY on fault steps — the
        caller's array is never mutated)."""
        c = self.config
        if (c.overflow_at_step is None
                or not (c.overflow_at_step <= step
                        < c.overflow_at_step + c.count)):
            return features
        import numpy as np

        bad = np.array(features, dtype=np.float32, copy=True)
        bad.reshape(-1)[0] = np.inf if c.mode == "inf" else np.nan
        self.log.append((step, f"overflow:{c.mode}"))
        return bad


@dataclass
class SpecChaosConfig:
    """Declarative all-reject plan for the speculative-decode acceptance
    contract (serving/speculate.py): corrupt the draft's proposals for
    round ``reject_at_round`` (1-based) so the target's greedy choice
    disagrees at every position — the all-reject path must discard the
    whole draft suffix and still commit the target's own first token,
    byte-exact vs target-only decoding. Config-driven, never ambient."""

    reject_at_round: Optional[int] = None
    count: int = 1     # consecutive corrupted rounds from reject_at_round

    def __post_init__(self):
        if self.count < 1:
            raise ValueError("count must be >= 1")


class SpecChaos:
    """Stateful executor of a :class:`SpecChaosConfig`. The corruption
    fires at ACCEPTANCE-COMPARISON time, after the verify dispatch ran on
    the true proposals: each proposal becomes (target_greedy + 1) % vocab,
    which can never match, so the round rejects everything deterministically.
    This is byte-safe by the all-reject commit rule — the only token an
    all-reject round commits is the target's first correction, which is a
    function of the last COMMITTED token and no proposal at all."""

    def __init__(self, config: SpecChaosConfig):
        if isinstance(config, dict):
            config = SpecChaosConfig(**config)
        self.config = config
        self.log: list = []  # (round, fault) audit trail for tests

    def corrupt(self, round_idx: int, proposed, target_greedy,
                vocab_size: int):
        """``round_idx`` is the 1-based speculative round about to score
        acceptance. Returns the proposals to compare (a corrupted COPY on
        fault rounds — the caller's array is never mutated)."""
        c = self.config
        if (c.reject_at_round is None
                or not (c.reject_at_round <= round_idx
                        < c.reject_at_round + c.count)):
            return proposed
        import numpy as np

        bad = np.array(proposed, dtype=np.int32, copy=True)
        g = np.asarray(target_greedy, np.int32).reshape(-1)[:bad.size]
        bad[:] = (g + 1) % int(vocab_size)
        self.log.append((round_idx, "reject_all"))
        return bad


@dataclass
class AutoscaleChaosConfig:
    """Declarative load-wave plan for the autoscaler's decision loop
    (serving/autoscale.py): overlay the SCRAPED /signals snapshot for a
    scripted tick window so scale decisions can be forced and replayed
    without generating real traffic. The chaos corrupts the DECISION
    INPUT only — the autoscaler still decides, and the fleet's
    spawn/depart hooks still enact (decide-vs-enact). Config-driven,
    never ambient.

      load_wave — {"at_tick": t, "ticks": n, "queue_depth": q[,
                  "sheds_per_tick": s]}: ticks t..t+n-1 (1-based) see
                  total queue depth q (and, optionally, s new router
                  sheds per tick) in place of the measured values;
                  outside the window the snapshot passes untouched.
    """

    load_wave: Optional[dict] = None

    def __post_init__(self):
        c = self.load_wave
        if c is None:
            return
        if int(c.get("ticks", 1)) < 1:
            raise ValueError("load_wave ticks must be >= 1")
        if "queue_depth" not in c:
            raise ValueError("load_wave needs queue_depth")


class AutoscaleChaos:
    """Stateful executor of an :class:`AutoscaleChaosConfig` (the
    LowPrecChaos shape): :meth:`on_signals` returns the snapshot to
    decide on — an overlaid COPY on wave ticks, the caller's dict
    untouched. Deterministic: the same config over the same tick
    sequence overlays the same values, so a replay of the recorded
    post-overlay signal log reproduces the decision list bit-exact."""

    def __init__(self, config: AutoscaleChaosConfig):
        if isinstance(config, dict):
            config = AutoscaleChaosConfig(**config)
        self.config = config
        self.log: list = []  # (tick, fault) audit trail for tests

    def on_signals(self, tick: int, signals: dict) -> dict:
        """``tick`` is the 1-based autoscaler tick about to decide."""
        c = self.config.load_wave
        if c is None:
            return signals
        at = int(c.get("at_tick", 1))
        if not (at <= tick < at + int(c.get("ticks", 1))):
            return signals
        out = dict(signals)
        out["queue_depth"] = int(c["queue_depth"])
        sheds = int(c.get("sheds_per_tick", 0))
        if sheds:
            # cumulative: the decision loop votes on per-tick DELTAS
            out["shed_total"] = (int(signals.get("shed_total", 0))
                                 + sheds * (tick - at + 1))
        self.log.append((tick, f"load_wave:q={out['queue_depth']}"))
        return out


def truncate_file(path: str, keep: int = 16) -> None:
    """Write-then-truncate fault: keep only the first `keep` bytes (a
    crash mid-write that an atomic rename would normally prevent —
    simulates torn storage underneath the checkpoint)."""
    with open(path, "r+b") as f:
        f.truncate(keep)


def bitflip_file(path: str, offset: Optional[int] = None) -> None:
    """Flip one bit of `path` in place (silent media corruption). With no
    offset the middle byte is flipped — deterministic, no RNG."""
    size = os.path.getsize(path)
    if size == 0:
        return
    off = size // 2 if offset is None else int(offset) % size
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0x01]))
