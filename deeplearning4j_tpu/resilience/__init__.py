"""Fault-tolerant training runtime.

The resilience analogue of the repo's distributed==serial convention:
interrupted-and-resumed training == uninterrupted training, proven under
deterministically injected faults. See checkpoint.py (async atomic
CheckpointManager), trainer.py (ResilientTrainer: preemption +
restore-and-continue + retry), chaos.py (the fault-injection harness the
tests drive — never ambient).
"""

from deeplearning4j_tpu.resilience.chaos import (  # noqa: F401
    AutoscaleChaos,
    AutoscaleChaosConfig,
    ChaosConfig,
    ChaosMonkey,
    CoordinatorPartitioned,
    FleetChaos,
    FleetChaosConfig,
    InjectedKill,
    InjectedServingFault,
    LowPrecChaos,
    LowPrecChaosConfig,
    ReplicaPartitioned,
    RouterChaos,
    RouterChaosConfig,
    ServingChaos,
    ServingChaosConfig,
    SpecChaos,
    SpecChaosConfig,
    TransientDeviceError,
)
from deeplearning4j_tpu.resilience.checkpoint import (  # noqa: F401
    CheckpointCorrupt,
    CheckpointManager,
)
from deeplearning4j_tpu.resilience.trainer import (  # noqa: F401
    Preempted,
    ResilientTrainer,
)
