"""Graph data structures.

Capability mirror of the reference deeplearning4j-graph api/graph packages
(deeplearning4j-graph/.../graph/api/{IGraph,Vertex,Edge}.java and
graph/graph/Graph.java): vertex objects with optional values, directed or
undirected edges with weights, adjacency-list storage, degree queries, and
random connected-vertex sampling (Graph.getRandomConnectedVertex, used by
the walk iterators).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Generic, List, Optional, Sequence, TypeVar

import numpy as np

V = TypeVar("V")


@dataclass
class Vertex(Generic[V]):
    """Reference api/Vertex.java: index + value."""

    idx: int
    value: Any = None


@dataclass
class Edge:
    """Reference api/Edge.java: from/to + optional weight + directed flag."""

    src: int
    dst: int
    weight: float = 1.0
    directed: bool = False


class Graph:
    """Adjacency-list graph (reference graph/Graph.java)."""

    def __init__(self, num_vertices: int, directed: bool = False,
                 vertex_values: Optional[Sequence[Any]] = None):
        self.directed = directed
        self._vertices = [
            Vertex(i, vertex_values[i] if vertex_values is not None else None)
            for i in range(num_vertices)
        ]
        self._adj: List[List[Edge]] = [[] for _ in range(num_vertices)]

    # -- construction -----------------------------------------------------
    def add_edge(self, src: int, dst: int, weight: float = 1.0,
                 directed: Optional[bool] = None) -> None:
        directed = self.directed if directed is None else directed
        e = Edge(src, dst, weight, directed)
        self._adj[src].append(e)
        if not directed and src != dst:
            self._adj[dst].append(Edge(dst, src, weight, directed))

    # -- queries (IGraph surface) -----------------------------------------
    def num_vertices(self) -> int:
        return len(self._vertices)

    def get_vertex(self, idx: int) -> Vertex:
        return self._vertices[idx]

    def get_vertex_degree(self, idx: int) -> int:
        return len(self._adj[idx])

    def get_edges_out(self, idx: int) -> List[Edge]:
        return list(self._adj[idx])

    def get_connected_vertex_indices(self, idx: int) -> List[int]:
        return [e.dst for e in self._adj[idx]]

    def get_random_connected_vertex(self, idx: int, rng: np.random.Generator) -> int:
        """Uniform neighbor choice (Graph.getRandomConnectedVertex)."""
        nbrs = self._adj[idx]
        if not nbrs:
            raise NoEdgesException(f"vertex {idx} has no outgoing edges")
        return nbrs[int(rng.integers(0, len(nbrs)))].dst

    def degrees(self) -> np.ndarray:
        return np.array([len(a) for a in self._adj], np.int64)


class NoEdgesException(Exception):
    """Reference exception/NoEdgesException.java."""
