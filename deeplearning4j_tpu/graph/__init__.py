"""Graph embeddings — capability surface of deeplearning4j-graph
(SURVEY.md section 2.4): Graph/IGraph adjacency structures, edge/vertex
loaders, random-walk iterators, DeepWalk (random walks + hierarchical-softmax
skip-gram), GraphHuffman coding, graph-vector serialization."""

from deeplearning4j_tpu.graph.api import Edge, Graph, Vertex
from deeplearning4j_tpu.graph.loaders import (
    load_delimited_edges,
    load_weighted_edges,
)
from deeplearning4j_tpu.graph.walks import (
    NoEdgeHandling,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.graph.deepwalk import DeepWalk, build_graph_huffman

__all__ = [
    "Edge",
    "Graph",
    "Vertex",
    "load_delimited_edges",
    "load_weighted_edges",
    "NoEdgeHandling",
    "RandomWalkIterator",
    "WeightedRandomWalkIterator",
    "DeepWalk",
    "build_graph_huffman",
]
