"""Graph loaders.

Capability mirror of the reference data package
(deeplearning4j-graph/.../graph/data/GraphLoader.java with
DelimitedEdgeLineProcessor / WeightedEdgeLineProcessor /
DelimitedVertexLoader): parse "src<delim>dst[<delim>weight]" edge-list
files into Graph objects, skipping comment lines.
"""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.graph.api import Graph


def load_delimited_edges(
    path: str,
    num_vertices: int,
    delimiter: str = ",",
    directed: bool = False,
    comment_prefix: str = "//",
) -> Graph:
    """GraphLoader.loadUndirectedGraphEdgeListFile equivalent."""
    g = Graph(num_vertices, directed=directed)
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith(comment_prefix):
                continue
            parts = line.split(delimiter)
            g.add_edge(int(parts[0]), int(parts[1]))
    return g


def load_weighted_edges(
    path: str,
    num_vertices: int,
    delimiter: str = ",",
    directed: bool = False,
    comment_prefix: str = "//",
) -> Graph:
    """GraphLoader.loadWeightedEdgeListFile equivalent (weight in col 3)."""
    g = Graph(num_vertices, directed=directed)
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith(comment_prefix):
                continue
            parts = line.split(delimiter)
            w = float(parts[2]) if len(parts) > 2 else 1.0
            g.add_edge(int(parts[0]), int(parts[1]), weight=w)
    return g
