"""DeepWalk: random walks + hierarchical-softmax skip-gram on vertices.

Capability mirror of the reference
(deeplearning4j-graph/.../graph/models/deepwalk/DeepWalk.java:37: initialize
builds a Huffman tree over VERTEX DEGREES (initialize(int[]) — degree plays
the word-frequency role), then walks are consumed as "sentences" and each
(center, context) vertex pair does an HS skip-gram update through
InMemoryGraphLookupTable.trainVertexPair; GraphHuffman.java for the coding;
query surface GraphVectorsImpl: similarity/verticesNearest;
GraphVectorSerializer for IO).

TPU-native: walks are generated on host (numpy), all pairs batched, and the
SAME jitted HS step as word2vec (`_skipgram_hs_step` — gathers + sigmoid +
scatter-mean) trains vertex vectors. One XLA program instead of one thread
per GraphWalkIterator.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.graph.api import Graph
from deeplearning4j_tpu.graph.walks import (
    NoEdgeHandling,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)
from deeplearning4j_tpu.nlp.huffman import build_huffman
from deeplearning4j_tpu.nlp.vocab import VocabWord
from deeplearning4j_tpu.nlp.word2vec import _pad_batch, _skipgram_hs_step


def build_graph_huffman(degrees: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Huffman codes over vertex degrees (GraphHuffman.buildTree — degree ==
    frequency). Returns (points, codes, mask) padded tensors INDEXED BY
    VERTEX ID (the reference keeps codes in vertex order too)."""
    n = len(degrees)
    words = [VocabWord(word=str(i), count=max(1.0, float(degrees[i])), index=i)
             for i in range(n)]
    order = sorted(range(n), key=lambda i: (-words[i].count, i))
    sorted_words = [words[i] for i in order]
    build_huffman(sorted_words)
    L = max(len(w.codes) for w in sorted_words)
    points = np.zeros((n, L), np.int32)
    codes = np.zeros((n, L), np.float32)
    mask = np.zeros((n, L), np.float32)
    for w in words:  # codes were attached in-place through sorted_words refs
        l = len(w.codes)
        points[w.index, :l] = w.points[:l]
        codes[w.index, :l] = w.codes[:l]
        mask[w.index, :l] = 1.0
    return points, codes, mask


class DeepWalk:
    """Reference DeepWalk builder surface: vectorSize, windowSize,
    learningRate, seed (DeepWalk.Builder)."""

    def __init__(
        self,
        vector_size: int = 100,
        window_size: int = 5,
        learning_rate: float = 0.01,
        seed: int = 12345,
        batch_size: int = 2048,
    ):
        self.vector_size = vector_size
        self.window_size = window_size
        self.learning_rate = learning_rate
        self.seed = seed
        self.batch_size = batch_size
        self.num_vertices = 0
        self.vertex_vectors: Optional[np.ndarray] = None  # syn0
        self._syn1: Optional[np.ndarray] = None
        self._huffman: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None
        self._init_called = False

    # -- init -------------------------------------------------------------
    def initialize(self, graph_or_degrees) -> "DeepWalk":
        """Build degree-based Huffman tree + lookup table
        (DeepWalk.initialize :85-105)."""
        degrees = (
            graph_or_degrees.degrees()
            if isinstance(graph_or_degrees, Graph)
            else np.asarray(graph_or_degrees, np.int64)
        )
        n = len(degrees)
        self.num_vertices = n
        self._huffman = build_graph_huffman(degrees)
        rng = np.random.default_rng(self.seed)
        self.vertex_vectors = (
            (rng.random((n, self.vector_size)) - 0.5) / self.vector_size
        ).astype(np.float32)
        self._syn1 = np.zeros((n, self.vector_size), np.float32)
        self._init_called = True
        return self

    # -- training ---------------------------------------------------------
    def fit(self, graph: Graph, walk_length: int = 40, epochs: int = 1,
            weighted: bool = False) -> "DeepWalk":
        """Generate walks (one per vertex per epoch) and train
        (DeepWalk.fit(IGraph,int) :100-115 + fit(iteratorProvider))."""
        if not self._init_called:
            self.initialize(graph)
        it_cls = WeightedRandomWalkIterator if weighted else RandomWalkIterator
        for epoch in range(epochs):
            walks = list(
                it_cls(
                    graph,
                    walk_length,
                    seed=self.seed + epoch,
                    no_edge_handling=NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
                )
            )
            self.fit_walks(walks)
        return self

    def fit_walks(self, walks: Sequence[np.ndarray]) -> "DeepWalk":
        """Train on explicit walk sequences (DeepWalk.fit(GraphWalkIterator)
        — each walk is a sentence; window pairs like word2vec skipGram)."""
        if not self._init_called:
            raise RuntimeError("DeepWalk not initialized (call initialize first)")
        P, C, M = self._huffman
        w = self.window_size
        rng = np.random.default_rng(self.seed)
        centers, contexts = [], []
        for walk in walks:
            n = len(walk)
            for i in range(n):
                lo, hi = max(0, i - w), min(n, i + w + 1)
                for c in range(lo, hi):
                    if c != i:
                        centers.append(walk[i])
                        contexts.append(walk[c])
        if not centers:
            return self
        centers = np.asarray(centers, np.int32)
        contexts = np.asarray(contexts, np.int32)
        order = rng.permutation(len(centers))
        centers, contexts = centers[order], contexts[order]

        syn0 = jnp.asarray(self.vertex_vectors)
        syn1 = jnp.asarray(self._syn1)
        B = self.batch_size
        for bi in range(-(-len(centers) // B)):
            sl = slice(bi * B, (bi + 1) * B)
            cen, cx = centers[sl], contexts[sl]
            npad = len(cen)
            cen, cx = _pad_batch(cen, B), _pad_batch(cx, B)
            pad_live = (np.arange(B) < npad).astype(np.float32)
            syn0, syn1 = _skipgram_hs_step(
                syn0, syn1, jnp.asarray(cx),
                jnp.asarray(P[cen]), jnp.asarray(C[cen]),
                jnp.asarray(M[cen] * pad_live[:, None]),
                jnp.float32(self.learning_rate),
            )
        self.vertex_vectors = np.asarray(syn0)
        self._syn1 = np.asarray(syn1)
        return self

    # -- query surface (GraphVectorsImpl) ---------------------------------
    def get_vertex_vector(self, idx: int) -> np.ndarray:
        return self.vertex_vectors[idx]

    def similarity(self, v1: int, v2: int) -> float:
        """Cosine similarity (GraphVectorsImpl.similarity)."""
        a, b = self.vertex_vectors[v1], self.vertex_vectors[v2]
        denom = float(np.linalg.norm(a) * np.linalg.norm(b)) or 1.0
        return float(np.dot(a, b) / denom)

    def vertices_nearest(self, idx: int, top_n: int = 10) -> List[int]:
        v = self.vertex_vectors[idx]
        norms = np.linalg.norm(self.vertex_vectors, axis=1)
        norms = np.where(norms == 0, 1.0, norms)
        sims = self.vertex_vectors @ v / (norms * (np.linalg.norm(v) or 1.0))
        order = [int(i) for i in np.argsort(-sims) if int(i) != idx]
        return order[:top_n]

    # -- IO (GraphVectorSerializer) ---------------------------------------
    def save(self, path: str) -> None:
        np.savez(
            path,
            vertex_vectors=self.vertex_vectors,
            syn1=self._syn1,
            points=self._huffman[0],
            codes=self._huffman[1],
            mask=self._huffman[2],
            meta=np.array(
                [self.vector_size, self.window_size, self.num_vertices], np.int64
            ),
            lr=np.array([self.learning_rate], np.float64),
        )

    @classmethod
    def load(cls, path: str) -> "DeepWalk":
        data = np.load(path if path.endswith(".npz") else path + ".npz")
        vs, ws, n = (int(x) for x in data["meta"])
        dw = cls(vector_size=vs, window_size=ws, learning_rate=float(data["lr"][0]))
        dw.num_vertices = n
        dw.vertex_vectors = data["vertex_vectors"]
        dw._syn1 = data["syn1"]
        dw._huffman = (data["points"], data["codes"], data["mask"])
        dw._init_called = True
        return dw
