"""Random-walk iterators over graphs.

Capability mirror of the reference iterator package
(deeplearning4j-graph/.../graph/iterator/RandomWalkIterator.java,
WeightedRandomWalkIterator.java, api/NoEdgeHandling.java): fixed-length
walks starting from each vertex in order, uniform or edge-weight-proportional
neighbor transition, SELF_LOOP or EXCEPTION handling for dangling vertices.
"""

from __future__ import annotations

from enum import Enum
from typing import Iterator, Optional

import numpy as np

from deeplearning4j_tpu.graph.api import Graph, NoEdgesException


class NoEdgeHandling(Enum):
    SELF_LOOP_ON_DISCONNECTED = "self_loop"
    EXCEPTION_ON_DISCONNECTED = "exception"


class RandomWalkIterator:
    """Uniform random walks of fixed length, one starting at each vertex
    0..n-1 (RandomWalkIterator.java)."""

    def __init__(
        self,
        graph: Graph,
        walk_length: int,
        seed: int = 12345,
        no_edge_handling: NoEdgeHandling = NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
        first_vertex: int = 0,
        last_vertex: Optional[int] = None,
    ):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.no_edge_handling = no_edge_handling
        self.first_vertex = first_vertex
        self.last_vertex = (
            graph.num_vertices() if last_vertex is None else last_vertex
        )

    def _next_vertex(self, cur: int, rng: np.random.Generator) -> int:
        if self.graph.get_vertex_degree(cur) == 0:
            if self.no_edge_handling is NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED:
                return cur
            raise NoEdgesException(f"vertex {cur} has no edges mid-walk")
        return self.graph.get_random_connected_vertex(cur, rng)

    def __iter__(self) -> Iterator[np.ndarray]:
        rng = np.random.default_rng(self.seed)
        for start in range(self.first_vertex, self.last_vertex):
            walk = np.empty((self.walk_length + 1,), np.int32)
            walk[0] = start
            cur = start
            for t in range(1, self.walk_length + 1):
                cur = self._next_vertex(cur, rng)
                walk[t] = cur
            yield walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Transition probability proportional to edge weight
    (WeightedRandomWalkIterator.java)."""

    def _next_vertex(self, cur: int, rng: np.random.Generator) -> int:
        edges = self.graph.get_edges_out(cur)
        if not edges:
            if self.no_edge_handling is NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED:
                return cur
            raise NoEdgesException(f"vertex {cur} has no edges mid-walk")
        weights = np.array([e.weight for e in edges], np.float64)
        probs = weights / weights.sum()
        return edges[int(rng.choice(len(edges), p=probs))].dst
