from deeplearning4j_tpu.eval.evaluation import (
    ConfusionMatrix,
    Evaluation,
    RegressionEvaluation,
    ROC,
)
