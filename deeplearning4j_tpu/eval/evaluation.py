"""Classification / regression evaluation.

Mirrors the reference's ``eval`` package (SURVEY.md section 2.1):
``Evaluation`` (868 LoC — accuracy/precision/recall/F1 from a ConfusionMatrix,
eval(realOutcomes, guesses) at Evaluation.java:168, time-series + masked
variants, stats() report, merge() at :795 for distributed reduce),
``RegressionEvaluation`` (MSE/MAE/RMSE/R2 per column), ``ConfusionMatrix``.

Host-side numpy: evaluation is not in the jit hot path; outputs are devices'
batched argmax results. `merge` supports the map-reduce distributed eval
pattern (dl4j-spark EvaluationReduceFunction.java:18-19).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Optional

import numpy as np


class ConfusionMatrix:
    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        self.matrix = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual: int, predicted: int, count: int = 1):
        self.matrix[actual, predicted] += count

    def count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def merge(self, other: "ConfusionMatrix"):
        self.matrix += other.matrix

    def __str__(self):
        return str(self.matrix)


class Evaluation:
    """Multi-class classification metrics (reference eval/Evaluation.java)."""

    def __init__(self, num_classes: Optional[int] = None,
                 labels: Optional[List[str]] = None, top_n: int = 1):
        self.num_classes = num_classes
        self.label_names = labels
        self.confusion: Optional[ConfusionMatrix] = None
        # top-N accuracy (later-DL4J Evaluation(topN) surface, beyond the
        # 0.4 reference): counted from full prediction vectors at eval time
        self.top_n = max(1, int(top_n))
        self._topn_correct = 0
        self._topn_total = 0

    def _ensure(self, n: int):
        if self.confusion is None:
            self.num_classes = self.num_classes or n
            self.confusion = ConfusionMatrix(self.num_classes)

    def eval(self, labels, predictions, mask=None):
        """labels/predictions: [N, C] one-hot/probabilities, or time series
        [N, T, C] with optional mask [N, T] (reference time-series variants)."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            n, t, c = labels.shape
            labels = labels.reshape(n * t, c)
            predictions = predictions.reshape(n * t, c)
            if mask is not None:
                flat = np.asarray(mask).reshape(n * t).astype(bool)
                labels = labels[flat]
                predictions = predictions[flat]
        self._ensure(labels.shape[-1])
        actual = labels.argmax(axis=-1)
        guess = predictions.argmax(axis=-1)
        for a, g in zip(actual, guess):
            self.confusion.add(int(a), int(g))
        if self.top_n > 1:
            k = min(self.top_n, predictions.shape[-1])
            topk = np.argpartition(-predictions, k - 1, axis=-1)[:, :k]
            self._topn_correct += int((topk == actual[:, None]).any(-1).sum())
        else:
            self._topn_correct += int((guess == actual).sum())
        self._topn_total += len(actual)

    # -- metrics ------------------------------------------------------------
    @property
    def _m(self):
        if self.confusion is None:
            raise ValueError("no evaluations recorded")
        return self.confusion.matrix

    def accuracy(self) -> float:
        m = self._m
        total = m.sum()
        return float(np.trace(m)) / total if total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        m = self._m
        if cls is not None:
            denom = m[:, cls].sum()
            return float(m[cls, cls]) / denom if denom else 0.0
        vals = [self.precision(c) for c in range(m.shape[0]) if m[:, c].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        m = self._m
        if cls is not None:
            denom = m[cls, :].sum()
            return float(m[cls, cls]) / denom if denom else 0.0
        vals = [self.recall(c) for c in range(m.shape[0]) if m[c, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def top_n_accuracy(self) -> float:
        if self._topn_total == 0:
            raise ValueError("no evaluations recorded")
        return self._topn_correct / self._topn_total

    def merge(self, other: "Evaluation"):
        """Distributed-eval reduce (reference Evaluation.merge :795)."""
        if other._topn_total and other.top_n != self.top_n:
            raise ValueError(
                f"cannot merge Evaluation(top_n={other.top_n}) into "
                f"Evaluation(top_n={self.top_n}) — the summed counters "
                "would blend different metrics")
        self._topn_correct += other._topn_correct
        self._topn_total += other._topn_total
        if other.confusion is None:
            return self
        if self.confusion is None:
            self.num_classes = other.num_classes
            self.confusion = ConfusionMatrix(other.num_classes)
        self.confusion.merge(other.confusion)
        return self

    def stats(self) -> str:
        m = self._m
        lines = [
            "==========================Scores========================================",
            f" Accuracy:  {self.accuracy():.4f}",
        ]
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} Accuracy: "
                         f"{self.top_n_accuracy():.4f}")
        lines += [
            f" Precision: {self.precision():.4f}",
            f" Recall:    {self.recall():.4f}",
            f" F1 Score:  {self.f1():.4f}",
            "========================================================================",
            "Confusion matrix:",
            str(self.confusion),
        ]
        return "\n".join(lines)


class RegressionEvaluation:
    """Per-column regression metrics (reference eval/RegressionEvaluation.java):
    MSE, MAE, RMSE, RSE-based R^2, correlation."""

    def __init__(self, num_columns: Optional[int] = None):
        self.num_columns = num_columns
        self._labels: List[np.ndarray] = []
        self._preds: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, dtype=np.float64)
        predictions = np.asarray(predictions, dtype=np.float64)
        if labels.ndim == 3:
            n, t, c = labels.shape
            labels = labels.reshape(n * t, c)
            predictions = predictions.reshape(n * t, c)
            if mask is not None:
                flat = np.asarray(mask).reshape(n * t).astype(bool)
                labels = labels[flat]
                predictions = predictions[flat]
        self.num_columns = self.num_columns or labels.shape[-1]
        self._labels.append(labels)
        self._preds.append(predictions)

    def _stacked(self):
        return np.concatenate(self._labels), np.concatenate(self._preds)

    def mean_squared_error(self, col: int) -> float:
        l, p = self._stacked()
        return float(np.mean((l[:, col] - p[:, col]) ** 2))

    def mean_absolute_error(self, col: int) -> float:
        l, p = self._stacked()
        return float(np.mean(np.abs(l[:, col] - p[:, col])))

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int) -> float:
        l, p = self._stacked()
        ss_res = np.sum((l[:, col] - p[:, col]) ** 2)
        ss_tot = np.sum((l[:, col] - np.mean(l[:, col])) ** 2)
        return float(1.0 - ss_res / ss_tot) if ss_tot else 0.0

    def correlation_r2(self, col: int) -> float:
        l, p = self._stacked()
        if np.std(l[:, col]) == 0 or np.std(p[:, col]) == 0:
            return 0.0
        return float(np.corrcoef(l[:, col], p[:, col])[0, 1] ** 2)

    def stats(self) -> str:
        cols = self.num_columns or 0
        lines = ["column  MSE        MAE        RMSE       R^2"]
        for c in range(cols):
            lines.append(
                f"{c:<7d} {self.mean_squared_error(c):<10.5f} "
                f"{self.mean_absolute_error(c):<10.5f} "
                f"{self.root_mean_squared_error(c):<10.5f} "
                f"{self.r_squared(c):<10.5f}"
            )
        return "\n".join(lines)


class ROC:
    """Binary ROC / AUC (threshold sweep over predicted P(class 1)).

    Beyond the 0.4-era reference (whose eval/ stops at Evaluation +
    RegressionEvaluation; ROC arrived in later DL4J) but part of the eval
    surface users coming from any dl4j version expect. Exact
    trapezoidal AUC over the unique-score thresholds; merge() accumulates
    raw (score, label) pairs so distributed evaluation reduces the same
    way Evaluation.merge does."""

    def __init__(self):
        self._scores: List[float] = []
        self._labels: List[int] = []

    def eval(self, labels, probabilities) -> "ROC":
        """labels: [N] 0/1 ints or [N, 2] one-hot; probabilities: [N]
        P(positive) or [N, 2] class probabilities."""
        labels = np.asarray(labels)
        probs = np.asarray(probabilities, np.float64)
        if labels.ndim == 2:
            # (N, 1) column labels ARE the 0/1 values; only 2-column
            # one-hot gets argmax (argmax of a column is silently all-0)
            labels = (labels[:, 0] if labels.shape[1] == 1
                      else labels.argmax(axis=1))
        if probs.ndim == 2:
            # (N, 1) sigmoid output IS P(positive); (N, 2) takes column 1
            probs = probs[:, 0] if probs.shape[1] == 1 else probs[:, 1]
        self._labels.extend(int(v) for v in labels)
        self._scores.extend(float(v) for v in probs)
        return self

    def merge(self, other: "ROC") -> "ROC":
        self._labels.extend(other._labels)
        self._scores.extend(other._scores)
        return self

    def roc_curve(self):
        """(fpr, tpr) arrays over descending score thresholds."""
        if not self._labels:
            return np.zeros(0), np.zeros(0)
        y = np.asarray(self._labels)
        s = np.asarray(self._scores)
        order = np.argsort(-s, kind="stable")
        y = y[order]
        s = s[order]
        tps = np.cumsum(y)
        fps = np.cumsum(1 - y)
        # one operating point per unique threshold (last index of each run)
        last = np.r_[np.nonzero(np.diff(s))[0], len(s) - 1]
        tp, fp = tps[last], fps[last]
        p = int(y.sum())
        n = len(y) - p
        if p == 0 or n == 0:
            # single-class data: ROC is undefined (NOT 0.0 — an
            # all-positive batch must not report worst-possible AUC)
            return np.full(1, np.nan), np.full(1, np.nan)
        return np.r_[0.0, fp / n], np.r_[0.0, tp / p]

    def auc(self) -> float:
        fpr, tpr = self.roc_curve()
        if len(fpr) < 2 or np.isnan(fpr).any():
            return float("nan")
        return float(np.trapezoid(tpr, fpr))

    def stats(self) -> str:
        return (f"ROC: {len(self._labels)} examples, "
                f"{int(np.sum(self._labels))} positive, AUC {self.auc():.4f}")
