"""VectorStore: online-mutable vector index with atomic generation swaps.

The mutation/publish split re-proves the PR 14 promotion contract for
indexes (online/promote.py's atomic default swap): writers mutate a
STAGING arena — slot-addressed ``[capacity + 1, dim]`` device buffer,
row ``capacity`` a permanent zero TRASH row (the paged-KV block-0
argument, serving/paged.py), updated in place through a DONATED
``ops/dispatch.arena_jit`` scatter (single-owner accumulator: the store
always rebinds, never re-reads a donated input) — while readers search
an IMMUTABLE published :class:`~deeplearning4j_tpu.retrieval.index.
IndexSnapshot`. ``publish()`` packs live slots into a fresh device
arena (one jitted gather — no host->device re-upload of the corpus),
optionally trains the IVF quantizer, and swaps the published reference
atomically: in-flight ``/search`` readers keep the old generation's
buffers (searches never donate), so a swap fails ZERO admitted
requests by construction.

Publishes are gated like promotions: a latched
``online/drift.DriftMonitor`` alarm (live embedding moments past the z
bar) VETOES the publish (:class:`PublishVetoed` — journaled, counted,
the published generation unmoved). Feeds ride the PR 14
``StreamSource``: one :meth:`feed_once` = one poll window of
upsert/delete batches then a gated publish.

Capacity is sized AOT against ``DL4J_TPU_HBM_GB`` via
``ops/memory.ann_arena_rows`` when ``DL4J_TPU_ANN_ROWS`` is 0 —
closed-form arithmetic, tunnel-free.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.obs import journal as obs_journal
from deeplearning4j_tpu.obs import registry as obs_registry
from deeplearning4j_tpu.ops import dispatch, env as envknob
from deeplearning4j_tpu.retrieval.index import (
    ExactIndex,
    IndexSnapshot,
    IVFIndex,
    measure_recall,
)
from deeplearning4j_tpu.retrieval.stats import RetrievalStats


class IndexFullError(RuntimeError):
    """No free slot for a new id — the arena is at capacity."""


class PublishVetoed(RuntimeError):
    """A latched drift alarm blocked the publish; the previously
    published generation keeps serving (a veto is not an outage)."""


def _resolve_capacity(dim: int, capacity: Optional[int]) -> int:
    if capacity is not None and int(capacity) > 0:
        return int(capacity)
    rows = envknob.get_int("DL4J_TPU_ANN_ROWS", 0)
    if rows and rows > 0:
        return int(rows)
    from deeplearning4j_tpu.ops import memory

    return memory.ann_arena_rows(dim)


class VectorStore:
    """One named, online-mutable ANN index (``kind`` = ``exact``/``ivf``)."""

    def __init__(self, dim: int, *, capacity: Optional[int] = None,
                 kind: str = "ivf", metric: str = "cosine",
                 clusters: Optional[int] = None,
                 nprobe: Optional[int] = None, ivf_iters: int = 25,
                 min_ivf_rows: int = 32, name: str = "index",
                 stats: Optional[RetrievalStats] = None) -> None:
        if kind not in ("exact", "ivf"):
            raise ValueError(f"kind must be exact|ivf, got {kind!r}")
        if metric not in ("cosine", "ip"):
            raise ValueError(f"metric must be cosine|ip, got {metric!r}")
        self.name = name
        self.dim = int(dim)
        self.kind = kind
        self.metric = metric
        self.capacity = _resolve_capacity(self.dim, capacity)
        self.min_ivf_rows = int(min_ivf_rows)
        self.retrieval_stats = stats or RetrievalStats()
        obs_registry.default_registry().register_ledger(
            self, "retrieval_stats", self.retrieval_stats)
        self._exact = ExactIndex()
        self._ivf = IVFIndex(clusters=clusters, nprobe=nprobe,
                             iters=ivf_iters)
        # host master (the authoritative copy, kmeans training substrate)
        self._host_vecs = np.zeros((self.capacity, self.dim), np.float32)
        self._ids = np.full(self.capacity, -1, np.int64)
        self._id2slot: Dict[int, int] = {}
        self._free = list(range(self.capacity - 1, -1, -1))
        # staging arena: slot-addressed, trash row at index `capacity`,
        # mutated only through the donated scatter below
        self._staging = jnp.zeros((self.capacity + 1, self.dim), jnp.float32)
        self._scatter = dispatch.arena_jit(
            lambda arena, slots, rows: arena.at[slots].set(rows),
            donate=(0,))
        self._pack = dispatch.arena_jit(
            lambda arena, slots: jnp.take(arena, slots, axis=0))
        self._mut = threading.Lock()
        self._pub = threading.Lock()  # serializes whole publishes
        self._snapshot = self._empty_snapshot()
        self._dirty = False

    # -- snapshot plumbing -------------------------------------------------

    def _empty_snapshot(self) -> IndexSnapshot:
        n_pad = dispatch.bucket_size(1)
        return IndexSnapshot(
            vecs=jnp.zeros((n_pad, self.dim), jnp.float32),
            ids=np.full(n_pad, -1, np.int64), n=0, generation=0,
            metric=self.metric)

    @property
    def snapshot(self) -> IndexSnapshot:
        """The current published generation (immutable; safe to search
        without any lock — a concurrent publish swaps the reference,
        never the buffers)."""
        return self._snapshot

    @property
    def rows(self) -> int:
        return len(self._id2slot)

    @property
    def generation(self) -> int:
        return self._snapshot.generation

    # -- mutation plane (staging arena + host master) ----------------------

    def _norm_rows(self, vecs: np.ndarray) -> np.ndarray:
        rows = np.array(vecs, np.float32, copy=True).reshape(-1, self.dim)
        if self.metric == "cosine":
            norms = np.linalg.norm(rows, axis=1, keepdims=True)
            rows = rows / np.maximum(norms, 1e-12)
        return rows

    def _scatter_padded(self, slots, rows) -> None:
        """Donated scatter with the slot list padded up the bucket
        ladder onto the TRASH row (zero writes to row `capacity` keep it
        zero), so mutation batch sizes reuse one program per bucket."""
        m = len(slots)
        pad = dispatch.bucket_size(m)
        s = np.full(pad, self.capacity, np.int32)
        s[:m] = slots
        r = np.zeros((pad, self.dim), np.float32)
        r[:m] = rows
        self._staging = self._scatter(self._staging, jnp.asarray(s),
                                      jnp.asarray(r))

    def upsert(self, ids, vecs) -> int:
        """Insert-or-replace rows by external id. Returns rows written."""
        id_arr = np.asarray(ids, np.int64).reshape(-1)
        rows = self._norm_rows(vecs)
        if rows.shape[0] != id_arr.shape[0]:
            raise ValueError(
                f"{id_arr.shape[0]} ids vs {rows.shape[0]} vectors")
        with self._mut:
            slots = []
            for ext in id_arr:
                ext = int(ext)
                slot = self._id2slot.get(ext)
                if slot is None:
                    if not self._free:
                        raise IndexFullError(
                            f"index {self.name!r} full at "
                            f"{self.capacity} rows")
                    slot = self._free.pop()
                    self._id2slot[ext] = slot
                    self._ids[slot] = ext
                slots.append(slot)
            self._host_vecs[slots] = rows
            self._scatter_padded(slots, rows)
            self._dirty = True
        self.retrieval_stats.bump("upserts", len(slots))
        return len(slots)

    def delete(self, ids) -> int:
        """Drop rows by external id (unknown ids ignored). Returns rows
        dropped."""
        id_arr = np.asarray(ids, np.int64).reshape(-1)
        with self._mut:
            slots = []
            for ext in id_arr:
                slot = self._id2slot.pop(int(ext), None)
                if slot is None:
                    continue
                slots.append(slot)
                self._ids[slot] = -1
                self._free.append(slot)
            if slots:
                self._host_vecs[slots] = 0.0
                self._scatter_padded(slots, np.zeros((len(slots), self.dim),
                                                     np.float32))
                self._dirty = True
        if slots:
            self.retrieval_stats.bump("deletes", len(slots))
        return len(slots)

    # -- publish plane (generation swap) -----------------------------------

    def publish(self, drift=None, force: bool = False) -> IndexSnapshot:
        """Pack live slots into a fresh immutable generation and swap it
        in atomically. ``drift`` (an ``online/drift.DriftMonitor``) with
        a latched/firing alarm VETOES the publish unless ``force``."""
        if drift is not None and not force:
            verdict = drift.check()
            if verdict.get("alarmed"):
                self.retrieval_stats.bump("publish_vetoes")
                obs_journal.event(
                    "retrieval.publish_veto", index=self.name,
                    generation=self._snapshot.generation,
                    max_z=verdict.get("max_z"))
                raise PublishVetoed(
                    f"index {self.name!r}: drift alarm "
                    f"(max_z={verdict.get('max_z')}) vetoed the publish; "
                    f"generation {self._snapshot.generation} keeps serving")
        with self._pub:
            with self._mut:
                live = sorted(self._id2slot.values())
                n = len(live)
                # n_pad >= n + 1 guarantees at least one zero pad row —
                # the IVF member-table sentinel (index.py layout
                # discipline)
                n_pad = dispatch.bucket_size(n + 1)
                slots = np.full(n_pad, self.capacity, np.int32)
                slots[:n] = live
                ids = np.full(n_pad, -1, np.int64)
                ids[:n] = self._ids[slots[:n]]
                packed = self._pack(self._staging, jnp.asarray(slots))
                host_live = self._host_vecs[slots[:n]]
                gen = self._snapshot.generation + 1
                self._dirty = False
            snap = IndexSnapshot(vecs=packed, ids=ids, n=n, generation=gen,
                                 metric=self.metric)
            if self.kind == "ivf" and n >= self.min_ivf_rows:
                snap = self._ivf.build(snap, host_live)
            with self._mut:
                self._snapshot = snap
        self.retrieval_stats.bump("publishes")
        self.retrieval_stats.set("generation", gen)
        self.retrieval_stats.set("rows", n)
        obs_journal.event("retrieval.publish", index=self.name,
                          generation=gen, rows=n,
                          ivf=snap.centroids is not None)
        return snap

    # -- search plane (lock-free over the published generation) -----------

    def search(self, queries, k: int = 10,
               nprobe: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Top-k over the CURRENT published generation. Returns
        ``(ids [B, k] int64, scores [B, k] float32)``; id -1 marks
        fewer-than-k live rows."""
        snap = self._snapshot
        if self.kind == "ivf" and snap.centroids is not None:
            ids, scores = self._ivf.search(snap, queries, k, nprobe=nprobe)
        else:
            ids, scores = self._exact.search(snap, queries, k)
        self.retrieval_stats.bump("search_requests")
        self.retrieval_stats.bump("search_rows", int(ids.shape[0]))
        return ids, scores

    def search_exact(self, queries, k: int = 10):
        """The oracle path, always exhaustive — recall probes and tests
        compare against this on the SAME generation."""
        snap = self._snapshot
        ids, scores = self._exact.search(snap, queries, k)
        return ids, scores

    def probe_recall(self, queries, k: int = 10) -> float:
        """Measured recall@k of this store's probe path vs the exact
        oracle on the current generation (never assumed)."""
        snap = self._snapshot
        if snap.centroids is None:
            recall = 1.0  # exact path IS the oracle
        else:
            recall = measure_recall(snap, self._ivf, queries, k)
        self.retrieval_stats.bump("recall_probes")
        self.retrieval_stats.set("last_recall", recall)
        return recall

    # -- online feed (PR 14 StreamSource loop) -----------------------------

    def apply_batch(self, batch) -> Tuple[int, int]:
        """One feed batch -> (upserted, deleted). Accepts a DataSet
        (features = vectors, labels = ids; features None => labels are
        ids to DELETE) or an ('upsert'|'delete', ...) tuple."""
        if isinstance(batch, tuple) and batch and isinstance(batch[0], str):
            op = batch[0]
            if op == "delete":
                return 0, self.delete(batch[1])
            if op == "upsert":
                return self.upsert(batch[1], batch[2]), 0
            raise ValueError(f"unknown feed op {op!r}")
        feats = getattr(batch, "features", None)
        labels = getattr(batch, "labels", None)
        if labels is None:
            raise ValueError(
                "feed batch needs labels (external ids); got "
                f"{type(batch).__name__}")
        if feats is None:
            return 0, self.delete(labels)
        return self.upsert(labels, feats), 0

    def feed_once(self, stream, drift=None, publish: bool = True) -> dict:
        """Drain ONE StreamSource poll window (ends when the feed idles
        ``DL4J_TPU_ONLINE_IDLE_S``), observing vectors into ``drift``
        before they land, then publish gated on the drift verdict.
        Returns a window report; a veto rides it as ``vetoed=True``
        (the generation field then names the UNMOVED generation)."""
        upserted = deleted = batches = 0
        for batch in stream:
            feats = getattr(batch, "features", None)
            if drift is not None and feats is not None:
                drift.observe(np.asarray(feats, np.float32).reshape(
                    -1, self.dim))
            u, d = self.apply_batch(batch)
            upserted += u
            deleted += d
            batches += 1
            self.retrieval_stats.bump("feed_batches")
        self.retrieval_stats.bump("feed_windows")
        report = {"batches": batches, "upserted": upserted,
                  "deleted": deleted, "published": False, "vetoed": False,
                  "generation": self._snapshot.generation}
        if publish and batches:
            try:
                snap = self.publish(drift=drift)
                report.update(published=True, generation=snap.generation)
            except PublishVetoed:
                report.update(vetoed=True)
        return report

    # -- reporting (AOT, tunnel-free) --------------------------------------

    def report(self) -> Dict[str, Any]:
        """Capacity/row-count report for ``/models`` — host-side ints
        only, beside the serving engine's ``kv_report``."""
        from deeplearning4j_tpu.ops import memory

        snap = self._snapshot
        return {
            "kind": self.kind,
            "metric": self.metric,
            "dim": self.dim,
            "capacity": self.capacity,
            "rows": self.rows,
            "generation": snap.generation,
            "ivf_built": snap.centroids is not None,
            "clusters": (int(snap.centroids.shape[0])
                         if snap.centroids is not None else 0),
            "nprobe": envknob.get_int("DL4J_TPU_ANN_NPROBE", 8),
            "arena_bytes": (self.capacity + 1) * memory.ann_row_bytes(
                self.dim),
        }
