"""Embedding & retrieval serving (ISSUE 17): /embed adapters +
device-resident ANN search with online, generation-swapped index
updates. The serving half the reference's scaleout-nlp module never
grew — its InMemoryLookupTable answers wordsNearest with a host-side
full scan; here the arena lives on device and top-k is one batched
matmul (the MXU-friendly shape, BENCH_NOTES.md)."""

from deeplearning4j_tpu.retrieval.embed import (
    BertEmbedding,
    FeedForwardEmbedding,
    LookupEmbedding,
    resolve_adapter,
)
from deeplearning4j_tpu.retrieval.index import (
    ExactIndex,
    IndexSnapshot,
    IVFIndex,
    measure_recall,
)
from deeplearning4j_tpu.retrieval.stats import RetrievalStats
from deeplearning4j_tpu.retrieval.store import (
    IndexFullError,
    PublishVetoed,
    VectorStore,
)

__all__ = [
    "BertEmbedding",
    "ExactIndex",
    "FeedForwardEmbedding",
    "IndexFullError",
    "IndexSnapshot",
    "IVFIndex",
    "LookupEmbedding",
    "PublishVetoed",
    "RetrievalStats",
    "VectorStore",
    "measure_recall",
    "resolve_adapter",
]
