"""Embedding adapters: one uniform ``rows -> [N, dim] float32`` surface.

The reference serves models, not embeddings — its nlp module (SURVEY
module map, deeplearning4j-scaleout-nlp; InMemoryLookupTable.java:73)
trains word vectors and answers ``wordsNearest`` on the host. This
module is the serving half that never existed: every registered net
becomes an encoder behind ``/embed``, routed through the same
``DynamicBatcher`` bucket ladder as ``/predict`` so the batcher==direct
byte-equivalence contract comes for free.

Three adapter families, resolved by duck type (``resolve_adapter``):

- ``FeedForwardEmbedding`` — MLN/CG hidden-layer activations via
  ``feed_forward`` (reference feedForward(train),
  MultiLayerNetwork.java:1016 role). ``layer`` picks the activation:
  an int index into the MLN activations list (input is index 0; the
  default -2 is the last hidden layer), or a vertex NAME for a
  ComputationGraph (default: the vertex feeding the first output).
- ``BertEmbedding`` — ``BertMLM.embed_tokens`` contextual embeddings
  pooled over the sequence axis (``mean``/``cls``/``max``).
- ``LookupEmbedding`` — word2vec ``InMemoryLookupTable.vectors`` row
  lookup (token-id rows; the vocab-scale table the SGNS plane trains).

``dim`` is resolved WITHOUT running the model: config fields, param
shapes, or ``jax.eval_shape`` abstract evaluation — tunnel-free, so
``/models`` can report per-model embedding dims while the TPU tunnel is
down (the same AOT discipline as ``ops/memory``).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.ops import env as envknob

_POOLS = ("mean", "cls", "max")


def _env_layer() -> Optional[int]:
    return envknob.get_int("DL4J_TPU_EMBED_LAYER", None)


def _env_pool() -> str:
    pool = envknob.get_str("DL4J_TPU_EMBED_POOL") or "mean"
    return pool if pool in _POOLS else "mean"


class FeedForwardEmbedding:
    """Hidden-layer encoder over MLN/CG ``feed_forward`` activations
    (reference feedForward map, MultiLayerNetwork.java feedForward /
    ComputationGraph.java feedForward roles)."""

    kind = "feedforward"

    def __init__(self, net: Any, layer=None,
                 input_shape: Optional[Sequence[int]] = None) -> None:
        self.net = net
        self._graph = hasattr(net, "conf") and hasattr(
            getattr(net, "conf", None), "vertex_inputs")
        if layer is None and not self._graph:
            layer = _env_layer()
        self.layer = self._default_layer() if layer is None else layer
        self._input_shape = tuple(input_shape) if input_shape else None
        self._dim: Optional[int] = self._aot_dim()

    def _default_layer(self):
        if self._graph:
            conf = self.net.conf
            out = conf.outputs[0]
            return conf.vertex_inputs[out][0]
        return -2

    def _pick(self, acts):
        if self._graph:
            return acts[self.layer]
        idx = int(self.layer)
        if not (-len(acts) <= idx < len(acts)):
            raise ValueError(
                f"embed layer {idx} out of range for {len(acts)} activations")
        return acts[idx]

    def _aot_dim(self) -> Optional[int]:
        """Abstract-eval the forward pass for the embedding width — no
        execution, no device dispatch (works tunnel-free)."""
        if self._input_shape is None or self._graph:
            return None
        try:
            import jax

            spec = jax.ShapeDtypeStruct(
                (1,) + self._input_shape, np.float32)
            shapes = jax.eval_shape(
                lambda x: self.net.feed_forward(x), spec)
            return int(self._pick(shapes).shape[-1])
        except Exception:
            return None

    @property
    def dim(self) -> Optional[int]:
        return self._dim

    def __call__(self, rows) -> np.ndarray:
        x = np.asarray(rows, np.float32)
        if self._graph:
            acts = self.net.feed_forward(x)
        else:
            acts = self.net.feed_forward(x, train=False)
        out = np.asarray(self._pick(acts), np.float32)
        out = out.reshape(out.shape[0], -1)
        if self._dim is None:
            self._dim = int(out.shape[-1])
        return out


class BertEmbedding:
    """Pooled contextual embeddings over ``BertMLM.embed_tokens``
    (the feature-extraction use; reference word-vector serving never had
    a contextual analogue)."""

    kind = "bert"

    def __init__(self, lm: Any, pool: Optional[str] = None) -> None:
        if pool is None:
            pool = _env_pool()
        if pool not in _POOLS:
            raise ValueError(f"pool must be one of {_POOLS}, got {pool!r}")
        self.lm = lm
        self.pool = pool
        self._dim = int(lm.cfg.d_model)

    @property
    def dim(self) -> int:
        return self._dim

    def __call__(self, rows) -> np.ndarray:
        tokens = np.asarray(rows)
        if tokens.dtype.kind == "f":
            tokens = np.rint(tokens)
        tokens = tokens.astype(np.int32)
        emb = np.asarray(self.lm.embed_tokens(tokens), np.float32)  # [N,T,d]
        if self.pool == "cls":
            return emb[:, 0, :]
        if self.pool == "max":
            return emb.max(axis=1)
        return emb.mean(axis=1)


class LookupEmbedding:
    """Word2vec table rows by token id (reference
    InMemoryLookupTable.java:73 syn0; the lookup IS the encoder)."""

    kind = "lookup"

    def __init__(self, table: Any) -> None:
        # accept a Word2Vec model or the bare lookup table
        if hasattr(table, "lookup_table") and table.lookup_table is not None:
            table = table.lookup_table
        if not hasattr(table, "syn0"):
            raise TypeError("LookupEmbedding needs an InMemoryLookupTable "
                            "(or a fitted Word2Vec)")
        self.table = table
        self._dim = int(table.vector_length)

    @property
    def dim(self) -> int:
        return self._dim

    def __call__(self, rows) -> np.ndarray:
        ids = np.asarray(rows)
        if ids.dtype.kind == "f":
            ids = np.rint(ids)
        return self.table.vectors(ids.astype(np.int64).reshape(ids.shape[0], -1)[:, 0])


def resolve_adapter(model: Any, layer=None, pool: Optional[str] = None,
                    input_shape: Optional[Sequence[int]] = None):
    """Duck-typed adapter resolution for any registrable model: BertMLM
    (``embed_tokens``), word2vec tables (``syn0``/``lookup_table``), and
    the MLN/CG container family (``feed_forward``)."""
    if hasattr(model, "embed_tokens"):
        return BertEmbedding(model, pool=pool)
    if hasattr(model, "syn0") or (
            hasattr(model, "lookup_table")
            and getattr(model, "lookup_table", None) is not None):
        return LookupEmbedding(model)
    if hasattr(model, "feed_forward"):
        return FeedForwardEmbedding(model, layer=layer,
                                    input_shape=input_shape)
    raise TypeError(
        f"no embedding surface on {type(model).__name__}: expected "
        "embed_tokens (BERT), lookup_table/syn0 (word2vec), or "
        "feed_forward (MLN/CG)")
