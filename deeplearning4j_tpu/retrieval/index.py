"""Device-resident vector indexes: exact top-k oracle + IVF probing.

The reference answers ``wordsNearest`` with a host-side full scan
(BasicModelUtils.java wordsNearest — an O(vocab) numpy pass per query);
this module is the TPU-native serving form: batched top-k over a
device-resident arena, the MXU-friendly matmul shape the chip likes
(~119 TFLOPS bf16 at 8192^3, BENCH_NOTES.md).

Two index families over ONE immutable published snapshot layout
(:class:`IndexSnapshot`, produced by ``retrieval/store.VectorStore``
generation publishes):

- :class:`ExactIndex` — one jitted ``scores = q @ vecs.T`` +
  ``jax.lax.top_k`` over the whole arena. Exact by construction: the
  correctness oracle every IVF recall number is MEASURED against.
- :class:`IVFIndex` — a k-means coarse quantizer
  (``clustering/kmeans.KMeansClustering``, the reference
  KMeansClustering.java:31 machinery reused as infrastructure) built at
  publish time; a query scores ``DL4J_TPU_ANN_NPROBE`` nearest clusters
  and ranks only their members — one jit, zero retrace across
  publishes at a fixed (n_pad, cap_per, k, nprobe) bucket.

Snapshot layout discipline (mirrors the paged-KV trash-block argument,
serving/paged.py): the packed arena is ``[n_pad, dim]`` with rows
``>= n`` zero; IVF member tables pad with sentinel ``n_pad - 1``
(guaranteed a pad row — the store packs to ``bucket_size(n + 1)``), and
sentinel/pad scores are masked to ``-inf`` before top_k, so garbage is
invisible by construction. Searches never donate — published snapshots
stay valid for in-flight readers across a generation swap; only the
store's STAGING arena rides ``ops/dispatch.arena_jit`` donation.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops import env as envknob

_EPS = 1e-12


@dataclass(frozen=True)
class IndexSnapshot:
    """One immutable published index generation. ``vecs`` is the packed
    device arena [n_pad, dim] (rows >= n zero); ``ids`` the aligned
    external ids (int64, -1 on pad rows); IVF fields are None on
    exact-only publishes."""

    vecs: Any
    ids: np.ndarray
    n: int
    generation: int
    metric: str = "cosine"
    centroids: Any = None
    members: Any = None

    @property
    def dim(self) -> int:
        return int(self.vecs.shape[1])

    @property
    def n_pad(self) -> int:
        return int(self.vecs.shape[0])


def _normalize(q):
    return q / jnp.maximum(jnp.linalg.norm(q, axis=-1, keepdims=True), _EPS)


@functools.partial(jax.jit, static_argnames=("k", "cosine"))
def _exact_topk(q, vecs, n, *, k: int, cosine: bool):
    """[B, n_pad] scores -> top-k (scores, packed row indices); pad rows
    (arange >= n) masked to -inf so they can never win."""
    if cosine:
        q = _normalize(q)
    scores = q @ vecs.T
    valid = jnp.arange(vecs.shape[0]) < n
    scores = jnp.where(valid[None, :], scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "cosine"))
def _ivf_topk(q, vecs, centroids, members, *, k: int, nprobe: int,
              cosine: bool):
    """Coarse-probe then rank: top-nprobe centroids -> gather member
    rows -> exact scores on the candidate set only. Sentinel member
    slots (n_pad - 1, a zero pad row) masked to -inf."""
    if cosine:
        q = _normalize(q)
    coarse = q @ centroids.T                        # [B, K]
    _, probe = jax.lax.top_k(coarse, nprobe)        # [B, nprobe]
    cand = members[probe]                           # [B, nprobe, cap_per]
    cand = cand.reshape(cand.shape[0], -1)          # [B, M]
    cvecs = vecs[cand]                              # [B, M, dim]
    scores = jnp.einsum("bd,bmd->bm", q, cvecs)
    sentinel = vecs.shape[0] - 1
    scores = jnp.where(cand != sentinel, scores, -jnp.inf)
    top, pos = jax.lax.top_k(scores, k)
    return top, jnp.take_along_axis(cand, pos, axis=1)


def _as_queries(queries, dim: int) -> np.ndarray:
    q = np.asarray(queries, np.float32)
    if q.ndim == 1:
        q = q[None, :]
    if q.ndim != 2 or q.shape[1] != dim:
        raise ValueError(f"queries must be [B, {dim}], got {q.shape}")
    return q


def _bucket_queries(q: np.ndarray) -> Tuple[np.ndarray, int]:
    """Pad the query batch up the serving bucket ladder (zero rows,
    sliced back off the result) so a stream of ragged /search batch
    sizes compiles one program per bucket, not per shape."""
    from deeplearning4j_tpu.ops import dispatch

    b = q.shape[0]
    pad = dispatch.bucket_size(b)
    if pad > b:
        q = np.concatenate([q, np.zeros((pad - b, q.shape[1]), q.dtype)])
    return q, b


def _finalize(snap: IndexSnapshot, scores, rows, b: int):
    """Host readback + slot->external-id mapping; -inf entries (fewer
    than k live rows) surface as id -1."""
    scores = np.asarray(scores)[:b]
    rows = np.asarray(rows)[:b]
    ids = snap.ids[rows]
    ids = np.where(np.isfinite(scores), ids, -1)
    return ids, scores


class ExactIndex:
    """Exhaustive batched top-k — the correctness oracle
    (reference wordsNearest full-scan role, device-batched)."""

    kind = "exact"

    def search(self, snap: IndexSnapshot, queries, k: int = 10):
        q = _as_queries(queries, snap.dim)
        q, b = _bucket_queries(q)
        k_eff = min(int(k), snap.n_pad)
        scores, rows = _exact_topk(
            jnp.asarray(q), snap.vecs, np.int32(snap.n),
            k=k_eff, cosine=snap.metric == "cosine")
        return _finalize(snap, scores, rows, b)


class IVFIndex:
    """Inverted-file probing over a k-means coarse quantizer. Recall is
    a property of (clusters, nprobe, data) — ``measure_recall`` reports
    it against the exact oracle on the SAME snapshot, never assumed."""

    kind = "ivf"

    def __init__(self, clusters: Optional[int] = None,
                 nprobe: Optional[int] = None, seed: int = 0,
                 iters: int = 25) -> None:
        self.clusters = clusters
        self.nprobe = nprobe
        self.seed = seed
        self.iters = int(iters)
        self._exact = ExactIndex()

    def _n_clusters(self, n: int) -> int:
        k = self.clusters
        if k is None:
            k = envknob.get_int("DL4J_TPU_ANN_CLUSTERS", 0)
        if not k or k <= 0:
            k = int(np.sqrt(max(1, n)))
        return max(1, min(int(k), max(1, n)))

    def _n_probe(self, n_clusters: int, override=None) -> int:
        p = override if override is not None else self.nprobe
        if p is None:
            p = envknob.get_int("DL4J_TPU_ANN_NPROBE", 8)
        return max(1, min(int(p), n_clusters))

    def build(self, snap: IndexSnapshot,
              host_vecs: np.ndarray) -> IndexSnapshot:
        """Train the coarse quantizer on the live rows (host-side master
        copy — no device readback) and attach centroids + padded member
        tables to the snapshot. cap_per is bucketed so membership churn
        across publishes reuses the same search program."""
        from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
        from deeplearning4j_tpu.ops import dispatch

        n, n_pad = snap.n, snap.n_pad
        if n < 1:
            raise ValueError("cannot build an IVF quantizer over 0 rows")
        kc = self._n_clusters(n)
        km = KMeansClustering(kc, max_iterations=self.iters, seed=self.seed)
        km.apply_to(np.asarray(host_vecs[:n], np.float32))
        assign = km.assignments_
        counts = np.bincount(assign, minlength=kc)
        cap_per = dispatch.bucket_size(max(1, int(counts.max())))
        sentinel = n_pad - 1
        members = np.full((kc, cap_per), sentinel, np.int32)
        fill = np.zeros(kc, np.int64)
        for row, c in enumerate(assign):
            members[c, fill[c]] = row
            fill[c] += 1
        centroids = km.centers_
        if snap.metric == "cosine":
            norms = np.linalg.norm(centroids, axis=1, keepdims=True)
            centroids = centroids / np.maximum(norms, _EPS)
        return IndexSnapshot(
            vecs=snap.vecs, ids=snap.ids, n=n, generation=snap.generation,
            metric=snap.metric, centroids=jnp.asarray(centroids, jnp.float32),
            members=jnp.asarray(members))

    def search(self, snap: IndexSnapshot, queries, k: int = 10,
               nprobe: Optional[int] = None):
        if snap.centroids is None:
            return self._exact.search(snap, queries, k)
        q = _as_queries(queries, snap.dim)
        q, b = _bucket_queries(q)
        k_eff = min(int(k), snap.n_pad)
        scores, rows = _ivf_topk(
            jnp.asarray(q), snap.vecs, snap.centroids, snap.members,
            k=k_eff, nprobe=self._n_probe(int(snap.centroids.shape[0]),
                                          nprobe),
            cosine=snap.metric == "cosine")
        return _finalize(snap, scores, rows, b)


def measure_recall(snap: IndexSnapshot, ivf: IVFIndex, queries,
                   k: int = 10) -> float:
    """recall@k of the IVF probe vs the exact oracle on the SAME
    snapshot — the measured-never-assumed discipline (the Pallas
    measured-win gate's sibling for index quality)."""
    exact_ids, _ = ExactIndex().search(snap, queries, k)
    ivf_ids, _ = ivf.search(snap, queries, k)
    hits, total = 0, 0
    for row_e, row_i in zip(exact_ids, ivf_ids):
        truth = set(int(i) for i in row_e if i >= 0)
        if not truth:
            continue
        got = set(int(i) for i in row_i if i >= 0)
        hits += len(truth & got)
        total += len(truth)
    return hits / total if total else 1.0
