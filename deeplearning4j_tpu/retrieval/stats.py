"""Retrieval telemetry: the ``retrieval_stats`` ledger.

One thread-safe counter surface for the embedding/ANN plane (``/embed``
requests -> index upserts/deletes -> generation publishes -> ``/search``
probes -> measured recall), shaped like every other ledger in the repo
(``dispatch_stats``/``pipeline_stats``/``resilience_stats``/
``serving_stats``/``online_stats``): plain counters behind a lock,
``snapshot()`` as the JSON-able read surface the central
``obs.MetricsRegistry`` flattens into Prometheus samples. The
reference's scaleout-nlp module (SURVEY module map,
deeplearning4j-scaleout-nlp) trains word vectors but never serves a
nearest-neighbor lookup; this ledger is what makes that new workload
surface operable.

Registration happens at the ATTACH point (``retrieval/store.py``
registers each ``VectorStore``'s ledger at construction) — the graftlint
``ledger-registration`` rule enforces that mechanically.
"""

from __future__ import annotations

import threading
from typing import Any, Dict


class RetrievalStats:
    """Counters for the embed -> upsert -> publish -> search loop.
    Writers: the serving embed path, the store mutation path, the
    publisher, the search path, the recall probe. One lock — every field
    is a scalar bump, never a device sync."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # embed plane (bumped by the serving engine per answered /embed)
        self.embed_requests = 0
        self.embed_rows = 0
        # mutation plane
        self.upserts = 0
        self.deletes = 0
        self.feed_batches = 0
        self.feed_windows = 0
        # publish plane
        self.publishes = 0
        self.publish_vetoes = 0
        self.generation = 0
        self.rows = 0
        # search plane
        self.search_requests = 0
        self.search_rows = 0
        # recall probe (measured against the exact oracle, never assumed)
        self.recall_probes = 0
        self.last_recall = 0.0

    def bump(self, field: str, by: float = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + by)

    def set(self, field: str, value: float) -> None:
        with self._lock:
            setattr(self, field, value)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "embed_requests": self.embed_requests,
                "embed_rows": self.embed_rows,
                "upserts": self.upserts,
                "deletes": self.deletes,
                "feed_batches": self.feed_batches,
                "feed_windows": self.feed_windows,
                "publishes": self.publishes,
                "publish_vetoes": self.publish_vetoes,
                "generation": self.generation,
                "rows": self.rows,
                "search_requests": self.search_requests,
                "search_rows": self.search_rows,
                "recall_probes": self.recall_probes,
                "last_recall": round(float(self.last_recall), 6),
            }

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"RetrievalStats({self.snapshot()})"
