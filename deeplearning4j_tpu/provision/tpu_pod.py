"""TPU pod-slice provisioning (ClusterSetup.java:39 role, gcloud edition).

The reference provisions an EC2 master + N workers (Ec2BoxCreator), pushes
setup scripts over SSH/SCP (HostProvisioner.java), and launches the
distributed trainer (DistributedDeepLearningTrainer.java). On TPU the
"cluster" is a pod slice: ONE gcloud resource whose hosts are addressed
with `--worker=<i>|all`, and the service-discovery role (the reference's
ZooKeeper) is jax.distributed's coordinator triple — which this module
wires through the DL4J_TPU_* env vars that
parallel/multihost.MultiHostConfig.from_env reads.

Everything is PLAN-FIRST and runner-injected: `plan()` returns the exact
gcloud invocations, `apply(runner=...)` executes them through a callable
(subprocess by default), so the zero-egress test environment validates the
full command/bootstrap/env generation without touching a cloud API — the
same reason the reference's ClusterSetup is driven by args4j options
rather than hardcoded infra.
"""

from __future__ import annotations

import shlex
import subprocess
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

_HOSTS_PER_TYPE_DEFAULT = 8  # chips per host on current TPU generations


@dataclass(frozen=True)
class TpuPodSpec:
    """The provisioning request (reference ClusterSetup options -w/-ami/-s
    mapped to their TPU equivalents)."""

    name: str
    zone: str = "us-central2-b"
    accelerator_type: str = "v5litepod-16"   # -s instance size role
    runtime_version: str = "tpu-ubuntu2204-base"  # -ami role
    project: Optional[str] = None
    coordinator_port: int = 8476
    chips_per_host: int = _HOSTS_PER_TYPE_DEFAULT

    @property
    def num_chips(self) -> int:
        # accelerator types encode the chip count after the last '-'
        try:
            return int(self.accelerator_type.rsplit("-", 1)[1])
        except (IndexError, ValueError):
            raise ValueError(
                f"cannot infer chip count from accelerator_type "
                f"{self.accelerator_type!r} (expected e.g. 'v5litepod-16')")

    @property
    def num_hosts(self) -> int:
        return max(1, self.num_chips // self.chips_per_host)

    def _gcloud(self, *args: str) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", *args,
               f"--zone={self.zone}"]
        if self.project:
            cmd.append(f"--project={self.project}")
        return cmd


def host_env(spec: TpuPodSpec, process_id: int,
             coordinator_host: str = "$(hostname -i)") -> Dict[str, str]:
    """The per-host jax.distributed env (MultiHostConfig.from_env contract;
    the reference's ZooKeeperConfigurationRegister role): worker 0 is the
    coordinator, every host learns the triple through env vars."""
    return {
        "DL4J_TPU_COORDINATOR": f"{coordinator_host}:{spec.coordinator_port}",
        "DL4J_TPU_NUM_PROCESSES": str(spec.num_hosts),
        "DL4J_TPU_PROCESS_ID": str(process_id),
    }


def bootstrap_script(spec: TpuPodSpec, repo_dir: str, train_cmd: str) -> str:
    """The worker setup script (reference -wscript/-mscript roles unified:
    a pod slice has no master/worker asymmetry — worker 0 merely also
    hosts the coordinator). gcloud ssh --worker=all runs this on every
    host. The coordinator address is resolved ON-HOST from the TPU
    metadata environment (TPU_WORKER_HOSTNAMES lists every host, worker 0
    first; TPU_WORKER_ID is this host's index) — no describe-output
    parsing, and a single-host slice falls back to its own address."""
    lines = [
        "#!/bin/bash",
        "set -euo pipefail",
        f"cd {shlex.quote(repo_dir)}",
        'PROC_ID="${TPU_WORKER_ID:-0}"',
        # worker 0's hostname from the TPU metadata env; self for 1-host
        'COORDINATOR_IP="$(echo "${TPU_WORKER_HOSTNAMES:-$(hostname -i)}" '
        '| cut -d, -f1)"',
        f'export DL4J_TPU_COORDINATOR='
        f'"${{COORDINATOR_IP}}:{spec.coordinator_port}"',
        f'export DL4J_TPU_NUM_PROCESSES={spec.num_hosts}',
        'export DL4J_TPU_PROCESS_ID="${PROC_ID}"',
        f"export PYTHONPATH={shlex.quote(repo_dir)}:${{PYTHONPATH:-}}",
        # initialize_multihost() picks the triple up from the env
        train_cmd,
    ]
    return "\n".join(lines) + "\n"


Runner = Callable[[List[str]], "subprocess.CompletedProcess"]


def _default_runner(cmd: List[str]):
    return subprocess.run(cmd, check=True, capture_output=True, text=True)


@dataclass
class ClusterSetup:
    """Provision -> bootstrap -> launch, the reference ClusterSetup.exec()
    sequence, plan-first. `apply` executes through an injected runner so
    tests (and dry runs) never touch gcloud."""

    spec: TpuPodSpec
    repo_dir: str = "/opt/deeplearning4j_tpu"
    train_cmd: str = ("python -m deeplearning4j_tpu.cli train "
                      "--conf conf.json --input train.csv --output model.zip")
    setup_cmds: List[str] = field(default_factory=list)

    def plan(self) -> List[List[str]]:
        """The exact gcloud invocations, in order: create the slice, read
        back its state, push the bootstrap to every host, run it."""
        s = self.spec
        create = s._gcloud(
            "create", s.name,
            f"--accelerator-type={s.accelerator_type}",
            f"--version={s.runtime_version}",
        )
        describe = s._gcloud("describe", s.name)
        ssh_all = s._gcloud(
            "ssh", s.name, "--worker=all",
            f"--command={self._remote_command()}",
        )
        return [create, describe, ssh_all]

    def _remote_command(self) -> str:
        parts = list(self.setup_cmds)
        parts.append(f"bash -s <<'DL4J_BOOTSTRAP'\n"
                     f"{bootstrap_script(self.spec, self.repo_dir, self.train_cmd)}"
                     f"DL4J_BOOTSTRAP")
        return " && ".join(parts)

    def teardown_plan(self) -> List[List[str]]:
        return [self.spec._gcloud("delete", self.spec.name, "--quiet")]

    def apply(self, runner: Runner = _default_runner) -> List:
        """Execute the plan (reference exec(): provisionMaster +
        provisionWorkers). Raises on the first failing command — the
        reference's HostProvisioner logs and aborts the same way."""
        return [runner(cmd) for cmd in self.plan()]

    def teardown(self, runner: Runner = _default_runner) -> List:
        return [runner(cmd) for cmd in self.teardown_plan()]
