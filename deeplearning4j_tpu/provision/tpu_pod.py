"""TPU pod-slice provisioning (ClusterSetup.java:39 role, gcloud edition).

The reference provisions an EC2 master + N workers (Ec2BoxCreator), pushes
setup scripts over SSH/SCP (HostProvisioner.java), and launches the
distributed trainer (DistributedDeepLearningTrainer.java). On TPU the
"cluster" is a pod slice: ONE gcloud resource whose hosts are addressed
with `--worker=<i>|all`, and the service-discovery role (the reference's
ZooKeeper) is jax.distributed's coordinator triple — which this module
wires through the DL4J_TPU_* env vars that
parallel/multihost.MultiHostConfig.from_env reads.

Everything is PLAN-FIRST and runner-injected: `plan()` returns the exact
gcloud invocations, `apply(runner=...)` executes them through a callable
(subprocess by default), so the zero-egress test environment validates the
full command/bootstrap/env generation without touching a cloud API — the
same reason the reference's ClusterSetup is driven by args4j options
rather than hardcoded infra.
"""

from __future__ import annotations

import shlex
import subprocess
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.parallel.multihost import (
    COORDINATOR_ENV,
    NUM_PROCESSES_ENV,
    PROCESS_ID_ENV,
)


@dataclass(frozen=True)
class TpuPodSpec:
    """The provisioning request (reference ClusterSetup options -w/-ami/-s
    mapped to their TPU equivalents)."""

    name: str
    zone: str = "us-central2-b"
    accelerator_type: str = "v5litepod-16"   # -s instance size role
    runtime_version: str = "tpu-ubuntu2204-base"  # -ami role
    project: Optional[str] = None
    coordinator_port: int = 8476

    @property
    def num_chips(self) -> int:
        # accelerator types encode the chip count after the last '-'
        try:
            return int(self.accelerator_type.rsplit("-", 1)[1])
        except (IndexError, ValueError):
            raise ValueError(
                f"cannot infer chip count from accelerator_type "
                f"{self.accelerator_type!r} (expected e.g. 'v5litepod-16')")

    @property
    def num_hosts(self) -> int:
        """Planning ESTIMATE only (v5e/v5p/v6e VMs carry 4 chips; v4 types
        count TensorCores, 8 per 4-chip host). The bootstrap derives the
        AUTHORITATIVE process count on-host from TPU_WORKER_HOSTNAMES, so
        a topology this table mispredicts still launches correctly."""
        n = self.num_chips
        if self.accelerator_type.startswith("v4"):
            return max(1, n // 8)
        return max(1, n // 4)

    def _gcloud(self, *args: str) -> List[str]:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", *args,
               f"--zone={self.zone}"]
        if self.project:
            cmd.append(f"--project={self.project}")
        return cmd


def host_env(spec: TpuPodSpec, process_id: int,
             coordinator_host: str = "$(hostname -i)",
             num_processes: Optional[int] = None) -> Dict[str, str]:
    """The per-host jax.distributed env (MultiHostConfig.from_env contract;
    the reference's ZooKeeperConfigurationRegister role): worker 0 is the
    coordinator, every host learns the triple through env vars. Env NAMES
    come from parallel/multihost.py so launcher and runtime cannot drift."""
    return {
        COORDINATOR_ENV: f"{coordinator_host}:{spec.coordinator_port}",
        NUM_PROCESSES_ENV: str(num_processes or spec.num_hosts),
        PROCESS_ID_ENV: str(process_id),
    }


def bootstrap_script(spec: TpuPodSpec, repo_dir: str, train_cmd: str) -> str:
    """The worker setup script (reference -wscript/-mscript roles unified:
    a pod slice has no master/worker asymmetry — worker 0 merely also
    hosts the coordinator). gcloud ssh --worker=all runs this on every
    host. The coordinator address is resolved ON-HOST from the TPU
    metadata environment (TPU_WORKER_HOSTNAMES lists every host, worker 0
    first; TPU_WORKER_ID is this host's index) — no describe-output
    parsing, and a single-host slice falls back to its own address."""
    # the three exports are GENERATED from host_env() so the script and
    # the tested MultiHostConfig contract share one source of truth; the
    # values are shell expressions resolved on-host (true process count
    # from the hostname list — never a python-side per-generation guess)
    env = host_env(spec, process_id=0, coordinator_host="${COORDINATOR_IP}")
    exports = {
        COORDINATOR_ENV: env[COORDINATOR_ENV],
        NUM_PROCESSES_ENV: '"${NUM_PROC}"',
        PROCESS_ID_ENV: '"${PROC_ID}"',
    }
    lines = [
        "#!/bin/bash",
        "set -euo pipefail",
        f"cd {shlex.quote(repo_dir)}",
        'PROC_ID="${TPU_WORKER_ID:-0}"',
        # worker 0's hostname from the TPU metadata env; self for 1-host
        'COORDINATOR_IP="$(echo "${TPU_WORKER_HOSTNAMES:-$(hostname -i)}" '
        '| cut -d, -f1)"',
        # AUTHORITATIVE host count = length of the hostname list
        'NUM_PROC="$(echo "${TPU_WORKER_HOSTNAMES:-localhost}" '
        "| awk -F, '{print NF}')\"",
    ] + [f'export {k}={v}' for k, v in exports.items()] + [
        f"export PYTHONPATH={shlex.quote(repo_dir)}:${{PYTHONPATH:-}}",
        # initialize_multihost() picks the triple up from the env
        train_cmd,
    ]
    return "\n".join(lines) + "\n"


Runner = Callable[[List[str]], "subprocess.CompletedProcess"]


def _default_runner(cmd: List[str]):
    return subprocess.run(cmd, check=True, capture_output=True, text=True)


@dataclass
class ClusterSetup:
    """Provision -> bootstrap -> launch, the reference ClusterSetup.exec()
    sequence, plan-first. `apply` executes through an injected runner so
    tests (and dry runs) never touch gcloud."""

    spec: TpuPodSpec
    repo_dir: str = "/opt/deeplearning4j_tpu"
    train_cmd: str = ("python -m deeplearning4j_tpu.cli train "
                      "--conf conf.json --input train.csv --output model.zip")
    setup_cmds: List[str] = field(default_factory=list)

    def plan(self) -> List[List[str]]:
        """The exact gcloud invocations, in order: create the slice, read
        back its state, push the bootstrap to every host, run it."""
        s = self.spec
        create = s._gcloud(
            "create", s.name,
            f"--accelerator-type={s.accelerator_type}",
            f"--version={s.runtime_version}",
        )
        describe = s._gcloud("describe", s.name)
        ssh_all = s._gcloud(
            "ssh", s.name, "--worker=all",
            f"--command={self._remote_command()}",
        )
        return [create, describe, ssh_all]

    def _remote_command(self) -> str:
        parts = list(self.setup_cmds)
        parts.append(f"bash -s <<'DL4J_BOOTSTRAP'\n"
                     f"{bootstrap_script(self.spec, self.repo_dir, self.train_cmd)}"
                     f"DL4J_BOOTSTRAP")
        return " && ".join(parts)

    def teardown_plan(self) -> List[List[str]]:
        return [self.spec._gcloud("delete", self.spec.name, "--quiet")]

    def apply(self, runner: Runner = _default_runner) -> List:
        """Execute the plan (reference exec(): provisionMaster +
        provisionWorkers). Raises on the first failing command — the
        reference's HostProvisioner logs and aborts the same way."""
        return [runner(cmd) for cmd in self.plan()]

    def teardown(self, runner: Runner = _default_runner) -> List:
        return [runner(cmd) for cmd in self.teardown_plan()]
