"""Cloud-bucket dataset IO — the s3/ package's role on GCS.

Reference: deeplearning4j-aws/.../s3/reader/{S3Downloader,BucketIterator,
BaseS3DataSetIterator}.java + uploader/S3Uploader.java + dataset/
DataSetLoader.java: stream bucket objects into DataSets / upload model
artifacts. TPU-native reading: the bucket is gs:// and the transfer tool
is gsutil (runner-injected, like provision/tpu_pod.py, so the zero-egress
test environment exercises listing/downloading/uploading logic against a
fake runner); downloaded npz/csv payloads feed the SAME record readers
the local pipeline uses (datasets/records.py) — no separate parse path.
"""

from __future__ import annotations

import os
import subprocess
from dataclasses import dataclass
from typing import Callable, Iterator, List, Optional

Runner = Callable[[List[str]], "subprocess.CompletedProcess"]


def _default_runner(cmd: List[str]):
    return subprocess.run(cmd, check=True, capture_output=True, text=True)


def _require_gs(uri: str) -> str:
    if not uri.startswith("gs://"):
        raise ValueError(f"expected a gs:// URI, got {uri!r}")
    return uri


@dataclass
class BucketIterator:
    """List a bucket prefix (reference BucketIterator.java): yields object
    URIs via `gsutil ls`."""

    prefix: str
    runner: Runner = _default_runner

    def __iter__(self) -> Iterator[str]:
        out = self.runner(["gsutil", "ls", _require_gs(self.prefix)])
        for line in (out.stdout or "").splitlines():
            line = line.strip()
            if line.startswith("gs://") and not line.endswith("/"):
                yield line


@dataclass
class GcsDownloader:
    """reference S3Downloader.java: fetch objects to a local cache dir
    (idempotent — existing files are not re-fetched)."""

    cache_dir: str
    runner: Runner = _default_runner

    def fetch(self, uri: str) -> str:
        _require_gs(uri)
        os.makedirs(self.cache_dir, exist_ok=True)
        # cache key is the FULL object path (sanitized), not the basename —
        # gs://b/train/shard0.npz and gs://b/eval/shard0.npz must never
        # collide into one cache file
        key = uri[len("gs://"):].replace("/", "__")
        local = os.path.join(self.cache_dir, key)
        if not os.path.exists(local):
            self.runner(["gsutil", "cp", uri, local])
        return local


@dataclass
class GcsUploader:
    """reference S3Uploader.java: push a local artifact (model zip,
    checkpoint dir) to the bucket. Directories use recursive copy (the
    sharded-orbax checkpoint layout)."""

    runner: Runner = _default_runner

    def upload(self, local_path: str, uri: str) -> None:
        _require_gs(uri)
        cmd = ["gsutil", "cp", local_path, uri]
        if os.path.isdir(local_path):
            cmd = ["gsutil", "-m", "cp", "-r", local_path, uri]
        self.runner(cmd)


class GcsDataSetLoader:
    """reference dataset/DataSetLoader.java + BaseS3DataSetIterator: walk a
    bucket prefix, download each object, and parse it with the local
    record-reading path (npz with 'features'/'labels' arrays, or csv with
    the label in the last column — the CLI's formats)."""

    def __init__(self, prefix: str, cache_dir: str,
                 runner: Runner = _default_runner,
                 batch_size: Optional[int] = None,
                 num_classes: Optional[int] = None):
        self.prefix = prefix
        self.downloader = GcsDownloader(cache_dir, runner)
        self.runner = runner
        self.batch_size = batch_size
        # CSV shards one-hot their integer labels to THIS width; inferring
        # it per shard would give different shards different label shapes
        self.num_classes = num_classes

    def __iter__(self):
        from deeplearning4j_tpu.datasets.iterator import DataSet

        for uri in BucketIterator(self.prefix, self.runner):
            local = self.downloader.fetch(uri)
            x, y = self._parse(local, self.num_classes)
            if self.batch_size is None:
                yield DataSet(x, y)
            else:
                for i in range(0, len(x), self.batch_size):
                    yield DataSet(x[i:i + self.batch_size],
                                  y[i:i + self.batch_size])

    @staticmethod
    def _parse(path: str, num_classes: Optional[int]):
        import numpy as np

        if path.endswith(".npz"):
            z = np.load(path)
            return z["features"], z["labels"]
        if path.endswith(".csv"):
            if num_classes is None:
                raise ValueError(
                    "CSV shards need num_classes= on the loader — a "
                    "per-shard labels.max() would give different shards "
                    "different one-hot widths")
            # ndmin=2: a single-row shard must keep the 2-D contract
            raw = np.loadtxt(path, delimiter=",", dtype=np.float32, ndmin=2)
            labels = raw[:, -1].astype(np.int64)
            return (raw[:, :-1],
                    np.eye(num_classes, dtype=np.float32)[labels])
        raise ValueError(f"unsupported dataset object {path!r} "
                         "(expected .npz or .csv)")
