"""Cluster provisioning — the TPU-native reading of deeplearning4j-aws.

Reference: deeplearning4j-scaleout/deeplearning4j-aws/.../ec2/provision/
ClusterSetup.java:39 (EC2 boxes + setup scripts), HostProvisioner.java
(SSH/SCP fan-out), DistributedDeepLearningTrainer.java, s3/ (bucket
dataset IO). The TPU equivalent provisions a TPU pod slice with gcloud,
fans the bootstrap out over `gcloud compute tpus tpu-vm ssh --worker=all`,
and wires every host's jax.distributed coordinator env
(parallel/multihost.py MultiHostConfig) — see provision/tpu_pod.py and
provision/gcs.py.
"""

from deeplearning4j_tpu.provision.tpu_pod import (  # noqa: F401
    ClusterSetup,
    TpuPodSpec,
)
from deeplearning4j_tpu.provision.gcs import GcsDataSetLoader  # noqa: F401
