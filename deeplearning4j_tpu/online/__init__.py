"""Online learning loop: streaming ingest → continuous fit → drift
alarms → shadow eval → hot promotion.

The reference's streaming story (deeplearning4j-scaleout streaming —
Camel/Kafka ingest routes feeding the Spark training master, one model
per serving route) rebuilt on this repo's planes: ``StreamSource`` is the
broker-consumer contract as an ``InputPipeline`` source (monotone
offsets, backpressure, the delivered-batch cursor IS the committed
offset), ``ContinuousTrainer`` drives round-based incremental fit under
the ``ResilientTrainer`` fault plane, ``DriftMonitor`` compares live
feature moments against the training-time fitted normalizer, and
``ShadowPromoter`` promotes a candidate through the serving registry
behind live gates (shadow traffic mirroring, drift veto, atomic swap
with recorded rollback lineage).
"""

from deeplearning4j_tpu.online.drift import DriftMonitor
from deeplearning4j_tpu.online.promote import (
    PromotionRefused,
    ShadowMirror,
    ShadowPromoter,
)
from deeplearning4j_tpu.online.stats import OnlineStats
from deeplearning4j_tpu.online.stream import (
    StreamBackpressure,
    StreamClosed,
    StreamSource,
)
from deeplearning4j_tpu.online.trainer import ContinuousTrainer

__all__ = [
    "ContinuousTrainer",
    "DriftMonitor",
    "OnlineStats",
    "PromotionRefused",
    "ShadowMirror",
    "ShadowPromoter",
    "StreamBackpressure",
    "StreamClosed",
    "StreamSource",
]
