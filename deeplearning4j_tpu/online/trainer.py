"""ContinuousTrainer: incremental fit over a live stream, in rounds.

The reference trains on streams by gluing its ingest routes to the Spark
``ParameterAveragingMaster`` fit loop (SURVEY module map,
deeplearning4j-scaleout streaming + spark training master); this class is
that loop shrunk to the repo's fault plane: a :class:`StreamSource` feeds
an :class:`InputPipeline` (wrap mode — vectorized staging, resume
cursor), and each ROUND drives ``ResilientTrainer.fit(..., num_epochs=1)``
over one poll window of the stream (the pass ends when the feed idles).

Round discipline:

  * every round ends with a BLOCKING checkpoint carrying the pipeline
    cursor (which IS the stream offset — ``online/stream.py``), and every
    round BEGINS by restoring the latest checkpoint through
    ``ResilientTrainer``'s own resume path. Kill at stream offset k +
    resume is therefore the same code path as round turnover: replay,
    bit-exact (the quick tier's contract a).
  * each delivered batch is offered to the :class:`DriftMonitor` BEFORE
    the fit step (the drift window sees exactly what the net trained on).
  * every ``DL4J_TPU_ONLINE_SNAPSHOT_ROUNDS`` rounds the net is exported
    as a CANDIDATE zip (ModelSerializer + the serving normalizer) — the
    artifact :class:`~deeplearning4j_tpu.online.promote.ShadowPromoter`
    stages into the serving registry.

SIGTERM during a round is ``ResilientTrainer``'s checkpoint-before-death:
``Preempted`` propagates to the caller with the goodbye checkpoint
already committed; re-running the same command resumes.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.etl.pipeline import InputPipeline
from deeplearning4j_tpu.obs import journal as obs_journal
from deeplearning4j_tpu.obs import registry as obs_registry
from deeplearning4j_tpu.ops import env as envknob
from deeplearning4j_tpu.online.stats import OnlineStats
from deeplearning4j_tpu.resilience.trainer import ResilientTrainer

logger = logging.getLogger(__name__)

SNAPSHOT_ROUNDS_ENV = "DL4J_TPU_ONLINE_SNAPSHOT_ROUNDS"


class _RoundView:
    """One round's iterator face over the pipeline: forwards the resume
    protocol, hooks every delivered batch (drift observation + round
    accounting), and deliberately exposes NO ``reset`` — the trainer's
    end-of-epoch ``hasattr(iterator, "reset")`` must never rewind a live
    stream's cursor."""

    def __init__(self, owner: "ContinuousTrainer") -> None:
        self._owner = owner

    def __iter__(self):
        owner = self._owner
        for ds in owner.pipe:
            if owner.drift is not None:
                owner.drift.observe(ds.features)
            owner.online_stats.bump("round_batches")
            yield ds

    def state(self):
        return self._owner.pipe.state()

    def restore_state(self, state) -> None:
        self._owner.pipe.restore_state(state)

    def batch_size(self) -> int:
        return self._owner.pipe.batch_size()

    def total_examples(self) -> int:
        return self._owner.pipe.total_examples()


class ContinuousTrainer:
    def __init__(self, net, source, *, manager=None, drift=None,
                 normalizer=None, workers: int = 1, shard=None,
                 device_put: bool = True,
                 candidate_path: Optional[str] = None,
                 snapshot_rounds: Optional[int] = None,
                 chaos=None, handle_signals: bool = True,
                 stats: Optional[OnlineStats] = None) -> None:
        self.online_stats = stats if stats is not None else OnlineStats()
        self.source = source
        if getattr(source, "stats", None) is None:
            source.stats = self.online_stats
        self.drift = drift
        if drift is not None and getattr(drift, "stats", None) is None:
            drift.stats = self.online_stats
        self.normalizer = normalizer
        self.pipe = InputPipeline(source, workers=workers, shard=shard,
                                  device_put=device_put)
        self.resilient = ResilientTrainer(
            net, manager, chaos=chaos, save_on_exit=False,
            handle_signals=handle_signals)
        self.net = self.resilient.net
        self.manager = manager
        self.candidate_path = candidate_path
        self.snapshot_rounds = int(
            snapshot_rounds if snapshot_rounds is not None
            else envknob.get_int(SNAPSHOT_ROUNDS_ENV, 1))
        self.rounds_done = 0
        # the loop's ledger joins the central registry beside the net's
        # dispatch/pipeline/resilience ledgers
        self.net.online_stats = self.online_stats
        obs_registry.register_net(self.net)

    # -- the round loop ----------------------------------------------------
    def fit_round(self) -> List[float]:
        """One fit round = one stream poll window. Restores the latest
        checkpoint (round turnover IS the resume path), fits until the
        feed idles, commits a blocking round-end checkpoint with the
        stream cursor, and exports a candidate on cadence. Returns the
        round's losses. ``Preempted`` propagates (goodbye checkpoint
        already on disk)."""
        n0 = len(self.resilient.losses)
        view = _RoundView(self)
        self.resilient.fit(view, num_epochs=1)
        losses = self.resilient.losses[n0:]
        if losses:
            self.rounds_done += 1
            self.online_stats.bump("rounds")
            if self.manager is not None:
                self.manager.save(
                    self.net, step=self.resilient.step, epoch=0,
                    iterator_state=self.pipe.state(), block=True)
            obs_journal.event(
                "online.round", round=self.rounds_done,
                step=self.resilient.step, batches=len(losses),
                offset=self.source.state().get("offset")
                if hasattr(self.source, "state") else None)
            if (self.candidate_path and self.snapshot_rounds > 0
                    and self.rounds_done % self.snapshot_rounds == 0):
                self.export_candidate(self.candidate_path)
        return losses

    def run(self, max_rounds: Optional[int] = None):
        """Round loop until the stream is closed AND drained (or
        ``max_rounds``). An idle open stream just polls again — each
        empty pass costs one idle window, never a busy spin."""
        while max_rounds is None or self.rounds_done < max_rounds:
            self.fit_round()
            if self.source.closed and self.source.backlog == 0:
                break
        return self.net

    # -- candidate export --------------------------------------------------
    def export_candidate(self, path: str) -> str:
        """Snapshot the live net as a promotable artifact: the model zip
        plus the serving normalizer (the training-time statistics the
        DriftMonitor compares against ride WITH the candidate)."""
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        ModelSerializer.write_model(self.net, path,
                                    normalizer=self.normalizer)
        self.online_stats.bump("snapshots")
        obs_journal.event("online.candidate", step=self.resilient.step,
                          round=self.rounds_done, path=str(path))
        return path

    # -- introspection -----------------------------------------------------
    @property
    def step(self) -> int:
        return self.resilient.step

    @property
    def losses(self) -> List[float]:
        return self.resilient.losses

    def snapshot(self) -> Dict[str, Any]:
        return self.online_stats.snapshot()
