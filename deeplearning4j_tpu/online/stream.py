"""StreamSource: an unbounded live feed as an ``InputPipeline`` source.

The reference ingests live data through its scaleout streaming module
(Camel/Kafka routes — SURVEY module map, deeplearning4j-scaleout
streaming): records arrive on a broker topic at their own pace and the
consumer reads from a MONOTONE OFFSET it can commit and seek back to.
This class is that consumer contract shrunk to one process, shaped as a
pipeline source (``etl/pipeline.InputPipeline`` wrap mode —
``from_native`` generalized to a feed that never ends):

  push(ds)    the producer side: assigns the next monotone offset and
              buffers the batch. BLOCKS while ``watermark`` batches sit
              undelivered (backpressure — a slow trainer must slow the
              feed, not OOM the host; ``StreamBackpressure`` on a push
              timeout so a producer can shed instead of hang).
  __iter__    ONE POLL WINDOW, not the whole stream: yields buffered
              batches in offset order, waits up to ``idle_s`` for the
              next arrival, and ends the pass when the stream idles
              (``idle_s=0`` blocks until close). The pipeline's
              end-of-pass is therefore "the feed went quiet", which is
              what bounds one ContinuousTrainer fit round.
  state()     ``{"offset": next_to_deliver}`` — snapshotted by the
              pipeline AFTER each delivered batch, so the pipeline's
              delivered-batch cursor IS the stream offset and
              ``ResilientTrainer`` kill/resume == replay, bit-exact
              (the Kafka committed-offset model: ``restore_state``
              seeks; a fresh process re-pushes from the committed
              offset and the offsets line up again).

Deliberately NO ``reset()``: a live feed cannot rewind, and its absence
keeps both ``InputPipeline.reset`` and ``ResilientTrainer``'s
end-of-epoch reset from destroying the cursor (hasattr-guarded at both
call sites).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from deeplearning4j_tpu.ops import env as envknob

WATERMARK_ENV = "DL4J_TPU_ONLINE_WATERMARK"
IDLE_ENV = "DL4J_TPU_ONLINE_IDLE_S"


class StreamClosed(RuntimeError):
    """push() after close() — the feed is shut down."""


class StreamBackpressure(RuntimeError):
    """push() timed out waiting for watermark headroom."""


class StreamSource:
    def __init__(self, *, watermark: Optional[int] = None,
                 idle_s: Optional[float] = None, stats=None) -> None:
        self.watermark = max(1, int(
            watermark if watermark is not None
            else envknob.get_int(WATERMARK_ENV, 64)))
        self.idle_s = float(idle_s if idle_s is not None
                            else envknob.get_float(IDLE_ENV, 0.2))
        self.stats = stats  # optional OnlineStats ledger
        self._cond = threading.Condition()
        self._buf: Dict[int, Any] = {}   # offset -> DataSet
        self._read = 0                   # next offset to DELIVER
        self._next_push = 0              # next offset push() assigns
        self._closed = False
        self._last_batch_rows = 0

    # -- producer side -----------------------------------------------------
    def push(self, ds, timeout_s: Optional[float] = None) -> int:
        """Buffer one batch; returns its assigned stream offset. Blocks
        while ``watermark`` batches sit undelivered; ``timeout_s`` bounds
        the wait (``StreamBackpressure`` past it)."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + float(timeout_s))
        with self._cond:
            while (not self._closed
                   and self._next_push - self._read >= self.watermark):
                if self.stats is not None:
                    self.stats.bump("backpressure_waits")
                wait = 0.2
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        raise StreamBackpressure(
                            f"{self._next_push - self._read} batches "
                            f"undelivered >= watermark {self.watermark}")
                self._cond.wait(timeout=wait)
            if self._closed:
                raise StreamClosed("stream is closed")
            off = self._next_push
            self._buf[off] = ds
            self._next_push += 1
            try:
                self._last_batch_rows = int(ds.num_examples())
            except Exception:  # noqa: BLE001 — telemetry only
                pass
            if self.stats is not None:
                self.stats.bump("pushed_batches")
            self._cond.notify_all()
            return off

    def close(self) -> None:
        """Stop the feed: buffered batches still deliver, then iteration
        ends permanently; further push() raises StreamClosed."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def backlog(self) -> int:
        """Undelivered buffered batches (the backpressure quantity)."""
        with self._cond:
            return self._next_push - self._read

    # -- consumer side (the pipeline's dispatcher thread) ------------------
    def __iter__(self):
        idle = self.idle_s
        while True:
            with self._cond:
                deadline = (None if idle <= 0
                            else time.monotonic() + idle)
                while self._read not in self._buf and not self._closed:
                    wait = 0.2
                    if deadline is not None:
                        wait = min(wait, deadline - time.monotonic())
                        if wait <= 0:
                            break
                    self._cond.wait(timeout=wait)
                if self._read not in self._buf:
                    if not self._closed and self.stats is not None:
                        self.stats.bump("idle_windows")
                    return  # idle window expired, or closed and drained
                ds = self._buf.pop(self._read)
                self._read += 1
                if self.stats is not None:
                    self.stats.bump("delivered_batches")
                self._cond.notify_all()
            yield ds

    # -- resume protocol (datasets/iterator.DataSetIterator.state) ---------
    def state(self) -> Dict[str, int]:
        with self._cond:
            return {"offset": self._read}

    def restore_state(self, state: Dict[str, int]) -> None:
        """Seek to a committed offset. Buffered batches below it are
        dropped (already consumed by the run being resumed); on a FRESH
        source the producer re-pushes from the committed offset and the
        monotone numbering continues from there — exactly the Kafka
        seek-to-committed replay."""
        k = int(state["offset"])
        with self._cond:
            for off in [o for o in self._buf if o < k]:
                del self._buf[off]
            self._read = k
            self._next_push = max(self._next_push, k)
            self._cond.notify_all()

    # -- DataSetIterator surface ------------------------------------------
    def batch_size(self) -> int:
        return self._last_batch_rows

    def total_examples(self) -> int:
        return 0  # unbounded stream — no total exists
