"""ShadowPromoter: zero-downtime candidate promotion behind live gates.

The reference rolls a new model by rolling the serving route
(DL4jServeRouteBuilder.java — one model per route build); promotion here
is data, staged through the registry lifecycle the serving plane already
trusts (ISSUE 8 isolation):

  stage      load the candidate zip + warm its bucket ladder (a failure
             lands the record BROKEN, the serving default never moves),
             then attach a :class:`ShadowMirror` to the engine: a
             configurable fraction of answered /predict traffic is
             re-run against the candidate OFF the client thread. Shadow
             answers NEVER reach clients, never block the answer path,
             and never vote a replica/model breaker — mirroring on must
             leave client-visible outputs byte-identical (quick tier,
             contract d).
  evaluate   render the promotion gates over the mirror's telemetry
             (min mirrored volume, zero shadow errors, argmax agreement
             vs the primary) and the DriftMonitor verdict.
  promote    all gates green: atomically swap the serving default
             (``registry.serve`` — in-flight requests finish on the old
             version; admitted requests never fail across the swap).
             Any gate red: the candidate is marked BROKEN (auditable at
             /models) and ``PromotionRefused`` raises — the default
             never moves on drift or a failed gate. A drain racing the
             promotion hits the SEALED registry (DrainingError) before
             any traffic moves — the mirror is detached either way.
  rollback   re-serve the lineage's recorded prior default
             (``registry.rollback_target``).

``promote_fleet`` runs the same local gates, then delegates the swap to
``FleetRouter.rollout`` (per-replica load → warmup → serve with
auto-rollback).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Optional

import numpy as np

from deeplearning4j_tpu.obs import journal as obs_journal
from deeplearning4j_tpu.obs import registry as obs_registry
from deeplearning4j_tpu.ops import env as envknob
from deeplearning4j_tpu.online.stats import OnlineStats

FRACTION_ENV = "DL4J_TPU_ONLINE_SHADOW_FRACTION"
SHADOW_MIN_ENV = "DL4J_TPU_ONLINE_SHADOW_MIN"
GATE_AGREE_ENV = "DL4J_TPU_ONLINE_GATE_AGREE"


class PromotionRefused(RuntimeError):
    """A promotion gate failed (or drift is alarmed); the serving
    default did not move and the candidate landed broken."""

    def __init__(self, report: Dict[str, Any]):
        super().__init__(
            f"promotion refused: {', '.join(report.get('failed', []))}")
        self.report = report


class ShadowMirror:
    """Mirrors answered /predict traffic to a candidate record.

    ``offer(x, primary_out)`` is called on the CLIENT answer path
    (engine._offer_shadow) and therefore never raises, never blocks and
    never votes: a deterministic fraction stride (accumulated
    ``DL4J_TPU_ONLINE_SHADOW_FRACTION`` — no RNG, so contract-d replays
    are exact) selects requests into a bounded queue; a queue at
    capacity DROPS (counted) rather than stalls. One worker thread
    shapes the rows for the candidate (its OWN input_shape/normalizer)
    and runs ``model.output`` under a private lock — the candidate's
    dispatches never contend with the primary's serving lock."""

    def __init__(self, rec, *, fraction: Optional[float] = None,
                 stats: Optional[OnlineStats] = None,
                 queue_cap: int = 256) -> None:
        self.rec = rec
        f = (fraction if fraction is not None
             else envknob.get_float(FRACTION_ENV, 1.0))
        self.fraction = min(1.0, max(0.0, float(f)))
        self.stats = stats if stats is not None else OnlineStats()
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(queue_cap)))
        self._accum_lock = threading.Lock()
        self._accum = 0.0
        self._count_lock = threading.Lock()
        self.compared_rows = 0
        self.agreed_rows = 0
        self._shadow_lock = threading.Lock()  # serializes candidate output()
        self._busy = False
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, name="shadow-mirror", daemon=True)
        self._thread.start()

    # -- the answer-path hook (MUST be non-throwing / non-blocking) --------
    def offer(self, x, primary_out) -> None:
        try:
            with self._accum_lock:
                self._accum += self.fraction
                take = self._accum >= 1.0
                if take:
                    self._accum -= 1.0
            if not take:
                self.stats.bump("mirror_skipped")
                return
            self._q.put_nowait((np.asarray(x), np.asarray(primary_out)))
        except queue.Full:
            self.stats.bump("mirror_dropped")
        except Exception:  # noqa: BLE001 — the client path is sacred
            self.stats.bump("mirror_errors")

    # -- the worker --------------------------------------------------------
    def _worker(self) -> None:
        from deeplearning4j_tpu.serving.engine import ServingEngine

        while not self._stop.is_set():
            try:
                x, primary = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            self._busy = True
            try:
                x2 = ServingEngine._shape_rows(self.rec, x)
                with self._shadow_lock:
                    out = self.rec.model.output(x2)
                out0 = np.asarray(
                    out[0] if isinstance(out, (list, tuple)) else out)
                self.stats.bump("mirrored")
                self._compare(primary, out0)
            except Exception:  # noqa: BLE001 — shadow failure is telemetry
                self.stats.bump("mirror_errors")
            finally:
                self._busy = False

    def _compare(self, primary: np.ndarray, shadow: np.ndarray) -> None:
        """Per-row argmax agreement — the cheap label-level fidelity
        signal the agreement gate consumes (regression outputs with no
        class axis just skip the comparison)."""
        if primary.ndim < 2 or shadow.shape != primary.shape:
            return
        agree = int(np.sum(np.argmax(primary, axis=-1)
                           == np.argmax(shadow, axis=-1)))
        rows = int(primary.shape[0])
        with self._count_lock:
            self.compared_rows += rows
            self.agreed_rows += agree
        if agree < rows:
            self.stats.bump("mirror_disagreements", rows - agree)

    # -- lifecycle / reporting ---------------------------------------------
    def wait_idle(self, timeout_s: float = 5.0) -> bool:
        """Block until the mirror queue is drained (tests/bench sync)."""
        import time

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._q.empty() and not self._busy:
                return True
            time.sleep(0.005)
        return False

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    def report(self) -> Dict[str, Any]:
        snap = self.stats.snapshot()
        with self._count_lock:
            compared, agreed = self.compared_rows, self.agreed_rows
        return {
            "candidate": self.rec.key,
            "fraction": self.fraction,
            "mirrored": snap["mirrored"],
            "skipped": snap["mirror_skipped"],
            "dropped": snap["mirror_dropped"],
            "errors": snap["mirror_errors"],
            "disagreements": snap["mirror_disagreements"],
            "agreement": (agreed / compared) if compared else None,
        }


class ShadowPromoter:
    def __init__(self, engine, *, drift=None,
                 fraction: Optional[float] = None,
                 min_mirrored: Optional[int] = None,
                 gate_agree: Optional[float] = None,
                 gate_fn: Optional[Callable[[Dict[str, Any]],
                                            Optional[str]]] = None,
                 stats: Optional[OnlineStats] = None) -> None:
        self.engine = engine
        self.drift = drift
        self.fraction = fraction
        self.min_mirrored = int(
            min_mirrored if min_mirrored is not None
            else envknob.get_int(SHADOW_MIN_ENV, 32))
        self.gate_agree = float(
            gate_agree if gate_agree is not None
            else envknob.get_float(GATE_AGREE_ENV, 0.0))
        self.gate_fn = gate_fn
        self.online_stats = stats if stats is not None else OnlineStats()
        # the promotion ledger joins the central registry beside the
        # engine's serving_stats
        obs_registry.default_registry().register_ledger(
            self, "online_stats", self.online_stats)
        self.candidate = None
        self.mirror: Optional[ShadowMirror] = None

    # -- stage -------------------------------------------------------------
    def stage(self, name: str, model_path: Optional[str] = None,
              model=None, *, input_shape=None, normalizer=None,
              max_batch: int = 64, sample_row=None):
        """Load + warm the candidate and start mirroring. A load/warmup
        failure lands the record broken (ISSUE 8) and re-raises — the
        serving default never moves, nothing was attached."""
        registry = self.engine.registry
        rec = registry.load(name, model=model, model_path=model_path,
                            input_shape=input_shape, normalizer=normalizer)
        registry.warmup(rec.name, rec.version, max_batch=max_batch,
                        sample_row=sample_row)
        self.candidate = rec
        self.mirror = ShadowMirror(rec, fraction=self.fraction,
                                   stats=self.online_stats)
        self.engine.attach_shadow(self.mirror)
        obs_journal.event("online.shadow_staged", candidate=rec.key,
                          fraction=self.mirror.fraction)
        return rec

    # -- gates -------------------------------------------------------------
    def evaluate(self) -> Dict[str, Any]:
        """Render every promotion gate over the current shadow window.
        Side-effect-free: safe to poll while traffic flows."""
        if self.candidate is None or self.mirror is None:
            raise RuntimeError("no staged candidate (call stage() first)")
        report = self.mirror.report()
        failed = []
        if self.drift is not None:
            verdict = self.drift.check()
            report["drift"] = verdict
            if verdict["alarmed"]:
                failed.append("drift_alarm")
        if report["mirrored"] < self.min_mirrored:
            failed.append(
                f"min_mirrored ({report['mirrored']}/{self.min_mirrored})")
        if report["errors"] > 0:
            failed.append(f"mirror_errors ({report['errors']})")
        if self.gate_agree > 0:
            agreement = report["agreement"]
            if agreement is None or agreement < self.gate_agree:
                failed.append(
                    f"agreement ({agreement} < {self.gate_agree})")
        if self.gate_fn is not None:
            why = self.gate_fn(dict(report))
            if why:
                failed.append(str(why))
        report["failed"] = failed
        report["ok"] = not failed
        return report

    # -- promote / refuse --------------------------------------------------
    def _detach(self) -> None:
        if self.mirror is not None:
            self.engine.detach_shadow(self.mirror)
            self.mirror.close()

    def _refuse(self, report: Dict[str, Any]) -> None:
        """The refusal path: candidate lands BROKEN (auditable, never
        promotable by a later stray serve()), mirror detached, journaled."""
        self._detach()
        self.engine.registry.mark_broken(
            self.candidate.name, self.candidate.version,
            error="promotion refused: " + ", ".join(report["failed"]))
        self.online_stats.bump("promotion_refusals")
        obs_journal.event("online.promotion_refused",
                          candidate=self.candidate.key,
                          failed=report["failed"])
        raise PromotionRefused(report)

    def promote(self) -> Dict[str, Any]:
        """Evaluate the gates and, all green, atomically swap the serving
        default to the candidate. Gate failure → ``PromotionRefused``
        (default unmoved, candidate broken). A drain racing this call
        hits the sealed registry: DrainingError propagates, the default
        never moved, the mirror is detached (the candidate record stays
        warm — a drain is not a verdict on the model)."""
        report = self.evaluate()
        if not report["ok"]:
            self._refuse(report)
        try:
            rec = self.engine.registry.serve(self.candidate.name,
                                             self.candidate.version)
        finally:
            # success or DrainingError: the mirror's job is done either way
            self._detach()
        self.online_stats.bump("promotions")
        obs_journal.event("online.promoted", candidate=rec.key,
                          prior=rec.prior_default,
                          mirrored=report["mirrored"],
                          agreement=report["agreement"])
        report["promoted"] = rec.key
        report["prior_default"] = rec.prior_default
        return report

    def abort(self, reason: str = "aborted by operator") -> None:
        """Tear down a staged shadow without promoting (candidate marked
        broken so the staging attempt is auditable)."""
        if self.candidate is None:
            return
        self._detach()
        try:
            self.engine.registry.mark_broken(
                self.candidate.name, self.candidate.version, error=reason)
        except ValueError:
            pass  # already the default (promoted elsewhere) — leave it
        self.online_stats.bump("promotion_refusals")
        obs_journal.event("online.shadow_aborted",
                          candidate=self.candidate.key, reason=reason)

    def rollback(self):
        """Re-serve the lineage's recorded prior default."""
        target = self.engine.registry.rollback_target()
        if target is None:
            raise ValueError("no promotable rollback target in lineage")
        rec = self.engine.registry.serve(*target)
        self.online_stats.bump("rollbacks")
        obs_journal.event("online.rollback", to=rec.key)
        return rec

    # -- fleet-scoped promotion --------------------------------------------
    def promote_fleet(self, router, name: str, path: str, *,
                      input_shape=None, max_batch: Optional[int] = None,
                      gen_tokens: int = 0) -> Dict[str, Any]:
        """Same local gates, fleet-scoped swap: delegates to
        ``FleetRouter.rollout`` (per-replica load → warmup → serve with
        auto-rollback). A rollout that rolled back counts as a refusal."""
        report = self.evaluate()
        if not report["ok"]:
            self._refuse(report)
        res = router.rollout(name, path, input_shape=input_shape,
                             max_batch=max_batch, gen_tokens=gen_tokens)
        report["rollout"] = res
        if not res.get("ok"):
            self.online_stats.bump("promotion_refusals")
            self._detach()
            raise PromotionRefused({**report,
                                    "failed": ["fleet_rollout_rolled_back"]})
        self._detach()
        self.online_stats.bump("promotions")
        obs_journal.event("online.promoted_fleet", model=name,
                          replicas=len(res.get("replicas", [])))
        return report
