"""Online-learning telemetry: the ``online_stats`` ledger.

One thread-safe counter surface for the whole continuous-learning loop
(stream ingest -> fit rounds -> drift verdicts -> shadow mirroring ->
promotions), shaped like every other ledger in the repo
(``dispatch_stats``/``pipeline_stats``/``resilience_stats``/
``serving_stats``): plain counters behind a lock, ``snapshot()`` as the
JSON-able read surface the central ``obs.MetricsRegistry`` flattens into
Prometheus samples. The reference's streaming module exposes nothing
comparable (the Camel routes are fire-and-forget — SURVEY module map,
deeplearning4j-scaleout streaming); this ledger is what makes the loop
operable.

Registration happens at the ATTACH points (``online/trainer.py`` binds
it onto the net beside ``pipeline_stats``; ``online/promote.py``
registers the promoter's ledger) — the graftlint ``ledger-registration``
rule enforces that mechanically.
"""

from __future__ import annotations

import threading
from typing import Any, Dict


class OnlineStats:
    """Counters for the ingest -> fit -> drift -> shadow -> promote loop.
    Writers: the stream producer, the trainer round loop, the drift
    monitor, the shadow-mirror worker, the promoter. One lock — every
    field is a scalar bump, never a device sync."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # stream plane
        self.pushed_batches = 0
        self.delivered_batches = 0
        self.backpressure_waits = 0
        self.idle_windows = 0
        # fit plane
        self.rounds = 0
        self.round_batches = 0
        self.snapshots = 0
        # drift plane
        self.drift_checks = 0
        self.drift_alarms = 0
        self.last_drift_z = 0.0
        # shadow/promotion plane
        self.mirrored = 0
        self.mirror_skipped = 0
        self.mirror_dropped = 0
        self.mirror_errors = 0
        self.mirror_disagreements = 0
        self.promotions = 0
        self.promotion_refusals = 0
        self.rollbacks = 0

    def bump(self, field: str, by: float = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + by)

    def set(self, field: str, value: float) -> None:
        with self._lock:
            setattr(self, field, value)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "pushed_batches": self.pushed_batches,
                "delivered_batches": self.delivered_batches,
                "backpressure_waits": self.backpressure_waits,
                "idle_windows": self.idle_windows,
                "rounds": self.rounds,
                "round_batches": self.round_batches,
                "snapshots": self.snapshots,
                "drift_checks": self.drift_checks,
                "drift_alarms": self.drift_alarms,
                "last_drift_z": round(float(self.last_drift_z), 6),
                "mirrored": self.mirrored,
                "mirror_skipped": self.mirror_skipped,
                "mirror_dropped": self.mirror_dropped,
                "mirror_errors": self.mirror_errors,
                "mirror_disagreements": self.mirror_disagreements,
                "promotions": self.promotions,
                "promotion_refusals": self.promotion_refusals,
                "rollbacks": self.rollbacks,
            }

    def __repr__(self) -> str:  # pragma: no cover - debugging nicety
        return f"OnlineStats({self.snapshot()})"
