"""DriftMonitor: live feature moments vs the training-time statistics.

The fitted normalizer that rides the model zip (``etl/normalize.py``,
reference NormalizerStandardize) IS the training-time distribution record
— mean/std per final-axis column, fitted once over the training stream.
This monitor accumulates the SAME streaming moments (count/sum/sumsq in
float64, ``NormalizerStandardize._acc_one`` — literally the same
machinery, so live and baseline moments are computed identically) over
the live feed and renders a z-score verdict:

    z_j = |live_mean_j - base_mean_j| / base_std_j
    alarm  when  max_j z_j > DL4J_TPU_ONLINE_DRIFT_Z
           once  live rows >= DL4J_TPU_ONLINE_DRIFT_MIN

The alarm is LATCHED (``alarmed`` stays up until ``reset()``): drift is a
state, not an event — the promoter refuses to promote while it holds
(the serving default must not move onto a model trained on data the
live distribution has left behind). Alarms ride the obs flight recorder
(``online.drift_alarm``) and the ``online_stats`` ledger.

Deterministic by construction: pure arithmetic on the observed batches —
a scripted distribution shift alarms identically every run (the quick
tier's contract c).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import numpy as np

from deeplearning4j_tpu.etl.normalize import NormalizerStandardize
from deeplearning4j_tpu.obs import journal as obs_journal
from deeplearning4j_tpu.ops import env as envknob

DRIFT_Z_ENV = "DL4J_TPU_ONLINE_DRIFT_Z"
DRIFT_MIN_ENV = "DL4J_TPU_ONLINE_DRIFT_MIN"


class DriftMonitor:
    def __init__(self, baseline, *, z_threshold: Optional[float] = None,
                 min_rows: Optional[int] = None, stats=None) -> None:
        """``baseline`` is a FITTED NormalizerStandardize (the record's
        serving normalizer — the training-time statistics travelling
        with the model) or an explicit ``(mean, std)`` pair."""
        if hasattr(baseline, "mean"):
            if not getattr(baseline, "is_fit", False):
                raise ValueError("baseline normalizer is not fitted")
            mean, std = baseline.mean, baseline.std
        else:
            mean, std = baseline
        self.base_mean = np.asarray(mean, np.float64)
        self.base_std = np.where(
            np.asarray(std, np.float64) == 0, 1.0,
            np.asarray(std, np.float64))
        self.z_threshold = float(
            z_threshold if z_threshold is not None
            else envknob.get_float(DRIFT_Z_ENV, 3.0))
        self.min_rows = int(min_rows if min_rows is not None
                            else envknob.get_int(DRIFT_MIN_ENV, 64))
        self.stats = stats  # optional OnlineStats ledger
        self._lock = threading.Lock()
        self._acc = None   # [n, sum, sumsq] per column
        self._rows = 0
        self.alarmed = False
        self.last_z = 0.0

    def observe(self, features) -> None:
        """Accumulate one live batch's moments (float64 streaming sums —
        array work OUTSIDE the lock, scalar/array adds inside)."""
        x64 = np.asarray(features, np.float64)
        contrib = NormalizerStandardize._acc_one(None, x64)
        rows = int(x64.shape[0]) if x64.ndim else 1
        with self._lock:
            if self._acc is None:
                self._acc = contrib
            else:
                self._acc[0] += contrib[0]
                self._acc[1] += contrib[1]
                self._acc[2] += contrib[2]
            self._rows += rows

    def check(self) -> Dict[str, Any]:
        """Render the verdict for the window observed so far. Idempotent
        and side-effect-free except the FIRST crossing, which latches the
        alarm, journals ``online.drift_alarm`` and bumps the ledger."""
        with self._lock:
            acc = None if self._acc is None else list(self._acc)
            rows = self._rows
            alarmed = self.alarmed
        if self.stats is not None:
            self.stats.bump("drift_checks")
        if acc is None or rows < self.min_rows:
            return {"verdict": "pending", "rows": rows,
                    "min_rows": self.min_rows, "alarmed": alarmed}
        live_mean, _live_std = NormalizerStandardize._fin_one(acc)
        z = np.abs(live_mean - self.base_mean) / self.base_std
        max_z = float(np.max(z))
        worst = int(np.argmax(z))
        fresh_alarm = False
        with self._lock:
            self.last_z = max_z
            if max_z > self.z_threshold and not self.alarmed:
                self.alarmed = fresh_alarm = True
            alarmed = self.alarmed
        if self.stats is not None:
            self.stats.set("last_drift_z", max_z)
            if fresh_alarm:
                self.stats.bump("drift_alarms")
        if fresh_alarm:
            obs_journal.event("online.drift_alarm", max_z=round(max_z, 4),
                              threshold=self.z_threshold, column=worst,
                              rows=rows)
        return {"verdict": "alarm" if alarmed else "ok", "rows": rows,
                "max_z": max_z, "column": worst,
                "threshold": self.z_threshold, "alarmed": alarmed}

    def reset(self) -> None:
        """Drop the live window AND the latched alarm (the operator's
        acknowledge — e.g. after retraining on the shifted stream)."""
        with self._lock:
            self._acc = None
            self._rows = 0
            self.alarmed = False
            self.last_z = 0.0
