"""Config builder + JSON round-trip tests (reference
NeuralNetConfigurationTest / MultiLayerNeuralNetConfigurationTest pattern:
builder -> JSON -> rebuild -> equality — SURVEY.md section 4)."""

import pytest

from deeplearning4j_tpu.nn.conf import (
    ConvolutionLayer,
    DenseLayer,
    GravesLSTM,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.preprocessors import (
    CnnToFeedForwardPreProcessor,
    FeedForwardToCnnPreProcessor,
)


def mlp_conf():
    return (
        NeuralNetConfiguration.builder()
        .seed(42)
        .learning_rate(0.15)
        .updater("nesterovs")
        .momentum(0.9)
        .l2(1e-4)
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(
            1,
            OutputLayer(
                n_in=8, n_out=3, activation="softmax", loss_function="mcxent"
            ),
        )
        .backprop(True)
        .pretrain(False)
        .build()
    )


def test_builder_inheritance():
    conf = mlp_conf()
    assert conf.layers[0].learning_rate == 0.15
    assert conf.layers[0].updater == "nesterovs"
    assert conf.layers[0].momentum == 0.9
    assert conf.layers[0].l2 == 1e-4
    assert conf.layers[0].activation == "tanh"  # layer overrides global
    assert conf.layers[1].activation == "softmax"
    assert conf.seed == 42


def test_layer_override_beats_global():
    conf = (
        NeuralNetConfiguration.builder()
        .learning_rate(0.1)
        .list()
        .layer(0, DenseLayer(n_in=2, n_out=2, learning_rate=0.9))
        .layer(1, OutputLayer(n_in=2, n_out=2))
        .build()
    )
    assert conf.layers[0].learning_rate == 0.9
    assert conf.layers[1].learning_rate == 0.1


def test_json_round_trip_mlp():
    conf = mlp_conf()
    j = conf.to_json()
    conf2 = MultiLayerConfiguration.from_json(j)
    assert conf2.to_json() == j
    assert conf2.layers[0] == conf.layers[0]
    assert conf2.layers[1] == conf.layers[1]
    assert conf2.seed == conf.seed


def test_json_round_trip_cnn_with_preprocessors():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .list()
        .layer(
            0,
            ConvolutionLayer(
                n_in=1,
                n_out=6,
                kernel_size=(5, 5),
                stride=(1, 1),
                activation="relu",
            ),
        )
        .layer(1, SubsamplingLayer(pooling_type="max", kernel_size=(2, 2)))
        .layer(2, OutputLayer(n_in=864, n_out=10, activation="softmax"))
        .input_preprocessor(2, CnnToFeedForwardPreProcessor(12, 12, 6))
        .build()
    )
    conf2 = MultiLayerConfiguration.from_json(conf.to_json())
    assert conf2.to_json() == conf.to_json()
    assert isinstance(conf2.input_preprocessors[2], CnnToFeedForwardPreProcessor)
    assert conf2.layers[0].kernel_size == (5, 5)


def test_json_round_trip_rnn_tbptt():
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(0, GravesLSTM(n_in=10, n_out=20, activation="tanh"))
        .layer(1, RnnOutputLayer(n_in=20, n_out=5, activation="softmax"))
        .backprop_type("truncated_bptt")
        .t_bptt_forward_length(15)
        .t_bptt_backward_length(15)
        .build()
    )
    conf2 = MultiLayerConfiguration.from_json(conf.to_json())
    assert conf2.backprop_type == "truncated_bptt"
    assert conf2.tbptt_fwd_length == 15
    assert conf2.layers[0] == conf.layers[0]


def test_lr_schedule_round_trip():
    conf = (
        NeuralNetConfiguration.builder()
        .learning_rate(0.1)
        .learning_rate_schedule({100: 0.01, 200: 0.001})
        .list()
        .layer(0, OutputLayer(n_in=2, n_out=2))
        .build()
    )
    conf2 = MultiLayerConfiguration.from_json(conf.to_json())
    assert conf2.lr_schedule == {100: 0.01, 200: 0.001}
    assert conf2.lr_policy == "schedule"


def test_missing_layer_index_raises():
    with pytest.raises(ValueError):
        (
            NeuralNetConfiguration.builder()
            .list()
            .layer(0, DenseLayer(n_in=2, n_out=2))
            .layer(2, OutputLayer(n_in=2, n_out=2))
            .build()
        )


def test_yaml_round_trip():
    """Reference NeuralNetConfiguration.java:285-345 supports both JSON and
    YAML mappers; both round-trip the same dict schema."""
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration

    conf = (
        NeuralNetConfiguration.builder()
        .seed(9)
        .learning_rate(0.01)
        .updater("adam")
        .list()
        .layer(0, DenseLayer(n_in=5, n_out=7, activation="relu", dropout=0.25))
        .layer(1, OutputLayer(n_in=7, n_out=2, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    back = MultiLayerConfiguration.from_yaml(conf.to_yaml())
    assert back.to_dict() == conf.to_dict()


def test_graph_yaml_round_trip():
    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration

    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration

    conf = (
        NeuralNetConfiguration.builder()
        .graph_builder()
        .add_inputs("in")
        .add_layer("d", DenseLayer(n_in=4, n_out=6, activation="tanh"), "in")
        .add_layer("out", OutputLayer(n_in=6, n_out=3, activation="softmax",
                                      loss_function="mcxent"), "d")
        .set_outputs("out")
        .build()
    )
    back = ComputationGraphConfiguration.from_yaml(conf.to_yaml())
    assert back.to_dict() == conf.to_dict()
