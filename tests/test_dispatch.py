"""Dispatch-efficiency layer tests (ops/dispatch.py).

Covers the zero-retrace hot path end to end on the virtual CPU mesh:
  - bucket policy unit math
  - retrace counter: ragged batch sizes {96, 100, 128} through fit_iterator
    compile the train step at most TWICE bucketed (one per bucket) vs once
    per shape unbucketed — the acceptance bar of the dispatch PR
  - bucketing numerics: mask-corrected padding preserves the training
    math (exact-bucket batches keep bit-identical params; padded batches
    agree to reduction-reassociation tolerance)
  - buffer donation: forced donation on CPU (this jax implements it for
    real — the superseded arrays are deleted) is bit-exact against the
    non-donated step for one updater per family, never re-reads donated
    buffers, and clone() survives it
  - persistent compile cache round-trip across OS processes
  - the solver oracles' donation GUARD (they re-read the flat param
    vector by design and must never donate it)
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops import dispatch
from deeplearning4j_tpu.optimize.listeners import DispatchStatsListener

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mlp(seed=3, updater="sgd", lr=0.1, algo="stochastic_gradient_descent"):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(lr)
        .updater(updater)
        .optimization_algo(algo)
        .list()
        .layer(0, DenseLayer(n_in=12, n_out=8, activation="tanh"))
        .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _data(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 12)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


@pytest.fixture
def bucketing_on(monkeypatch):
    monkeypatch.setenv(dispatch.ENV_BUCKET, "1")


@pytest.fixture
def bucketing_off(monkeypatch):
    monkeypatch.setenv(dispatch.ENV_BUCKET, "0")


# ---------------------------------------------------------------------------
# bucket policy
# ---------------------------------------------------------------------------


def test_bucket_size_policy():
    # powers of two and 1.5x powers of two; identity on bucket members
    for n, want in [(1, 1), (2, 2), (3, 3), (4, 4), (5, 6), (6, 6), (7, 8),
                    (8, 8), (9, 12), (12, 12), (13, 16), (17, 24), (25, 32),
                    (95, 96), (96, 96), (97, 128), (100, 128), (128, 128),
                    (129, 192), (200, 256)]:
        assert dispatch.bucket_size(n) == want, (n, dispatch.bucket_size(n))
    # padding waste is bounded: bucket < 1.5x the real batch (worst case
    # sits just above a power of two, e.g. 17 -> 24)
    for n in range(1, 600):
        b = dispatch.bucket_size(n)
        assert n <= b < n * 1.5, (n, b)


# ---------------------------------------------------------------------------
# retrace counter (the acceptance criterion)
# ---------------------------------------------------------------------------


def test_one_trace_per_bucket_through_fit_iterator(monkeypatch):
    """{96, 100, 128} -> at most TWO train-step compiles (96 is a bucket;
    100 pads to 128; 128 joins the padded signature), repeats are cache
    hits — verified by the new retrace counter. Runs in the DEFAULT
    bucketing mode ("auto": the fit_iterator loop buckets out of the
    box, no env knob needed)."""
    monkeypatch.delenv(dispatch.ENV_BUCKET, raising=False)
    assert dispatch.bucketing_mode() == "auto"
    net = mlp()
    x, y = _data(324)
    offs = {96: 0, 100: 96, 128: 196}
    for b in (96, 100, 128, 100, 96, 128):
        i = offs[b]
        net.fit_iterator(ListDataSetIterator(x[i:i + b], y[i:i + b], b))
    s = net.dispatch_stats
    assert s.traces["train_step"] == 2, dict(s.traces)
    assert s.calls["train_step"] == 6
    assert s.cache_hits("train_step") == 4
    assert s.padded_batches == 2  # the two 100-row batches
    assert s.padded_examples == 2 * 28


def test_unbucketed_traces_once_per_shape(bucketing_off):
    """Seed behavior: every distinct batch shape is a full retrace."""
    net = mlp()
    x, y = _data(324)
    offs = {96: 0, 100: 96, 128: 196}
    for b in (96, 100, 128, 100):
        i = offs[b]
        net.fit(x[i:i + b], y[i:i + b])
    assert net.dispatch_stats.traces["train_step"] == 3
    assert net.dispatch_stats.cache_hits("train_step") == 1


def test_direct_fit_stays_unpadded_in_auto_mode(monkeypatch):
    """Default ("auto") mode leaves DIRECT fit() calls byte-exact — the
    equivalence contracts (fit_batches == K serial fits, distributed ==
    serial) compare direct-fit trajectories at tight tolerance."""
    monkeypatch.delenv(dispatch.ENV_BUCKET, raising=False)
    net = mlp()
    x, y = _data(100)
    net.fit(x, y)
    assert net.dispatch_stats.padded_batches == 0
    # no row mask was attached either: the unpadded signature (trailing
    # False = the lowprec train policy rides the cache key, off here)
    assert ("train_step", False, False, False, None, False) in net._jit_cache


def test_output_buckets_and_slices(bucketing_on):
    net = mlp()
    x, y = _data(128)
    net.fit(x, y)
    out_full = np.asarray(net.output(x))
    out_ragged = np.asarray(net.output(x[:100]))
    assert out_ragged.shape == (100, 3)
    # pad rows cannot leak into real rows in inference
    np.testing.assert_array_equal(out_ragged, out_full[:100])
    # 128 and padded-100 share one compiled program
    assert net.dispatch_stats.traces["output"] == 1
    assert net.dispatch_stats.calls["output"] == 2


def test_graph_container_buckets(bucketing_on):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(11)
        .learning_rate(0.1)
        .graph_builder()
        .add_inputs("in")
        .add_layer("d", DenseLayer(n_in=12, n_out=8, activation="tanh"), "in")
        .add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                      loss_function="mcxent"), "d")
        .set_outputs("out")
        .build()
    )
    net = ComputationGraph(conf).init()
    x, y = _data(324)
    offs = {96: 0, 100: 96, 128: 196}
    for b in (96, 100, 128, 100):
        i = offs[b]
        net.fit(x[i:i + b], y[i:i + b])
    s = net.dispatch_stats
    assert s.traces["train_step"] == 2, dict(s.traces)
    assert s.padded_batches == 2
    out = np.asarray(net.output(x[:100])[0])
    assert out.shape == (100, 3)


# ---------------------------------------------------------------------------
# bucketing numerics (mask-corrected padding preserves the training math)
# ---------------------------------------------------------------------------


def test_exact_bucket_batch_trains_bit_identical(monkeypatch):
    """An exact-bucket batch (the all-ones row mask — bucketing's uniform
    jit signature) must not perturb training AT ALL: the masked mean
    reduces to the plain mean and the parameter trajectory is bit-equal."""
    x, y = _data(128)
    monkeypatch.setenv(dispatch.ENV_BUCKET, "1")
    a = mlp(updater="adam", lr=0.05)
    for _ in range(5):
        a.fit(x, y)
    monkeypatch.setenv(dispatch.ENV_BUCKET, "0")
    b = mlp(updater="adam", lr=0.05)
    for _ in range(5):
        b.fit(x, y)
    for pa, pb in zip(a.params, b.params):
        for k in pa:
            np.testing.assert_array_equal(np.asarray(pa[k]),
                                          np.asarray(pb[k]))


def test_padded_batch_trains_equivalent(monkeypatch):
    """A ragged batch (100 -> 128 pad) preserves the mathematical loss and
    gradients exactly; the committed tolerance covers float32 reduction
    reassociation only (measured ~1e-7 relative on this backend)."""
    x, y = _data(100)
    monkeypatch.setenv(dispatch.ENV_BUCKET, "1")
    a = mlp(updater="adam", lr=0.05)
    la = [float(np.asarray(a.fit(x, y))) for _ in range(5)]
    assert a.dispatch_stats.padded_batches == 5
    monkeypatch.setenv(dispatch.ENV_BUCKET, "0")
    b = mlp(updater="adam", lr=0.05)
    lb = [float(np.asarray(b.fit(x, y))) for _ in range(5)]
    np.testing.assert_allclose(la, lb, rtol=1e-5)
    for pa, pb in zip(a.params, b.params):
        for k in pa:
            np.testing.assert_allclose(np.asarray(pa[k]), np.asarray(pb[k]),
                                       rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# buffer donation
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("updater", ["sgd", "adam", "rmsprop"])
def test_donated_step_bit_exact_per_updater_family(monkeypatch, updater):
    """Donation changes buffer aliasing, never math: the donated step must
    be bit-exact against the non-donated seed step (acceptance bar, one
    optimizer per family)."""
    x, y = _data(64)
    monkeypatch.setenv(dispatch.ENV_DONATE, "force")
    a = mlp(updater=updater)
    la = [float(np.asarray(a.fit(x, y))) for _ in range(4)]
    assert a.dispatch_stats.donated_steps == 4
    assert a.dispatch_stats.copied_steps == 0
    monkeypatch.setenv(dispatch.ENV_DONATE, "0")
    b = mlp(updater=updater)
    lb = [float(np.asarray(b.fit(x, y))) for _ in range(4)]
    assert b.dispatch_stats.donated_steps == 0
    assert b.dispatch_stats.copied_steps == 4
    assert la == lb
    for pa, pb in zip(a.params, b.params):
        for k in pa:
            np.testing.assert_array_equal(np.asarray(pa[k]),
                                          np.asarray(pb[k]))


def test_donation_consumes_old_buffers_and_never_rereads(monkeypatch):
    """The smoke test of the donation contract: after a donated step the
    SUPERSEDED params/updater-state arrays are deleted (donation is real on
    this jax even on CPU), and the training loop keeps working because it
    re-binds instead of re-reading."""
    monkeypatch.setenv(dispatch.ENV_DONATE, "force")
    x, y = _data(64)
    net = mlp(updater="adam")
    net.fit(x, y)  # builds + runs the donated step once
    step = net._get_train_step(False, False)
    assert step.donated_argnums == (0, 1, 2)
    old_params, old_upd = net.params, net.updater_state
    net.fit(x, y)
    deleted = [leaf.is_deleted()
               for tree in (old_params, old_upd)
               for leaf in jax.tree_util.tree_leaves(tree)]
    assert deleted and all(deleted), "donated inputs were not consumed"
    # the loop itself never touches the dead buffers: more steps work and
    # the current state is readable
    net.fit(x, y)
    assert np.isfinite(float(np.asarray(net._score_dev)))


def test_donation_default_off_on_cpu_platform(monkeypatch):
    """Platform default (no env): CPU skips donation — the equivalence
    substrate re-reads params trees (models/transformer._donation_kwargs
    rationale, now shared via dispatch.donation_enabled)."""
    monkeypatch.delenv(dispatch.ENV_DONATE, raising=False)
    assert not dispatch.donation_enabled()  # conftest pins jax_platforms=cpu
    net = mlp()
    x, y = _data(32)
    net.fit(x, y)
    assert net._get_train_step(False, False).donated_argnums == ()


def test_clone_survives_donation(monkeypatch):
    monkeypatch.setenv(dispatch.ENV_DONATE, "force")
    x, y = _data(64)
    net = mlp(updater="adam")
    net.fit(x, y)
    twin = net.clone()
    net.fit(x, y)  # donates the original's buffers
    # the clone's leaves are REAL copies, still alive and trainable (under
    # leaf-sharing the donated originals would now be deleted arrays)
    assert not any(leaf.is_deleted()
                   for leaf in jax.tree_util.tree_leaves(twin.params))
    np.asarray(twin.params[0]["W"])  # readable
    twin.fit(x, y)
    assert np.isfinite(float(np.asarray(twin._score_dev)))


def test_solver_oracles_never_donate(monkeypatch):
    """The donation GUARD: line-search oracles re-read the flat param
    vector (backtrack probes x + step*d while x stays live), so they must
    opt out even under forced donation."""
    monkeypatch.setenv(dispatch.ENV_DONATE, "force")
    net = mlp(updater="sgd", algo="conjugate_gradient")
    x, y = _data(32)
    net.fit(x, y)
    vg, v = net._jit_cache[("solver_vg", False, False)]
    assert vg.donated_argnums == ()
    assert v.donated_argnums == ()
    assert net.dispatch_stats.traces["solver_vg"] >= 1
    # params remained readable throughout (the optimizers re-read them)
    assert np.isfinite(float(np.asarray(net.params[0]["W"]).sum()))


# ---------------------------------------------------------------------------
# telemetry surfacing
# ---------------------------------------------------------------------------


def test_dispatch_stats_listener_snapshots():
    net = mlp()
    lst = DispatchStatsListener(frequency=1)
    net.set_listeners(lst)
    x, y = _data(32)
    for _ in range(3):
        net.fit(x, y)
    assert len(lst.snapshots) == 3
    snap = lst.snapshots[-1]
    for key in ("traces", "calls", "cache_hits", "donated_steps",
                "copied_steps", "padded_batches", "iteration"):
        assert key in snap
    assert snap["traces"].get("train_step") == 1


# ---------------------------------------------------------------------------
# persistent compile cache
# ---------------------------------------------------------------------------

_CACHE_CHILD = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.ops import dispatch
d = dispatch.enable_compile_cache(sys.argv[1], min_compile_secs=0.0)
assert d == sys.argv[1], d
import jax.numpy as jnp
f = jax.jit(lambda a, b: jnp.tanh(a @ b).sum())
x = jnp.ones((32, 32))
val = float(f(x, x))
print(json.dumps({"val": val, "entries": sorted(os.listdir(sys.argv[1]))}))
"""


def _run_cache_child(cache_dir):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
    env.pop("DL4J_TPU_COMPILE_CACHE", None)
    out = subprocess.run(
        [sys.executable, "-c", _CACHE_CHILD, cache_dir],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_compile_cache_round_trip(tmp_path):
    """Two fresh OS processes share one cache dir: the first populates it,
    the second compiles the same program and adds NO new entries (same
    cache key -> served from disk) while computing the same value."""
    d = str(tmp_path / "cache")
    os.makedirs(d)
    first = _run_cache_child(d)
    assert first["entries"], "first process wrote no cache entries"
    second = _run_cache_child(d)
    assert second["val"] == first["val"]
    cache_files = [e for e in first["entries"] if e.endswith("-cache")]
    cache_files2 = [e for e in second["entries"] if e.endswith("-cache")]
    assert cache_files2 == cache_files, (
        "second process missed the persistent cache (new entries appeared)")


def test_compile_cache_env_off(monkeypatch):
    monkeypatch.setenv(dispatch.ENV_CACHE, "0")
    assert dispatch.compile_cache_dir() is None
    assert dispatch.enable_compile_cache("/tmp/ignored") is None


# ---------------------------------------------------------------------------
# fusion policy: the XLA:CPU scan-of-conv guard (ISSUE 4 satellite)
# ---------------------------------------------------------------------------

def _tiny_conv_net(seed=11):
    from deeplearning4j_tpu.nn.conf import (
        ConvolutionLayer,
        SubsamplingLayer,
    )
    from deeplearning4j_tpu.nn.conf.preprocessors import (
        CnnToFeedForwardPreProcessor,
    )

    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(0.05)
        .updater("sgd")
        .weight_init("xavier")
        .list()
        .layer(0, ConvolutionLayer(n_in=1, n_out=3, kernel_size=(3, 3),
                                   stride=(1, 1), activation="relu"))
        .layer(1, SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                   stride=(2, 2)))
        .layer(2, OutputLayer(n_in=3 * 3 * 3, n_out=2, activation="softmax",
                              loss_function="mcxent"))
        .input_preprocessor(2, CnnToFeedForwardPreProcessor(3, 3, 3))
        .build()
    )
    return MultiLayerNetwork(conf).init(input_shape=(8, 8, 1))


def _conv_data(k=2, n=4, seed=0):
    rng = np.random.default_rng(seed)
    xs = rng.standard_normal((k, n, 8, 8, 1)).astype(np.float32)
    ys = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (k, n))]
    return xs, ys


class TestScanOfConvGuard:
    def test_policy_unit(self, monkeypatch):
        monkeypatch.delenv(dispatch.ENV_FUSE, raising=False)
        # non-conv programs always fuse; conv-in-scan is CPU-gated
        assert dispatch.fusion_enabled(scanned_conv=False)
        assert not dispatch.fusion_enabled(scanned_conv=True)  # CPU substrate
        monkeypatch.setenv(dispatch.ENV_FUSE, "force")
        assert dispatch.fusion_enabled(scanned_conv=True)
        monkeypatch.setenv(dispatch.ENV_FUSE, "1")  # _ON siblings == force
        assert dispatch.fusion_enabled(scanned_conv=True)
        monkeypatch.setenv(dispatch.ENV_FUSE, "0")
        assert not dispatch.fusion_enabled(scanned_conv=False)

    def test_conv_fit_batches_falls_back_per_step(self, monkeypatch):
        """On the CPU backend a conv fit_batches drains through per-step
        fit() (the measured ~15x XLA:CPU scan-of-conv pessimization,
        BENCH_NOTES round-6) with IDENTICAL semantics — fit_batches is
        defined as K serial fits — and the fallback is visible in
        dispatch_stats."""
        monkeypatch.delenv(dispatch.ENV_FUSE, raising=False)
        xs, ys = _conv_data()

        serial = _tiny_conv_net()
        serial_losses = [float(serial.fit(xs[k], ys[k]))
                         for k in range(xs.shape[0])]

        net = _tiny_conv_net()
        losses = net.fit_batches(xs, ys)
        assert net.dispatch_stats.fused_fallbacks == 1
        # the scanned program was never built, the per-step one was
        assert net.dispatch_stats.traces.get("fit_batches", 0) == 0
        assert net.dispatch_stats.traces.get("train_step", 0) >= 1
        np.testing.assert_allclose(losses, serial_losses, rtol=1e-6)
        assert net.iteration == serial.iteration == xs.shape[0]
        for p_s, p_f in zip(serial.params, net.params):
            for name in p_s:
                np.testing.assert_allclose(
                    np.asarray(p_f[name]), np.asarray(p_s[name]),
                    rtol=1e-6, atol=1e-7, err_msg=name)

    def test_force_keeps_fused_program(self, monkeypatch):
        monkeypatch.setenv(dispatch.ENV_FUSE, "force")
        xs, ys = _conv_data()
        net = _tiny_conv_net()
        losses = net.fit_batches(xs, ys)
        assert losses.shape == (xs.shape[0],)
        assert net.dispatch_stats.fused_fallbacks == 0
        assert net.dispatch_stats.traces.get("fit_batches", 0) == 1

    def test_dense_nets_unaffected(self, monkeypatch):
        monkeypatch.delenv(dispatch.ENV_FUSE, raising=False)
        x, y = _data(24)
        net = mlp()
        losses = net.fit_batches(np.stack([x[:12], x[12:]]),
                                 np.stack([y[:12], y[12:]]))
        assert losses.shape == (2,)
        assert net.dispatch_stats.fused_fallbacks == 0
        assert net.dispatch_stats.traces.get("fit_batches", 0) == 1


# ---------------------------------------------------------------------------
# per-trace wall-seconds (compile-time triage telemetry, ISSUE 4 satellite)
# ---------------------------------------------------------------------------

class TestTraceSeconds:
    def test_trace_seconds_accrue_only_on_traces(self):
        net = mlp()
        x, y = _data(32)
        net.fit(x, y)
        s = net.dispatch_stats
        first = s.trace_seconds.get("train_step", 0.0)
        assert first > 0.0
        net.fit(x, y)  # cache hit: no new trace, no new seconds
        assert s.trace_seconds["train_step"] == first
        net.fit(x[:16], y[:16])  # new shape: retrace accrues more
        assert s.trace_seconds["train_step"] > first

    def test_snapshot_and_listener_carry_trace_seconds(self):
        net = mlp()
        x, y = _data(16)
        lst = DispatchStatsListener(frequency=1)
        net.listeners.append(lst)
        net.fit(x, y)
        snap = net.dispatch_stats.snapshot()
        assert snap["trace_seconds"]["train_step"] > 0.0
        assert snap["fused_fallbacks"] == 0
        assert lst.snapshots and "trace_seconds" in lst.snapshots[-1]
