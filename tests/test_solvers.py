"""Optimizer tests on analytic toy objectives.

Mirrors the reference's TestOptimizers
(deeplearning4j-core/src/test/java/org/deeplearning4j/optimize/solver/
TestOptimizers.java:141-302): Sphere / Rastrigin / Rosenbrock functions per
algorithm per dimension, plus BackTrackLineSearchTest and a Solver-on-network
integration test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.optimize.solvers import (
    EpsTermination,
    Norm2Termination,
    OPTIMIZERS,
    Solver,
    backtrack_line_search,
    conjugate_gradient,
    lbfgs,
    line_gradient_descent,
)


def sphere_vg():
    @jax.jit
    def vg(x):
        def f(x_):
            return jnp.sum(x_ * x_)

        return jax.value_and_grad(f)(x)

    return vg


def rosenbrock_vg():
    @jax.jit
    def vg(x):
        def f(x_):
            return jnp.sum(
                100.0 * (x_[1:] - x_[:-1] ** 2) ** 2 + (1.0 - x_[:-1]) ** 2
            )

        return jax.value_and_grad(f)(x)

    return vg


def rastrigin_vg():
    @jax.jit
    def vg(x):
        def f(x_):
            return 10.0 * x_.size + jnp.sum(
                x_ * x_ - 10.0 * jnp.cos(2.0 * jnp.pi * x_)
            )

        return jax.value_and_grad(f)(x)

    return vg


@pytest.mark.parametrize("dim", [2, 10, 100])
@pytest.mark.parametrize("opt_name", sorted(OPTIMIZERS))
def test_sphere_converges(opt_name, dim):
    """Sphere: every algorithm must reach near-zero from a random start
    (reference testSphereFnOptimization variants)."""
    rng = np.random.default_rng(dim)
    x0 = jnp.asarray(rng.uniform(-4, 4, dim))
    res = OPTIMIZERS[opt_name](
        sphere_vg(), x0, max_iterations=200, line_search_iterations=20
    )
    assert res.score < 1e-2, f"{opt_name} dim={dim}: {res.score}"


@pytest.mark.parametrize("opt_name", ["conjugate_gradient", "lbfgs"])
def test_rosenbrock_improves(opt_name):
    """Rosenbrock valley: second-order-ish methods must make strong progress
    (reference testRosenbrockFnOptimization — asserts score decreases)."""
    rng = np.random.default_rng(0)
    x0 = jnp.asarray(rng.uniform(-2, 2, 10))
    vg = rosenbrock_vg()
    first = float(vg(x0)[0])
    res = OPTIMIZERS[opt_name](vg, x0, max_iterations=300, line_search_iterations=30)
    assert res.score < first * 1e-2, f"{opt_name}: {first} -> {res.score}"


@pytest.mark.parametrize("opt_name", sorted(OPTIMIZERS))
def test_rastrigin_decreases(opt_name):
    """Rastrigin is multimodal — require decrease, not global optimum
    (reference uses the same weak assertion)."""
    rng = np.random.default_rng(1)
    x0 = jnp.asarray(rng.uniform(-4, 4, 10))
    vg = rastrigin_vg()
    first = float(vg(x0)[0])
    res = OPTIMIZERS[opt_name](vg, x0, max_iterations=100, line_search_iterations=20)
    assert res.score < first


class TestBackTrackLineSearch:
    def test_finds_decreasing_step(self):
        vg = sphere_vg()
        x = jnp.asarray([3.0, 4.0])
        score, grad = vg(x)
        step, new_score = backtrack_line_search(
            lambda p: vg(p)[0], x, float(score), grad, -grad, max_iterations=10
        )
        assert step > 0
        assert new_score < float(score)

    def test_rejects_ascent_direction(self):
        vg = sphere_vg()
        x = jnp.asarray([3.0, 4.0])
        score, grad = vg(x)
        step, new_score = backtrack_line_search(
            lambda p: vg(p)[0], x, float(score), grad, grad, max_iterations=10
        )
        assert step == 0.0
        assert new_score == float(score)


class TestTerminations:
    def test_eps_termination(self):
        t = EpsTermination(eps=1e-3, tolerance=0.0)
        assert t.terminate(100.0, 100.05)
        assert not t.terminate(100.0, 150.0)

    def test_norm2_termination(self):
        t = Norm2Termination(gradient_norm_threshold=1e-3)
        assert t.terminate(0, 0, jnp.asarray([1e-5, 1e-5]))
        assert not t.terminate(0, 0, jnp.asarray([1.0, 1.0]))


class TestSolverOnNetwork:
    @pytest.mark.parametrize("algo", ["conjugate_gradient", "lbfgs"])
    def test_network_trains_with_line_search_family(self, algo):
        """Full-batch CG/LBFGS training of a tiny MLP (reference
        MultiLayerTest with OptimizationAlgorithm.CONJUGATE_GRADIENT/LBFGS)."""
        from deeplearning4j_tpu.nn.conf import (
            DenseLayer,
            NeuralNetConfiguration,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (
            NeuralNetConfiguration.builder()
            .seed(42)
            .optimization_algo(algo)
            .iterations(30)
            .max_num_line_search_iterations(10)
            .list()
            .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(
                1,
                OutputLayer(
                    n_in=8, n_out=3, activation="softmax", loss_function="mcxent"
                ),
            )
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(3)
        x = rng.normal(size=(32, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        first = float(net.score(x, y))
        net.fit(x, y)
        last = float(net.score(x, y))
        assert last < first * 0.7, f"{algo}: {first} -> {last}"

    def test_solver_rejects_sgd(self):
        from deeplearning4j_tpu.nn.conf import (
            DenseLayer,
            NeuralNetConfiguration,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (
            NeuralNetConfiguration.builder()
            .list()
            .layer(0, DenseLayer(n_in=2, n_out=2))
            .layer(
                1,
                OutputLayer(
                    n_in=2, n_out=2, activation="softmax", loss_function="mcxent"
                ),
            )
            .build()
        )
        with pytest.raises(ValueError, match="stochastic_gradient_descent"):
            Solver(MultiLayerNetwork(conf).init(), algo="stochastic_gradient_descent")
