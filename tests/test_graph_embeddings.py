"""Graph-embedding tests — mirrors the reference deeplearning4j-graph test
strategy: graph construction/loaders, walk iterators (TestGraph,
TestRandomWalkIterator), DeepWalk end-to-end on a community graph, and the
HS gradient check (DeepWalkGradientCheck.java)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.graph import (
    DeepWalk,
    Graph,
    NoEdgeHandling,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
    build_graph_huffman,
    load_delimited_edges,
)
from deeplearning4j_tpu.graph.api import NoEdgesException
from deeplearning4j_tpu.nlp.word2vec import _skipgram_hs_step


def two_communities(n_per=8, p_in=1.0, seed=0):
    """Two dense cliques joined by a single bridge edge."""
    g = Graph(2 * n_per)
    for base in (0, n_per):
        for i in range(n_per):
            for j in range(i + 1, n_per):
                g.add_edge(base + i, base + j)
    g.add_edge(0, n_per)  # bridge
    return g


class TestGraphStructure:
    def test_adjacency_and_degree(self):
        g = Graph(4)
        g.add_edge(0, 1)
        g.add_edge(1, 2)
        assert g.get_vertex_degree(1) == 2  # undirected: 0 and 2
        assert set(g.get_connected_vertex_indices(1)) == {0, 2}

    def test_directed(self):
        g = Graph(3, directed=True)
        g.add_edge(0, 1)
        assert g.get_vertex_degree(0) == 1
        assert g.get_vertex_degree(1) == 0

    def test_loader(self, tmp_path):
        p = tmp_path / "edges.csv"
        p.write_text("// comment\n0,1\n1,2\n2,0\n")
        g = load_delimited_edges(str(p), 3)
        assert g.get_vertex_degree(0) == 2


class TestWalks:
    def test_walk_length_and_connectivity(self):
        g = two_communities()
        walks = list(RandomWalkIterator(g, walk_length=10, seed=1))
        assert len(walks) == g.num_vertices()
        for w in walks:
            assert len(w) == 11
            for a, b in zip(w[:-1], w[1:]):
                assert b in g.get_connected_vertex_indices(a) or a == b

    def test_self_loop_on_disconnected(self):
        g = Graph(3)
        g.add_edge(0, 1)
        # vertex 2 is isolated
        walks = list(RandomWalkIterator(g, walk_length=5, seed=1))
        assert all(v == 2 for v in walks[2])

    def test_exception_on_disconnected(self):
        g = Graph(2)  # no edges at all
        it = RandomWalkIterator(
            g, walk_length=3,
            no_edge_handling=NoEdgeHandling.EXCEPTION_ON_DISCONNECTED,
        )
        with pytest.raises(NoEdgesException):
            list(it)

    def test_weighted_walk_follows_heavy_edge(self):
        g = Graph(3, directed=True)
        g.add_edge(0, 1, weight=1000.0)
        g.add_edge(0, 2, weight=0.001)
        it = WeightedRandomWalkIterator(g, walk_length=1, seed=3,
                                        first_vertex=0, last_vertex=1)
        hits = [next(iter(WeightedRandomWalkIterator(
            g, walk_length=1, seed=s, first_vertex=0, last_vertex=1)))[1]
            for s in range(20)]
        assert hits.count(1) >= 19  # overwhelmingly the heavy edge


class TestGraphHuffman:
    def test_codes_prefix_free_and_in_range(self):
        degrees = np.array([10, 5, 5, 3, 2, 1])
        P, C, M = build_graph_huffman(degrees)
        n = len(degrees)
        assert P.shape[0] == n
        codes = []
        for i in range(n):
            l = int(M[i].sum())
            assert l > 0
            codes.append("".join(str(int(c)) for c in C[i, :l]))
            assert (P[i, :l] >= 0).all() and (P[i, :l] <= n - 2).all()
        for i, c1 in enumerate(codes):
            for j, c2 in enumerate(codes):
                if i != j:
                    assert not c2.startswith(c1)


class TestDeepWalkGradient:
    def test_hs_step_matches_autodiff_gradient(self):
        """DeepWalkGradientCheck analog: one (center, context) HS update must
        equal one step of gradient DESCENT on the HS loss
        sum_l -log sigmoid((1-2*code_l) * syn0[ctx]@syn1[point_l])."""
        rng = np.random.default_rng(0)
        n, d = 6, 4
        P, C, M = build_graph_huffman(np.array([5, 4, 3, 2, 2, 1]))
        syn0 = rng.normal(0, 0.1, (n, d)).astype(np.float32)
        syn1 = rng.normal(0, 0.1, (n - 1, d)).astype(np.float32)
        # pad syn1 to n rows like DeepWalk does (points < n-1 used only)
        syn1 = np.concatenate([syn1, np.zeros((1, d), np.float32)])
        center, ctx = 2, 4
        L = P.shape[1]
        l = int(M[center].sum())

        def hs_loss(s0, s1):
            tot = 0.0
            for k in range(l):
                dot = s0[ctx] @ s1[P[center, k]]
                sign = 1.0 - 2.0 * C[center, k]
                tot = tot - jax.nn.log_sigmoid(sign * dot)
            return tot

        g0, g1 = jax.grad(hs_loss, argnums=(0, 1))(jnp.asarray(syn0), jnp.asarray(syn1))
        alpha = 0.05
        out0, out1 = _skipgram_hs_step(
            jnp.asarray(syn0), jnp.asarray(syn1),
            jnp.asarray(np.array([ctx], np.int32)),
            jnp.asarray(P[[center]]), jnp.asarray(C[[center]]),
            jnp.asarray(M[[center]]), jnp.float32(alpha),
        )
        np.testing.assert_allclose(
            np.asarray(out0), syn0 - alpha * np.asarray(g0), rtol=1e-4, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(out1), syn1 - alpha * np.asarray(g1), rtol=1e-4, atol=1e-6
        )


class TestDeepWalkEndToEnd:
    def test_communities_cluster(self):
        g = two_communities(n_per=8)
        dw = DeepWalk(vector_size=16, window_size=4, learning_rate=0.05, seed=1)
        dw.fit(g, walk_length=20, epochs=8)
        in_sims, out_sims = [], []
        for i in range(1, 8):
            in_sims.append(dw.similarity(1, i + 0) if i != 1 else 1.0)
            out_sims.append(dw.similarity(1, 8 + i))
        assert np.mean(in_sims) > np.mean(out_sims)

    def test_nearest_within_community(self):
        g = two_communities(n_per=8)
        dw = DeepWalk(vector_size=16, window_size=4, learning_rate=0.05, seed=2)
        dw.fit(g, walk_length=20, epochs=8)
        near = dw.vertices_nearest(3, top_n=5)
        in_community = sum(1 for v in near if v < 8)
        assert in_community >= 3

    def test_save_load_roundtrip(self, tmp_path):
        g = two_communities(n_per=4)
        dw = DeepWalk(vector_size=8, window_size=2, seed=3)
        dw.fit(g, walk_length=8, epochs=1)
        path = str(tmp_path / "deepwalk.npz")
        dw.save(path)
        dw2 = DeepWalk.load(path)
        np.testing.assert_allclose(dw2.vertex_vectors, dw.vertex_vectors)
        assert dw2.vector_size == 8 and dw2.num_vertices == 8
