"""Decode amortization (ISSUE 16): multi-token ticks + self-speculative
decoding.

Two ways to pay the fixed per-dispatch overhead less often, both bound
by the same contract — the committed token stream is BYTE-IDENTICAL to
what k=1 ticking produces:

  * k-scanned ticks (serving/decode._tick_for(k) and the paged twin):
    the scan body IS the k=1 body, so a k-tick equals k single ticks
    across the whole PR 11 contract matrix (prefix sharing, preemption,
    crash eviction, streaming order) — the worker's adaptive drop to
    k=1 keeps admission/eviction/SLO semantics per-token;
  * speculative rounds (serving/speculate.SpeculativeDecoder): the int8
    or truncated-layer self-draft proposes, the target verifies k+1
    positions in one dispatch, and greedy acceptance commits only
    tokens the target's own argmax endorses — equal to target-only
    greedy even when chaos forces every proposal to reject.

Reference anchor: the reference decodes one token per model call
(dl4j-streaming/.../routes/DL4jServeRouteBuilder.java); provenance for
the techniques is Leviathan et al. 2023 via serving/speculate.py's
module docstring.
"""

import os
import re
import time

import numpy as np
import pytest

from deeplearning4j_tpu.ops import env
from deeplearning4j_tpu.ops import lowprec
from deeplearning4j_tpu.resilience import (
    InjectedServingFault,
    ServingChaos,
    ServingChaosConfig,
    SpecChaos,
    SpecChaosConfig,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_lm(**over):
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    kw = dict(vocab_size=29, d_model=16, n_layers=2, n_heads=2, d_ff=32,
              max_len=32, use_flash=False)
    kw.update(over)
    return TransformerLM(TransformerConfig(**kw))


PROMPTS = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]


def run_pool(dec, n_new=10, temps=(0.0, 0.0, 0.0), seed=11, stream=True):
    """Submit PROMPTS concurrently (with per-token streaming callbacks on
    the paged pool); returns (transcripts, per-request streamed tokens)."""
    streams = [[] for _ in PROMPTS]
    try:
        futs = []
        for i, (p, t) in enumerate(zip(PROMPTS, temps)):
            kw = {"on_token": streams[i].append} if stream else {}
            futs.append(dec.submit(p, n_new, temperature=t, seed=seed, **kw))
        outs = [f.result(timeout=240).tolist() for f in futs]
    finally:
        dec.stop()
    return outs, streams


# ---------------------------------------------------------------------------
# k-tick == k x 1-tick byte-identity
# ---------------------------------------------------------------------------


class TestTickIdentity:
    def test_fixed_slot_k_tick(self):
        """ContinuousDecoder at tick_k=4 == tick_k=1 byte-for-byte on a
        mixed greedy/sampled pool, in fewer dispatches."""
        from deeplearning4j_tpu.serving.decode import ContinuousDecoder

        lm = tiny_lm()
        d1 = ContinuousDecoder(lm, slots=3, tick_k=1)
        o1, _ = run_pool(d1, temps=(0.0, 0.8, 0.0), stream=False)
        dk = ContinuousDecoder(lm, slots=3, tick_k=4)
        ok, _ = run_pool(dk, temps=(0.0, 0.8, 0.0), stream=False)
        assert o1 == ok
        assert dk.dispatch_stats.decode_ticks < d1.dispatch_stats.decode_ticks
        assert dk.dispatch_stats.decode_tokens == \
            d1.dispatch_stats.decode_tokens

    def test_paged_k_tick_with_prefix_sharing(self):
        """Paged k-tick identity while co-residents physically share
        prefix blocks (the PR 11 independence matrix at k>1)."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        shared = [2, 4, 6, 8, 10, 12, 14, 16, 3, 5]
        results = []
        for k in (1, 4):
            d = PagedDecoder(lm, block_tokens=8, n_blocks=16, tick_k=k)
            try:
                f1 = d.submit(shared + [7], 5, temperature=0.0)
                f2 = d.submit(shared + [9], 5, temperature=0.0)
                results.append((f1.result(timeout=120).tolist(),
                                f2.result(timeout=120).tolist(),
                                d.stats.prefix_hits > 0))
            finally:
                d.stop()
        assert results[0] == results[1]
        assert results[0][2]  # the share actually registered

    def test_paged_k_tick_under_preemption(self):
        """A starved arena preempts mid-flight at k=4 exactly as it
        would at k=1: transcripts stay byte-equal and the preempted
        sequence replays nothing."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        outs = {}
        for k in (1, 4):
            # 7 blocks * 8 tokens cannot hold three ~24-token sequences
            # at once: growth must preempt (test_serving_paged.py idiom)
            d = PagedDecoder(lm, lanes=3, block_tokens=8, n_blocks=7,
                             tick_k=k)
            try:
                futs = [d.submit(p, 20, temperature=0.7, seed=3)
                        for p in PROMPTS]
                outs[k] = [f.result(timeout=240).tolist() for f in futs]
                preempted = d.stats.preemptions
            finally:
                d.stop()
        assert outs[1] == outs[4]
        assert preempted > 0  # the k=4 run actually exercised the path

    def test_paged_k_tick_crash_eviction(self):
        """A chaos-crashed admission under k=4 fails only its own
        future; the co-resident's stream equals its solo baseline."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        d0 = PagedDecoder(lm, block_tokens=8, n_blocks=16, tick_k=4)
        try:
            solo = d0.generate(np.asarray([[1, 5, 2, 9]]), 8,
                               temperature=0.0)[0]
        finally:
            d0.stop()
        chaos = ServingChaos(ServingChaosConfig(admit_raise_at=2))
        d = PagedDecoder(lm, block_tokens=8, n_blocks=16, tick_k=4,
                         chaos=chaos)
        try:
            ok_fut = d.submit([1, 5, 2, 9], 8, temperature=0.0)
            time.sleep(0.05)
            crash_fut = d.submit([3, 3, 4], 6, temperature=0.0)
            with pytest.raises(InjectedServingFault):
                crash_fut.result(timeout=60)
            np.testing.assert_array_equal(solo, ok_fut.result(timeout=120))
        finally:
            d.stop()

    def test_tokens_per_dispatch_ledger(self):
        """dispatch_stats grows decode_ticks/decode_tokens and derives
        tokens_per_dispatch — and the decoder registered the ledger with
        the obs registry (the scrape surface)."""
        from deeplearning4j_tpu.obs.registry import default_registry
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        d = PagedDecoder(lm, block_tokens=8, n_blocks=16, tick_k=4)
        try:
            d.generate(np.asarray([[1, 5, 2, 9]]), 8, temperature=0.0)
            snap = d.dispatch_stats.snapshot()
            assert snap["decode_ticks"] > 0
            assert snap["decode_tokens"] == 8
            assert snap["tokens_per_dispatch"] == pytest.approx(
                snap["decode_tokens"] / snap["decode_ticks"])
            samples = default_registry().collect_ledger_samples()
            assert any(name == "dl4j_dispatch_decode_ticks"
                       for name, _, _ in samples)
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# speculative greedy == target-only greedy
# ---------------------------------------------------------------------------


def spec_decoder(lm, mode="int8", **kw):
    from deeplearning4j_tpu.serving.speculate import SpeculativeDecoder

    kw.setdefault("lanes", 3)
    kw.setdefault("block_tokens", 4)
    kw.setdefault("n_blocks", 24)
    return SpeculativeDecoder(lm, draft=lowprec.draft_lm(lm, mode),
                              spec_k=3, **kw)


class TestSpeculative:
    def test_spec_equals_target_greedy(self):
        """Both self-draft modes commit the exact target-only greedy
        stream (transcripts AND streaming order), with the acceptance
        ledger live."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        base_o, base_s = run_pool(
            PagedDecoder(lm, lanes=3, block_tokens=4, n_blocks=24))
        for mode in ("int8", "layers:1"):
            d = spec_decoder(lm, mode)
            o, s = run_pool(d)
            assert o == base_o and s == base_s, mode
            assert d.spec_rounds > 0
            snap = d.stats.snapshot()
            assert snap["draft_proposed"] > 0
            assert 0.0 <= snap["acceptance_rate"] <= 1.0

    def test_chaos_all_reject_round_stays_byte_exact(self):
        """SpecChaos corrupts every proposal at acceptance-comparison
        time: the round commits only the target's own correction, so the
        stream is unchanged — the draft can slow decoding, never bend
        it."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        base_o, base_s = run_pool(
            PagedDecoder(lm, lanes=3, block_tokens=4, n_blocks=24))
        chaos = SpecChaos(SpecChaosConfig(reject_at_round=0, count=2))
        d = spec_decoder(lm, spec_chaos=chaos)
        o, s = run_pool(d)
        assert o == base_o and s == base_s
        assert chaos.log and chaos.log[0][1] == "reject_all"
        assert d.stats.draft_rejected > 0
        assert d.stats.snapshot()["acceptance_rate"] < 1.0

    def test_sampled_pool_falls_back_to_base_tick(self):
        """A sampled lane makes the pool ineligible: the decoder runs
        the inherited tick phase (spec_rounds == 0) and stays
        byte-identical to PagedDecoder."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        base_o, base_s = run_pool(
            PagedDecoder(lm, lanes=3, block_tokens=4, n_blocks=24),
            temps=(0.8, 0.8, 0.8))
        d = spec_decoder(lm)
        o, s = run_pool(d, temps=(0.8, 0.8, 0.8))
        assert o == base_o and s == base_s
        assert d.spec_rounds == 0

    def test_spec_under_preemption(self):
        """Block exhaustion preempts and re-admits under the spec
        decoder exactly as under the base pool (greedy: byte-equal)."""
        from deeplearning4j_tpu.serving.paged import PagedDecoder

        lm = tiny_lm()
        base = PagedDecoder(lm, lanes=3, block_tokens=8, n_blocks=7)
        base_o, base_s = run_pool(base, n_new=20)
        d = spec_decoder(lm, block_tokens=8, n_blocks=7)
        o, s = run_pool(d, n_new=20)
        assert o == base_o and s == base_s
        assert d.stats.preemptions > 0

    def test_draft_validation(self):
        from deeplearning4j_tpu.serving.speculate import SpeculativeDecoder

        lm = tiny_lm()
        with pytest.raises(ValueError):
            SpeculativeDecoder(lm, draft=tiny_lm(vocab_size=31),
                               block_tokens=8, n_blocks=16)
        with pytest.raises(ValueError):
            SpeculativeDecoder(lm, draft=None, block_tokens=8, n_blocks=16)

    def test_acceptance_ledger_arithmetic(self):
        from deeplearning4j_tpu.serving.telemetry import ServingStats

        st = ServingStats()
        st.record_draft(3, 3)
        st.record_draft(3, 0)
        snap = st.snapshot()
        assert snap["draft_proposed"] == 6
        assert snap["draft_accepted"] == 3
        assert snap["draft_rejected"] == 3
        assert snap["acceptance_rate"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# drafts: ops/lowprec.draft_lm + registry caching
# ---------------------------------------------------------------------------


class TestDrafts:
    def test_draft_lm_modes(self):
        lm = tiny_lm()
        d8 = lowprec.draft_lm(lm, "int8")
        assert d8.draft_mode == "int8"
        assert d8._run_cfg == lm._run_cfg
        # fake-quantization actually moved the block weights
        assert not np.allclose(np.asarray(d8.params["blocks"]["Wq"]),
                               np.asarray(lm.params["blocks"]["Wq"]))
        dl = lowprec.draft_lm(lm, "layers:1")
        assert dl._run_cfg.n_layers == 1
        assert np.asarray(dl.params["blocks"]["Wq"]).shape[0] == 1
        with pytest.raises(ValueError):
            lowprec.draft_lm(lm, "layers:9")
        with pytest.raises(ValueError):
            lowprec.draft_lm(lm, "bogus")

    def test_record_draft_net_cached(self):
        """One derivation per (record, mode) however many decoders the
        engine rebuilds around the record."""
        from deeplearning4j_tpu.serving.registry import ModelRecord

        rec = ModelRecord("m", 1, tiny_lm())
        d1 = rec.draft_net("int8")
        assert d1 is rec.draft_net("int8")
        assert d1 is not rec.draft_net("layers:1")

    def test_spec_mode_parsing(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_SERVE_SPEC", raising=False)
        assert lowprec.spec_mode() == ""
        monkeypatch.setenv("DL4J_TPU_SERVE_SPEC", "0")
        assert lowprec.spec_mode() == ""
        monkeypatch.setenv("DL4J_TPU_SERVE_SPEC", "1")
        assert lowprec.spec_mode() == "int8"
        monkeypatch.setenv("DL4J_TPU_SERVE_SPEC", "layers:2")
        assert lowprec.spec_mode() == "layers:2"


# ---------------------------------------------------------------------------
# engine wiring
# ---------------------------------------------------------------------------


class TestEngineWiring:
    def test_engine_builds_spec_decoder_and_stays_byte_exact(self,
                                                             monkeypatch):
        """DL4J_TPU_SERVE_SPEC=int8 + a paged pool: the engine serves
        /generate through a SpeculativeDecoder and the greedy output is
        byte-identical to the spec-off engine."""
        from deeplearning4j_tpu.serving.engine import ServingEngine
        from deeplearning4j_tpu.serving.speculate import SpeculativeDecoder

        lm = tiny_lm()
        prompts = np.asarray([[1, 5, 2, 9]])
        monkeypatch.delenv("DL4J_TPU_SERVE_SPEC", raising=False)
        eng = ServingEngine(model=lm, kv_block=8, kv_blocks=16)
        try:
            base = eng.generate(prompts, 8, temperature=0.0)
        finally:
            eng.stop()
        monkeypatch.setenv("DL4J_TPU_SERVE_SPEC", "int8")
        eng = ServingEngine(model=lm, kv_block=8, kv_blocks=16)
        try:
            out = eng.generate(prompts, 8, temperature=0.0)
            rec = eng.registry.default()
            assert isinstance(eng._decoder_for(rec), SpeculativeDecoder)
        finally:
            eng.stop()
        np.testing.assert_array_equal(base, out)


# ---------------------------------------------------------------------------
# knob + bench-leg registration
# ---------------------------------------------------------------------------


class TestRegistration:
    def test_knobs_registered(self):
        for name in ("DL4J_TPU_SERVE_TICK_K", "DL4J_TPU_SERVE_SPEC",
                     "DL4J_TPU_SERVE_SPEC_K"):
            assert env.is_registered(name), name

    def test_decode_amortize_leg_registered(self):
        """bench.py defines the decode_amortize leg, bench_state expects
        it, and it is marked CPU-only (runs with the tunnel down)."""
        from scripts.bench_state import EXPECTED

        assert "decode_amortize" in EXPECTED
        src = open(os.path.join(REPO, "bench.py")).read()
        legs = set(re.findall(r'^\s*run\("([a-z0-9_]+)"', src, re.M))
        assert "decode_amortize" in legs
        cpu_only = re.search(r"_CPU_ONLY_LEGS\s*=\s*\{([^}]*)\}", src)
        assert "decode_amortize" in cpu_only.group(1)
