"""Transformer LM flagship: correctness, sharded == serial, MoE, generation.

The sharded-vs-serial equivalence tests mirror the reference's
distributed==serial strategy (SURVEY.md section 4) on the virtual 8-device
CPU mesh: the SAME train step jitted (a) unsharded on one device and
(b) GSPMD-sharded over a data x model mesh must produce the same loss curve.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    forward,
    init_params,
    make_train_step,
    init_opt_state,
    shard_params,
)
from deeplearning4j_tpu.parallel.mesh import device_mesh


def _cfg(**kw):
    base = dict(vocab_size=50, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                max_len=16, learning_rate=1e-3, seed=0)
    base.update(kw)
    return TransformerConfig(**base)


def _batch(cfg, n=4, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, cfg.vocab_size, (n, cfg.max_len + 1))
    return jnp.asarray(toks[:, :-1], jnp.int32), jnp.asarray(toks[:, 1:], jnp.int32)


class TestForward:
    def test_shapes_and_causality(self):
        cfg = _cfg()
        params = init_params(cfg)
        x, _ = _batch(cfg)
        logits, aux = forward(params, x, cfg)
        assert logits.shape == (4, cfg.max_len, cfg.vocab_size)
        assert float(aux) == 0.0  # dense model: no aux loss
        # causality: changing a future token must not change past logits
        x2 = x.at[:, -1].set((x[:, -1] + 1) % cfg.vocab_size)
        logits2, _ = forward(params, x2, cfg)
        np.testing.assert_allclose(np.asarray(logits[:, :-1]),
                                   np.asarray(logits2[:, :-1]), atol=1e-5)

    def test_initial_loss_near_log_vocab(self):
        cfg = _cfg()
        lm = TransformerLM(cfg)
        x, y = _batch(cfg)
        from deeplearning4j_tpu.models.transformer import loss_fn

        loss = float(loss_fn(lm.params, x, y, cfg))
        assert abs(loss - np.log(cfg.vocab_size)) < 0.5


class TestTraining:
    def test_loss_decreases(self):
        cfg = _cfg()
        lm = TransformerLM(cfg)
        x, y = _batch(cfg)
        first = float(lm.fit(x, y))
        for _ in range(20):
            last = float(lm.fit(x, y))
        assert last < first

    def test_sharded_matches_serial(self):
        cfg = _cfg()
        x, y = _batch(cfg, n=8)
        serial = TransformerLM(cfg)
        mesh = device_mesh(shape=(2, 4), axis_names=("data", "model"))
        sharded = TransformerLM(cfg, mesh=mesh)
        for i in range(3):
            ls = float(serial.fit(x, y))
            lm_ = float(sharded.fit(x, y))
            assert abs(ls - lm_) < 1e-3 * max(1.0, abs(ls)), (i, ls, lm_)

    def test_param_placement(self):
        cfg = _cfg()
        mesh = device_mesh(shape=(2, 4), axis_names=("data", "model"))
        params = shard_params(init_params(cfg), cfg, mesh)
        # column-parallel Wq shards its output dim over the 4-way model axis
        shard = params["blocks"]["Wq"].addressable_shards[0]
        assert shard.data.shape == (cfg.n_layers, 32, 32 // 4)


class TestMoE:
    def test_moe_trains_and_matches_serial(self):
        cfg = _cfg(moe_experts=4, d_ff=32)
        x, y = _batch(cfg, n=8)
        serial = TransformerLM(cfg)
        mesh = device_mesh(shape=(2, 2, 2),
                           axis_names=("data", "model", "expert"))
        sharded = TransformerLM(cfg, mesh=mesh)
        for _ in range(2):
            ls = float(serial.fit(x, y))
            le = float(sharded.fit(x, y))
            assert abs(ls - le) < 1e-3 * max(1.0, abs(ls))

    def test_moe_aux_loss_nonzero(self):
        cfg = _cfg(moe_experts=4, d_ff=32)
        params = init_params(cfg)
        x, _ = _batch(cfg)
        _, aux = forward(params, x, cfg)
        assert float(aux) > 0.0


class TestRingForward:
    def test_matches_dense_forward(self):
        from deeplearning4j_tpu.models.transformer import ring_forward
        from jax.sharding import Mesh

        cfg = _cfg(max_len=32)
        params = init_params(cfg)
        x, _ = _batch(cfg)
        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        ring = ring_forward(params, x, cfg, mesh)
        dense, _ = forward(params, x, cfg)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                                   atol=2e-4)


class TestFitBatches:
    def test_fused_equals_sequential(self):
        cfg = _cfg()
        rng = np.random.default_rng(0)
        toks = rng.integers(0, cfg.vocab_size, (4, 8, cfg.max_len + 1))
        xs = jnp.asarray(toks[..., :-1], jnp.int32)
        ys = jnp.asarray(toks[..., 1:], jnp.int32)
        seq = TransformerLM(cfg)
        seq_losses = [float(seq.fit(xs[k], ys[k])) for k in range(4)]
        fused = TransformerLM(cfg)
        fused_losses = np.asarray(fused.fit_batches(xs, ys))
        np.testing.assert_allclose(fused_losses, seq_losses, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(seq.output(xs[0])), np.asarray(fused.output(xs[0])),
            atol=1e-5)

    def test_fused_sharded(self):
        cfg = _cfg()
        rng = np.random.default_rng(1)
        toks = rng.integers(0, cfg.vocab_size, (3, 8, cfg.max_len + 1))
        xs = jnp.asarray(toks[..., :-1], jnp.int32)
        ys = jnp.asarray(toks[..., 1:], jnp.int32)
        mesh = device_mesh(shape=(2, 4), axis_names=("data", "model"))
        serial = TransformerLM(cfg)
        ref = [float(serial.fit(xs[k], ys[k])) for k in range(3)]
        sharded = TransformerLM(cfg, mesh=mesh)
        got = np.asarray(sharded.fit_batches(xs, ys))
        np.testing.assert_allclose(got, ref, rtol=1e-3)


class TestFitIterator:
    def test_iterator_with_listeners(self):
        from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
        from deeplearning4j_tpu.optimize.listeners import (
            CollectScoresIterationListener,
        )

        cfg = _cfg()
        lm = TransformerLM(cfg)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, cfg.vocab_size, (16, cfg.max_len + 1))
        it = ListDataSetIterator(toks[:, :-1], toks[:, 1:], batch=8,
                                 drop_partial=True)
        collector = CollectScoresIterationListener()
        lm.fit_iterator(it, num_epochs=3, listeners=[collector])
        scores = [s for _, s in collector.scores]
        assert len(scores) == 6  # 2 batches x 3 epochs
        assert scores[-1] < scores[0]  # training actually progresses


class TestRingForwardMoE:
    def test_moe_ring_matches_dense(self):
        from deeplearning4j_tpu.models.transformer import ring_forward
        from jax.sharding import Mesh

        cfg = _cfg(max_len=32, moe_experts=4, d_ff=32)
        params = init_params(cfg)
        x, _ = _batch(cfg)
        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        ring = ring_forward(params, x, cfg, mesh)
        dense, _ = forward(params, x, cfg)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                                   atol=2e-4)


class TestPipelineForward:
    def test_matches_dense_forward(self):
        from deeplearning4j_tpu.models.transformer import pipeline_forward
        from jax.sharding import Mesh

        cfg = _cfg(n_layers=4)
        params = init_params(cfg)
        x, _ = _batch(cfg, n=8)
        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        pp = pipeline_forward(params, x, cfg, mesh, n_micro=4)
        dense, _ = forward(params, x, cfg)
        np.testing.assert_allclose(np.asarray(pp), np.asarray(dense),
                                   atol=2e-4)

    def test_gradients_match_dense(self):
        from deeplearning4j_tpu.models.transformer import pipeline_forward
        from jax.sharding import Mesh

        cfg = _cfg(n_layers=4)
        params = init_params(cfg)
        x, _ = _batch(cfg, n=8)
        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))

        def loss_pp(p):
            return jnp.mean(pipeline_forward(p, x, cfg, mesh, n_micro=4) ** 2)

        def loss_dense(p):
            return jnp.mean(forward(p, x, cfg)[0] ** 2)

        g_pp = jax.grad(loss_pp)(params)
        g_d = jax.grad(loss_dense)(params)
        for k in ("Wq", "W1"):
            np.testing.assert_allclose(
                np.asarray(g_pp["blocks"][k]), np.asarray(g_d["blocks"][k]),
                atol=1e-4, err_msg=f"grad {k}")

    def test_layers_not_divisible_raises(self):
        from deeplearning4j_tpu.models.transformer import pipeline_forward
        from jax.sharding import Mesh

        import pytest

        cfg = _cfg(n_layers=2)
        params = init_params(cfg)
        x, _ = _batch(cfg)
        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        with pytest.raises(ValueError):
            pipeline_forward(params, x, cfg, mesh, n_micro=2)


class TestGeneration:
    def test_generate_shapes_and_determinism(self):
        cfg = _cfg()
        lm = TransformerLM(cfg)
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        out1 = lm.generate(prompt, n_new=5, seed=7)
        out2 = lm.generate(prompt, n_new=5, seed=7)
        assert out1.shape == (1, 5)
        np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
        assert int(out1.max()) < cfg.vocab_size
        # the jitted sampler is cached per n_new, not rebuilt per call
        assert len(lm._gen_cache) == 1

    def test_greedy_first_token_matches_forward_argmax(self):
        """Position correctness: with near-zero temperature the first
        sampled token must be the argmax of the forward logits at the
        prompt's true last position (a left-padded window would break
        this by shifting position embeddings)."""
        cfg = _cfg()
        lm = TransformerLM(cfg)
        prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
        out = lm.generate(prompt, n_new=1, temperature=1e-8, seed=0)
        expect = int(jnp.argmax(lm.logits(prompt)[0, -1]))
        assert int(out[0, 0]) == expect

    def test_n_new_too_large_raises(self):
        cfg = _cfg()
        lm = TransformerLM(cfg)
        import pytest

        with pytest.raises(ValueError):
            lm.generate(jnp.asarray([[1]], jnp.int32), n_new=cfg.max_len)


class TestCheckpoint:
    def test_save_load_roundtrip(self, tmp_path):
        cfg = _cfg()
        lm = TransformerLM(cfg)
        x, y = _batch(cfg)
        lm.fit(x, y)
        p = str(tmp_path / "lm.zip")
        lm.save(p)
        lm2 = TransformerLM.load(p)
        np.testing.assert_allclose(
            np.asarray(lm.logits(x)), np.asarray(lm2.logits(x)), atol=1e-6)
        # dispatch through the generic ModelSerializer.restore
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        lm3 = ModelSerializer.restore(p)
        assert isinstance(lm3, TransformerLM)
        # training resumes identically (opt state round-trips)
        l2 = float(lm2.fit(x, y))
        l1 = float(lm.fit(x, y))
        assert abs(l1 - l2) < 1e-6


class TestMixedPrecision:
    def test_bf16_policy_trains(self):
        cfg = _cfg(dtype_policy="performance")
        lm = TransformerLM(cfg)
        x, y = _batch(cfg)
        first = float(lm.fit(x, y))
        for _ in range(10):
            last = float(lm.fit(x, y))
        assert np.isfinite(last) and last < first
        # master params stay f32
        assert lm.params["blocks"]["Wq"].dtype == jnp.float32


class TestAccumAndSchedule:
    def test_accumulation_matches_full_batch(self):
        cfg_full = _cfg()
        cfg_acc = _cfg(accum_steps=4)
        x, y = _batch(cfg_full, n=8)
        full = TransformerLM(cfg_full)
        acc = TransformerLM(cfg_acc)
        for i in range(3):
            lf = float(full.fit(x, y))
            la = float(acc.fit(x, y))
            assert abs(lf - la) < 1e-4 * max(1.0, abs(lf)), (i, lf, la)

    def test_accum_not_dividing_batch_raises(self):
        cfg = _cfg(accum_steps=3)
        lm = TransformerLM(cfg)
        x, y = _batch(cfg, n=8)
        import pytest

        with pytest.raises(ValueError):
            lm.fit(x, y)

    def test_warmup_cosine_schedule(self):
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.transformer import _scheduled_lr

        cfg = _cfg(warmup_steps=10, lr_schedule="cosine", total_steps=110)
        lr0 = float(_scheduled_lr(cfg, jnp.asarray(1)))
        lr_w = float(_scheduled_lr(cfg, jnp.asarray(10)))
        lr_end = float(_scheduled_lr(cfg, jnp.asarray(110)))
        assert abs(lr0 - cfg.learning_rate / 10) < 1e-9
        assert abs(lr_w - cfg.learning_rate) < 1e-9
        assert lr_end < 1e-6

    def test_scheduled_training_runs(self):
        cfg = _cfg(warmup_steps=3, lr_schedule="cosine", total_steps=30)
        lm = TransformerLM(cfg)
        x, y = _batch(cfg)
        first = float(lm.fit(x, y))
        for _ in range(10):
            last = float(lm.fit(x, y))
        assert np.isfinite(last) and last < first

    def test_accum_moe_equals_pipelined_groups(self):
        """Gradient accumulation x MoE (round-4: the former rejection)
        optimizes the GROUPED objective — with the same contiguous-group
        split, accum A=2 and PP n_micro=2 must compute the SAME loss on
        the same batch (cross-validation of the two microbatched MoE
        paths against each other)."""
        import jax as _jax
        from jax.sharding import Mesh

        from deeplearning4j_tpu.models.transformer import (
            init_opt_state,
            init_params,
            make_pipeline_train_step,
            make_train_step,
            shard_params_pipeline,
        )

        cfg_a = _cfg(accum_steps=2, moe_experts=4, d_ff=32)
        cfg_p = _cfg(accum_steps=1, moe_experts=4, d_ff=32)
        params = init_params(cfg_a)
        x, y = _batch(cfg_a, n=4, seed=4)

        _, _, loss_a = make_train_step(cfg_a)(
            params, init_opt_state(params), x, y)

        mesh = Mesh(np.array(_jax.devices()[:2]), ("pipe",))
        pp = shard_params_pipeline(params, cfg_p, mesh)
        _, _, loss_p = make_pipeline_train_step(cfg_p, mesh, n_micro=2)(
            pp, init_opt_state(pp), x, y)
        np.testing.assert_allclose(float(loss_a), float(loss_p), rtol=1e-5)


class TestKVCacheDecoding:
    def test_cached_equals_full_forward_sampler(self):
        """KV-cache decode must reproduce the full-forward sampler exactly
        (same seed/temperature): the cached path recomputes nothing, the
        oracle recomputes everything — matching outputs prove the cache
        holds the right K/V at every step."""
        cfg = _cfg()
        lm = TransformerLM(cfg)
        prompt = jnp.asarray([[5, 9, 2, 7], [1, 1, 3, 8]], jnp.int32)
        # GREEDY comparison only: at finite temperature a single low-order
        # ulp difference between the two (differently-ordered) f32 logit
        # computations could flip one categorical draw and cascade — the
        # per-position logits equivalence is covered by
        # test_decode_step_matches_forward_logits
        out_kv = lm.generate(prompt, n_new=8, temperature=1e-8, seed=3,
                             use_cache=True)
        out_full = lm.generate(prompt, n_new=8, temperature=1e-8, seed=3,
                               use_cache=False)
        np.testing.assert_array_equal(np.asarray(out_kv),
                                      np.asarray(out_full))

    def test_long_prompt_window(self):
        cfg = _cfg()
        lm = TransformerLM(cfg)
        t = cfg.max_len + 5  # longer than max_len: keeps the tail window
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (1, t)),
            jnp.int32)
        out_kv = lm.generate(prompt, n_new=4, temperature=1e-8, seed=1,
                             use_cache=True)
        out_full = lm.generate(prompt, n_new=4, temperature=1e-8, seed=1,
                               use_cache=False)
        np.testing.assert_array_equal(np.asarray(out_kv),
                                      np.asarray(out_full))

    def test_moe_generate_kv_equals_full(self):
        """KV-cache decoding through MoE blocks (round-4: the former
        'dense FFN only' rejection at prefill_cache). Drop-free regime
        (capacity_factor = n_experts => capacity >= every possible expert
        load), so batch routing == streamed routing and greedy decode
        must match the full-forward sampler token-for-token."""
        cfg = _cfg(moe_experts=4, d_ff=32, moe_capacity_factor=4.0)
        lm = TransformerLM(cfg)
        prompt = jnp.asarray([[5, 9, 2, 7], [1, 1, 3, 8]], jnp.int32)
        out_kv = lm.generate(prompt, n_new=8, temperature=1e-8, seed=3,
                             use_cache=True)
        out_full = lm.generate(prompt, n_new=8, temperature=1e-8, seed=3,
                               use_cache=False)
        np.testing.assert_array_equal(np.asarray(out_kv),
                                      np.asarray(out_full))

    def test_tp_mesh_kv_decode_equals_serial(self):
        """KV-cache decoding under a tensor-parallel mesh (round-4):
        GSPMD propagates the Megatron shardings through prefill_cache and
        decode_step (cache sharded on the head dim), so use_cache=True on
        a ('data','model') mesh reproduces the single-device oracle."""
        from jax.sharding import Mesh

        from deeplearning4j_tpu.models.transformer import (
            param_shardings_for_mesh,
        )

        cfg = _cfg()
        serial = TransformerLM(cfg)
        prompt = jnp.asarray([[5, 9, 2, 7], [1, 1, 3, 8]], jnp.int32)
        ref = serial.generate(prompt, n_new=8, temperature=1e-8, seed=3,
                              use_cache=False)

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(1, 4),
                    ("data", "model"))
        tp = TransformerLM(cfg, mesh=mesh)
        tp.params = jax.tree_util.tree_map(
            jax.device_put, serial.params,
            param_shardings_for_mesh(cfg, mesh))
        wq = tp.params["blocks"]["Wq"]
        assert "model" in str(wq.sharding.spec)  # genuinely TP-sharded
        out = tp.generate(prompt, n_new=8, temperature=1e-8, seed=3,
                          use_cache=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_moe_decode_step_matches_forward_logits(self):
        from deeplearning4j_tpu.models.transformer import (
            decode_step,
            forward,
            init_params,
            prefill_cache,
        )

        cfg = _cfg(moe_experts=4, d_ff=32, moe_capacity_factor=4.0)
        params = init_params(cfg)
        rng = np.random.default_rng(1)
        toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 6)),
                           jnp.int32)
        full_logits, _ = forward(params, toks, cfg)
        cache, _ = prefill_cache(params, toks, cfg)
        cache, logits = decode_step(params, cache, toks[:, 3],
                                    jnp.asarray(3, jnp.int32), cfg)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full_logits[:, 3]),
                                   rtol=1e-4, atol=1e-4)

    def test_decode_step_matches_forward_logits(self):
        """decode_step at position p == forward()'s logits at p (the
        step-by-step equivalence underlying the sampler test)."""
        from deeplearning4j_tpu.models.transformer import (
            decode_step,
            forward,
            init_params,
            prefill_cache,
        )

        cfg = _cfg()
        params = init_params(cfg)
        rng = np.random.default_rng(1)
        toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 6)),
                           jnp.int32)
        full_logits, _ = forward(params, toks, cfg)
        cache, _ = prefill_cache(params, toks, cfg)
        # feed token at position 3; logits must match forward's position 3
        cache, logits = decode_step(params, cache, toks[:, 3],
                                    jnp.asarray(3, jnp.int32), cfg)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full_logits[:, 3]),
                                   rtol=1e-4, atol=1e-4)


class TestSamplingFilters:
    def test_top_k_1_equals_greedy(self):
        cfg = _cfg()
        lm = TransformerLM(cfg)
        prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
        greedy = lm.generate(prompt, n_new=6, temperature=1e-8, seed=0)
        topk1 = lm.generate(prompt, n_new=6, temperature=1.0, seed=0,
                            top_k=1)
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(topk1))

    def test_top_k_restricts_support(self):
        """Every sampled token must be inside the per-step top-k set; with
        k=2 and many samples the argmax or runner-up appears."""
        cfg = _cfg()
        lm = TransformerLM(cfg)
        prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
        first_logits = lm.logits(prompt)[0, -1]
        top2 = set(np.argsort(np.asarray(first_logits))[-2:].tolist())
        for seed in range(5):
            out = lm.generate(prompt, n_new=1, temperature=1.0, seed=seed,
                              top_k=2)
            assert int(out[0, 0]) in top2

    def test_top_p_keeps_at_least_argmax(self):
        cfg = _cfg()
        lm = TransformerLM(cfg)
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        out = lm.generate(prompt, n_new=1, temperature=1.0, seed=0,
                          top_p=1e-9)  # nucleus collapses to the argmax
        expect = int(jnp.argmax(lm.logits(prompt)[0, -1]))
        assert int(out[0, 0]) == expect

    def test_filters_on_full_forward_sampler_too(self):
        cfg = _cfg()
        lm = TransformerLM(cfg)
        prompt = jnp.asarray([[5, 9, 2, 7]], jnp.int32)
        a = lm.generate(prompt, n_new=4, temperature=1.0, seed=2, top_k=1,
                        use_cache=False)
        b = lm.generate(prompt, n_new=4, temperature=1e-8, seed=2,
                        use_cache=False)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestSamplingValidation:
    def test_bad_filter_args_raise(self):
        import pytest

        cfg = _cfg()
        lm = TransformerLM(cfg)
        p = jnp.asarray([[1, 2]], jnp.int32)
        with pytest.raises(ValueError):
            lm.generate(p, n_new=2, top_k=0)
        with pytest.raises(ValueError):
            lm.generate(p, n_new=2, top_k=cfg.vocab_size + 1)
        with pytest.raises(ValueError):
            lm.generate(p, n_new=2, top_p=0.0)
        with pytest.raises(ValueError):
            lm.generate(p, n_new=2, top_p=1.5)

    def test_top_p_sweep_reuses_one_compile(self):
        """top_p is a traced scalar: sweeping it must hit ONE cached
        sampler, not compile per value."""
        cfg = _cfg()
        lm = TransformerLM(cfg)
        p = jnp.asarray([[1, 2, 3]], jnp.int32)
        for tp in (0.8, 0.9, 0.95):
            lm.generate(p, n_new=2, top_p=tp, seed=0)
        assert len(lm._gen_cache) == 1


class TestAdamWAndClipping:
    def test_clip_by_global_norm_math(self):
        import pytest

        from deeplearning4j_tpu.models.transformer import (
            _clip_by_global_norm,
        )

        g = {"a": jnp.asarray([3.0, 4.0]), "b": jnp.zeros((2,))}  # norm 5
        clipped, norm = _clip_by_global_norm(g, 1.0)
        assert float(norm) == pytest.approx(5.0)
        np.testing.assert_allclose(np.asarray(clipped["a"]),
                                   [0.6, 0.8], rtol=1e-6)
        # under the threshold: untouched
        same, _ = _clip_by_global_norm(g, 10.0)
        np.testing.assert_allclose(np.asarray(same["a"]), [3.0, 4.0])

    def test_weight_decay_shrinks_matrices_not_ln(self):
        """AdamW decay applies to matrices; LN scales and the position
        table are exempt (decay mask)."""
        import pytest

        cfg_wd = _cfg(weight_decay=0.1, learning_rate=1e-2)
        cfg_no = _cfg(weight_decay=0.0, learning_rate=1e-2)
        x, y = _batch(cfg_wd)
        lm_wd, lm_no = TransformerLM(cfg_wd), TransformerLM(cfg_no)
        for _ in range(5):
            lm_wd.fit(x, y)
            lm_no.fit(x, y)
        wq_wd = float(jnp.linalg.norm(lm_wd.params["blocks"]["Wq"]))
        wq_no = float(jnp.linalg.norm(lm_no.params["blocks"]["Wq"]))
        assert wq_wd < wq_no  # decayed matrices are smaller
        # pos table is exempt: decay must not have shrunk it vs no-decay
        pos_wd = float(jnp.linalg.norm(lm_wd.params["pos"]))
        pos_no = float(jnp.linalg.norm(lm_no.params["pos"]))
        assert pos_wd == pytest.approx(pos_no, rel=1e-3)
        # the mask itself: exactly W* + embed decay ([L,...]-stacked LN
        # scales and biases are 2-D, so ndim cannot be the criterion)
        from deeplearning4j_tpu.models.transformer import (
            _decay_mask,
            init_params,
        )

        mask = _decay_mask(init_params(cfg_wd))
        assert mask["embed"] and mask["blocks"]["Wq"]
        assert not mask["blocks"]["ln1_g"] and not mask["blocks"]["b1"]
        assert not mask["pos"] and not mask["lnf_g"]

    def test_clipping_trains_and_composes_with_pipeline(self):
        """clip_grad_norm + weight_decay flow through the pipelined step
        too (the shared _adam_update)."""
        from jax.sharding import Mesh

        cfg = _cfg(n_layers=4, clip_grad_norm=1.0, weight_decay=0.01,
                   learning_rate=1e-2, use_flash=False)
        lm = TransformerLM(cfg)
        x, y = _batch(cfg, n=8)
        l1 = float(lm.fit(x, y))
        assert np.isfinite(l1)

        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        lmp = TransformerLM(cfg, mesh=mesh)
        serial = TransformerLM(cfg)
        a = [float(serial.fit(x, y)) for _ in range(3)]
        b = [float(lmp.fit(x, y)) for _ in range(3)]
        np.testing.assert_allclose(b, a, rtol=1e-4)
        # ...and through the sequence-parallel step (the clipped global
        # norm must be GLOBAL over sharded grads, not per-shard)
        smesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        lms = TransformerLM(cfg, mesh=smesh)
        serial2 = TransformerLM(cfg)
        c = [float(serial2.fit(x, y)) for _ in range(3)]
        d = [float(lms.fit(x, y)) for _ in range(3)]
        np.testing.assert_allclose(d, c, rtol=1e-4)


class TestEvaluatePerplexity:
    def test_perplexity_of_uniform_model_is_vocab_size(self):
        """An untrained-but-uniform check: with zeroed params the logits
        are constant, so loss == ln(V) and perplexity == V exactly."""
        import pytest

        from deeplearning4j_tpu.datasets.iterator import DataSet

        cfg = _cfg()
        lm = TransformerLM(cfg)
        lm.params = jax.tree_util.tree_map(jnp.zeros_like, lm.params)
        rng = np.random.default_rng(0)
        toks = rng.integers(0, cfg.vocab_size, (4, cfg.max_len + 1))
        ds = [DataSet(toks[:, :-1], toks[:, 1:])]
        res = lm.evaluate(ds)
        assert res["perplexity"] == pytest.approx(cfg.vocab_size, rel=1e-4)
        assert res["tokens"] == 4 * cfg.max_len

    def test_training_reduces_perplexity(self):
        from deeplearning4j_tpu.datasets.iterator import DataSet

        cfg = _cfg(learning_rate=1e-2)
        lm = TransformerLM(cfg)
        rng = np.random.default_rng(1)
        toks = rng.integers(0, cfg.vocab_size, (8, cfg.max_len + 1))
        x, y = toks[:, :-1], toks[:, 1:]
        ds = [DataSet(x, y)]
        before = lm.evaluate(ds)["perplexity"]
        for _ in range(10):
            lm.fit(jnp.asarray(x, jnp.int32), jnp.asarray(y, jnp.int32))
        after = lm.evaluate(ds)["perplexity"]
        assert after < before

    def test_masked_positions_excluded(self):
        """Pad positions count in neither the loss nor the token total."""
        import pytest

        from deeplearning4j_tpu.datasets.iterator import DataSet

        cfg = _cfg()
        lm = TransformerLM(cfg)
        rng = np.random.default_rng(2)
        toks = rng.integers(0, cfg.vocab_size, (2, cfg.max_len + 1))
        x, y = toks[:, :-1].copy(), toks[:, 1:].copy()
        mask = np.ones_like(x, np.float32)
        mask[:, 8:] = 0.0
        y_garbage = y.copy()
        y_garbage[:, 8:] = 0  # garbage labels under the mask
        res_a = lm.evaluate([DataSet(x, y, None, mask)])
        res_b = lm.evaluate([DataSet(x, y_garbage, None, mask)])
        assert res_a["tokens"] == 2 * 8
        assert res_a["loss"] == pytest.approx(res_b["loss"], rel=1e-6)
