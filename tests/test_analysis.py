"""graftlint contracts (ISSUE 10): per-rule positive/negative/suppression
fixtures, the repo-wide clean sweep, and the knob-table↔CLAUDE.md
consistency gate.

Fixture style: each rule gets synthetic snippets written to tmp_path and
parsed through the real ``engine.parse_file`` pipeline with a
plane-appropriate ``rel`` (scoped rules key off the repo-relative path).
The snippets deliberately SPELL violations — which is exactly why
``tests/`` is outside the linter's DEFAULT_TARGETS and why the repo-wide
sweep must stay clean while these fixtures fire.

Everything here is pure-AST and jax-free (the analysis package never
imports jax), so the whole file fits the quick tier.
"""

import os
import subprocess
import sys
import textwrap

from deeplearning4j_tpu.analysis import engine
from deeplearning4j_tpu.analysis.engine import (
    DEFAULT_TARGETS,
    parse_file,
    rule_names,
    run_paths,
)
from deeplearning4j_tpu.analysis.rules_conventions import (
    DocstringProvenance,
    LedgerRegistration,
    PallasRent,
    SignalHandlerSafety,
)
from deeplearning4j_tpu.analysis.rules_env import ChaosAmbient, EnvKnobRegistry
from deeplearning4j_tpu.analysis.rules_threads import (
    HostSyncUnderLock,
    ThreadSharedState,
)
from deeplearning4j_tpu.analysis.rules_tunnel import (
    BlockUntilReadyFence,
    DonationThroughDispatch,
    NondeterminismInJit,
    TunnelDeviceProbe,
)
from deeplearning4j_tpu.ops.env import KNOBS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint(tmp_path, source, rule_cls,
          rel="deeplearning4j_tpu/serving/fixture_mod.py"):
    """Write a snippet, parse it as ``rel``, run one rule; returns
    (unsuppressed findings, parsed file)."""
    p = tmp_path / "fixture_mod.py"
    p.write_text(textwrap.dedent(source))
    pf = parse_file(str(p), rel, rule_names())
    found = [f for f in rule_cls().check(pf)
             if not pf.is_suppressed(f.rule, f.line)]
    return found, pf


# ---------------------------------------------------------------------------
# tunnel-device-probe
# ---------------------------------------------------------------------------


def test_device_probe_at_import_time_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        import jax
        N = len(jax.devices())
        """, TunnelDeviceProbe)
    assert len(found) == 1
    assert found[0].rule == "tunnel-device-probe"
    assert found[0].line == 2


def test_device_probe_guarded_by_platform_pin_is_clean(tmp_path):
    found, _ = _lint(tmp_path, """\
        import jax
        jax.config.update("jax_platforms", "cpu")
        N = len(jax.devices())
        """, TunnelDeviceProbe)
    assert found == []


def test_device_probe_in_constructor_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        import jax

        class Master:
            def __init__(self):
                self.n = jax.device_count()
        """, TunnelDeviceProbe)
    assert len(found) == 1
    assert "constructor" in found[0].message


def test_device_probe_in_default_arg_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        import jax

        def fit(n=len(jax.devices())):
            return n
        """, TunnelDeviceProbe)
    assert len(found) == 1


def test_device_probe_inside_plain_function_is_clean(tmp_path):
    # deferred-to-first-use is exactly the sanctioned pattern
    found, _ = _lint(tmp_path, """\
        import jax

        def n_devices():
            return len(jax.devices())
        """, TunnelDeviceProbe)
    assert found == []


# ---------------------------------------------------------------------------
# block-until-ready-fence
# ---------------------------------------------------------------------------


def test_block_until_ready_warns_and_suppression_is_honored(tmp_path):
    found, pf = _lint(tmp_path, """\
        import jax
        jax.block_until_ready(x)
        jax.block_until_ready(y)  # graftlint: disable=block-until-ready-fence -- virtual CPU mesh, never the tunnel
        """, BlockUntilReadyFence)
    assert len(found) == 1
    assert found[0].line == 2
    assert found[0].severity == "warning"
    assert pf.bad_suppressions == []


# ---------------------------------------------------------------------------
# donation-through-dispatch
# ---------------------------------------------------------------------------


def test_direct_donation_fires_outside_dispatch(tmp_path):
    found, _ = _lint(tmp_path, """\
        import jax
        step = jax.jit(f, donate_argnums=(0,))
        """, DonationThroughDispatch)
    assert len(found) == 1


def test_partial_jit_decorator_donation_fires(tmp_path):
    # the functools.partial(jax.jit, ...) decorator idiom must be caught
    found, _ = _lint(tmp_path, """\
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(a, b):
            return a + b
        """, DonationThroughDispatch)
    assert len(found) == 1


def test_donation_inside_dispatch_is_the_sanctioned_home(tmp_path):
    found, _ = _lint(tmp_path, """\
        import jax
        step = jax.jit(f, donate_argnums=(0,))
        """, DonationThroughDispatch,
        rel="deeplearning4j_tpu/ops/dispatch.py")
    assert found == []


# ---------------------------------------------------------------------------
# nondeterminism-in-jit
# ---------------------------------------------------------------------------


def test_wall_clock_inside_jitted_fn_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        import time
        import jax

        @jax.jit
        def step(x):
            return x * time.time()
        """, NondeterminismInJit)
    assert len(found) == 1


def test_nondet_via_jit_call_by_name_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        import jax
        import numpy as np

        def step(x):
            return x + np.random.randn()

        fast = jax.jit(step)
        """, NondeterminismInJit)
    assert len(found) == 1


def test_nondet_outside_traced_code_is_clean(tmp_path):
    found, _ = _lint(tmp_path, """\
        import time

        def host_timer():
            return time.time()
        """, NondeterminismInJit)
    assert found == []


# ---------------------------------------------------------------------------
# env-knob-registry
# ---------------------------------------------------------------------------


def test_direct_environ_read_of_knob_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        import os
        v = os.environ.get("DL4J_TPU_DONATE")
        """, EnvKnobRegistry)
    assert len(found) == 1
    assert "ops.env" in found[0].message


def test_knob_typo_literal_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        NAME = "DL4J_TPU_DONAET"
        """, EnvKnobRegistry)
    assert len(found) == 1
    assert "not a registered knob" in found[0].message


def test_registered_literal_and_env_write_are_clean(tmp_path):
    # writes stay legal (tests/bench pin knobs for subprocesses), and a
    # registered name as a literal is how call sites name knobs
    found, _ = _lint(tmp_path, """\
        import os
        os.environ["DL4J_TPU_DONATE"] = "force"
        os.environ.setdefault("DL4J_TPU_OFFLINE", "1")
        NAME = "DL4J_TPU_DONATE"
        """, EnvKnobRegistry)
    assert found == []


def test_knob_table_and_claude_md_agree():
    # the project-level two-way diff the CLI runs — kept as its own test
    # so doc drift fails here by name, not just in the sweep
    findings = EnvKnobRegistry().check_project(REPO, [])
    assert findings == [], "\n".join(f.format() for f in findings)


def test_every_knob_documented_in_claude_md():
    with open(os.path.join(REPO, "CLAUDE.md"), encoding="utf-8") as f:
        text = f.read()
    missing = [k for k in KNOBS if k not in text]
    assert missing == [], f"knobs undocumented in CLAUDE.md: {missing}"


# ---------------------------------------------------------------------------
# chaos-ambient
# ---------------------------------------------------------------------------


def test_chaos_config_at_import_time_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        from deeplearning4j_tpu.resilience.chaos import FleetChaosConfig
        CHAOS = FleetChaosConfig(kill_worker=1)
        """, ChaosAmbient)
    assert len(found) == 1
    assert "import time" in found[0].message


def test_chaos_config_as_param_default_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        def fit(chaos=ServingChaosConfig()):
            return chaos
        """, ChaosAmbient)
    assert len(found) == 1
    assert "parameter default" in found[0].message


def test_chaos_config_inside_test_body_is_clean(tmp_path):
    found, _ = _lint(tmp_path, """\
        def test_kill():
            chaos = FleetChaosConfig(kill_worker=2)
            return chaos
        """, ChaosAmbient)
    assert found == []


# ---------------------------------------------------------------------------
# ledger-registration
# ---------------------------------------------------------------------------


def test_unregistered_ledger_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        class Net:
            def __init__(self):
                self.shiny_stats = object()
        """, LedgerRegistration, rel="deeplearning4j_tpu/nn/fixture.py")
    assert len(found) == 1
    assert "register_net" in found[0].message


def test_ledger_with_registration_hook_is_clean(tmp_path):
    found, _ = _lint(tmp_path, """\
        from deeplearning4j_tpu.obs.registry import register_net

        class Net:
            def __init__(self):
                self.shiny_stats = object()
                register_net(self)
        """, LedgerRegistration, rel="deeplearning4j_tpu/nn/fixture.py")
    assert found == []


# ---------------------------------------------------------------------------
# signal-handler-safety
# ---------------------------------------------------------------------------


def test_lock_taking_signal_handler_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        import signal

        def on_term(signum, frame):
            with state_lock:
                flags.append(signum)

        signal.signal(signal.SIGTERM, on_term)
        """, SignalHandlerSafety)
    assert len(found) == 1
    assert "deadlock" in found[0].message


def test_minimal_flag_handler_is_clean(tmp_path):
    found, _ = _lint(tmp_path, """\
        import signal

        def on_term(signum, frame):
            global preempted
            preempted = True

        signal.signal(signal.SIGTERM, on_term)
        """, SignalHandlerSafety)
    assert found == []


# ---------------------------------------------------------------------------
# host-sync-under-lock / thread-shared-state
# ---------------------------------------------------------------------------


def test_readback_under_lock_warns_in_threaded_plane(tmp_path):
    found, _ = _lint(tmp_path, """\
        import numpy as np

        class Batcher:
            def flush(self):
                with self._lock:
                    out = np.asarray(self._device_buf)
                return out
        """, HostSyncUnderLock)
    assert len(found) == 1
    assert found[0].severity == "warning"


def test_readback_outside_lock_and_outside_scope_is_clean(tmp_path):
    src = """\
        import numpy as np

        class Batcher:
            def flush(self):
                with self._lock:
                    buf = self._device_buf
                return np.asarray(buf)
        """
    found, _ = _lint(tmp_path, src, HostSyncUnderLock)
    assert found == []
    # same violation OUTSIDE the threaded planes is out of scope
    found, _ = _lint(tmp_path, """\
        import numpy as np

        class C:
            def f(self):
                with self._lock:
                    return np.asarray(self.x)
        """, HostSyncUnderLock, rel="deeplearning4j_tpu/nn/fixture.py")
    assert found == []


def test_racing_writes_across_thread_entries_warn(tmp_path):
    found, _ = _lint(tmp_path, """\
        import threading

        class Pool:
            def start(self):
                threading.Thread(target=self._worker).start()
                threading.Thread(target=self._reaper).start()

            def _worker(self):
                self.inflight = self.inflight + 1

            def _reaper(self):
                self.inflight -= 1
        """, ThreadSharedState)
    assert len(found) == 1
    assert "inflight" in found[0].message


def test_constant_flag_and_locked_writes_are_sanctioned(tmp_path):
    found, _ = _lint(tmp_path, """\
        import threading

        class Pool:
            def start(self):
                threading.Thread(target=self._worker).start()
                threading.Thread(target=self._reaper).start()

            def _worker(self):
                self.draining = True
                with self._lock:
                    self.inflight = self.inflight + 1

            def _reaper(self):
                self.draining = False
                with self._lock:
                    self.inflight -= 1
        """, ThreadSharedState)
    assert found == []


# ---------------------------------------------------------------------------
# docstring-provenance
# ---------------------------------------------------------------------------


def test_uncited_public_class_in_parity_dir_warns(tmp_path):
    found, _ = _lint(tmp_path, """\
        class ShinyLayer:
            \"\"\"A layer with no provenance at all.\"\"\"
        """, DocstringProvenance, rel="deeplearning4j_tpu/nn/fixture.py")
    assert len(found) == 1
    assert found[0].severity == "warning"


def test_cited_class_and_beyond_reference_plane_are_clean(tmp_path):
    src = """\
        class ShinyLayer:
            \"\"\"Parity port of DenseLayer.java:42.\"\"\"
        """
    found, _ = _lint(tmp_path, src, DocstringProvenance,
                     rel="deeplearning4j_tpu/nn/fixture.py")
    assert found == []
    # beyond-reference planes (serving/ etc.) are exempt by design
    found, _ = _lint(tmp_path, """\
        class Breaker:
            \"\"\"No citation needed here.\"\"\"
        """, DocstringProvenance)
    assert found == []


# ---------------------------------------------------------------------------
# pallas-rent
# ---------------------------------------------------------------------------


def test_pallas_call_outside_ops_pallas_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        from jax.experimental import pallas as pl

        def hot_path(x):
            return pl.pallas_call(lambda r, o: None, out_shape=x)(x)
        """, PallasRent, rel="deeplearning4j_tpu/serving/fixture.py")
    assert len(found) == 1
    assert "outside ops/pallas_" in found[0].message


def test_pallas_module_without_interpret_param_fires(tmp_path):
    found, _ = _lint(tmp_path, """\
        from jax.experimental import pallas as pl

        def kernel_wrapper(x):
            return pl.pallas_call(lambda r, o: None, out_shape=x)(x)
        """, PallasRent, rel="deeplearning4j_tpu/ops/pallas_fixture.py")
    assert len(found) == 1
    assert "interpret" in found[0].message


def test_pallas_module_with_interpret_fallback_is_clean(tmp_path):
    found, _ = _lint(tmp_path, """\
        from jax.experimental import pallas as pl

        def kernel_wrapper(x, *, interpret=False):
            return pl.pallas_call(lambda r, o: None, out_shape=x,
                                  interpret=interpret)(x)
        """, PallasRent, rel="deeplearning4j_tpu/ops/pallas_fixture.py")
    assert found == []
    # no pallas_call at all: nothing to check, wherever the file lives
    found, _ = _lint(tmp_path, """\
        def plain(x):
            return x
        """, PallasRent, rel="deeplearning4j_tpu/serving/fixture.py")
    assert found == []


def test_pallas_rent_suppression_is_honored(tmp_path):
    found, _ = _lint(tmp_path, """\
        from jax.experimental import pallas as pl

        def hot_path(x):
            # graftlint: disable=pallas-rent -- fixture: migration shim, kernel moving to ops/pallas_x.py
            return pl.pallas_call(lambda r, o: None, out_shape=x)(x)
        """, PallasRent, rel="deeplearning4j_tpu/serving/fixture.py")
    assert found == []


# ---------------------------------------------------------------------------
# suppression mechanics
# ---------------------------------------------------------------------------


def test_standalone_suppression_covers_next_code_line(tmp_path):
    found, pf = _lint(tmp_path, """\
        import jax
        # graftlint: disable=tunnel-device-probe -- fixture: guard proven elsewhere

        N = len(jax.devices())
        """, TunnelDeviceProbe)
    assert found == []
    assert pf.bad_suppressions == []


def test_suppression_without_justification_is_itself_a_finding(tmp_path):
    _, pf = _lint(tmp_path, """\
        import jax
        N = len(jax.devices())  # graftlint: disable=tunnel-device-probe
        """, TunnelDeviceProbe)
    assert len(pf.bad_suppressions) == 1
    assert pf.bad_suppressions[0].rule == "bad-suppression"
    assert "justification" in pf.bad_suppressions[0].message


def test_suppression_of_unknown_rule_is_a_finding(tmp_path):
    _, pf = _lint(tmp_path, """\
        x = 1  # graftlint: disable=no-such-rule -- because
        """, TunnelDeviceProbe)
    assert len(pf.bad_suppressions) == 1
    assert "unknown rule" in pf.bad_suppressions[0].message


def test_disable_file_covers_every_line(tmp_path):
    found, pf = _lint(tmp_path, """\
        # graftlint: disable-file=block-until-ready-fence -- fixture: whole file exempt
        import jax
        jax.block_until_ready(x)
        jax.block_until_ready(y)
        """, BlockUntilReadyFence)
    assert found == []
    assert pf.bad_suppressions == []


# ---------------------------------------------------------------------------
# the repo-wide gate + CLI contract
# ---------------------------------------------------------------------------


def test_repo_surface_is_lint_clean():
    """THE gate: the committed tree has zero unsuppressed findings."""
    report = run_paths(root=REPO)
    assert report.clean, "\n".join(f.format() for f in report.findings)
    assert report.files_scanned > 100  # the surface really was scanned


def test_default_targets_exist():
    # a renamed entrypoint must not silently shrink the scanned surface
    missing = [t for t in DEFAULT_TARGETS
               if not os.path.exists(os.path.join(REPO, t))]
    assert missing == [], f"DEFAULT_TARGETS entries missing: {missing}"


def test_cli_exit_codes(tmp_path):
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import jax\nN = len(jax.devices())\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    r = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.analysis", "--json",
         str(dirty)], capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "tunnel-device-probe" in r.stdout
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    r = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.analysis", str(clean)],
        capture_output=True, text=True, env=env, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr


def test_rule_registry_is_well_formed():
    names = rule_names()
    assert "bad-suppression" in names
    for rule in engine.all_rules():
        assert rule.name and rule.doc
        assert rule.severity in engine.SEVERITIES
