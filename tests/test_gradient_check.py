"""Gradient-check suite — the numerical-correctness backbone
(reference: GradientCheckTests, CNNGradientCheckTest, BNGradientCheckTest,
GradientCheckTestsMasking — SURVEY.md section 4). Validates the loss/forward
plumbing (losses, masking, regularization, conv, recurrence) against central
differences in float64."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    ConvolutionLayer,
    DenseLayer,
    GravesLSTM,
    GRU,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.preprocessors import CnnToFeedForwardPreProcessor
from deeplearning4j_tpu.utils.gradient_check import check_network_gradients

RNG = np.random.default_rng(12345)


def random_classification(n, nin, nout):
    x = RNG.standard_normal((n, nin))
    y = np.eye(nout)[RNG.integers(0, nout, n)]
    return x, y


@pytest.mark.parametrize("activation", ["sigmoid", "tanh", "relu"])
@pytest.mark.parametrize(
    "loss,out_act",
    [("mcxent", "softmax"), ("mse", "identity"), ("xent", "sigmoid")],
)
def test_mlp_gradients(activation, loss, out_act):
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(12345)
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=5, activation=activation))
        .layer(
            1, OutputLayer(n_in=5, n_out=3, activation=out_act, loss_function=loss)
        )
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x, y = random_classification(6, 4, 3)
    ok, max_rel = check_network_gradients(net, x, y)
    assert ok, f"max relative error {max_rel}"


def test_mlp_gradients_with_l1_l2():
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(1)
        .l1(0.01)
        .l2(0.02)
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=5, activation="tanh"))
        .layer(1, OutputLayer(n_in=5, n_out=3, activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x, y = random_classification(5, 4, 3)
    ok, max_rel = check_network_gradients(net, x, y)
    assert ok, f"max relative error {max_rel}"


def test_cnn_gradients():
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(42)
        .list()
        .layer(
            0,
            ConvolutionLayer(
                n_in=1, n_out=2, kernel_size=(2, 2), stride=(1, 1),
                activation="tanh",
            ),
        )
        .layer(1, SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
        .layer(2, OutputLayer(n_in=8, n_out=2, activation="softmax"))
        .input_preprocessor(2, CnnToFeedForwardPreProcessor(2, 2, 2))
        .build()
    )
    net = MultiLayerNetwork(conf).init(input_shape=(5, 5, 1))
    x = RNG.standard_normal((3, 5, 5, 1))
    y = np.eye(2)[RNG.integers(0, 2, 3)]
    ok, max_rel = check_network_gradients(net, x, y, max_params_per_leaf=20)
    assert ok, f"max relative error {max_rel}"


def test_lstm_gradients():
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .list()
        .layer(0, GravesLSTM(n_in=3, n_out=4, activation="tanh"))
        .layer(1, RnnOutputLayer(n_in=4, n_out=2, activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.standard_normal((2, 4, 3))
    y = np.eye(2)[RNG.integers(0, 2, (2, 4))]
    ok, max_rel = check_network_gradients(net, x, y, max_params_per_leaf=25)
    assert ok, f"max relative error {max_rel}"


def test_gru_gradients():
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(8)
        .list()
        .layer(0, GRU(n_in=3, n_out=4, activation="tanh"))
        .layer(1, RnnOutputLayer(n_in=4, n_out=2, activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.standard_normal((2, 4, 3))
    y = np.eye(2)[RNG.integers(0, 2, (2, 4))]
    ok, max_rel = check_network_gradients(net, x, y, max_params_per_leaf=25)
    assert ok, f"max relative error {max_rel}"


def test_mha_gradients():
    """Central-difference check for the MultiHeadAttention layer's dense
    path (VERDICT r5 ask #6 — the gradcheck backbone stops at GRU while
    the beyond-reference layers go unchecked). The attention softmax
    upcast is at-least-f32 (ops/dtypes.softmax_dtype), so the whole check
    runs in true f64 like the MLP/CNN/LSTM checks."""
    from deeplearning4j_tpu.nn.conf.layers import MultiHeadAttention
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(11)
        .list()
        .layer(0, MultiHeadAttention(n_in=4, n_out=4, num_heads=2,
                                     causal=True, activation="identity"))
        .layer(1, RnnOutputLayer(n_in=4, n_out=2, activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.standard_normal((2, 5, 4))
    y = np.eye(2)[RNG.integers(0, 2, (2, 5))]
    ok, max_rel = check_network_gradients(net, x, y,
                                          max_params_per_leaf=20)
    assert ok, f"max relative error {max_rel}"


def test_moe_ffn_gradients():
    """Central-difference check for one MoE FFN block
    (models/transformer._moe_ffn: routing + expert MLP + load-balance aux
    — the expert_parallel math). top_k == n_experts keeps every expert
    selected, so the discrete routing structure is locally constant and
    the objective is differentiable at the probe point; gradients flow
    through the gate softmax (at-least-f32 upcast, f64 here), the
    combine weights, and the aux loss."""
    import jax

    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig,
        _moe_ffn,
        init_params,
    )
    from deeplearning4j_tpu.utils.gradient_check import check_gradients

    cfg = TransformerConfig(vocab_size=13, d_model=8, n_layers=1,
                            n_heads=2, d_ff=8, max_len=8, moe_experts=2,
                            moe_top_k=2, seed=5)
    blocks = init_params(cfg)["blocks"]
    bp0 = {k: jax.tree_util.tree_map(lambda a: a[0], blocks[k])
           for k in ("Wg", "W1", "b1", "W2", "b2")}
    h = jnp.asarray(RNG.standard_normal((2, 4, 8)))

    def loss(p):
        out, aux = _moe_ffn(p, h.astype(p["W1"].dtype), cfg)
        return (out ** 2).mean() + cfg.moe_aux_coef * aux

    ok, max_rel = check_gradients(loss, bp0, max_params_per_leaf=15)
    assert ok, f"max relative error {max_rel}"


def test_bert_mlm_loss_gradients():
    """Central-difference check for the BERT masked-LM loss
    (models/bert.mlm_loss: bidirectional encoder + selected-position
    cross-entropy). The loss's log-softmax upcast is at-least-f32
    (ops/dtypes.softmax_dtype — a hard f32 pin quantized the x64 loss
    below central-difference resolution: numeric grads read exactly 0
    against analytic 1e-4 before the fix), so this runs in true f64."""
    from deeplearning4j_tpu.models.bert import (
        BertConfig,
        init_params,
        mask_tokens,
        mlm_loss,
    )
    from deeplearning4j_tpu.utils.gradient_check import check_gradients

    cfg = BertConfig(vocab_size=17, d_model=8, n_layers=1, n_heads=2,
                     d_ff=16, max_len=6, mlm_prob=0.3, pad_token_id=0,
                     mask_token_id=16, seed=3)
    params = init_params(cfg)
    rng = np.random.default_rng(7)
    tokens = rng.integers(1, 16, (2, 6))
    inputs, targets, weights = mask_tokens(tokens, cfg, rng)

    def loss(p):
        return mlm_loss(p, jnp.asarray(inputs), jnp.asarray(targets),
                        jnp.asarray(weights), cfg)

    ok, max_rel = check_gradients(loss, params, max_params_per_leaf=10)
    assert ok, f"max relative error {max_rel}"


def test_rnn_masked_gradients():
    """Masked-timestep gradients (reference GradientCheckTestsMasking)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(9)
        .list()
        .layer(0, GravesLSTM(n_in=2, n_out=3, activation="tanh"))
        .layer(1, RnnOutputLayer(n_in=3, n_out=2, activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.standard_normal((2, 5, 2))
    y = np.eye(2)[RNG.integers(0, 2, (2, 5))]
    mask = np.array([[1, 1, 1, 1, 1], [1, 1, 1, 0, 0]], dtype=np.float64)
    ok, max_rel = check_network_gradients(
        net, x, y, mask=jnp.asarray(mask), max_params_per_leaf=25
    )
    assert ok, f"max relative error {max_rel}"
