"""Gradient-check suite — the numerical-correctness backbone
(reference: GradientCheckTests, CNNGradientCheckTest, BNGradientCheckTest,
GradientCheckTestsMasking — SURVEY.md section 4). Validates the loss/forward
plumbing (losses, masking, regularization, conv, recurrence) against central
differences in float64."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    ConvolutionLayer,
    DenseLayer,
    GravesLSTM,
    GRU,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.preprocessors import CnnToFeedForwardPreProcessor
from deeplearning4j_tpu.utils.gradient_check import check_network_gradients

RNG = np.random.default_rng(12345)


def random_classification(n, nin, nout):
    x = RNG.standard_normal((n, nin))
    y = np.eye(nout)[RNG.integers(0, nout, n)]
    return x, y


@pytest.mark.parametrize("activation", ["sigmoid", "tanh", "relu"])
@pytest.mark.parametrize(
    "loss,out_act",
    [("mcxent", "softmax"), ("mse", "identity"), ("xent", "sigmoid")],
)
def test_mlp_gradients(activation, loss, out_act):
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(12345)
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=5, activation=activation))
        .layer(
            1, OutputLayer(n_in=5, n_out=3, activation=out_act, loss_function=loss)
        )
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x, y = random_classification(6, 4, 3)
    ok, max_rel = check_network_gradients(net, x, y)
    assert ok, f"max relative error {max_rel}"


def test_mlp_gradients_with_l1_l2():
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(1)
        .l1(0.01)
        .l2(0.02)
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=5, activation="tanh"))
        .layer(1, OutputLayer(n_in=5, n_out=3, activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x, y = random_classification(5, 4, 3)
    ok, max_rel = check_network_gradients(net, x, y)
    assert ok, f"max relative error {max_rel}"


def test_cnn_gradients():
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(42)
        .list()
        .layer(
            0,
            ConvolutionLayer(
                n_in=1, n_out=2, kernel_size=(2, 2), stride=(1, 1),
                activation="tanh",
            ),
        )
        .layer(1, SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
        .layer(2, OutputLayer(n_in=8, n_out=2, activation="softmax"))
        .input_preprocessor(2, CnnToFeedForwardPreProcessor(2, 2, 2))
        .build()
    )
    net = MultiLayerNetwork(conf).init(input_shape=(5, 5, 1))
    x = RNG.standard_normal((3, 5, 5, 1))
    y = np.eye(2)[RNG.integers(0, 2, 3)]
    ok, max_rel = check_network_gradients(net, x, y, max_params_per_leaf=20)
    assert ok, f"max relative error {max_rel}"


def test_lstm_gradients():
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .list()
        .layer(0, GravesLSTM(n_in=3, n_out=4, activation="tanh"))
        .layer(1, RnnOutputLayer(n_in=4, n_out=2, activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.standard_normal((2, 4, 3))
    y = np.eye(2)[RNG.integers(0, 2, (2, 4))]
    ok, max_rel = check_network_gradients(net, x, y, max_params_per_leaf=25)
    assert ok, f"max relative error {max_rel}"


def test_gru_gradients():
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(8)
        .list()
        .layer(0, GRU(n_in=3, n_out=4, activation="tanh"))
        .layer(1, RnnOutputLayer(n_in=4, n_out=2, activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.standard_normal((2, 4, 3))
    y = np.eye(2)[RNG.integers(0, 2, (2, 4))]
    ok, max_rel = check_network_gradients(net, x, y, max_params_per_leaf=25)
    assert ok, f"max relative error {max_rel}"


def test_rnn_masked_gradients():
    """Masked-timestep gradients (reference GradientCheckTestsMasking)."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(9)
        .list()
        .layer(0, GravesLSTM(n_in=2, n_out=3, activation="tanh"))
        .layer(1, RnnOutputLayer(n_in=3, n_out=2, activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = RNG.standard_normal((2, 5, 2))
    y = np.eye(2)[RNG.integers(0, 2, (2, 5))]
    mask = np.array([[1, 1, 1, 1, 1], [1, 1, 1, 0, 0]], dtype=np.float64)
    ok, max_rel = check_network_gradients(
        net, x, y, mask=jnp.asarray(mask), max_params_per_leaf=25
    )
    assert ok, f"max relative error {max_rel}"
