"""HBM preflight for the MFU-chase bench leg (VERDICT r03 weak #8: an
untested d2048 L8 b16 config must not OOM away the round's one tunnel
window). The estimator must be exact on params/optimizer (jax.eval_shape
against the real init) and conservative enough to downsize the batch."""

import numpy as np

import bench


class TestTransformerHbmPreflight:
    def test_big_config_b16_rejected_b8_accepted(self):
        """The round-3 planned config (b16 d2048 L8) estimates past 16GB —
        exactly the first-contact OOM the preflight exists to prevent —
        while b8 fits with headroom."""
        fits16, rep16 = bench.transformer_hbm_preflight(16, 1024, 2048, 8, 32)
        fits8, rep8 = bench.transformer_hbm_preflight(8, 1024, 2048, 8, 32)
        assert not fits16
        assert fits8
        assert rep16["total_gb_est"] > rep8["total_gb_est"]

    def test_param_bytes_exact(self):
        """params_gb comes from eval_shape on the real init_params — cross
        check against a hand count of the dominant matrices (embedding +
        per-layer attn/mlp) to within 5% (norms/bias are the remainder)."""
        _, rep = bench.transformer_hbm_preflight(8, 1024, 2048, 8, 32)
        d, v, layers = 2048, 8192, 8
        dominant = v * d + layers * (4 * d * d + 2 * d * 4 * d)
        assert rep["params_gb"] >= dominant * 4 / 2**30 * 0.95
        assert rep["opt_gb"] >= 2 * rep["params_gb"] * 0.95  # adam m+v

    def test_scales_down_with_batch(self):
        ests = [bench.transformer_hbm_preflight(b, 1024, 2048, 8, 32)[1][
            "total_gb_est"] for b in (16, 8, 4)]
        assert ests[0] > ests[1] > ests[2]
        # fixed state (params+opt+grads) is batch-independent
        fixed = [bench.transformer_hbm_preflight(b, 1024, 2048, 8, 32)[1]
                 for b in (16, 4)]
        for key in ("params_gb", "opt_gb", "grads_gb"):
            assert fixed[0][key] == fixed[1][key]

    def test_tiny_config_fits_easily(self):
        fits, rep = bench.transformer_hbm_preflight(4, 256, 256, 2, 4,
                                                    vocab=1024)
        assert fits
        assert rep["total_gb_est"] < 1.0

    def test_b32_d2048_accepted_under_remat(self):
        """ISSUE 4 acceptance: the b32 config that exceeded usable HBM
        un-rematted (BENCH_NOTES round-2 ceiling) is accepted under a
        remat rung — armed for the next tunnel window."""
        fits_none, _ = bench.transformer_hbm_preflight(32, 1024, 2048, 8, 32)
        fits_block, rep = bench.transformer_hbm_preflight(
            32, 1024, 2048, 8, 32, remat="block")
        assert not fits_none
        assert fits_block
        assert rep["remat"] == "block" and rep["batch"] == 32

    def test_auto_fit_arms_b32_with_remat(self):
        """The transformer_lm_big ladder: auto-fit keeps the largest
        batch by climbing the remat ladder instead of shrinking to b16."""
        from deeplearning4j_tpu.ops.memory import auto_fit_transformer

        cfg = bench._transformer_bench_cfg(1024, 2048, 8, 32)
        choice = auto_fit_transformer(cfg, batches=(32, 16, 8, 4),
                                      accum_steps=(1,), hbm_gb=16.0)
        assert choice is not None
        assert choice["batch"] == 32
        assert choice["remat"] in ("dots", "block")

    def test_accum_shrinks_activation_estimate(self):
        """accum_steps sizes activations/logits per microbatch (and
        doubles the grad tree) — the composing axis of the auto-fit
        sizer."""
        _, rep1 = bench.transformer_hbm_preflight(16, 1024, 2048, 8, 32)
        _, rep4 = bench.transformer_hbm_preflight(16, 1024, 2048, 8, 32,
                                                  accum_steps=4)
        assert rep4["activations_gb_est"] < rep1["activations_gb_est"]
        assert rep4["logits_gb"] < rep1["logits_gb"]
        assert rep4["grads_gb"] == 2 * rep1["grads_gb"]
