"""HBM preflight for the MFU-chase bench leg (VERDICT r03 weak #8: an
untested d2048 L8 b16 config must not OOM away the round's one tunnel
window). The estimator must be exact on params/optimizer (jax.eval_shape
against the real init) and conservative enough to downsize the batch."""

import numpy as np

import bench


class TestTransformerHbmPreflight:
    def test_big_config_b16_rejected_b8_accepted(self):
        """The round-3 planned config (b16 d2048 L8) estimates past 16GB —
        exactly the first-contact OOM the preflight exists to prevent —
        while b8 fits with headroom."""
        fits16, rep16 = bench.transformer_hbm_preflight(16, 1024, 2048, 8, 32)
        fits8, rep8 = bench.transformer_hbm_preflight(8, 1024, 2048, 8, 32)
        assert not fits16
        assert fits8
        assert rep16["total_gb_est"] > rep8["total_gb_est"]

    def test_param_bytes_exact(self):
        """params_gb comes from eval_shape on the real init_params — cross
        check against a hand count of the dominant matrices (embedding +
        per-layer attn/mlp) to within 5% (norms/bias are the remainder)."""
        _, rep = bench.transformer_hbm_preflight(8, 1024, 2048, 8, 32)
        d, v, layers = 2048, 8192, 8
        dominant = v * d + layers * (4 * d * d + 2 * d * 4 * d)
        assert rep["params_gb"] >= dominant * 4 / 2**30 * 0.95
        assert rep["opt_gb"] >= 2 * rep["params_gb"] * 0.95  # adam m+v

    def test_scales_down_with_batch(self):
        ests = [bench.transformer_hbm_preflight(b, 1024, 2048, 8, 32)[1][
            "total_gb_est"] for b in (16, 8, 4)]
        assert ests[0] > ests[1] > ests[2]
        # fixed state (params+opt+grads) is batch-independent
        fixed = [bench.transformer_hbm_preflight(b, 1024, 2048, 8, 32)[1]
                 for b in (16, 4)]
        for key in ("params_gb", "opt_gb", "grads_gb"):
            assert fixed[0][key] == fixed[1][key]

    def test_tiny_config_fits_easily(self):
        fits, rep = bench.transformer_hbm_preflight(4, 256, 256, 2, 4,
                                                    vocab=1024)
        assert fits
        assert rep["total_gb_est"] < 1.0
