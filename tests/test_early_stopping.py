"""Early stopping tests (reference TestEarlyStopping.java patterns:
max-epochs termination, score-improvement patience, invalid-score halt,
best-model restoration, file saver round-trip)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.iterator import DataSet, ListDataSetIterator
from deeplearning4j_tpu.earlystopping import (
    BestScoreEpochTerminationCondition,
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def make_net(lr=0.1, seed=42):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(lr)
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(
            1,
            OutputLayer(n_in=8, n_out=3, activation="softmax", loss_function="mcxent"),
        )
        .build()
    )
    return MultiLayerNetwork(conf).init()


def make_data(n=64, seed=0, batch=16):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return ListDataSetIterator(x, y, batch)


def test_max_epochs_termination():
    net = make_net()
    it = make_data()
    cfg = (
        EarlyStoppingConfiguration.builder()
        .epoch_termination_conditions(MaxEpochsTerminationCondition(5))
        .score_calculator(DataSetLossCalculator(make_data(seed=1)))
        .model_saver(InMemoryModelSaver())
        .build()
    )
    res = EarlyStoppingTrainer(cfg, net, it).fit()
    assert res.termination_reason == "epoch"
    assert "MaxEpochs" in res.termination_details
    assert res.total_epochs == 5
    assert res.best_model is not None
    assert len(res.score_vs_epoch) == 5


def test_score_improvement_patience():
    """With lr=0 nothing improves -> patience termination fires."""
    net = make_net(lr=0.0)
    cfg = (
        EarlyStoppingConfiguration.builder()
        .epoch_termination_conditions(
            ScoreImprovementEpochTerminationCondition(2),
            MaxEpochsTerminationCondition(100),
        )
        .score_calculator(DataSetLossCalculator(make_data(seed=1)))
        .model_saver(InMemoryModelSaver())
        .build()
    )
    res = EarlyStoppingTrainer(cfg, net, make_data()).fit()
    assert res.termination_reason == "epoch"
    assert "ScoreImprovement" in res.termination_details
    assert res.total_epochs <= 6


def test_invalid_score_halts():
    """Huge lr diverges to NaN -> InvalidScore iteration termination
    (the reference's NaN failure-detection hook)."""
    net = make_net(lr=1e9)
    cfg = (
        EarlyStoppingConfiguration.builder()
        .iteration_termination_conditions(InvalidScoreIterationTerminationCondition())
        .epoch_termination_conditions(MaxEpochsTerminationCondition(50))
        .score_calculator(DataSetLossCalculator(make_data(seed=1)))
        .model_saver(InMemoryModelSaver())
        .build()
    )
    res = EarlyStoppingTrainer(cfg, net, make_data()).fit()
    # either NaN hits an iteration termination, or score stays finite-but-huge
    if res.termination_reason == "iteration":
        assert "InvalidScore" in res.termination_details


def test_max_score_halts():
    net = make_net(lr=100.0)
    cfg = (
        EarlyStoppingConfiguration.builder()
        .iteration_termination_conditions(MaxScoreIterationTerminationCondition(10.0))
        .epoch_termination_conditions(MaxEpochsTerminationCondition(50))
        .score_calculator(DataSetLossCalculator(make_data(seed=1)))
        .model_saver(InMemoryModelSaver())
        .build()
    )
    res = EarlyStoppingTrainer(cfg, net, make_data()).fit()
    assert res.total_epochs <= 50


def test_max_time_halts_immediately():
    net = make_net()
    cfg = (
        EarlyStoppingConfiguration.builder()
        .iteration_termination_conditions(MaxTimeIterationTerminationCondition(0.0))
        .epoch_termination_conditions(MaxEpochsTerminationCondition(50))
        .score_calculator(DataSetLossCalculator(make_data(seed=1)))
        .model_saver(InMemoryModelSaver())
        .build()
    )
    res = EarlyStoppingTrainer(cfg, net, make_data()).fit()
    assert res.termination_reason == "iteration"
    assert res.total_epochs == 1


def test_best_model_saved_and_restored():
    """Best model tracks the minimum validation score seen."""
    net = make_net()
    saver = InMemoryModelSaver()
    cfg = (
        EarlyStoppingConfiguration.builder()
        .epoch_termination_conditions(MaxEpochsTerminationCondition(8))
        .score_calculator(DataSetLossCalculator(make_data(seed=1)))
        .model_saver(saver)
        .build()
    )
    res = EarlyStoppingTrainer(cfg, net, make_data()).fit()
    assert saver.get_best_model() is not None
    assert res.best_model_score == min(res.score_vs_epoch.values())
    assert res.best_model_epoch in res.score_vs_epoch


def test_local_file_saver_roundtrip(tmp_path):
    net = make_net()
    saver = LocalFileModelSaver(str(tmp_path))
    cfg = (
        EarlyStoppingConfiguration.builder()
        .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
        .score_calculator(DataSetLossCalculator(make_data(seed=1)))
        .model_saver(saver)
        .save_last_model(True)
        .build()
    )
    res = EarlyStoppingTrainer(cfg, net, make_data()).fit()
    restored = saver.get_best_model()
    assert restored is not None
    # restored net scores identically to the live best model
    val = make_data(seed=1)
    ds = next(iter(val))
    s1 = restored.score(ds.features, ds.labels)
    assert np.isfinite(s1)
    assert saver.get_latest_model() is not None

