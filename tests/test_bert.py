"""BERT-style masked-LM encoder (models/bert.py).

Key properties: genuinely BIDIRECTIONAL attention (a future token changes
an earlier position's logits — the opposite of the causal flagship),
padding invisibility, the 80/10/10 masking recipe, and learnability (a
model trained on a deterministic pattern recovers masked tokens)."""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.bert import (
    BertConfig,
    BertMLM,
    encode,
    init_params,
    mask_tokens,
    mlm_logits,
)


def _cfg(**kw):
    base = dict(vocab_size=40, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                max_len=12, learning_rate=3e-3, seed=0)
    base.update(kw)
    return BertConfig(**base)


class TestEncoder:
    def test_bidirectional_not_causal(self):
        """Changing a LATER token must change an EARLIER position's
        hidden state — the defining difference from the causal LM."""
        cfg = _cfg()
        params = init_params(cfg)
        t1 = jnp.asarray([[5, 6, 7, 8, 9, 10]], jnp.int32)
        t2 = t1.at[0, 5].set(11)  # perturb only the last position
        h1 = encode(params, t1, cfg)
        h2 = encode(params, t2, cfg)
        dev = float(jnp.max(jnp.abs(h1[0, 0] - h2[0, 0])))
        assert dev > 1e-6, "position 0 blind to position 5: causal, not BERT"

    def test_padding_is_invisible(self):
        """Extending a sequence with pad tokens must not change the real
        positions' hidden states (key-padding mask)."""
        cfg = _cfg()
        params = init_params(cfg)
        short = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
        padded = jnp.asarray([[5, 6, 7, 8, 0, 0, 0]], jnp.int32)
        h_s = encode(params, short, cfg)
        h_p = encode(params, padded, cfg)
        np.testing.assert_allclose(np.asarray(h_p[0, :4]),
                                   np.asarray(h_s[0]), atol=1e-5)

    def test_logits_shape(self):
        cfg = _cfg()
        params = init_params(cfg)
        toks = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
        assert mlm_logits(params, toks, cfg).shape == (2, 3, cfg.vocab_size)


class TestMasking:
    def test_recipe_bounds_and_targets(self):
        cfg = _cfg(mlm_prob=0.3)
        rng = np.random.default_rng(0)
        toks = rng.integers(1, cfg.vocab_size - 1, (16, 12))
        inputs, targets, weights = mask_tokens(toks, cfg, rng)
        np.testing.assert_array_equal(targets, toks)  # originals kept
        sel = weights > 0
        assert sel.any()
        # unselected positions pass through untouched
        np.testing.assert_array_equal(inputs[~sel], toks[~sel])
        # most selected positions carry [MASK] (80% branch)
        frac_mask = (inputs[sel] == cfg.mask_id).mean()
        assert 0.5 < frac_mask <= 1.0

    def test_pad_never_selected(self):
        cfg = _cfg()
        rng = np.random.default_rng(1)
        toks = np.full((4, 8), cfg.pad_token_id)
        toks[:, :3] = 7
        _, _, weights = mask_tokens(toks, cfg, rng)
        assert (weights[:, 3:] == 0).all()

    def test_random_branch_never_injects_pad(self):
        """A 'random' replacement drawing the pad id would make a real
        position invisible as a key (key_mask comes from the corrupted
        inputs) — the draw must exclude pad for ANY pad_token_id."""
        cfg = _cfg(pad_token_id=3, mlm_prob=0.9)
        rng = np.random.default_rng(5)
        toks = np.full((32, 12), 9)
        for _ in range(20):
            inputs, _, _ = mask_tokens(toks, cfg, rng)
            assert (inputs != cfg.pad_token_id).all()

    def test_bad_schedule_rejected_loudly(self):
        import pytest

        with pytest.raises(ValueError, match="total_steps"):
            BertMLM(_cfg(lr_schedule="cosine", total_steps=0))
        with pytest.raises(ValueError, match="lr_schedule"):
            BertMLM(_cfg(lr_schedule="consine"))

    def test_at_least_one_selection(self):
        cfg = _cfg(mlm_prob=1e-9)
        rng = np.random.default_rng(2)
        toks = np.full((2, 6), 9)
        _, _, weights = mask_tokens(toks, cfg, rng)
        assert weights.sum() >= 1


class TestTraining:
    def test_mlm_learns_deterministic_pattern(self):
        """Sequences follow token[i+1] = token[i] + 1 (mod small range):
        with both-side context every masked token is perfectly inferable,
        so the loss must fall and masked accuracy must become high."""
        cfg = _cfg(vocab_size=24, mlm_prob=0.25, learning_rate=5e-3)
        lm = BertMLM(cfg)
        rng = np.random.default_rng(3)
        batches = []
        for _ in range(8):
            start = rng.integers(1, 10, (16, 1))
            seq = (start + np.arange(12)[None]) % 20 + 1
            batches.append(seq)
        first = lm.fit(batches[0])
        last = None
        # 60 epochs (was 40): the masking-draw rng stream differs across
        # jax versions and this environment's stream converges a bit
        # later (measured: acc 0.68 @40, 0.94 @60) — same bar, more steps
        for _ in range(60):
            for b in batches:
                last = lm.fit(b)
        assert last < first * 0.5, (first, last)
        acc = lm.masked_accuracy(batches[0], n_draws=4)
        assert acc > 0.75, acc

    def test_embed_tokens_shape(self):
        cfg = _cfg()
        lm = BertMLM(cfg)
        out = lm.embed_tokens(np.array([[1, 2, 3, 4]]))
        assert out.shape == (1, 4, cfg.d_model)


class TestFineTuning:
    def test_classifier_learns_from_pretrained_encoder(self):
        """Pretrain MLM on patterned sequences, then fine-tune a
        classifier to predict which pattern family a sequence belongs
        to (full fine-tune, scale 1.0); held-out accuracy must be
        high."""
        from deeplearning4j_tpu.models.bert import BertClassifier

        cfg = _cfg(vocab_size=24, mlm_prob=0.25, learning_rate=5e-3)
        lm = BertMLM(cfg)
        rng = np.random.default_rng(6)

        def family(kind, n):
            start = rng.integers(1, 8, (n, 1))
            step = 1 if kind == 0 else 2  # ascending-by-1 vs by-2
            return (start + step * np.arange(12)[None]) % 20 + 1

        pre = np.concatenate([family(0, 32), family(1, 32)])
        for _ in range(15):
            lm.fit(pre)

        X = np.concatenate([family(0, 48), family(1, 48)])
        y = np.concatenate([np.zeros(48, np.int64), np.ones(48, np.int64)])
        sh = rng.permutation(len(X))
        X, y = X[sh], y[sh]
        clf = BertClassifier(lm, n_classes=2)
        first = clf.fit(X[:64], y[:64])
        for _ in range(30):
            last = clf.fit(X[:64], y[:64])
        assert last < first, (first, last)
        acc = clf.accuracy(X[64:], y[64:])  # held-out
        assert acc > 0.85, acc

    def test_encoder_lr_scale_orders_update_magnitudes(self):
        """The discriminative scale must act on the UPDATE, not the
        gradients — Adam's m/(sqrt(v)+eps) cancels a pure gradient
        scale, which would make any scale in (0,1) a silent no-op.
        Pin: encoder movement at scale 0.2 is strictly between frozen
        (0.0) and full (1.0), and roughly 0.2x of full on step one."""
        from deeplearning4j_tpu.models.bert import BertClassifier

        cfg = _cfg(vocab_size=24)
        rng = np.random.default_rng(8)
        X = rng.integers(1, 20, (16, 12))
        y = rng.integers(0, 2, 16)

        def delta(scale):
            lm = BertMLM(cfg)
            before = jax.tree_util.tree_map(np.asarray, lm.params)
            clf = BertClassifier(lm, n_classes=2, encoder_lr_scale=scale)
            clf.fit(X, y)  # one step
            return sum(
                float(np.sum(np.abs(np.asarray(a) - b)))
                for a, b in zip(
                    jax.tree_util.tree_leaves(clf.state["encoder"]),
                    jax.tree_util.tree_leaves(before)))

        d0, d02, d1 = delta(0.0), delta(0.2), delta(1.0)
        assert d0 == 0.0
        assert 0.0 < d02 < d1, (d02, d1)
        np.testing.assert_allclose(d02 / d1, 0.2, rtol=1e-3)

    def test_frozen_encoder_trains_head_only(self):
        from deeplearning4j_tpu.models.bert import BertClassifier

        cfg = _cfg(vocab_size=24)
        lm = BertMLM(cfg)
        before = jax.tree_util.tree_map(np.asarray, lm.params)
        clf = BertClassifier(lm, n_classes=2, encoder_lr_scale=0.0)
        rng = np.random.default_rng(7)
        X = rng.integers(1, 20, (16, 12))
        y = rng.integers(0, 2, 16)
        for _ in range(4):
            clf.fit(X, y)
        after = clf.state["encoder"]
        dev = max(float(np.max(np.abs(np.asarray(a) - b)))
                  for a, b in zip(jax.tree_util.tree_leaves(after),
                                  jax.tree_util.tree_leaves(before)))
        assert dev == 0.0, f"frozen encoder moved by {dev}"
        # head must have MOVED from its init (a regression freezing the
        # whole tree would leave it at the random init exactly)
        from deeplearning4j_tpu.models.bert import init_classifier_head

        hw0 = np.asarray(init_classifier_head(cfg, 2,
                                              seed=cfg.seed + 1)["Wc"])
        hw = np.asarray(clf.state["head"]["Wc"])
        assert float(np.max(np.abs(hw - hw0))) > 1e-6  # head did train


class TestFusedMultiStep:
    def test_fit_batches_equals_sequential_fits(self):
        """K steps in one lax.scan program == K fit() calls on the same
        batches (same rng stream => same mask draws => identical
        optimizer trajectory, the flagship's fit_batches contract)."""
        cfg = _cfg(vocab_size=24)
        rng = np.random.default_rng(9)
        batches = rng.integers(1, 20, (3, 8, 12))

        seq = BertMLM(cfg)
        for b in batches:
            last_seq = seq.fit(b)

        fused = BertMLM(cfg)
        last_fused = fused.fit_batches(batches)

        np.testing.assert_allclose(last_fused, last_seq, rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(seq.params),
                        jax.tree_util.tree_leaves(fused.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestCheckpoint:
    def test_save_load_round_trip(self, tmp_path):
        """ModelSerializer zip layout round trip: params, optimizer
        state, AND the training trajectory (a restored model continues
        with identical steps)."""
        cfg = _cfg(vocab_size=24)
        lm = BertMLM(cfg)
        rng = np.random.default_rng(10)
        batch = rng.integers(1, 20, (8, 12))
        for _ in range(3):
            lm.fit(batch)
        p = str(tmp_path / "bert.zip")
        lm.save(p)

        back = BertMLM.load(p)
        for a, b in zip(jax.tree_util.tree_leaves(lm.params),
                        jax.tree_util.tree_leaves(back.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(back.opt["t"]) == int(lm.opt["t"])
        # identical continued trajectory (same rng stream position is not
        # part of the checkpoint; re-seed both to compare fairly)
        lm._rng = np.random.default_rng(99)
        back._rng = np.random.default_rng(99)
        np.testing.assert_allclose(lm.fit(batch), back.fit(batch),
                                   rtol=1e-6)

    def test_wrong_model_class_rejected(self, tmp_path):
        import pytest

        from deeplearning4j_tpu.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )

        tl = TransformerLM(TransformerConfig(
            vocab_size=30, d_model=32, n_layers=1, n_heads=4, d_ff=32,
            max_len=8, learning_rate=1e-3, use_flash=False))
        p = str(tmp_path / "lm.zip")
        tl.save(p)
        with pytest.raises(ValueError, match="BertMLM"):
            BertMLM.load(p)

    def test_model_serializer_dispatches_bert(self, tmp_path):
        """ModelSerializer.restore (the serving/CLI entry point) must
        route a BertMLM zip to BertMLM.load, not crash in the MLN
        restorer."""
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        cfg = _cfg(vocab_size=24)
        lm = BertMLM(cfg)
        p = str(tmp_path / "bert.zip")
        lm.save(p)
        back = ModelSerializer.restore(p)
        assert isinstance(back, BertMLM)
        for a, b in zip(jax.tree_util.tree_leaves(lm.params),
                        jax.tree_util.tree_leaves(back.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_classifier_save_load_round_trip(self, tmp_path):
        from deeplearning4j_tpu.models.bert import BertClassifier

        cfg = _cfg(vocab_size=24)
        lm = BertMLM(cfg)
        clf = BertClassifier(lm, n_classes=3, encoder_lr_scale=0.5)
        rng = np.random.default_rng(12)
        X = rng.integers(1, 20, (8, 12))
        y = rng.integers(0, 3, 8)
        for _ in range(3):
            clf.fit(X, y)
        p = str(tmp_path / "clf.zip")
        clf.save(p)

        back = BertClassifier.load(p)
        assert back.n_classes == 3
        assert back._encoder_lr_scale == 0.5
        np.testing.assert_array_equal(back.predict(X), clf.predict(X))
        # continued fine-tuning takes the identical next step
        np.testing.assert_allclose(clf.fit(X, y), back.fit(X, y),
                                   rtol=1e-6)

    def test_model_serializer_dispatches_classifier(self, tmp_path):
        from deeplearning4j_tpu.models.bert import BertClassifier
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        cfg = _cfg(vocab_size=24)
        clf = BertClassifier(BertMLM(cfg), n_classes=2)
        p = str(tmp_path / "clf.zip")
        clf.save(p)
        back = ModelSerializer.restore(p)
        assert isinstance(back, BertClassifier)
