"""BERT-style masked-LM encoder (models/bert.py).

Key properties: genuinely BIDIRECTIONAL attention (a future token changes
an earlier position's logits — the opposite of the causal flagship),
padding invisibility, the 80/10/10 masking recipe, and learnability (a
model trained on a deterministic pattern recovers masked tokens)."""
import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.bert import (
    BertConfig,
    BertMLM,
    encode,
    init_params,
    mask_tokens,
    mlm_logits,
)


def _cfg(**kw):
    base = dict(vocab_size=40, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                max_len=12, learning_rate=3e-3, seed=0)
    base.update(kw)
    return BertConfig(**base)


class TestEncoder:
    def test_bidirectional_not_causal(self):
        """Changing a LATER token must change an EARLIER position's
        hidden state — the defining difference from the causal LM."""
        cfg = _cfg()
        params = init_params(cfg)
        t1 = jnp.asarray([[5, 6, 7, 8, 9, 10]], jnp.int32)
        t2 = t1.at[0, 5].set(11)  # perturb only the last position
        h1 = encode(params, t1, cfg)
        h2 = encode(params, t2, cfg)
        dev = float(jnp.max(jnp.abs(h1[0, 0] - h2[0, 0])))
        assert dev > 1e-6, "position 0 blind to position 5: causal, not BERT"

    def test_padding_is_invisible(self):
        """Extending a sequence with pad tokens must not change the real
        positions' hidden states (key-padding mask)."""
        cfg = _cfg()
        params = init_params(cfg)
        short = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
        padded = jnp.asarray([[5, 6, 7, 8, 0, 0, 0]], jnp.int32)
        h_s = encode(params, short, cfg)
        h_p = encode(params, padded, cfg)
        np.testing.assert_allclose(np.asarray(h_p[0, :4]),
                                   np.asarray(h_s[0]), atol=1e-5)

    def test_logits_shape(self):
        cfg = _cfg()
        params = init_params(cfg)
        toks = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
        assert mlm_logits(params, toks, cfg).shape == (2, 3, cfg.vocab_size)


class TestMasking:
    def test_recipe_bounds_and_targets(self):
        cfg = _cfg(mlm_prob=0.3)
        rng = np.random.default_rng(0)
        toks = rng.integers(1, cfg.vocab_size - 1, (16, 12))
        inputs, targets, weights = mask_tokens(toks, cfg, rng)
        np.testing.assert_array_equal(targets, toks)  # originals kept
        sel = weights > 0
        assert sel.any()
        # unselected positions pass through untouched
        np.testing.assert_array_equal(inputs[~sel], toks[~sel])
        # most selected positions carry [MASK] (80% branch)
        frac_mask = (inputs[sel] == cfg.mask_id).mean()
        assert 0.5 < frac_mask <= 1.0

    def test_pad_never_selected(self):
        cfg = _cfg()
        rng = np.random.default_rng(1)
        toks = np.full((4, 8), cfg.pad_token_id)
        toks[:, :3] = 7
        _, _, weights = mask_tokens(toks, cfg, rng)
        assert (weights[:, 3:] == 0).all()

    def test_random_branch_never_injects_pad(self):
        """A 'random' replacement drawing the pad id would make a real
        position invisible as a key (key_mask comes from the corrupted
        inputs) — the draw must exclude pad for ANY pad_token_id."""
        cfg = _cfg(pad_token_id=3, mlm_prob=0.9)
        rng = np.random.default_rng(5)
        toks = np.full((32, 12), 9)
        for _ in range(20):
            inputs, _, _ = mask_tokens(toks, cfg, rng)
            assert (inputs != cfg.pad_token_id).all()

    def test_bad_schedule_rejected_loudly(self):
        import pytest

        with pytest.raises(ValueError, match="total_steps"):
            BertMLM(_cfg(lr_schedule="cosine", total_steps=0))
        with pytest.raises(ValueError, match="lr_schedule"):
            BertMLM(_cfg(lr_schedule="consine"))

    def test_at_least_one_selection(self):
        cfg = _cfg(mlm_prob=1e-9)
        rng = np.random.default_rng(2)
        toks = np.full((2, 6), 9)
        _, _, weights = mask_tokens(toks, cfg, rng)
        assert weights.sum() >= 1


class TestTraining:
    def test_mlm_learns_deterministic_pattern(self):
        """Sequences follow token[i+1] = token[i] + 1 (mod small range):
        with both-side context every masked token is perfectly inferable,
        so the loss must fall and masked accuracy must become high."""
        cfg = _cfg(vocab_size=24, mlm_prob=0.25, learning_rate=5e-3)
        lm = BertMLM(cfg)
        rng = np.random.default_rng(3)
        batches = []
        for _ in range(8):
            start = rng.integers(1, 10, (16, 1))
            seq = (start + np.arange(12)[None]) % 20 + 1
            batches.append(seq)
        first = lm.fit(batches[0])
        last = None
        for _ in range(40):
            for b in batches:
                last = lm.fit(b)
        assert last < first * 0.5, (first, last)
        acc = lm.masked_accuracy(batches[0], n_draws=4)
        assert acc > 0.75, acc

    def test_embed_tokens_shape(self):
        cfg = _cfg()
        lm = BertMLM(cfg)
        out = lm.embed_tokens(np.array([[1, 2, 3, 4]]))
        assert out.shape == (1, 4, cfg.d_model)
