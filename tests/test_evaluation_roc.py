"""ROC / AUC (eval surface — beyond the 0.4 reference's Evaluation)."""

import numpy as np
import pytest

class TestROC:
    def test_auc_perfect_and_random(self):
        from deeplearning4j_tpu.eval import ROC

        y = np.array([0, 0, 0, 1, 1, 1])
        perfect = ROC().eval(y, np.array([.1, .2, .3, .7, .8, .9]))
        assert perfect.auc() == 1.0
        inverted = ROC().eval(y, np.array([.9, .8, .7, .3, .2, .1]))
        assert inverted.auc() == 0.0
        # ties at 0.5 for everything -> chance-level 0.5
        flat = ROC().eval(y, np.full(6, 0.5))
        assert flat.auc() == 0.5

    def test_matches_sklearn_free_reference(self):
        """Hand-checked AUC against the rank-statistic (Mann-Whitney U)
        definition on a random set."""
        from deeplearning4j_tpu.eval import ROC

        rng = np.random.default_rng(0)
        y = rng.integers(0, 2, 200)
        s = rng.random(200)
        roc = ROC().eval(y, s)
        pos = s[y == 1][:, None]
        neg = s[y == 0][None, :]
        u = (pos > neg).sum() + 0.5 * (pos == neg).sum()
        expect = u / (len(pos) * neg.shape[1])
        assert roc.auc() == pytest.approx(float(expect), abs=1e-9)

    def test_merge_and_onehot_inputs(self):
        from deeplearning4j_tpu.eval import ROC

        y1 = np.eye(2)[[0, 1, 1]]
        p1 = np.stack([[.8, .2], [.3, .7], [.4, .6]])
        y2 = np.eye(2)[[0, 0, 1]]
        p2 = np.stack([[.9, .1], [.6, .4], [.2, .8]])
        a = ROC().eval(y1, p1)
        b = ROC().eval(y2, p2)
        merged = a.merge(b)
        whole = ROC().eval(np.concatenate([y1, y2]),
                           np.concatenate([p1, p2]))
        assert merged.auc() == whole.auc() == 1.0
        assert "AUC" in merged.stats()


class TestROCEdgeShapes:
    def test_column_labels_and_sigmoid_probs(self):
        from deeplearning4j_tpu.eval import ROC

        roc = ROC().eval(np.array([[0], [1], [1], [0]]),
                         np.array([[.1], [.9], [.8], [.2]]))
        assert roc.auc() == 1.0

    def test_single_class_is_nan_not_zero(self):
        from deeplearning4j_tpu.eval import ROC

        assert np.isnan(ROC().eval([1, 1, 1], [.9, .8, .7]).auc())
        assert np.isnan(ROC().eval([0, 0], [.1, .2]).auc())


class TestTopNAccuracy:
    def test_top_n_counts(self):
        from deeplearning4j_tpu.eval import Evaluation

        y = np.eye(4)[[0, 1, 2, 3]]
        # argmax right only for row 0; true class is 2nd-best for rows 1-2,
        # dead last for row 3
        p = np.array([
            [.7, .1, .1, .1],
            [.6, .4, .0, .0],
            [.1, .5, .4, .0],
            [.5, .3, .2, .0],
        ])
        e1 = Evaluation()
        e1.eval(y, p)
        assert e1.top_n_accuracy() == e1.accuracy() == 0.25
        e2 = Evaluation(top_n=2)
        e2.eval(y, p)
        assert e2.top_n_accuracy() == 0.75
        assert e2.accuracy() == 0.25  # top-1 metrics unchanged

    def test_top_n_merges(self):
        from deeplearning4j_tpu.eval import Evaluation

        y = np.eye(3)[[0, 1]]
        p = np.array([[.5, .4, .1], [.4, .5, .1]])
        a = Evaluation(top_n=2)
        a.eval(y[:1], p[:1])
        b = Evaluation(top_n=2)
        b.eval(y[1:], p[1:])
        a.merge(b)
        assert a.top_n_accuracy() == 1.0

    def test_mixed_top_n_merge_rejected_and_stats_surface(self):
        from deeplearning4j_tpu.eval import Evaluation

        y = np.eye(3)[[0, 1]]
        p = np.array([[.5, .4, .1], [.4, .5, .1]])
        a = Evaluation(top_n=2)
        a.eval(y, p)
        b = Evaluation()  # top_n=1
        b.eval(y, p)
        with pytest.raises(ValueError):
            a.merge(b)
        assert "Top-2 Accuracy" in a.stats()
        assert "Top-" not in b.stats()
