"""Example smoke tier (`-m examples`): every stock entrypoint must RUN.

VERDICT r5 weak #4: no test executed any of the `examples/` scripts, yet
the north star is phrased over "stock dl4j-examples entrypoints" — an
entrypoint no test runs is rot waiting to be discovered during a 3-minute
tunnel window. The reference keeps its equivalent surface alive through
its suite (deeplearning4j-core/.../MultiLayerTest.java); here each script
runs in a SUBPROCESS exactly as a user would launch it (`python -u
examples/<name>.py` from the repo root), under the tiny-shape smoke knob
(DL4J_TPU_EXAMPLE_SMOKE=1) so 11 entrypoints cost minutes, not hours, on
this 1-core host. The scripts force the CPU platform themselves (their
first jax.config.update line — the dead-tunnel lesson), so the tier never
touches the accelerator.
"""

import glob
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(
    os.path.basename(p)
    for p in glob.glob(os.path.join(REPO, "examples", "*.py")))

# generous per-script cap: a healthy smoke run is seconds to ~2 min; the
# cap exists to turn a genuine hang into a failure, not to race the host
TIMEOUT_S = 600


def _run_example(name: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["DL4J_TPU_EXAMPLE_SMOKE"] = "1"
    env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
    # a leftover multihost env (e.g. from an aborted worker) must not
    # leak a distributed contract into single-process examples
    for k in ("DL4J_TPU_COORDINATOR", "DL4J_TPU_NUM_PROCESSES",
              "DL4J_TPU_PROCESS_ID"):
        env.pop(k, None)
    return subprocess.run(
        [sys.executable, "-u", os.path.join(REPO, "examples", name)],
        capture_output=True, text=True, timeout=TIMEOUT_S, env=env,
        cwd=REPO)


def test_every_example_is_covered():
    """The parametrized list below is generated from the directory, so a
    NEW example is auto-covered; this guard only ensures the glob still
    sees the directory at all."""
    assert len(EXAMPLES) >= 11, EXAMPLES


@pytest.mark.examples
@pytest.mark.parametrize("name", EXAMPLES)
def test_example_runs(name):
    r = _run_example(name)
    assert r.returncode == 0, (
        f"{name} exited {r.returncode}\n--- stdout ---\n{r.stdout[-4000:]}"
        f"\n--- stderr ---\n{r.stderr[-4000:]}")
    # every example prints SOMETHING (loss lines, samples, eval stats) —
    # an empty stdout means the entrypoint silently did nothing
    assert r.stdout.strip(), f"{name} produced no output"
