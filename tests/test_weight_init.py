"""Weight init distribution tests (reference WeightInitUtil.java:93-123 semantics)."""

import numpy as np
import jax
import pytest

from deeplearning4j_tpu.nn.weights import init_weights


KEY = jax.random.PRNGKey(0)
SHAPE = (200, 300)
FAN_IN, FAN_OUT = SHAPE


def test_zero():
    w = init_weights(KEY, SHAPE, "zero", FAN_IN, FAN_OUT)
    assert np.all(np.asarray(w) == 0)


def test_xavier_std():
    w = np.asarray(init_weights(KEY, SHAPE, "xavier", FAN_IN, FAN_OUT))
    expected = 1.0 / np.sqrt(FAN_IN + FAN_OUT)
    assert abs(w.std() - expected) / expected < 0.05
    assert abs(w.mean()) < 3 * expected / np.sqrt(w.size)


def test_relu_std():
    w = np.asarray(init_weights(KEY, SHAPE, "relu", FAN_IN, FAN_OUT))
    expected = np.sqrt(2.0 / FAN_IN)
    assert abs(w.std() - expected) / expected < 0.05


def test_uniform_range():
    w = np.asarray(init_weights(KEY, SHAPE, "uniform", FAN_IN, FAN_OUT))
    a = 1.0 / FAN_IN
    assert w.min() >= -a and w.max() <= a
    assert w.max() > 0.9 * a  # actually fills the range


def test_vi_range():
    w = np.asarray(init_weights(KEY, SHAPE, "vi", FAN_IN, FAN_OUT))
    r = np.sqrt(6.0) / np.sqrt(sum(SHAPE) + 1)
    assert w.min() >= -r and w.max() <= r


def test_size_range():
    w = np.asarray(init_weights(KEY, SHAPE, "size", FAN_IN, FAN_OUT))
    r = 4.0 * np.sqrt(6.0 / (FAN_IN + FAN_OUT))
    assert w.min() >= -r and w.max() <= r


def test_normalized():
    w = np.asarray(init_weights(KEY, SHAPE, "normalized", FAN_IN, FAN_OUT))
    assert w.min() >= -0.5 / FAN_IN and w.max() <= 0.5 / FAN_IN


def test_distribution_normal():
    w = np.asarray(
        init_weights(
            KEY, SHAPE, "distribution", FAN_IN, FAN_OUT,
            dist={"type": "normal", "mean": 1.0, "std": 0.1},
        )
    )
    assert abs(w.mean() - 1.0) < 0.01
    assert abs(w.std() - 0.1) < 0.01


def test_distribution_uniform():
    w = np.asarray(
        init_weights(
            KEY, SHAPE, "distribution", FAN_IN, FAN_OUT,
            dist={"type": "uniform", "lower": 2.0, "upper": 3.0},
        )
    )
    assert w.min() >= 2.0 and w.max() <= 3.0


def test_determinism():
    a = init_weights(KEY, SHAPE, "xavier", FAN_IN, FAN_OUT)
    b = init_weights(KEY, SHAPE, "xavier", FAN_IN, FAN_OUT)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_unknown_scheme_raises():
    with pytest.raises(ValueError):
        init_weights(KEY, SHAPE, "bogus", FAN_IN, FAN_OUT)
