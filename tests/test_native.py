"""Native host-runtime tests: parity between the C++ library and the pure
Python fallbacks (idx/CSV parsing, deterministic shuffle, threaded prefetch
— the nd4j-native/Canova/AsyncDataSetIterator roles, SURVEY.md L0/L5)."""

import struct

import numpy as np
import pytest

from deeplearning4j_tpu import native
from deeplearning4j_tpu.native import (
    NATIVE_AVAILABLE,
    NativePrefetchIterator,
    read_csv,
    read_idx,
    shuffle_indices,
)


def write_idx_bytes(path, arr: np.ndarray):
    """idx file with unsigned-byte payload."""
    with open(path, "wb") as f:
        f.write(bytes([0, 0, 0x08, arr.ndim]))
        for d in arr.shape:
            f.write(struct.pack(">i", d))
        f.write(arr.astype(np.uint8).tobytes())


class TestIdx:
    def test_read_idx_matches_python(self, tmp_path):
        rng = np.random.default_rng(0)
        arr = rng.integers(0, 256, (10, 5, 5), dtype=np.uint8)
        p = str(tmp_path / "images.idx")
        write_idx_bytes(p, arr)
        out = read_idx(p, normalize=True)
        assert out.shape == (10, 5, 5)
        np.testing.assert_allclose(out, arr.astype(np.float32) / 255.0,
                                   rtol=1e-6)
        py = native._read_idx_py(p, True)
        np.testing.assert_allclose(out, py, rtol=1e-6)

    def test_read_idx_unnormalized(self, tmp_path):
        arr = np.arange(12, dtype=np.uint8).reshape(3, 4)
        p = str(tmp_path / "l.idx")
        write_idx_bytes(p, arr)
        out = read_idx(p, normalize=False)
        np.testing.assert_array_equal(out, arr.astype(np.float32))


class TestCsv:
    def test_read_csv_matches_numpy(self, tmp_path):
        rng = np.random.default_rng(1)
        data = rng.normal(size=(50, 7))
        p = str(tmp_path / "d.csv")
        np.savetxt(p, data, delimiter=",", fmt="%.6f")
        out = read_csv(p)
        ref = np.loadtxt(p, delimiter=",", ndmin=2).astype(np.float32)
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_read_csv_no_trailing_newline(self, tmp_path):
        p = tmp_path / "x.csv"
        p.write_text("1.5,2.5\n3.5,4.5")  # no trailing \n
        out = read_csv(str(p))
        np.testing.assert_allclose(out, [[1.5, 2.5], [3.5, 4.5]])

    def test_ragged_csv_rejected(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("1,2\n3,4,5\n")
        with pytest.raises(Exception):
            read_csv(str(p))


class TestShuffle:
    def test_native_matches_python_fallback(self):
        for n, seed in [(10, 0), (1000, 42), (7, 123456789)]:
            a = shuffle_indices(n, seed)
            b = native._shuffle_py(n, seed)
            np.testing.assert_array_equal(a, b)
            assert sorted(a.tolist()) == list(range(n))

    def test_deterministic(self):
        np.testing.assert_array_equal(
            shuffle_indices(100, 7), shuffle_indices(100, 7)
        )
        assert not np.array_equal(shuffle_indices(100, 7),
                                  shuffle_indices(100, 8))


class TestPrefetch:
    def test_prefetch_covers_all_batches_and_matches_fallback(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(64, 3, 2)).astype(np.float32)
        y = rng.normal(size=(64, 5)).astype(np.float32)
        it_native = NativePrefetchIterator(x, y, batch=16, epochs=2, seed=9)
        batches = list(it_native)
        assert len(batches) == 8  # 4 per epoch x 2 epochs
        for fb, lb in batches:
            assert fb.shape == (16, 3, 2) and lb.shape == (16, 5)
        # bit-exact agreement with the pure-python path
        py_batches = list(it_native._iter_py())
        assert len(py_batches) == len(batches)
        for (fa, la), (fb, lb) in zip(batches, py_batches):
            np.testing.assert_array_equal(fa, fb)
            np.testing.assert_array_equal(la, lb)

    def test_each_epoch_is_a_permutation(self):
        x = np.arange(32, dtype=np.float32).reshape(32, 1)
        y = np.zeros((32, 1), np.float32)
        seen = [fb.reshape(-1) for fb, _ in
                NativePrefetchIterator(x, y, batch=8, epochs=1, seed=3)]
        flat = np.concatenate(seen)
        assert sorted(flat.tolist()) == list(range(32))


def test_native_library_loaded():
    """The toolchain is baked into this image, so the native path must be
    active (the fallback exists for foreign deployments)."""
    assert NATIVE_AVAILABLE


class TestNpzReader:
    """Native npz parsing for the exported-dataset plane (round 4):
    training_master.export_datasets writes one stored-entry npz per
    minibatch (the reference's RDDTrainingApproach.Export split files,
    ParameterAveragingTrainingMaster.java:148-168); fit(path) streams
    them back through iter_npz's ordered background prefetcher."""

    def _write(self, path, **arrays):
        np.savez(path, **arrays)
        return str(path)

    def test_read_npz_round_trips_all_dtypes(self, tmp_path):
        from deeplearning4j_tpu.native import read_npz

        ref = {
            "f4": np.random.randn(6, 3, 2).astype(np.float32),
            "f8": np.random.randn(6, 4),
            "i4": np.arange(12, dtype=np.int32).reshape(3, 4),
            "i8": np.arange(6, dtype=np.int64),
            "b1": np.array([[True, False], [False, True]]),
        }
        p = self._write(tmp_path / "mix.npz", **ref)
        out = read_npz(p)
        assert sorted(out) == sorted(ref)
        for k in ref:
            np.testing.assert_array_equal(out[k], ref[k])
            assert out[k].dtype == ref[k].dtype, k

    def test_read_npz_matches_numpy_on_exported_batch(self, tmp_path):
        from deeplearning4j_tpu.native import read_npz

        p = self._write(tmp_path / "ds.npz",
                        features=np.random.randn(8, 28 * 28)
                        .astype(np.float32),
                        labels=np.eye(10)[np.arange(8) % 10],
                        features_mask=np.ones((8, 4), bool))
        out = read_npz(p)
        with np.load(p) as z:
            for k in z.files:
                np.testing.assert_array_equal(out[k], z[k])

    def test_iter_npz_preserves_order(self, tmp_path):
        from deeplearning4j_tpu.native import iter_npz

        paths = [self._write(tmp_path / f"m{i:03d}.npz",
                             features=np.full((2, 2), i, np.float32),
                             labels=np.zeros((2, 1)))
                 for i in range(12)]
        seen = [int(z["features"][0, 0]) for z in iter_npz(paths,
                                                           capacity=3)]
        assert seen == list(range(12))

    def test_iter_npz_falls_back_per_file_for_compressed(self, tmp_path):
        """A compressed (deflate) member is outside the native parser's
        scope — the stream must transparently np.load that ONE file and
        keep native order for the rest."""
        from deeplearning4j_tpu.native import iter_npz

        paths = [self._write(tmp_path / f"m{i}.npz",
                             features=np.full((2, 2), i, np.float32),
                             labels=np.zeros((2, 1)))
                 for i in range(4)]
        np.savez_compressed(paths[2],
                            features=np.full((2, 2), 2, np.float32),
                            labels=np.zeros((2, 1)))
        seen = [int(z["features"][0, 0]) for z in iter_npz(paths)]
        assert seen == [0, 1, 2, 3]

    def test_python_fallback_matches(self, tmp_path, monkeypatch):
        import deeplearning4j_tpu.native as nat

        p = self._write(tmp_path / "fb.npz",
                        features=np.random.randn(3, 5).astype(np.float32),
                        labels=np.random.randn(3, 2))
        native = nat.read_npz(p)
        monkeypatch.setattr(nat, "_lib", None)
        monkeypatch.setattr(nat, "_load", lambda: None)
        fallback = nat.read_npz(p)
        assert sorted(native) == sorted(fallback)
        for k in native:
            np.testing.assert_array_equal(native[k], fallback[k])
            assert native[k].dtype == fallback[k].dtype

    def test_exported_fit_path_uses_stream(self, tmp_path):
        """End-to-end: export -> load_exported_datasets (now backed by
        iter_npz) round-trips the DataSets bit-exactly."""
        from deeplearning4j_tpu.datasets.iterator import DataSet
        from deeplearning4j_tpu.parallel.training_master import (
            export_datasets,
            load_exported_datasets,
        )

        rng = np.random.default_rng(0)
        sets = [DataSet(rng.standard_normal((4, 6)),
                        np.eye(3)[rng.integers(0, 3, 4)])
                for _ in range(5)]
        export_datasets(sets, str(tmp_path / "exp"))
        back = list(load_exported_datasets(str(tmp_path / "exp")))
        assert len(back) == 5
        for a, b in zip(sets, back):
            np.testing.assert_array_equal(np.asarray(a.features),
                                          b.features)
            np.testing.assert_array_equal(np.asarray(a.labels), b.labels)
