"""North-star equivalence harness tests (BASELINE.json north_star; SURVEY.md
§7 "Hard parts"). On the CPU-only test environment the CPU-vs-default
comparison degenerates to a two-run determinism check: curves must match
EXACTLY (bitwise) — the strongest form of the bar, validating that RNG
streams and compiled programs are reproducible. The real CPU-vs-TPU
deviation is measured by bench.py on hardware."""

import numpy as np

from deeplearning4j_tpu.utils.equivalence import (
    char_batches,
    compare_backends,
    loss_curve,
    mnist_batches,
)


def _lenet_builder():
    from deeplearning4j_tpu.models.lenet import build_lenet5

    return build_lenet5(seed=12345)


def test_lenet_curve_deterministic_and_decreasing():
    batches = mnist_batches(n_steps=12, batch=32)
    res = compare_backends(_lenet_builder, batches)
    assert res["same_backend"]  # cpu test env
    assert res["max_abs_deviation"] == 0.0, res  # bitwise reproducible
    curve = np.asarray(res["curve_cpu"])
    assert curve[-1] < curve[0], "loss did not decrease over 12 steps"


def test_char_rnn_curve_deterministic():
    from deeplearning4j_tpu.models.char_rnn import char_rnn_conf
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def builder():
        return MultiLayerNetwork(
            char_rnn_conf(20, lstm_size=16, num_layers=1, seed=3,
                          tbptt_length=8)
        ).init(input_shape=(1, 20))

    res = compare_backends(builder, char_batches(n_steps=6, batch=8, seq=16, vocab=20))
    assert res["max_abs_deviation"] == 0.0, res


def test_matmul_precision_context_applies():
    """float32-strict vs default precision produce (at minimum) a valid
    curve each; on CPU both are f32 so they agree — the context must not
    break compilation."""
    batches = mnist_batches(n_steps=3, batch=16)
    c_strict = loss_curve(_lenet_builder, batches, matmul_precision="float32")
    c_native = loss_curve(_lenet_builder, batches, matmul_precision=None)
    assert np.isfinite(c_strict).all() and np.isfinite(c_native).all()
    np.testing.assert_allclose(c_strict, c_native, rtol=1e-6)


class TestStrictConv3Pass:
    def test_decomposition_matches_highest_precision_conv(self):
        """bf16x3 conv (ops/precision.py) must be f32-class accurate vs the
        true f32 conv — the bound that makes the strict north-star leg
        honest (VERDICT round-2 #2)."""
        import jax.numpy as jnp
        from jax import lax

        from deeplearning4j_tpu.ops.precision import conv_f32_3pass

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 12, 12, 3)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(5, 5, 3, 8)) * 0.2, jnp.float32)
        kwargs = dict(window_strides=(1, 1), padding=[(0, 0), (0, 0)],
                      dimension_numbers=("NHWC", "HWIO", "NHWC"))
        exact = lax.conv_general_dilated(
            x, w, precision=lax.Precision.HIGHEST, **kwargs)
        approx = conv_f32_3pass(x, w, **kwargs)
        rel = float(jnp.max(jnp.abs(approx - exact))
                    / jnp.max(jnp.abs(exact)))
        assert rel < 1e-5, f"bf16x3 conv relative error {rel}"

    def test_strict_context_engages_layer_path(self):
        """Under strict_conv_3pass() the conv LAYER output changes by at
        most the decomposition bound and by at least something nonzero
        (proves the 3-pass path actually ran)."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.conf.layers import ConvolutionLayer
        from deeplearning4j_tpu.nn.layers.factory import create_layer
        from deeplearning4j_tpu.ops.precision import strict_conv_3pass

        conf = ConvolutionLayer(n_in=3, n_out=4, kernel_size=(3, 3),
                                weight_init="xavier", activation="identity")
        impl = create_layer(conf)
        params, state, _ = impl.initialize(jax.random.PRNGKey(0), (8, 8, 3))
        x = jnp.asarray(
            np.random.default_rng(1).normal(size=(2, 8, 8, 3)),
            jnp.float32)
        y_plain, _ = impl.apply(params, state, x)
        with strict_conv_3pass():
            y_strict, _ = impl.apply(params, state, x)
        dev = float(jnp.max(jnp.abs(y_plain - y_strict)))
        scale = float(jnp.max(jnp.abs(y_plain)))
        assert dev > 0.0, "3-pass path did not engage (outputs identical)"
        assert dev / scale < 1e-5

    def test_north_star_strict_cpu_determinism_with_3pass(self):
        """Two same-backend strict runs (both through the decomposition)
        must be bit-identical — the determinism bar with the new conv
        path engaged."""
        from deeplearning4j_tpu.utils.equivalence import (
            compare_backends,
            mnist_batches,
        )
        from deeplearning4j_tpu.models.lenet import build_lenet5

        res = compare_backends(lambda: build_lenet5(seed=3),
                               mnist_batches(3, batch=16))
        assert res["same_backend"]
        assert res["max_abs_deviation"] == 0.0
