"""North-star equivalence harness tests (BASELINE.json north_star; SURVEY.md
§7 "Hard parts"). On the CPU-only test environment the CPU-vs-default
comparison degenerates to a two-run determinism check: curves must match
EXACTLY (bitwise) — the strongest form of the bar, validating that RNG
streams and compiled programs are reproducible. The real CPU-vs-TPU
deviation is measured by bench.py on hardware."""

import numpy as np

from deeplearning4j_tpu.utils.equivalence import (
    char_batches,
    compare_backends,
    loss_curve,
    mnist_batches,
)


def _lenet_builder():
    from deeplearning4j_tpu.models.lenet import build_lenet5

    return build_lenet5(seed=12345)


def test_lenet_curve_deterministic_and_decreasing():
    batches = mnist_batches(n_steps=12, batch=32)
    res = compare_backends(_lenet_builder, batches)
    assert res["same_backend"]  # cpu test env
    assert res["max_abs_deviation"] == 0.0, res  # bitwise reproducible
    curve = np.asarray(res["curve_cpu"])
    assert curve[-1] < curve[0], "loss did not decrease over 12 steps"


def test_char_rnn_curve_deterministic():
    from deeplearning4j_tpu.models.char_rnn import char_rnn_conf
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    def builder():
        return MultiLayerNetwork(
            char_rnn_conf(20, lstm_size=16, num_layers=1, seed=3,
                          tbptt_length=8)
        ).init(input_shape=(1, 20))

    res = compare_backends(builder, char_batches(n_steps=6, batch=8, seq=16, vocab=20))
    assert res["max_abs_deviation"] == 0.0, res


def test_matmul_precision_context_applies():
    """float32-strict vs default precision produce (at minimum) a valid
    curve each; on CPU both are f32 so they agree — the context must not
    break compilation."""
    batches = mnist_batches(n_steps=3, batch=16)
    c_strict = loss_curve(_lenet_builder, batches, matmul_precision="float32")
    c_native = loss_curve(_lenet_builder, batches, matmul_precision=None)
    assert np.isfinite(c_strict).all() and np.isfinite(c_native).all()
    np.testing.assert_allclose(c_strict, c_native, rtol=1e-6)
