"""Remat policy ladder + AOT memory-accounting plane (ops/remat.py,
ops/memory.py — ISSUE 4, the HBM-lean training PR).

Contracts locked here:
  - remat is a MEMORY policy, never a VALUES policy: forward logits are
    bit-exact across every rung, gradients agree to 1e-6 in f64 for a
    transformer block and the BERT MLM loss (jax.checkpoint recomputes
    the identical ops, so any drift would be a policy-plumbing bug);
  - the ladder is monotone where it claims to be: AOT memory_analysis
    temp bytes at L=8 strictly shrink from none to block (the Chen et
    al. sublinear-memory direction), with dots in between;
  - the auto-fit sizer prefers the cheapest fitting triple and reaches
    for remat only when the batch needs it;
  - training still trains under every rung (values close to the
    none-rung trajectory), composing with accum_steps.

The reference's closest relative is nothing: dl4j 0.4 frees activations
when the JVM GC feels like it; gradient checkpointing as a POLICY only
exists once the whole step is one compiled program (ARCHITECTURE.md
decision #1).
"""

import dataclasses
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    _dense_block_f32,
    forward,
    init_opt_state,
    init_params,
    loss_fn,
)
from deeplearning4j_tpu.ops import memory as memory_mod
from deeplearning4j_tpu.ops.remat import (
    ENV_REMAT,
    POLICIES,
    remat_policy,
    remat_wrap,
)


def _tiny_cfg(**kw):
    base = dict(vocab_size=61, d_model=32, n_layers=2, n_heads=4,
                d_ff=64, max_len=16, learning_rate=1e-3, seed=3)
    base.update(kw)
    return TransformerConfig(**base)


def _data(cfg, batch=4, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, cfg.vocab_size, (batch, cfg.max_len + 1))
    return (jnp.asarray(toks[:, :-1], jnp.int32),
            jnp.asarray(toks[:, 1:], jnp.int32))


# ---------------------------------------------------------------------------
# policy resolution
# ---------------------------------------------------------------------------


class TestPolicyResolution:
    def test_explicit_wins_over_env(self, monkeypatch):
        monkeypatch.setenv(ENV_REMAT, "block")
        assert remat_policy("dots") == "dots"

    def test_auto_defers_to_env(self, monkeypatch):
        monkeypatch.setenv(ENV_REMAT, "dots")
        assert remat_policy("auto") == "dots"
        monkeypatch.delenv(ENV_REMAT)
        assert remat_policy("auto") == "none"
        assert remat_policy(None) == "none"

    def test_unknown_policy_raises(self):
        with pytest.raises(ValueError, match="unknown remat policy"):
            remat_policy("blocks")
        with pytest.raises(ValueError, match="unknown remat policy"):
            forward(init_params(_tiny_cfg(remat="auto")),
                    _data(_tiny_cfg())[0],
                    _tiny_cfg(remat="typo"))

    def test_none_returns_fn_untouched(self):
        f = lambda x: x * 2
        assert remat_wrap(f, "none") is f


# ---------------------------------------------------------------------------
# values are policy-invariant
# ---------------------------------------------------------------------------


class TestRematEqualsNoRemat:
    def test_forward_bitexact_across_ladder(self):
        cfg0 = _tiny_cfg()
        params = init_params(cfg0)
        x, _ = _data(cfg0)
        ref = np.asarray(forward(params, x, cfg0)[0])
        for pol in POLICIES[1:]:
            got = np.asarray(
                forward(params, x, dataclasses.replace(cfg0, remat=pol))[0])
            assert np.array_equal(ref, got), pol

    def test_block_grads_match_f64(self):
        """One transformer block in f64 (cdt=float64 through the shared
        block body): remat grads within 1e-6 of plain grads."""
        rng = np.random.default_rng(7)
        d, f, heads = 16, 32, 4
        bp = {
            "ln1_g": jnp.ones((d,), jnp.float64),
            "ln1_b": jnp.zeros((d,), jnp.float64),
            "Wq": jnp.asarray(rng.standard_normal((d, d)) * 0.2),
            "Wk": jnp.asarray(rng.standard_normal((d, d)) * 0.2),
            "Wv": jnp.asarray(rng.standard_normal((d, d)) * 0.2),
            "Wo": jnp.asarray(rng.standard_normal((d, d)) * 0.2),
            "ln2_g": jnp.ones((d,), jnp.float64),
            "ln2_b": jnp.zeros((d,), jnp.float64),
            "W1": jnp.asarray(rng.standard_normal((d, f)) * 0.2),
            "b1": jnp.zeros((f,), jnp.float64),
            "W2": jnp.asarray(rng.standard_normal((f, d)) * 0.2),
            "b2": jnp.zeros((d,), jnp.float64),
        }
        h = jnp.asarray(rng.standard_normal((2, 8, d)))
        assert h.dtype == jnp.float64  # x64 test substrate

        def obj(bp, h, pol):
            body = remat_wrap(
                lambda bp, h: _dense_block_f32(bp, h, heads,
                                               cdt=jnp.float64), pol)
            return (body(bp, h) ** 2).sum()

        for pol in ("dots", "block"):
            ref = jax.grad(obj, argnums=(0, 1))(bp, h, "none")
            got = jax.grad(obj, argnums=(0, 1))(bp, h, pol)
            diffs = jax.tree_util.tree_map(
                lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)
                                          ).max()), ref, got)
            assert max(jax.tree_util.tree_leaves(diffs)) < 1e-6, pol

    def test_bert_mlm_grads_match_f64(self):
        """BERT MLM loss in f64 (encode has no downcasts): remat grads
        within 1e-6 + logits bit-exact across the ladder."""
        from deeplearning4j_tpu.models.bert import (
            BertConfig,
            init_params as bert_init,
            mask_tokens,
            mlm_logits,
            mlm_loss,
        )

        cfg0 = BertConfig(vocab_size=51, d_model=16, n_layers=2, n_heads=4,
                          d_ff=32, max_len=12, mask_token_id=50, seed=1)
        params = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float64), bert_init(cfg0))
        rng = np.random.default_rng(0)
        toks = rng.integers(1, 50, (3, cfg0.max_len))
        inputs, targets, weights = mask_tokens(toks, cfg0, rng)
        inputs = jnp.asarray(inputs, jnp.int32)
        targets = jnp.asarray(targets, jnp.int32)
        weights = jnp.asarray(weights, jnp.float64)

        ref_logits = np.asarray(mlm_logits(params, inputs, cfg0))
        ref_grads = jax.grad(mlm_loss)(params, inputs, targets, weights,
                                       cfg0)
        for pol in ("dots", "block"):
            cfg = dataclasses.replace(cfg0, remat=pol)
            assert np.array_equal(
                ref_logits, np.asarray(mlm_logits(params, inputs, cfg)))
            got = jax.grad(mlm_loss)(params, inputs, targets, weights, cfg)
            diffs = jax.tree_util.tree_map(
                lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)
                                          ).max()), ref_grads, got)
            assert max(jax.tree_util.tree_leaves(diffs)) < 1e-6, pol

    def test_training_runs_under_every_rung_with_accum(self):
        """The full train step (remat composing with accum_steps) takes
        real optimizer steps under every rung, and the loss trajectory
        matches the none-rung trajectory tightly."""
        losses = {}
        for pol in POLICIES:
            cfg = _tiny_cfg(remat=pol, accum_steps=2)
            lm = TransformerLM(cfg)
            x, y = _data(cfg)
            losses[pol] = [float(lm.fit(x, y)) for _ in range(3)]
        assert losses["none"][-1] < losses["none"][0]  # it trains
        for pol in POLICIES[1:]:
            np.testing.assert_allclose(losses[pol], losses["none"],
                                       rtol=1e-5)


# ---------------------------------------------------------------------------
# the memory plane
# ---------------------------------------------------------------------------


def _aot_temp_bytes(cfg, batch=8):
    import deeplearning4j_tpu.models.transformer as tfm

    p_sh = jax.eval_shape(lambda: init_params(cfg))
    o_sh = jax.eval_shape(init_opt_state, p_sh)
    toks = jax.ShapeDtypeStruct((batch, cfg.max_len), jnp.int32)
    analysis = memory_mod.analyze_jit(tfm.make_train_step(cfg), p_sh, o_sh,
                                      toks, toks)
    assert analysis is not None
    return analysis["temp_bytes"]


class TestMemoryPlane:
    def test_memory_analysis_ladder_monotone_at_L8(self):
        """The ISSUE 4 monotonicity contract: block-remat temp bytes <
        none at L=8 on the CPU substrate (dots in between) — the AOT
        ledger, not a proxy."""
        cfg0 = TransformerConfig(vocab_size=256, d_model=64, n_layers=8,
                                 n_heads=4, d_ff=256, max_len=64)
        temps = {pol: _aot_temp_bytes(dataclasses.replace(cfg0, remat=pol))
                 for pol in POLICIES}
        assert temps["block"] < temps["none"]
        assert temps["block"] <= temps["dots"] <= temps["none"]
        # the headline claim is a 2x reduction at d512 L8 (bench leg);
        # the same program family should already clear 2x here
        assert temps["none"] / temps["block"] >= 2.0

    def test_transformer_lm_measure_memory_records(self):
        cfg = _tiny_cfg()
        lm = TransformerLM(cfg)
        x, y = _data(cfg)
        analysis = lm.measure_memory(x, y)
        assert analysis is not None and analysis["temp_bytes"] > 0
        assert lm.memory_stats.snapshot()["train_step"] == analysis

    def test_container_measure_memory_records(self):
        from deeplearning4j_tpu.nn.conf import (
            DenseLayer,
            NeuralNetConfiguration,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
                .updater("sgd").list()
                .layer(0, DenseLayer(n_in=12, n_out=8, activation="tanh"))
                .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                                      loss_function="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.standard_normal((16, 12)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        before = dict(net.dispatch_stats.traces)
        analysis = net.measure_memory(x, y)
        assert analysis is not None and analysis["temp_bytes"] > 0
        assert "train_step" in net.memory_stats.snapshot()
        # AOT lowering must not read as a phantom retrace
        assert dict(net.dispatch_stats.traces) == before

    def test_bert_measure_memory_records(self):
        from deeplearning4j_tpu.models.bert import BertConfig, BertMLM

        cfg = BertConfig(vocab_size=31, d_model=16, n_layers=2, n_heads=4,
                         d_ff=32, max_len=8, mask_token_id=30)
        mlm = BertMLM(cfg)
        toks = np.random.default_rng(0).integers(1, 30, (4, cfg.max_len))
        from deeplearning4j_tpu.models.bert import mask_tokens

        inputs, targets, weights = mask_tokens(
            toks, cfg, np.random.default_rng(1))
        analysis = mlm.measure_memory(inputs, targets, weights)
        assert analysis is not None and analysis["temp_bytes"] > 0
        assert "train_step" in mlm.memory_stats.snapshot()


class TestAutoFit:
    def test_prefers_cheapest_fitting_triple(self):
        """With room to spare the sizer must NOT reach for remat or
        accum (both cost recompute/serialization)."""
        cfg = _tiny_cfg()
        choice = memory_mod.auto_fit_transformer(
            cfg, batches=(8, 4), accum_steps=(1, 2), hbm_gb=16.0)
        assert choice == {"batch": 8, "accum_steps": 1, "remat": "none",
                          "report": choice["report"]}

    def test_reaches_for_remat_when_batch_needs_it(self):
        """Shrink the budget until b8 only fits rematted: the sizer must
        keep the larger batch by climbing the ladder, not shrink the
        batch."""
        cfg = TransformerConfig(vocab_size=1024, d_model=512, n_layers=8,
                                n_heads=8, d_ff=2048, max_len=1024,
                                dtype_policy="performance")
        fits_none = memory_mod.transformer_preflight(
            cfg, 64, remat="none", hbm_gb=4.0)[0]
        fits_block, rep = memory_mod.transformer_preflight(
            cfg, 64, remat="block", hbm_gb=4.0)
        assert not fits_none and fits_block
        choice = memory_mod.auto_fit_transformer(
            cfg, batches=(64, 32), accum_steps=(1,), hbm_gb=4.0)
        assert choice["batch"] == 64
        assert choice["remat"] in ("dots", "block")
        assert rep["remat"] == "block"

    def test_nothing_fits_returns_none(self):
        cfg = _tiny_cfg()
        assert memory_mod.auto_fit_transformer(
            cfg, batches=(4,), accum_steps=(1,), hbm_gb=1e-6) is None

    def test_batch_not_divisible_by_accum_raises(self):
        with pytest.raises(ValueError, match="not divisible"):
            memory_mod.transformer_preflight(_tiny_cfg(), 6, accum_steps=4)

    def test_hbm_env_knob(self, monkeypatch):
        monkeypatch.setenv(memory_mod.ENV_HBM, "7.5")
        assert memory_mod.hbm_budget_gb() == 7.5
        _, rep = memory_mod.transformer_preflight(_tiny_cfg(), 4)
        assert rep["hbm_gb"] == 7.5


class TestPerLayerUnification:
    def test_env_knob_drives_container_remat(self, monkeypatch):
        """DL4J_TPU_REMAT switches the containers' per-layer remat on
        without the conf flag, and values stay identical (the
        gradient_checkpointing invariance contract, now via the env
        ladder)."""
        from deeplearning4j_tpu.nn.conf import (
            DenseLayer,
            NeuralNetConfiguration,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        def build():
            conf = (NeuralNetConfiguration.builder().seed(9)
                    .learning_rate(0.1).updater("sgd").list()
                    .layer(0, DenseLayer(n_in=6, n_out=5,
                                         activation="tanh"))
                    .layer(1, OutputLayer(n_in=5, n_out=2,
                                          activation="softmax",
                                          loss_function="mcxent"))
                    .build())
            return MultiLayerNetwork(conf).init()

        rng = np.random.default_rng(2)
        x = rng.standard_normal((8, 6)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]

        monkeypatch.delenv(ENV_REMAT, raising=False)
        plain = build()
        l_plain = [float(plain.fit(x, y)) for _ in range(2)]
        for pol in ("dots", "block"):
            monkeypatch.setenv(ENV_REMAT, pol)
            net = build()
            l_remat = [float(net.fit(x, y)) for _ in range(2)]
            np.testing.assert_allclose(l_remat, l_plain, rtol=1e-6)
