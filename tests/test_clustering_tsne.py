"""Clustering + spatial tree + t-SNE tests — mirrors the reference's
clustering tests (KMeansTest, KDTreeTest, VPTreeTest, QuadTreeTest,
SpTreeTest) and plot tests (TsneTest, BarnesHutTsneTest: KL decreases,
clusters separate)."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    KDTree,
    KMeansClustering,
    Point,
    QuadTree,
    SPTree,
    VPTree,
)
from deeplearning4j_tpu.plot import BarnesHutTsne, Tsne


def blobs(n_per=30, centers=((0, 0), (10, 10), (-10, 10)), d=2, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    xs, ys = [], []
    for ci, c in enumerate(centers):
        pts = rng.normal(0, scale, (n_per, d)) + np.asarray(c)[None, :d]
        xs.append(pts)
        ys.extend([ci] * n_per)
    return np.concatenate(xs).astype(np.float32), np.array(ys)


class TestKMeans:
    def test_recovers_blobs(self):
        x, y = blobs()
        km = KMeansClustering.setup(3, 50, "euclidean", seed=1)
        cs = km.apply_to(x)
        assert len(cs) == 3
        # each cluster should be label-pure
        for c in cs.clusters:
            labels = [y[int(p.point_id)] for p in c.points]
            assert len(set(labels)) == 1
        assert km.iterations_run <= 50

    def test_point_objects_and_predict(self):
        x, _ = blobs(n_per=10)
        pts = [Point(row, point_id=str(i)) for i, row in enumerate(x)]
        km = KMeansClustering(3, 30, seed=2)
        km.apply_to(pts)
        pred = km.predict(x[:5])
        assert pred.shape == (5,)

    def test_cosine_distance(self):
        x, _ = blobs(n_per=10)
        km = KMeansClustering(3, 20, distance="cosine", seed=0)
        cs = km.apply_to(np.abs(x) + 0.1)
        assert len(cs) == 3


class TestKDTree:
    def test_knn_matches_bruteforce(self):
        rng = np.random.default_rng(3)
        pts = rng.normal(size=(200, 5))
        tree = KDTree.build(pts)
        q = rng.normal(size=(5,))
        res = tree.knn(q, 7)
        brute = np.argsort(np.linalg.norm(pts - q, axis=1))[:7]
        assert [i for _, i in res] == list(brute)

    def test_insert_and_nn(self):
        tree = KDTree(2)
        for i, p in enumerate([(0, 0), (5, 5), (1, 1), (9, 0)]):
            tree.insert(np.array(p, float), i)
        d, i = tree.nn(np.array([1.2, 1.1]))
        assert i == 2

    def test_range_query(self):
        pts = np.array([[0, 0], [1, 1], [2, 2], [5, 5]], float)
        tree = KDTree.build(pts)
        inside = tree.range([0.5, 0.5], [2.5, 2.5])
        assert sorted(inside) == [1, 2]


class TestVPTree:
    def test_knn_matches_bruteforce(self):
        rng = np.random.default_rng(4)
        pts = rng.normal(size=(150, 8))
        tree = VPTree(pts)
        q = rng.normal(size=(8,))
        res = tree.knn(q, 5)
        brute = np.argsort(np.linalg.norm(pts - q, axis=1))[:5]
        assert [i for _, i in res] == list(brute)

    def test_cosine_neighbors(self):
        pts = np.array([[1, 0], [0.9, 0.1], [0, 1], [-1, 0]], float)
        tree = VPTree(pts, distance="cosine")
        near = tree.words_nearest(np.array([1.0, 0.05]), 2)
        assert set(near) == {0, 1}


class TestSpatialTrees:
    def test_sptree_com_and_count(self):
        pts = np.array([[0, 0], [2, 0], [0, 2], [2, 2]], float)
        tree = SPTree.build(pts)
        assert tree.cum_size == 4
        np.testing.assert_allclose(tree.center_of_mass, [1, 1])

    def test_sptree_duplicate_points_no_recursion(self):
        pts = np.array([[1.0, 1.0]] * 10)
        tree = SPTree.build(pts)  # must not infinitely subdivide
        assert tree.cum_size == 10

    def test_bh_force_approximates_exact(self):
        rng = np.random.default_rng(5)
        pts = rng.normal(size=(100, 2))
        tree = SPTree.build(pts)
        q = pts[0]
        # exact repulsive force
        diff = q - pts[1:]
        d2 = np.sum(diff * diff, axis=1)
        qk = 1.0 / (1.0 + d2)
        exact_f = np.sum((qk * qk)[:, None] * diff, axis=0)
        exact_sq = qk.sum()
        f = np.zeros(2)
        sq = tree.compute_non_edge_forces(q, 0.3, f)
        np.testing.assert_allclose(f, exact_f, rtol=0.1, atol=1e-3)
        assert abs(sq - exact_sq) / exact_sq < 0.1

    def test_quadtree_is_2d(self):
        pts = np.random.default_rng(0).normal(size=(20, 2))
        tree = QuadTree.build(pts)
        assert tree.cum_size == 20
        with pytest.raises(AssertionError):
            QuadTree.build(np.zeros((5, 3)))


class TestTsne:
    def test_exact_tsne_separates_blobs_and_kl_decreases(self):
        x, y = blobs(n_per=25, d=8, centers=((0,) * 8, (8,) * 8, (-8, 8) * 4),
                     seed=1)
        ts = Tsne(perplexity=10, max_iter=300, learning_rate=100, seed=0)
        Y = ts.fit_transform(x)
        assert Y.shape == (75, 2)
        assert ts.kl_history[-1] < ts.kl_history[0]
        # cluster separation: mean intra-class dist < mean inter-class dist
        intra, inter = [], []
        for i in range(0, 75, 5):
            for j in range(0, 75, 7):
                d = np.linalg.norm(Y[i] - Y[j])
                (intra if y[i] == y[j] else inter).append(d)
        assert np.mean(intra) < np.mean(inter)

    def test_barnes_hut_tsne(self):
        x, y = blobs(n_per=20, d=5, centers=((0,) * 5, (10,) * 5), seed=2)
        ts = BarnesHutTsne(theta=0.5, perplexity=8, max_iter=150,
                           learning_rate=100, seed=0)
        Y = ts.fit_transform(x)
        assert Y.shape == (40, 2)
        assert np.isfinite(Y).all()
        intra, inter = [], []
        for i in range(40):
            for j in range(i + 1, 40):
                d = np.linalg.norm(Y[i] - Y[j])
                (intra if y[i] == y[j] else inter).append(d)
        assert np.mean(intra) < np.mean(inter)
