"""Pipelined TRAINING == serial training (loss curves, params, updater).

The GPipe schedule (parallel/pipeline_parallel.py) composed with loss +
Adam into one jitted step must take numerically the SAME optimizer steps
as the serial make_train_step on the same batches — the framework's
distributed==serial convention (the reference's
TestCompareParameterAveragingSparkVsSingleMachine.java idea) applied to
the pipeline axis the reference never had (SURVEY.md section 2.7).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    init_opt_state,
    init_params,
    make_pipeline_train_step,
    make_train_step,
    shard_params_pipeline,
)


def _cfg(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_layers", 4)
    kw.setdefault("n_heads", 4)
    kw.setdefault("d_ff", 64)
    kw.setdefault("max_len", 16)
    kw.setdefault("learning_rate", 1e-3)
    kw.setdefault("use_flash", False)
    return TransformerConfig(**kw)


def _batches(cfg, n=8, k=5, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, cfg.vocab_size, (k, n, cfg.max_len + 1))
    return (jnp.asarray(toks[:, :, :-1], jnp.int32),
            jnp.asarray(toks[:, :, 1:], jnp.int32))


def _run_curve(step, params, opt, xs, ys):
    losses = []
    for i in range(xs.shape[0]):
        params, opt, loss = step(params, opt, xs[i], ys[i])
        losses.append(float(loss))
    return params, opt, losses


class TestPipelineTrainStep:
    def test_pp_train_matches_serial_curve(self):
        cfg = _cfg()
        xs, ys = _batches(cfg)
        params = init_params(cfg)

        serial = make_train_step(cfg)
        p_s, o_s, curve_s = _run_curve(serial, params, init_opt_state(params),
                                       xs, ys)

        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        pp_step = make_pipeline_train_step(cfg, mesh, n_micro=4)
        p_p = shard_params_pipeline(params, cfg, mesh)
        p_p, o_p, curve_p = _run_curve(pp_step, p_p, init_opt_state(p_p),
                                       xs, ys)

        np.testing.assert_allclose(curve_p, curve_s, rtol=1e-4,
                                   err_msg="PP loss curve != serial")
        # end-state params must match too (same optimizer trajectory)
        np.testing.assert_allclose(
            np.asarray(p_p["blocks"]["Wq"]), np.asarray(p_s["blocks"]["Wq"]),
            atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(p_p["embed"]), np.asarray(p_s["embed"]), atol=1e-5)
        assert int(o_p["t"]) == int(o_s["t"]) == xs.shape[0]

    def test_ppxdp_train_matches_serial_curve(self):
        cfg = _cfg()
        xs, ys = _batches(cfg)
        params = init_params(cfg)

        serial = make_train_step(cfg)
        _, _, curve_s = _run_curve(serial, params, init_opt_state(params),
                                   xs, ys)

        mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2),
                    ("pipe", "data"))
        pp_step = make_pipeline_train_step(cfg, mesh, n_micro=4,
                                           data_axis="data")
        p_p = shard_params_pipeline(params, cfg, mesh)
        _, _, curve_p = _run_curve(pp_step, p_p, init_opt_state(p_p), xs, ys)
        np.testing.assert_allclose(curve_p, curve_s, rtol=1e-4,
                                   err_msg="PPxDP loss curve != serial")

    def test_pp_moe_forward_matches_serial_logits(self):
        """PP x MoE (round-4: the former dense-only rejection): in the
        drop-free regime (capacity_factor = n_experts) per-group routing
        picks the same experts as batch routing, so pipelined logits are
        the serial forward's logits."""
        from deeplearning4j_tpu.models.transformer import (
            forward,
            pipeline_forward,
        )

        cfg = _cfg(moe_experts=4, d_ff=32, moe_capacity_factor=4.0)
        params = init_params(cfg)
        rng = np.random.default_rng(2)
        toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, cfg.max_len)),
                           jnp.int32)
        ref, _ = forward(params, toks, cfg)
        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        pp = pipeline_forward(shard_params_pipeline(params, cfg, mesh),
                              toks, cfg, mesh, n_micro=4)
        np.testing.assert_allclose(np.asarray(pp), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_pp_moe_train_matches_serial_at_one_group(self):
        """n_micro=1: one group == the whole batch, so the grouped MoE
        objective IS the serial objective — curves must match exactly
        (the plumbing still hops every stage through the ppermute ring)."""
        cfg = _cfg(moe_experts=4, d_ff=32, moe_capacity_factor=4.0)
        xs, ys = _batches(cfg)
        params = init_params(cfg)

        serial = make_train_step(cfg)
        _, _, curve_s = _run_curve(serial, params, init_opt_state(params),
                                   xs, ys)

        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        pp_step = make_pipeline_train_step(cfg, mesh, n_micro=1)
        p_p = shard_params_pipeline(params, cfg, mesh)
        _, _, curve_p = _run_curve(pp_step, p_p, init_opt_state(p_p), xs, ys)
        np.testing.assert_allclose(curve_p, curve_s, rtol=1e-4,
                                   err_msg="PP MoE (1 group) != serial")

    def test_pp_moe_train_grouped_objective_close(self):
        """n_micro>1: the aux term is computed per group (GShard/Switch
        semantics), so the curve tracks serial closely but not bit-wise —
        the NLL part is identical (drop-free), only the 1e-2-weighted
        load-balance statistics regroup."""
        cfg = _cfg(moe_experts=4, d_ff=32, moe_capacity_factor=4.0)
        xs, ys = _batches(cfg)
        params = init_params(cfg)

        serial = make_train_step(cfg)
        _, _, curve_s = _run_curve(serial, params, init_opt_state(params),
                                   xs, ys)

        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        pp_step = make_pipeline_train_step(cfg, mesh, n_micro=4)
        p_p = shard_params_pipeline(params, cfg, mesh)
        _, _, curve_p = _run_curve(pp_step, p_p, init_opt_state(p_p), xs, ys)
        np.testing.assert_allclose(curve_p, curve_s, rtol=2e-2,
                                   err_msg="PP MoE grouped curve diverged")

    def test_bf16_policy_trains_close_to_serial(self):
        """dtype_policy='performance' carries the residual stream through
        the GPipe ppermutes in bf16; tolerance bar vs the serial bf16
        path (rounding orders differ)."""
        cfg = _cfg(dtype_policy="performance", learning_rate=1e-2)
        xs, ys = _batches(cfg)
        params = init_params(cfg)

        serial = make_train_step(cfg)
        _, _, curve_s = _run_curve(serial, params, init_opt_state(params),
                                   xs, ys)
        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        pp_step = make_pipeline_train_step(cfg, mesh, n_micro=4)
        p_p = shard_params_pipeline(params, cfg, mesh)
        _, _, curve_p = _run_curve(pp_step, p_p, init_opt_state(p_p), xs, ys)
        np.testing.assert_allclose(curve_p, curve_s, rtol=5e-2)
        assert all(np.isfinite(curve_p))


class TestTransformerLMPipelineMode:
    def test_lm_on_pipe_mesh_trains_and_matches_serial(self):
        cfg = _cfg(pipeline_microbatches=4)
        xs, ys = _batches(cfg, k=3)

        serial = TransformerLM(cfg)
        curve_s = [float(serial.fit(xs[i], ys[i])) for i in range(3)]

        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        lm = TransformerLM(cfg, mesh=mesh)
        curve_p = [float(lm.fit(xs[i], ys[i])) for i in range(3)]
        np.testing.assert_allclose(curve_p, curve_s, rtol=1e-4)
        assert lm.iteration == 3

        # blocks live depth-sharded over 'pipe'
        spec = lm.params["blocks"]["Wq"].sharding.spec
        assert spec[0] == "pipe"

    def test_sharded_dir_restore_with_pipe_mesh(self, tmp_path):
        # directory (orbax) checkpoints must restore straight into the
        # depth-sharded pipeline layout, not crash on Megatron specs
        from deeplearning4j_tpu.utils.sharded_checkpoint import (
            restore_lm,
            save_lm,
        )

        cfg = _cfg(pipeline_microbatches=4)
        xs, ys = _batches(cfg, k=1)
        lm = TransformerLM(cfg)
        lm.fit(xs[0], ys[0])
        save_lm(str(tmp_path / "ckpt"), lm)

        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        lm2 = restore_lm(str(tmp_path / "ckpt"), mesh=mesh)
        assert lm2.params["blocks"]["Wq"].sharding.spec[0] == "pipe"
        assert lm2.iteration == 1
        loss = float(lm2.fit(xs[0], ys[0]))
        assert np.isfinite(loss)

    def test_lm_pipe_fit_batches_fused(self):
        cfg = _cfg(pipeline_microbatches=4)
        xs, ys = _batches(cfg, k=4)

        serial = TransformerLM(cfg)
        curve_s = [float(serial.fit(xs[i], ys[i])) for i in range(4)]

        mesh = Mesh(np.array(jax.devices()[:4]), ("pipe",))
        lm = TransformerLM(cfg, mesh=mesh)
        losses = lm.fit_batches(xs, ys)
        np.testing.assert_allclose(np.asarray(losses), curve_s, rtol=1e-4)
        assert lm.iteration == 4
