"""Tensor / pipeline / expert parallelism == serial equivalence.

Mirrors the reference's distributed-without-a-cluster strategy (SURVEY.md
section 4, TestCompareParameterAveragingSparkVsSingleMachine.java:115-262:
exact equality of the distributed and single-machine paths) for the three
parallelism modes the reference never had (SURVEY.md section 2.7): each mode
must reproduce the single-device math on the virtual 8-device CPU mesh, and
its gradients must match the serial gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import (
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPELINE_AXIS,
    device_mesh,
)

jtu = jax.tree_util


def _mesh(axis, n=4):
    return device_mesh(num_devices=n, axis_names=(axis,))


# ---------------------------------------------------------------------------
# Tensor parallelism
# ---------------------------------------------------------------------------


class TestTensorParallel:
    def _setup(self):
        from deeplearning4j_tpu.parallel.tensor_parallel import (
            init_tp_block_params,
        )

        key = jax.random.PRNGKey(0)
        params = init_tp_block_params(key, d_model=32, d_ff=64, num_heads=4)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 32))
        return params, x

    def test_block_matches_serial(self):
        from deeplearning4j_tpu.parallel.tensor_parallel import (
            tp_block_apply,
            tp_block_reference,
        )

        params, x = self._setup()
        mesh = _mesh(MODEL_AXIS)
        y_tp = tp_block_apply(params, x, mesh, num_heads=4, causal=True)
        y_ref = tp_block_reference(params, x, num_heads=4, causal=True)
        np.testing.assert_allclose(np.asarray(y_tp), np.asarray(y_ref),
                                   atol=1e-5)

    def test_gradients_match_serial(self):
        from deeplearning4j_tpu.parallel.tensor_parallel import (
            tp_block_apply,
            tp_block_reference,
        )

        params, x = self._setup()
        mesh = _mesh(MODEL_AXIS)

        def loss_tp(p):
            return jnp.sum(tp_block_apply(p, x, mesh, num_heads=4) ** 2)

        def loss_ref(p):
            return jnp.sum(tp_block_reference(p, x, num_heads=4) ** 2)

        g_tp = jax.grad(loss_tp)(params)
        g_ref = jax.grad(loss_ref)(params)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(g_tp[k]), np.asarray(g_ref[k]), atol=1e-3,
                err_msg=f"grad mismatch for {k}",
            )

    def test_sharded_placement(self):
        """shard_tp_params actually splits the big matrices over the axis."""
        from deeplearning4j_tpu.parallel.tensor_parallel import (
            shard_tp_params,
        )

        params, _ = self._setup()
        mesh = _mesh(MODEL_AXIS)
        sp = shard_tp_params(params, mesh)
        shard = sp["W1"].addressable_shards[0]
        assert shard.data.shape == (32, 64 // 4)

    def test_column_row_dense_roundtrip(self):
        from deeplearning4j_tpu.parallel.tensor_parallel import (
            column_parallel_dense,
            row_parallel_dense,
        )

        mesh = _mesh(MODEL_AXIS)
        key = jax.random.PRNGKey(2)
        k1, k2, k3 = jax.random.split(key, 3)
        W1 = jax.random.normal(k1, (16, 32))
        b1 = jnp.zeros((32,))
        W2 = jax.random.normal(k2, (32, 16))
        b2 = jnp.zeros((16,))
        x = jax.random.normal(k3, (4, 16))
        h = column_parallel_dense(W1, b1, x, mesh, gather=False)
        y = row_parallel_dense(W2, b2, h, mesh)
        y_ref = (x @ W1 + b1) @ W2 + b2
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)

    def test_heads_not_divisible_raises(self):
        from deeplearning4j_tpu.parallel.tensor_parallel import tp_block_apply

        params, x = self._setup()
        mesh = _mesh(MODEL_AXIS, n=8)
        with pytest.raises(ValueError):
            tp_block_apply(params, x, mesh, num_heads=4)  # 4 heads, 8 devices


# ---------------------------------------------------------------------------
# Pipeline parallelism
# ---------------------------------------------------------------------------


def _mlp_stage(p, x):
    return jnp.tanh(x @ p["W"] + p["b"])


class TestPipelineParallel:
    def _setup(self, n_stages=4, width=16):
        key = jax.random.PRNGKey(0)
        k1, k2, k3 = jax.random.split(key, 3)
        params = {
            "W": jax.random.normal(k1, (n_stages, width, width)) * 0.3,
            "b": jax.random.normal(k2, (n_stages, width)) * 0.1,
        }
        x = jax.random.normal(k3, (8, width))
        return params, x

    def test_matches_serial(self):
        from deeplearning4j_tpu.parallel.pipeline_parallel import (
            pipeline_apply,
            pipeline_reference,
        )

        params, x = self._setup()
        mesh = _mesh(PIPELINE_AXIS)
        y = pipeline_apply(params, x, mesh, stage_fn=_mlp_stage, n_micro=4)
        y_ref = pipeline_reference(params, x, stage_fn=_mlp_stage, n_stages=4)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)

    def test_micro_not_dividing_batch_raises(self):
        from deeplearning4j_tpu.parallel.pipeline_parallel import (
            pipeline_apply,
        )

        params, x = self._setup()
        mesh = _mesh(PIPELINE_AXIS)
        with pytest.raises(ValueError):
            pipeline_apply(params, x, mesh, stage_fn=_mlp_stage, n_micro=3)

    def test_gradients_match_serial(self):
        from deeplearning4j_tpu.parallel.pipeline_parallel import (
            pipeline_apply,
            pipeline_reference,
        )

        params, x = self._setup()
        mesh = _mesh(PIPELINE_AXIS)

        def loss_pp(p):
            return jnp.sum(
                pipeline_apply(p, x, mesh, stage_fn=_mlp_stage, n_micro=4) ** 2
            )

        def loss_ref(p):
            return jnp.sum(
                pipeline_reference(p, x, stage_fn=_mlp_stage, n_stages=4) ** 2
            )

        g_pp = jax.grad(loss_pp)(params)
        g_ref = jax.grad(loss_ref)(params)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(g_pp[k]), np.asarray(g_ref[k]), atol=1e-4,
                err_msg=f"grad mismatch for {k}",
            )

    def test_more_micro_than_stages(self):
        from deeplearning4j_tpu.parallel.pipeline_parallel import (
            pipeline_apply,
            pipeline_reference,
        )

        params, x = self._setup()
        mesh = _mesh(PIPELINE_AXIS)
        y = pipeline_apply(params, x, mesh, stage_fn=_mlp_stage, n_micro=8)
        y_ref = pipeline_reference(params, x, stage_fn=_mlp_stage, n_stages=4)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)

    def test_param_placement(self):
        from deeplearning4j_tpu.parallel.pipeline_parallel import (
            shard_pipeline_params,
        )

        params, _ = self._setup()
        mesh = _mesh(PIPELINE_AXIS)
        sp = shard_pipeline_params(params, mesh)
        assert sp["W"].addressable_shards[0].data.shape == (1, 16, 16)

    def test_pp_x_dp_composition(self):
        """2-D (pipe, data) mesh: microbatches sharded over 'data' while
        stages pipeline over 'pipe' — result must equal serial."""
        from deeplearning4j_tpu.parallel.pipeline_parallel import (
            pipeline_apply,
            pipeline_reference,
        )

        params, x = self._setup()
        mesh = device_mesh(shape=(4, 2), axis_names=(PIPELINE_AXIS, "data"))
        y = pipeline_apply(params, x, mesh, stage_fn=_mlp_stage, n_micro=4,
                           data_axis="data")
        y_ref = pipeline_reference(params, x, stage_fn=_mlp_stage, n_stages=4)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   atol=1e-5)

    def test_pp_x_dp_gradients(self):
        from deeplearning4j_tpu.parallel.pipeline_parallel import (
            pipeline_apply,
            pipeline_reference,
        )

        params, x = self._setup()
        mesh = device_mesh(shape=(4, 2), axis_names=(PIPELINE_AXIS, "data"))

        def loss_pp(p):
            return jnp.sum(pipeline_apply(
                p, x, mesh, stage_fn=_mlp_stage, n_micro=4,
                data_axis="data") ** 2)

        def loss_ref(p):
            return jnp.sum(pipeline_reference(
                p, x, stage_fn=_mlp_stage, n_stages=4) ** 2)

        g_pp = jax.grad(loss_pp)(params)
        g_ref = jax.grad(loss_ref)(params)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(g_pp[k]), np.asarray(g_ref[k]), atol=1e-4,
                err_msg=f"grad mismatch for {k}")


# ---------------------------------------------------------------------------
# Expert parallelism
# ---------------------------------------------------------------------------


class TestExpertParallel:
    def _setup(self, n_experts=8):
        from deeplearning4j_tpu.parallel.expert_parallel import (
            init_moe_params,
        )

        params = init_moe_params(jax.random.PRNGKey(0), d_model=16, d_ff=32,
                                 n_experts=n_experts)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 12, 16))
        return params, x

    def test_matches_serial(self):
        from deeplearning4j_tpu.parallel.expert_parallel import (
            moe_apply,
            moe_reference,
        )

        params, x = self._setup()
        mesh = _mesh(EXPERT_AXIS)
        y = moe_apply(params, x, mesh, top_k=2)
        y_ref = moe_reference(params, x, top_k=2)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)

    def test_gradients_match_serial(self):
        from deeplearning4j_tpu.parallel.expert_parallel import (
            moe_apply,
            moe_reference,
        )

        params, x = self._setup()
        mesh = _mesh(EXPERT_AXIS)

        def loss_ep(p):
            return jnp.sum(moe_apply(p, x, mesh, top_k=2) ** 2)

        def loss_ref(p):
            return jnp.sum(moe_reference(p, x, top_k=2) ** 2)

        g_ep = jax.grad(loss_ep)(params)
        g_ref = jax.grad(loss_ref)(params)
        for k in params:
            np.testing.assert_allclose(
                np.asarray(g_ep[k]), np.asarray(g_ref[k]), atol=1e-4,
                err_msg=f"grad mismatch for {k}",
            )

    def test_top1_routing(self):
        from deeplearning4j_tpu.parallel.expert_parallel import (
            moe_apply,
            moe_reference,
        )

        params, x = self._setup()
        mesh = _mesh(EXPERT_AXIS)
        y = moe_apply(params, x, mesh, top_k=1)
        y_ref = moe_reference(params, x, top_k=1)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)

    def test_capacity_drops_tokens(self):
        """With a tiny capacity, some tokens get zero expert output (the
        residual carries them) — but nothing crashes and shapes hold."""
        from deeplearning4j_tpu.parallel.expert_parallel import moe_reference

        params, x = self._setup()
        y = moe_reference(params, x, top_k=1, capacity_factor=0.1)
        assert y.shape == x.shape
        # at least one token must have been dropped (zero row)
        flat = np.asarray(y).reshape(-1, y.shape[-1])
        assert (np.abs(flat).sum(-1) == 0).any()

    def test_load_balancing_loss_positive(self):
        from deeplearning4j_tpu.parallel.expert_parallel import (
            load_balancing_loss,
        )

        params, x = self._setup()
        aux = load_balancing_loss(x, params["Wg"])
        # E * sum f_e P_e >= 1 (equality at perfect balance)
        assert float(aux) >= 1.0 - 1e-6

    def test_experts_not_divisible_raises(self):
        from deeplearning4j_tpu.parallel.expert_parallel import moe_apply

        params, x = self._setup(n_experts=6)
        mesh = _mesh(EXPERT_AXIS)
        with pytest.raises(ValueError):
            moe_apply(params, x, mesh)

    def test_expert_param_placement(self):
        from deeplearning4j_tpu.parallel.expert_parallel import (
            shard_moe_params,
        )

        params, _ = self._setup()
        mesh = _mesh(EXPERT_AXIS)
        sp = shard_moe_params(params, mesh)
        assert sp["W1"].addressable_shards[0].data.shape == (2, 16, 32)
