"""InputPipeline (deeplearning4j_tpu/etl/pipeline.py): the overlapped
input-staging runtime's equivalence, telemetry, and resilience contracts.

Headline (ISSUE 5): the pipeline is BYTE-identical to direct iteration —
same reader through ``InputPipeline`` vs the serial
``RecordReaderDataSetIterator`` path, at ANY worker count (the reorder
buffer restores stream order no matter which worker finishes first) —
and training through it produces byte-identical params. Kill-at-step-k +
resume through the pipeline is bit-exact (the delivered-batch cursor
composes with ``ResilientTrainer``). Satellites: ``DL4J_TPU_PREFETCH``
on ``AsyncDataSetIterator``, ``DL4J_TPU_PIPELINE_WORKERS`` adoption in
``fit_iterator``, multi-process shard selection, the native feeder
source, and ``pipeline_stats`` accounting.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.datasets.records import (
    CollectionRecordReader,
    RecordReaderDataSetIterator,
)
from deeplearning4j_tpu.etl import (
    InputPipeline,
    NormalizerStandardize,
    Schema,
    TransformProcess,
    maybe_wrap,
)
from deeplearning4j_tpu.etl.pipeline import WORKERS_ENV, assemble_batch
from deeplearning4j_tpu.etl.transforms import TransformProcessRecordReader
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

_RNG = np.random.default_rng(0)
N, F, C = 210, 6, 3
RECORDS = [
    [f"{v:.5f}" for v in _RNG.standard_normal(F)]
    + [str(int(_RNG.integers(0, C)))]
    for _ in range(N)
]
X = _RNG.standard_normal((96, F)).astype(np.float32)
Y = np.eye(C, dtype=np.float32)[_RNG.integers(0, C, 96)]


def schema() -> Schema:
    return (Schema.builder()
            .add_numeric_column(*[f"x{i}" for i in range(F)])
            .add_integer_column("label").build())


def transform() -> TransformProcess:
    return (TransformProcess(schema())
            .math_op("x0", "mul", 2.0)
            .condition_filter("x1", "gt", 1.5)
            .rolling_window("x2", 4, "mean"))


def ds_bytes(ds):
    parts = [np.asarray(ds.features).tobytes(),
             np.asarray(ds.labels).tobytes()]
    if ds.features_mask is not None:
        parts.append(np.asarray(ds.features_mask).tobytes())
    return b"".join(parts)


def build_net() -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
            .updater("adam").list()
            .layer(0, DenseLayer(n_in=F, n_out=8, activation="tanh"))
            .layer(1, OutputLayer(n_in=8, n_out=C, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf)


def params_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


class TestReaderModeEquivalence:
    def serial_batches(self, tp):
        li = tp.final_schema().index_of("label") if tp else F
        return list(RecordReaderDataSetIterator(
            TransformProcessRecordReader(CollectionRecordReader(RECORDS), tp)
            if tp else CollectionRecordReader(RECORDS),
            batch_size=32, label_index=li, num_possible_labels=C))

    @pytest.mark.parametrize("workers", [1, 4])
    def test_byte_identical_with_transforms(self, workers):
        tp = transform()
        ref = self.serial_batches(tp)
        pipe = InputPipeline.from_reader(
            CollectionRecordReader(RECORDS), 32,
            label_index=tp.final_schema().index_of("label"),
            num_possible_labels=C, transform=tp,
            workers=workers, prefetch=3, device_put=False)
        got = list(pipe)
        assert len(got) == len(ref)
        for a, b in zip(ref, got):
            assert ds_bytes(a) == ds_bytes(b)
        # and a SECOND pass is identical too (fresh stateful transforms)
        got2 = list(pipe)
        assert [ds_bytes(d) for d in got2] == [ds_bytes(d) for d in ref]

    def test_byte_identical_no_transform_and_device_put(self):
        ref = self.serial_batches(None)
        pipe = InputPipeline.from_reader(
            CollectionRecordReader(RECORDS), 32, label_index=F,
            num_possible_labels=C, workers=2, device_put=True)
        got = list(pipe)
        assert [ds_bytes(d) for d in got] == [ds_bytes(d) for d in ref]

    def test_vectorized_assembly_matches_per_record(self):
        """The fast path (one C-level float64 parse of the chunk) is
        byte-identical to RecordReaderDataSetIterator's per-record
        float() loop — the property that makes the bench win honest."""
        recs = RECORDS[:40]
        for kw in ({"label_index": F, "num_possible_labels": C},
                   {"label_index": 0, "regression": True,
                    "num_possible_labels": -1},
                   {"label_index": 1, "label_index_to": 2,
                    "num_possible_labels": -1},
                   {"label_index": None, "num_possible_labels": -1}):
            fast = assemble_batch(recs, kw.get("label_index"),
                                  kw.get("num_possible_labels", -1),
                                  kw.get("regression", False),
                                  kw.get("label_index_to"))
            it = RecordReaderDataSetIterator(
                CollectionRecordReader(recs), batch_size=40, **kw)
            (ref,) = list(it)
            assert ds_bytes(fast) == ds_bytes(ref)

    def test_reader_error_propagates_to_consumer(self):
        bad = [["1", "2"], ["3"]]  # ragged -> assembly falls back, then
        # _split explodes on the short record
        pipe = InputPipeline.from_reader(
            CollectionRecordReader(bad), 2, label_index=1,
            regression=True, workers=2, device_put=False)
        with pytest.raises(Exception):
            list(pipe)


class TestWrapModeAndAdoption:
    def test_wrapped_iterator_byte_identical(self):
        ref = list(ListDataSetIterator(X, Y, 16))
        pipe = InputPipeline(ListDataSetIterator(X, Y, 16), workers=3,
                             device_put=False)
        got = list(pipe)
        assert [ds_bytes(d) for d in got] == [ds_bytes(d) for d in ref]

    def test_training_through_pipeline_bit_exact(self):
        plain = build_net()
        plain.fit_iterator(ListDataSetIterator(X, Y, 16), num_epochs=2)
        piped = build_net()
        piped.fit_iterator(
            InputPipeline(ListDataSetIterator(X, Y, 16), workers=4,
                          prefetch=2),
            num_epochs=2)
        assert params_equal(plain.params, piped.params)
        assert piped.pipeline_stats is not None
        snap = piped.pipeline_stats.snapshot()
        assert snap["batches"] == 12 and snap["epochs"] == 2

    def test_env_adoption_wraps_and_preserves_params(self, monkeypatch):
        plain = build_net()
        plain.fit_iterator(ListDataSetIterator(X, Y, 16), num_epochs=1)
        monkeypatch.setenv(WORKERS_ENV, "2")
        adopted = build_net()
        adopted.fit_iterator(ListDataSetIterator(X, Y, 16), num_epochs=1)
        assert params_equal(plain.params, adopted.params)
        assert adopted.pipeline_stats is not None
        assert adopted.pipeline_stats.workers == 2

    def test_maybe_wrap_identity_by_default(self, monkeypatch):
        monkeypatch.delenv(WORKERS_ENV, raising=False)
        it = ListDataSetIterator(X, Y, 16)
        assert maybe_wrap(it) is it
        monkeypatch.setenv(WORKERS_ENV, "2")
        wrapped = maybe_wrap(it)
        assert isinstance(wrapped, InputPipeline)
        # an already-staged iterator is never double-wrapped
        assert maybe_wrap(wrapped) is wrapped
        assert maybe_wrap(AsyncDataSetIterator(it)) is not None
        a = AsyncDataSetIterator(it)
        assert maybe_wrap(a) is a

    def test_normalizer_applied_purely(self):
        norm = NormalizerStandardize().fit(X)
        base = ListDataSetIterator(X, Y, 16)
        pipe = InputPipeline(base, workers=2, normalizer=norm,
                             device_put=False)
        got = list(pipe)
        want = norm.transform_array(X[:16])
        assert np.array_equal(np.asarray(got[0].features), want)
        # the SOURCE's backing array was not mutated (views stay intact)
        assert np.array_equal(base.features, X)


class TestStatsAndKnobs:
    def test_pipeline_stats_accounting(self):
        pipe = InputPipeline(ListDataSetIterator(X, Y, 16), workers=2,
                             prefetch=2, device_put=False)
        list(pipe)
        s = pipe.pipeline_stats.snapshot()
        assert s["batches"] == 6
        assert s["records"] == 96
        assert s["bytes"] == 6 * 16 * (F + C) * 4
        assert s["epochs"] == 1 and s["workers"] == 2
        assert s["wall_seconds"] > 0
        assert s["stall_seconds"] >= 0 and s["producer_stall_seconds"] >= 0
        assert 0.0 <= s["stall_fraction"] <= 1.0

    def test_async_iterator_prefetch_env_and_stats(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_PREFETCH", "7")
        it = AsyncDataSetIterator(ListDataSetIterator(X, Y, 16),
                                  device_put=False)
        assert it.queue_size == 7
        assert it.pipeline_stats.queue_capacity == 7
        list(it)
        s = it.pipeline_stats.snapshot()
        assert s["batches"] == 6 and s["records"] == 96
        assert s["epochs"] == 1
        # explicit queue_size still wins over the env
        assert AsyncDataSetIterator(ListDataSetIterator(X, Y, 16),
                                    queue_size=3).queue_size == 3

    def test_pipeline_prefetch_env_default(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_PREFETCH", "5")
        pipe = InputPipeline(ListDataSetIterator(X, Y, 16), workers=1)
        assert pipe.prefetch == 5


class TestSharding:
    def test_shard_partition_is_exact_and_disjoint(self):
        ref = list(ListDataSetIterator(X, Y, 16))
        parts = []
        for i in range(2):
            p = InputPipeline(ListDataSetIterator(X, Y, 16), workers=1,
                              device_put=False, shard=(i, 2))
            parts.append(list(p))
        assert len(parts[0]) + len(parts[1]) == len(ref)
        assert [ds_bytes(d) for d in parts[0]] == \
            [ds_bytes(d) for d in ref[0::2]]
        assert [ds_bytes(d) for d in parts[1]] == \
            [ds_bytes(d) for d in ref[1::2]]

    def test_auto_shard_from_multihost_env(self, monkeypatch):
        from deeplearning4j_tpu.parallel.multihost import (
            NUM_PROCESSES_ENV,
            PROCESS_ID_ENV,
        )

        monkeypatch.setenv(PROCESS_ID_ENV, "1")
        monkeypatch.setenv(NUM_PROCESSES_ENV, "2")
        pipe = InputPipeline(ListDataSetIterator(X, Y, 16), workers=1,
                             device_put=False)
        assert pipe.shard == (1, 2)
        ref = list(ListDataSetIterator(X, Y, 16))
        assert [ds_bytes(d) for d in list(pipe)] == \
            [ds_bytes(d) for d in ref[1::2]]

    def test_bad_shard_rejected(self):
        with pytest.raises(ValueError, match="shard index"):
            InputPipeline(ListDataSetIterator(X, Y, 16), shard=(2, 2))


class TestLiveResharding:
    """ISSUE 6: the elastic fleet re-partitions the multihost shard
    selection on a membership epoch bump — at an agreed absolute batch
    boundary, with no batch dropped or double-owned across the fleet's
    pipelines, and with the delivered-batch cursor semantics intact."""

    def mk(self, shard):
        return InputPipeline(ListDataSetIterator(X, Y, 16), workers=1,
                             device_put=False, shard=shard)

    def test_reshard_covers_every_batch_exactly_once(self):
        from deeplearning4j_tpu.etl.pipeline import DROP_SHARD

        ref = [ds_bytes(d) for d in ListDataSetIterator(X, Y, 16)]
        # membership {A,B} for seqs 0..2; B leaves at seq 3 -> A owns all
        pa, pb = self.mk((0, 2)), self.mk((1, 2))
        pa.reshard((0, 1), at_seq=3)
        pb.reshard(DROP_SHARD, at_seq=3)
        got_a = [ds_bytes(d) for d in pa]
        got_b = [ds_bytes(d) for d in pb]
        assert got_b == [ref[1]]  # old partition below the boundary
        assert got_a == [ref[0], ref[2]] + ref[3:]
        assert sorted(got_a + got_b) == sorted(ref)

    def test_reshard_boundary_already_passed_raises(self):
        pipe = self.mk((0, 2))
        it = iter(pipe)
        next(it)
        next(it)  # dispatcher has decided ownership past seq 0 by now
        with pytest.raises(ValueError, match="already passed"):
            pipe.reshard((0, 1), at_seq=0)
        it.close()

    def test_resume_replays_reshard_schedule(self):
        """The shard schedule rides the delivered-batch cursor: a
        kill/resume mid-schedule re-owns exactly the same batches."""
        pipe = self.mk((0, 2))
        pipe.reshard((0, 1), at_seq=3)
        full = [ds_bytes(d) for d in pipe]
        pipe2 = self.mk((0, 2))
        pipe2.reshard((0, 1), at_seq=3)
        it = iter(pipe2)
        first = [ds_bytes(next(it))]
        st = pipe2.state()
        assert st["shard_schedule"] == [[0, [0, 2]], [3, [0, 1]]]
        it.close()
        fresh = self.mk((0, 2))  # schedule comes from the cursor
        fresh.restore_state(st)
        rest = [ds_bytes(d) for d in fresh]
        assert first + rest == full

    def test_deferred_reshard_applies_next_pass(self):
        ref = [ds_bytes(d) for d in ListDataSetIterator(X, Y, 16)]
        pipe = self.mk((0, 2))
        assert [ds_bytes(d) for d in pipe] == ref[0::2]
        pipe.reshard((1, 2))  # at_seq=None: from the next pass
        assert [ds_bytes(d) for d in pipe] == ref[1::2]

    def test_deferred_reshard_survives_checkpoint_resume(self):
        """A deferred (next-pass) reshard scheduled before a checkpoint
        must ride the cursor: the restored pipeline applies it exactly
        like the survivor that never died."""
        ref = [ds_bytes(d) for d in ListDataSetIterator(X, Y, 16)]
        pipe = self.mk((0, 2))
        assert [ds_bytes(d) for d in pipe] == ref[0::2]
        pipe.reshard((1, 2))  # deferred; then the process is killed
        st = pipe.state()
        assert st["pending_shard"] == [1, 2]
        fresh = self.mk((0, 2))
        fresh.restore_state(st)
        fresh.reset()  # resume landed at an epoch boundary: fresh pass
        assert [ds_bytes(d) for d in fresh] == ref[1::2]

    def test_consumed_boundary_does_not_refire_next_pass(self):
        ref = [ds_bytes(d) for d in ListDataSetIterator(X, Y, 16)]
        pipe = self.mk((0, 2))
        pipe.reshard((0, 1), at_seq=3)
        list(pipe)  # consumes the boundary
        # next pass: the FINAL shard owns from seq 0 (no mid-pass flip)
        assert [ds_bytes(d) for d in pipe] == ref


class TestResume:
    def test_wrap_mode_resume_exact(self):
        ref = list(ListDataSetIterator(X, Y, 16))
        pipe = InputPipeline(ListDataSetIterator(X, Y, 16), workers=2,
                             device_put=False)
        it = iter(pipe)
        for _ in range(2):
            next(it)
        st = pipe.state()
        assert st["mode"] == "source"
        it.close()
        fresh = InputPipeline(ListDataSetIterator(X, Y, 16), workers=2,
                              device_put=False)
        fresh.restore_state(st)
        rest = list(fresh)
        assert [ds_bytes(d) for d in rest] == [ds_bytes(d) for d in ref[2:]]
        assert fresh.pipeline_stats.restores == 1

    def test_reader_mode_resume_replays_exactly(self):
        tp = transform()
        li = tp.final_schema().index_of("label")
        mk = lambda: InputPipeline.from_reader(
            CollectionRecordReader(RECORDS), 32, label_index=li,
            num_possible_labels=C, transform=tp, workers=2,
            device_put=False)
        ref = list(mk())
        pipe = mk()
        it = iter(pipe)
        for _ in range(3):
            next(it)
        st = pipe.state()
        assert st["mode"] == "replay" and st["next_seq"] == 3
        it.close()
        fresh = mk()
        fresh.restore_state(st)
        rest = list(fresh)
        assert [ds_bytes(d) for d in rest] == [ds_bytes(d) for d in ref[3:]]

    def test_cursor_survives_empty_poll_window(self):
        """ISSUE 14 satellite: a restored cursor must survive a pass that
        delivers ZERO batches (an exhausted live stream idling between
        poll windows) — state() keeps answering the restored position
        instead of resetting to a next_seq-0 snapshot, so the refilled
        window resumes at the right offset with no double-skip."""

        class Refillable:
            """Exhausted-then-refilled source: each __iter__ is one poll
            window draining whatever arrived since the cursor."""

            def __init__(self):
                self.data = list(ListDataSetIterator(X[:64], Y[:64], 16))
                self.pos = 0

            def __iter__(self):
                while self.pos < len(self.data):
                    ds = self.data[self.pos]
                    self.pos += 1
                    yield ds

            def state(self):
                return {"pos": self.pos}

            def restore_state(self, st):
                self.pos = int(st["pos"])

        src = Refillable()
        pipe = InputPipeline(src, workers=2, device_put=False)
        first = list(pipe)  # window 1 drains the 4 available batches
        assert len(first) == 4
        st = pipe.state()
        assert st["mode"] == "source" and st["next_seq"] == 4

        # fresh process: restore, then the stream idles — an EMPTY window
        fresh_src = Refillable()
        fresh = InputPipeline(fresh_src, workers=2, device_put=False)
        fresh.restore_state(st)
        assert list(fresh) == []
        st2 = fresh.state()
        assert st2["mode"] == "source" and st2["next_seq"] == 4
        assert st2["source"] == {"pos": 4}

        # the stream refills: re-anchor on the preserved cursor and the
        # new batches arrive at the right absolute offsets, exactly once
        more = list(ListDataSetIterator(X[64:], Y[64:], 16))
        fresh_src.data.extend(more)
        fresh.restore_state(st2)
        got = list(fresh)
        assert [ds_bytes(d) for d in got] == [ds_bytes(d) for d in more]
        assert fresh.state()["next_seq"] == 4 + len(more)

    def test_state_before_any_delivery(self):
        pipe = InputPipeline(ListDataSetIterator(X, Y, 16), workers=1,
                             device_put=False)
        st = pipe.state()
        assert st is not None  # ResilientTrainer gets a usable cursor
        fresh = InputPipeline(ListDataSetIterator(X, Y, 16), workers=1,
                              device_put=False)
        fresh.restore_state(st)
        assert len(list(fresh)) == 6


class TestResilienceThroughPipeline:
    def test_kill_and_resume_bit_exact(self, tmp_path):
        """ISSUE 5 acceptance: ResilientTrainer killed at step k and
        resumed THROUGH the InputPipeline == uninterrupted, bit-exact
        params and loss curve (the pipeline's delivered-batch cursor is
        the iterator state the checkpoint carries)."""
        from deeplearning4j_tpu.resilience import (
            ChaosConfig,
            ChaosMonkey,
            CheckpointManager,
            InjectedKill,
            ResilientTrainer,
        )

        mk_pipe = lambda: InputPipeline(
            ListDataSetIterator(X, Y, 16), workers=2, prefetch=2)
        epochs = 2

        baseline = ResilientTrainer(build_net())
        baseline.fit(mk_pipe(), num_epochs=epochs)

        tmp = str(tmp_path / "ckpt")
        mgr = CheckpointManager(tmp, every_steps=3, keep_last=3)
        killed = ResilientTrainer(
            build_net(), mgr,
            chaos=ChaosMonkey(ChaosConfig(kill_at_step=7)))
        with pytest.raises(InjectedKill):
            killed.fit(mk_pipe(), num_epochs=epochs)
        mgr.close()

        mgr2 = CheckpointManager(tmp, every_steps=3, keep_last=3)
        resumed = ResilientTrainer(build_net(), mgr2)
        resumed.fit(mk_pipe(), num_epochs=epochs)
        mgr2.close()

        assert resumed.resumed_step is not None
        assert 0 < resumed.resumed_step <= 7
        assert resumed.step == baseline.step
        stitched = killed.losses[:resumed.resumed_step] + resumed.losses
        assert stitched == baseline.losses
        assert params_equal(baseline.net.params, resumed.net.params)


class TestNativeSource:
    def test_from_native_matches_direct_feeder(self):
        from deeplearning4j_tpu.native import NativePrefetchIterator

        x = _RNG.standard_normal((64, 5)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[_RNG.integers(0, 2, 64)]
        ref = list(NativePrefetchIterator(x, y, batch=16, seed=3))
        pipe = InputPipeline.from_native(x, y, 16, seed=3, workers=2,
                                         device_put=False)
        got = list(pipe)
        assert len(got) == len(ref)
        for (rx, ry), ds in zip(ref, got):
            assert np.array_equal(rx, np.asarray(ds.features))
            assert np.array_equal(ry, np.asarray(ds.labels))
