"""Provisioning plane (deeplearning4j-aws parity, TPU/gcloud edition):
plan generation, bootstrap env wiring into MultiHostConfig, runner-injected
execution, and GCS dataset IO against a fake runner — the whole module is
exercised without a cloud API (zero-egress host)."""

import os
import subprocess
from types import SimpleNamespace

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.multihost import MultiHostConfig
from deeplearning4j_tpu.provision import ClusterSetup, TpuPodSpec
from deeplearning4j_tpu.provision.gcs import (
    BucketIterator,
    GcsDataSetLoader,
    GcsUploader,
)
from deeplearning4j_tpu.provision.tpu_pod import bootstrap_script, host_env


def _spec(**kw):
    kw.setdefault("name", "dl4j-test")
    kw.setdefault("zone", "us-central2-b")
    kw.setdefault("accelerator_type", "v5litepod-16")
    return TpuPodSpec(**kw)


class TestSpec:
    def test_chip_and_host_counts(self):
        assert _spec().num_chips == 16
        assert _spec().num_hosts == 4   # v5e: 4 chips per host VM
        assert _spec(accelerator_type="v4-8").num_hosts == 1

    def test_bad_accelerator_type_raises(self):
        with pytest.raises(ValueError):
            _ = _spec(accelerator_type="weird").num_chips


class TestClusterPlan:
    def test_plan_sequence(self):
        cs = ClusterSetup(_spec(project="my-proj"))
        plan = cs.plan()
        assert plan[0][:6] == ["gcloud", "compute", "tpus", "tpu-vm",
                               "create", "dl4j-test"]
        assert "--accelerator-type=v5litepod-16" in plan[0]
        assert "--project=my-proj" in plan[0]
        assert plan[1][4] == "describe"
        assert plan[2][4] == "ssh" and "--worker=all" in plan[2]
        assert cs.teardown_plan()[0][4] == "delete"

    def test_apply_uses_injected_runner(self):
        calls = []

        def fake_runner(cmd):
            calls.append(cmd)
            return SimpleNamespace(stdout="", returncode=0)

        cs = ClusterSetup(_spec())
        cs.apply(runner=fake_runner)
        cs.teardown(runner=fake_runner)
        assert len(calls) == 4  # create, describe, ssh, delete

    def test_bootstrap_wires_multihost_env(self, monkeypatch):
        """The generated env triple must be exactly what
        MultiHostConfig.from_env consumes (the ZooKeeper-role contract)."""
        spec = _spec()
        env = host_env(spec, process_id=1, coordinator_host="10.0.0.2")
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        monkeypatch.setenv("DL4J_TPU_PROCESS_ID", "1")
        cfg = MultiHostConfig.from_env()
        assert cfg.coordinator_address == "10.0.0.2:8476"
        assert cfg.num_processes == 4
        assert cfg.process_id == 1
        assert cfg.is_configured()

    def test_bootstrap_script_contents(self):
        script = bootstrap_script(_spec(), "/opt/repo", "python train.py")
        # the process count is resolved ON-HOST, never baked in python
        assert 'DL4J_TPU_NUM_PROCESSES="${NUM_PROC}"' in script
        assert 'DL4J_TPU_PROCESS_ID="${PROC_ID}"' in script
        assert "PYTHONPATH=/opt/repo" in script
        assert script.rstrip().endswith("python train.py")
        # remote command embeds the script for --worker=all fan-out
        remote = ClusterSetup(_spec(), repo_dir="/opt/repo",
                              train_cmd="python train.py")._remote_command()
        assert "DL4J_BOOTSTRAP" in remote


class TestGcsIO:
    def test_bucket_iterator_and_loader(self, tmp_path):
        npz = tmp_path / "shard0.npz"
        x = np.random.default_rng(0).normal(size=(10, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.arange(10) % 3]
        np.savez(npz, features=x, labels=y)

        def fake_runner(cmd):
            if cmd[:2] == ["gsutil", "ls"]:
                return SimpleNamespace(stdout="gs://b/shard0.npz\n",
                                       returncode=0)
            if cmd[:2] == ["gsutil", "cp"]:
                # "download": copy the local fixture into the cache path
                import shutil

                shutil.copy(npz, cmd[-1])
                return SimpleNamespace(stdout="", returncode=0)
            raise AssertionError(f"unexpected command {cmd}")

        loader = GcsDataSetLoader("gs://b/", str(tmp_path / "cache"),
                                  runner=fake_runner, batch_size=4)
        batches = list(loader)
        assert [b.features.shape[0] for b in batches] == [4, 4, 2]
        np.testing.assert_array_equal(batches[0].features, x[:4])

    def test_uploader_recursive_for_dirs(self, tmp_path):
        calls = []
        (tmp_path / "ckpt").mkdir()
        up = GcsUploader(runner=lambda cmd: calls.append(cmd))
        up.upload(str(tmp_path / "ckpt"), "gs://b/ckpt")
        assert calls[0][:3] == ["gsutil", "-m", "cp"] and "-r" in calls[0]

    def test_non_gs_uri_rejected(self):
        with pytest.raises(ValueError):
            list(BucketIterator("s3://nope"))


class TestReviewRegressions:
    def test_bootstrap_resolves_coordinator_on_host(self):
        """The script must derive COORDINATOR_IP itself (TPU metadata env)
        — an unbound ${COORDINATOR_IP} under set -u would abort every
        host's bootstrap."""
        script = bootstrap_script(_spec(), "/opt/repo", "python t.py")
        assert "TPU_WORKER_HOSTNAMES" in script
        assert 'COORDINATOR_IP="$(' in script
        # executable end-to-end: run it with a fake env + no-op train cmd
        import subprocess
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            s = bootstrap_script(_spec(), d, "env | grep DL4J_TPU_")
            out = subprocess.run(
                ["bash", "-s"], input=s, capture_output=True, text=True,
                env={"PATH": os.environ["PATH"],
                     "TPU_WORKER_HOSTNAMES": "10.0.0.5,10.0.0.6",
                     "TPU_WORKER_ID": "1"},
            )
            assert out.returncode == 0, out.stderr
            assert "DL4J_TPU_COORDINATOR=10.0.0.5:8476" in out.stdout
            assert "DL4J_TPU_PROCESS_ID=1" in out.stdout
            assert "DL4J_TPU_NUM_PROCESSES=2" in out.stdout  # from hostnames

    def test_cache_key_uses_full_object_path(self, tmp_path):
        from deeplearning4j_tpu.provision.gcs import GcsDownloader

        fetched = []

        def fake_runner(cmd):
            fetched.append(cmd[-2])
            open(cmd[-1], "w").write(cmd[-2])
            return SimpleNamespace(stdout="", returncode=0)

        dl = GcsDownloader(str(tmp_path), runner=fake_runner)
        a = dl.fetch("gs://b/train/shard0.npz")
        b = dl.fetch("gs://b/eval/shard0.npz")
        assert a != b and len(fetched) == 2
        assert open(a).read() != open(b).read()

    def test_csv_requires_num_classes(self, tmp_path):
        csv = tmp_path / "s.csv"
        csv.write_text("1.0,2.0,0\n3.0,4.0,1\n")
        with pytest.raises(ValueError):
            GcsDataSetLoader._parse(str(csv), None)
        x, y = GcsDataSetLoader._parse(str(csv), 3)
        assert y.shape == (2, 3)


class TestLoaderTrainingIntegration:
    def test_gcs_loader_feeds_fit_iterator(self, tmp_path):
        """The bucket loader is a normal DataSet iterable: it drives
        MultiLayerNetwork.fit_iterator (including the fused path) exactly
        like a local iterator — the reference's BaseS3DataSetIterator
        end-to-end role."""
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
        for i in range(4):
            np.savez(tmp_path / f"shard{i}.npz",
                     features=x[i * 16:(i + 1) * 16],
                     labels=y[i * 16:(i + 1) * 16])

        def fake_runner(cmd):
            if cmd[:2] == ["gsutil", "ls"]:
                listing = "".join(f"gs://b/shard{i}.npz\n" for i in range(4))
                return SimpleNamespace(stdout=listing, returncode=0)
            if cmd[:2] == ["gsutil", "cp"]:
                import shutil

                shutil.copy(tmp_path / cmd[-2].rsplit("/", 1)[1], cmd[-1])
                return SimpleNamespace(stdout="", returncode=0)
            raise AssertionError(cmd)

        from deeplearning4j_tpu.nn.conf import (
            DenseLayer,
            NeuralNetConfiguration,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.1)
                .updater("adam").list()
                .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
                .layer(1, OutputLayer(n_in=8, n_out=3,
                                      activation="softmax")).build())
        net = MultiLayerNetwork(conf).init()
        loader = GcsDataSetLoader("gs://b/", str(tmp_path / "cache"),
                                  runner=fake_runner)
        s0 = net.score(x, y)
        for _ in range(6):
            net.fit_iterator(loader, fused_batches=2)
        assert net.score(x, y) < s0 * 0.9
        assert net.iteration == 24  # 4 shards x 6 epochs
