"""Sharded orbax checkpoint/resume on the virtual 8-device mesh: save a
dp x model sharded transformer, restore into the same shardings, resume
training identically — the multi-chip ModelSerializer role."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
)
from deeplearning4j_tpu.parallel.mesh import device_mesh
from deeplearning4j_tpu.utils.sharded_checkpoint import (
    restore_lm,
    restore_pytree,
    save_lm,
    save_pytree,
)


def _cfg():
    return TransformerConfig(vocab_size=40, d_model=32, n_layers=2,
                             n_heads=4, d_ff=64, max_len=16,
                             learning_rate=1e-3)


def _batch(cfg, n=8, seed=0):
    rng = np.random.default_rng(seed)
    t = rng.integers(0, cfg.vocab_size, (n, cfg.max_len + 1))
    return (jnp.asarray(t[:, :-1], jnp.int32),
            jnp.asarray(t[:, 1:], jnp.int32))


class TestPytreeRoundtrip:
    def test_plain_pytree(self, tmp_path):
        tree = {"a": jnp.arange(12.0).reshape(3, 4),
                "b": {"c": jnp.ones((5,), jnp.int32)}}
        save_pytree(str(tmp_path / "t"), tree)
        back = restore_pytree(str(tmp_path / "t"), tree)
        jax.tree_util.tree_map(
            lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                       np.asarray(y)),
            tree, back)

    def test_sharded_restores_with_sharding(self, tmp_path):
        mesh = device_mesh(shape=(2, 4), axis_names=("data", "model"))
        cfg = _cfg()
        lm = TransformerLM(cfg, mesh=mesh)
        save_pytree(str(tmp_path / "p"), lm.params)
        back = restore_pytree(str(tmp_path / "p"), lm.params)
        wq = back["blocks"]["Wq"]
        assert wq.sharding == lm.params["blocks"]["Wq"].sharding
        assert wq.addressable_shards[0].data.shape == (2, 32, 32 // 4)


class TestLmCheckpoint:
    def test_save_restore_resume_identical(self, tmp_path):
        cfg = _cfg()
        x, y = _batch(cfg)
        mesh = device_mesh(shape=(2, 4), axis_names=("data", "model"))
        lm = TransformerLM(cfg, mesh=mesh)
        lm.fit(x, y)
        save_lm(str(tmp_path / "ckpt"), lm)

        lm2 = restore_lm(str(tmp_path / "ckpt"), mesh=mesh)
        np.testing.assert_allclose(np.asarray(lm.output(x)),
                                   np.asarray(lm2.output(x)), atol=1e-6)
        # resuming training produces the same loss (opt state round-trips)
        l1 = float(lm.fit(x, y))
        l2 = float(lm2.fit(x, y))
        assert abs(l1 - l2) < 1e-6

    def test_restore_single_device_from_sharded(self, tmp_path):
        """A checkpoint written from a mesh restores on one device (the
        cross-topology resume the flat-zip format can't do without a
        gather)."""
        cfg = _cfg()
        x, y = _batch(cfg)
        mesh = device_mesh(shape=(2, 4), axis_names=("data", "model"))
        lm = TransformerLM(cfg, mesh=mesh)
        lm.fit(x, y)
        save_lm(str(tmp_path / "ckpt"), lm)
        lm_single = restore_lm(str(tmp_path / "ckpt"), mesh=None)
        np.testing.assert_allclose(np.asarray(lm.output(x)),
                                   np.asarray(lm_single.output(x)), atol=1e-5)

    def test_overwrite_is_atomic_and_repeatable(self, tmp_path):
        cfg = _cfg()
        lm = TransformerLM(cfg)
        x, y = _batch(cfg)
        p = str(tmp_path / "ckpt")
        save_lm(p, lm)
        lm.fit(x, y)
        save_lm(p, lm)  # second save overwrites in place
        lm2 = restore_lm(p)
        np.testing.assert_allclose(np.asarray(lm.output(x)),
                                   np.asarray(lm2.output(x)), atol=1e-6)

    def test_generic_restore_dispatches_directory(self, tmp_path):
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        cfg = _cfg()
        lm = TransformerLM(cfg)
        p = str(tmp_path / "ckpt")
        save_lm(p, lm)
        lm2 = ModelSerializer.restore(p)
        assert isinstance(lm2, TransformerLM)
        x, _ = _batch(cfg)
        np.testing.assert_allclose(np.asarray(lm.output(x)),
                                   np.asarray(lm2.output(x)), atol=1e-6)

    def test_weights_only_restore(self, tmp_path):
        cfg = _cfg()
        lm = TransformerLM(cfg)
        x, y = _batch(cfg)
        lm.fit(x, y)
        save_lm(str(tmp_path / "ckpt"), lm)
        lm2 = restore_lm(str(tmp_path / "ckpt"), load_updater=False)
        assert int(lm2.opt["t"]) == 0  # fresh optimizer
        np.testing.assert_allclose(np.asarray(lm.output(x)),
                                   np.asarray(lm2.output(x)), atol=1e-6)


class TestCrashSafety:
    def test_pointer_commit_and_prune(self, tmp_path):
        import os

        tree = {"a": jnp.arange(6.0)}
        p = str(tmp_path / "t")
        save_pytree(p, tree)
        save_pytree(p, {"a": jnp.arange(6.0) * 2})
        assert os.path.isfile(p + ".current")
        with open(p + ".current") as f:
            assert f.read().strip() == "t.v2"
        assert not os.path.isdir(p + ".v1")  # superseded version pruned
        back = restore_pytree(p, tree)
        np.testing.assert_allclose(np.asarray(back["a"]),
                                   np.arange(6.0) * 2)

    def test_uncommitted_version_is_invisible(self, tmp_path):
        """A version directory without a pointer flip (the crash-mid-save
        state) must not be picked up by restore."""
        import os

        tree = {"a": jnp.arange(4.0)}
        p = str(tmp_path / "t")
        save_pytree(p, tree)
        # simulate a crashed later save: a newer version dir, no commit
        os.makedirs(p + ".v99")
        back = restore_pytree(p, tree)
        np.testing.assert_allclose(np.asarray(back["a"]), np.arange(4.0))
