"""Serving resilience plane tests (ISSUE 8): chaos-driven degradation
contracts for the circuit breaker, the hung-inference watchdog, graceful
drain, registry failure isolation and decode-slot crash eviction.

The training side proved interrupted==uninterrupted under injected faults
(tests/test_resilience.py, PR 3) and the fleet proved loss==replay
(tests/test_fleet.py, PR 6); this file is the serving third of that
convention: every failure path is provoked DETERMINISTICALLY through
resilience/chaos.ServingChaosConfig (never ambient — an engine without a
configured chaos object is byte-identical to one built before the plane
existed, which the equivalence test here locks) and every recovery claim
is asserted end-to-end: the engine serves fresh traffic again after the
injected wedge, the prior model version keeps serving through a failed
rollout, co-resident decode slots survive a crashed admission.

Reference anchor: the route being hardened had NO failure semantics at
all (dl4j-streaming/.../routes/DL4jServeRouteBuilder.java — one static
model, exceptions propagate, health is implicit) — every contract here is
beyond-reference, motivated by this host's documented stale-tunnel wedge
(a hung device call with ~0 CPU and NO error, CLAUDE.md).
"""

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.resilience import (
    InjectedServingFault,
    ServingChaos,
    ServingChaosConfig,
)
from deeplearning4j_tpu.serving import (
    BreakerOpenError,
    CircuitBreaker,
    DynamicBatcher,
    ServingEngine,
    ServingStats,
    WorkerDeadError,
)
from deeplearning4j_tpu.serving.resilience import BROKEN, DEGRADED, SERVING

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def small_net(seed=7, n_in=4, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.1)
            .list()
            .layer(0, DenseLayer(n_in=n_in, n_out=8, activation="tanh"))
            .layer(1, OutputLayer(n_in=8, n_out=n_out, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    net.fit(rng.normal(size=(32, n_in)).astype(np.float32),
            np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, 32)])
    return net


def _post(url, path, payload, timeout=60):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url, path, timeout=30):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return json.loads(r.read())


def _code_of(fn, *a, **kw):
    """(status_code, body_dict, headers) of an HTTP call that may error."""
    try:
        return 200, fn(*a, **kw), {}
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


@pytest.fixture
def obs_on():
    obs.set_enabled(True)
    obs.tracer().clear()
    try:
        yield
    finally:
        obs.set_enabled(None)


# ---------------------------------------------------------------------------
# CircuitBreaker state machine
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_serving_degraded_broken_walk(self):
        st = ServingStats()
        br = CircuitBreaker(fails=3, cooldown_s=60, stats=st, key="m@v1")
        assert br.state == SERVING
        br.record_failure("boom")
        assert br.state == DEGRADED  # failing but still admitting
        assert br.check() is False   # not a probe, not a shed
        br.record_success()
        assert br.state == SERVING   # one success heals DEGRADED
        for _ in range(3):
            br.record_failure("boom")
        assert br.state == BROKEN
        assert st.breaker_opens == 1
        with pytest.raises(BreakerOpenError) as ei:
            br.check()
        assert ei.value.retry_after_s > 0
        assert st.fast_fails_503 == 1

    def test_half_open_probe_close_and_reopen(self):
        st = ServingStats()
        br = CircuitBreaker(fails=2, cooldown_s=0.15, stats=st)
        br.record_failure("a")
        br.record_failure("a")
        assert br.state == BROKEN
        time.sleep(0.2)
        assert br.check() is True        # THE half-open probe
        with pytest.raises(BreakerOpenError):
            br.check()                   # co-requests shed until verdict
        br.record_failure("probe died")  # probe fails -> re-open
        assert br.state == BROKEN
        time.sleep(0.2)
        assert br.check() is True
        br.record_success()              # probe succeeds -> close
        assert br.state == SERVING
        assert br.check() is False
        assert st.breaker_probes == 2 and st.breaker_closes == 1

    def test_rate_window_opens_without_consecutive_run(self):
        """Alternating ok/fail never reaches `fails` consecutive, but the
        windowed failure rate crosses 0.5 once enough outcomes exist."""
        br = CircuitBreaker(fails=100, window_s=60, rate=0.5, min_window=8)
        for _ in range(5):
            br.record_success()
            br.record_failure("flaky")
        assert br.state == BROKEN
        assert "rate" in br.open_reason

    def test_trip_is_categorical(self):
        st = ServingStats()
        br = CircuitBreaker(fails=5, stats=st)
        br.trip("watchdog: wedged")
        assert br.state == BROKEN and st.breaker_opens == 1
        br.trip("again")  # re-trip: fresh cooldown, no double count
        assert st.breaker_opens == 1

    def test_disabled_breaker_never_sheds_and_never_breaks(self):
        """fails=0 means DISABLED end to end: no shedding AND no state
        tracking — a vote path that still flipped BROKEN would 503 the
        /health of a model that keeps serving fine, with no probe path
        back (check() never grants one when disabled)."""
        st = ServingStats()
        br = CircuitBreaker(fails=0, stats=st)
        for _ in range(20):
            br.record_failure("x")
        br.trip("categorical-looking evidence")
        assert br.check() is False
        assert br.state == SERVING
        assert st.breaker_opens == 0

    def test_ghost_probe_forfeits_slot_after_ttl(self):
        """A probe that never reaches a dispatch outcome (shed at
        submit, expired in queue, payload error) must not hold the
        half-open slot forever — past probe_ttl_s a NEW probe is
        granted, so the breaker cannot stay open behind a ghost."""
        br = CircuitBreaker(fails=1, cooldown_s=0.05, probe_ttl_s=0.15)
        br.record_failure("x")
        assert br.state == BROKEN
        time.sleep(0.06)
        assert br.check() is True   # probe granted...
        with pytest.raises(BreakerOpenError):
            br.check()              # ...slot held while fresh
        time.sleep(0.2)             # the probe never reported back
        assert br.check() is True   # TTL expired: slot forfeited, re-probe
        br.record_success()
        assert br.state == SERVING


# ---------------------------------------------------------------------------
# breaker over HTTP: chaos infer-raise walks the model to BROKEN and back
# ---------------------------------------------------------------------------


class TestBreakerHTTP:
    def test_consecutive_failures_503_then_probe_recovers(self):
        chaos = ServingChaos(ServingChaosConfig(infer_raise_at=1,
                                                infer_raise_count=3))
        eng = ServingEngine(model=small_net(), max_wait_ms=5,
                            breaker_fails=3, breaker_cooldown_s=0.3,
                            chaos=chaos).start()
        try:
            codes = []
            for _ in range(5):
                code, body, headers = _code_of(
                    _post, eng.url, "/predict",
                    {"record": [0.1, 0.2, 0.3, 0.4]}, 30)
                codes.append(code)
                if code == 503:
                    # the shed contract: Retry-After rides the 503 so a
                    # client backs off instead of hammering the breaker;
                    # RFC 9110 delta-seconds — an INTEGER >= 1, or
                    # standard retry parsers silently drop it
                    assert int(headers["Retry-After"]) >= 1
            # three injected failures (400 each), then the OPEN breaker
            # fast-fails everything else without touching the model
            assert codes == [400, 400, 400, 503, 503]
            assert len(chaos.log) == 3  # the breaker shed, chaos untouched
            m = eng.metrics()
            assert m["serving"]["breaker_opens"] == 1
            assert m["serving"]["fast_fails_503"] >= 2
            assert m["health"]["default@v1"] == "broken"
            # cooldown passes -> the next request IS the half-open probe;
            # chaos is exhausted so it succeeds and closes the breaker
            time.sleep(0.35)
            out = _post(eng.url, "/predict",
                        {"record": [0.1, 0.2, 0.3, 0.4]}, 30)
            assert len(out["output"]) == 3
            m = eng.metrics()
            assert m["serving"]["breaker_closes"] == 1
            assert m["health"]["default@v1"] == "serving"
        finally:
            eng.stop()


    def test_client_payload_errors_never_open_the_breaker(self):
        """400-class evidence stays 400-class: a stream of malformed
        requests (wrong row width -> reshape fails BEFORE the model
        call) must not walk a healthy model to BROKEN and 503 everyone
        else."""
        eng = ServingEngine(model=small_net(), input_shape=(4,),
                            max_wait_ms=5, breaker_fails=3).start()
        try:
            for _ in range(6):  # twice the breaker threshold
                code, _, _ = _code_of(_post, eng.url, "/predict",
                                      {"record": [0.1, 0.2]}, 30)  # width 2
                assert code == 400
            # the model is still healthy and still serving
            out = _post(eng.url, "/predict",
                        {"record": [0.1, 0.2, 0.3, 0.4]}, 30)
            assert len(out["output"]) == 3
            m = eng.metrics()
            assert m["serving"]["breaker_opens"] == 0
            assert m["health"]["default@v1"] == "serving"
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# hung-inference watchdog: the stale-tunnel wedge, detected and survived
# ---------------------------------------------------------------------------


class TestWatchdog:
    def test_injected_hang_diagnosed_journaled_recovered(self, obs_on):
        """The acceptance headline: an injected infer-hang (the stale
        tunnel's signature — blocks, ~0 CPU, no error) is detected within
        the watchdog deadline, pending requests fail with a DIAGNOSIS
        (well before their 504 budget — not 504-by-rot), serve.wedged is
        journaled, and the engine serves fresh traffic again."""
        # the hang injects at dispatch 2: dispatch 1 warms the jit trace
        # first, so the watchdog deadline is judged against a steady-state
        # dispatch — a first-dispatch trace under full quick-gate load on
        # this 1-core host can legitimately exceed a sub-second deadline
        chaos = ServingChaos(ServingChaosConfig(infer_hang_at=2,
                                                infer_hang_s=30.0))
        eng = ServingEngine(model=small_net(), max_wait_ms=5,
                            watchdog_s=0.8, breaker_fails=3,
                            breaker_cooldown_s=0.3, chaos=chaos).start()
        try:
            warm = _post(eng.url, "/predict",
                         {"record": [0.1, 0.2, 0.3, 0.4]}, 30)
            assert len(warm["output"]) == 3
            t0 = time.monotonic()
            code, body, _ = _code_of(
                _post, eng.url, "/predict",
                {"record": [0.1, 0.2, 0.3, 0.4], "timeout_s": 30}, 40)
            detect_s = time.monotonic() - t0
            assert code == 503
            assert "Wedged" in body["error"]          # the diagnosis...
            assert "watchdog" in body["error"]
            assert detect_s < 5.0                     # ...not 30s of rot
            m = eng.metrics()["serving"]
            assert m["wedged_batches"] == 1
            assert m["watchdog_restarts"] == 1
            # the flight recorder holds the wedge event (post-mortem
            # evidence even if the process dies next — it was fsync'd)
            wedged = obs.default_journal().events("serve.wedged")
            assert wedged and wedged[-1]["model"] == "default@v1"
            assert wedged[-1]["failed_requests"] == 1
            # the wedge tripped the breaker: immediate requests shed 503
            code, _, _ = _code_of(_post, eng.url, "/predict",
                                  {"record": [0.1, 0.2, 0.3, 0.4]}, 30)
            assert code == 503
            # cooldown passes; the probe rides the REPLACED worker (the
            # wedged thread is fenced out) and closes the breaker: the
            # engine is serving again with a live-but-abandoned hang
            # still pending inside the old thread
            time.sleep(0.35)
            out = _post(eng.url, "/predict",
                        {"record": [0.1, 0.2, 0.3, 0.4]}, 30)
            assert len(out["output"]) == 3
            assert eng.metrics()["health"]["default@v1"] == "serving"
        finally:
            chaos.release_hangs()  # unblock the abandoned worker thread
            eng.stop()

    def test_fast_traffic_never_false_positives(self):
        net = small_net()
        eng = ServingEngine(model=net, max_wait_ms=5, watchdog_s=5.0).start()
        try:
            rng = np.random.default_rng(3)
            rows = rng.normal(size=(8, 4)).astype(np.float32)
            with ThreadPoolExecutor(max_workers=8) as ex:
                list(ex.map(
                    lambda i: _post(eng.url, "/predict",
                                    {"record": rows[i].tolist()}, 30),
                    range(8)))
            m = eng.metrics()["serving"]
            assert m["wedged_batches"] == 0
            assert m["watchdog_restarts"] == 0
            assert m["completed"] == 8
        finally:
            eng.stop()

    def test_slow_infer_is_degradation_not_wedge(self):
        """A dispatch slower than typical but inside the deadline must
        complete normally — the watchdog keys on the DEADLINE, not on
        'slower than usual' heuristics."""
        chaos = ServingChaos(ServingChaosConfig(slow_infer_at=1,
                                                slow_infer_s=0.3))
        eng = ServingEngine(model=small_net(), max_wait_ms=5,
                            watchdog_s=5.0, chaos=chaos).start()
        try:
            out = _post(eng.url, "/predict",
                        {"record": [0.1, 0.2, 0.3, 0.4]}, 30)
            assert len(out["output"]) == 3
            assert eng.metrics()["serving"]["wedged_batches"] == 0
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# dead worker: fast-fail at submit, no abandoned futures at stop
# ---------------------------------------------------------------------------


class TestDeadWorker:
    def test_submit_fast_fails_after_worker_death(self):
        class Dying(DynamicBatcher):
            def _take_batch(self, gen):
                raise RuntimeError("worker loop bug")

        b = Dying(lambda x: np.asarray(x), max_batch=4, max_wait_ms=1)
        try:
            deadline = time.monotonic() + 5
            while b._dead is None and time.monotonic() < deadline:
                time.sleep(0.01)
            assert b._dead is not None
            # the satellite fix: submit checks liveness and fast-fails
            # instead of queueing onto a corpse until the 504
            with pytest.raises(WorkerDeadError):
                b.submit(np.zeros((1, 2), np.float32))
            assert b.stats.worker_deaths == 1
        finally:
            b.stop()

    def test_worker_death_fails_queued_futures(self):
        """Requests already queued when the worker dies get the REAL
        cause immediately, not a silent wait to 504."""
        gate = threading.Event()
        state = {"n": 0}

        def infer(x):
            state["n"] += 1
            if state["n"] == 1:
                gate.wait(timeout=10)  # hold batch 1 while queue builds
                return np.asarray(x)
            raise BaseException("out-of-band")  # noqa: TRY002 — unreachable

        b = DynamicBatcher(infer, max_batch=1, max_wait_ms=1)
        try:
            f1 = b.submit(np.zeros((1, 2), np.float32))
            f2 = b.submit(np.zeros((1, 2), np.float32))  # queued
            # kill the worker loop out from under the queue: the next
            # _take_batch call raises (simulates a loop bug, the same
            # class the Dying subclass hits at birth)
            b._take_batch = None  # TypeError on next call -> worker dies
            gate.set()
            np.testing.assert_array_equal(f1.result(timeout=10),
                                          np.zeros((1, 2), np.float32))
            with pytest.raises(WorkerDeadError):
                f2.result(timeout=10)
        finally:
            gate.set()
            b.stop()

    def test_stop_fails_inflight_futures(self):
        """stop() must fail — never abandon — the batch the worker holds
        INSIDE infer_fn: those futures are not in the queue, and the old
        stop() walked only the queue."""
        hold = threading.Event()

        def infer(x):
            hold.wait(timeout=30)
            return np.asarray(x)

        b = DynamicBatcher(infer, max_batch=2, max_wait_ms=1)
        try:
            f = b.submit(np.zeros((1, 2), np.float32))
            deadline = time.monotonic() + 5
            while b._inflight is None and time.monotonic() < deadline:
                time.sleep(0.01)
            assert b._inflight is not None
            b.stop(timeout_s=0.2)  # worker is stuck; do not wait 5s
            with pytest.raises(RuntimeError, match="in flight"):
                f.result(timeout=5)
        finally:
            hold.set()


# ---------------------------------------------------------------------------
# graceful drain: stop()/SIGTERM answers everything admitted
# ---------------------------------------------------------------------------


class TestDrain:
    def test_drain_under_load_completes_every_admitted_request(self):
        net = small_net()

        class SlowNet:
            def output(self, x):
                time.sleep(0.05)  # stretch the dispatch so a queue forms
                return net.output(x)

        eng = ServingEngine(model=SlowNet(), max_batch=2, max_wait_ms=1,
                            drain_s=20.0).start()
        try:
            with ThreadPoolExecutor(max_workers=8) as ex:
                futs = [ex.submit(_post, eng.url, "/predict",
                                  {"record": [0.1, 0.2, 0.3, 0.4]}, 30)
                        for _ in range(8)]
                time.sleep(0.08)  # some in flight, some queued
                t0 = time.monotonic()
                ok = eng.drain()
                drain_s = time.monotonic() - t0
                # every ADMITTED request completed with a real answer
                for f in futs:
                    assert len(f.result()["output"]) == 3
            assert ok and drain_s < 15.0
            m = eng.metrics()["serving"]
            assert m["drains_started"] == 1 and m["drains_completed"] == 1
            # admission is closed: new traffic sheds 503 + Retry-After
            code, _, headers = _code_of(
                _post, eng.url, "/predict",
                {"record": [0.1, 0.2, 0.3, 0.4]}, 30)
            assert code == 503 and "Retry-After" in headers
            code, body, _ = _code_of(_get, eng.url, "/health")
            assert code == 503 and body["draining"]
        finally:
            eng.stop(drain=False)

    def test_sigterm_stops_admission_and_drains(self, obs_on):
        """The preemption path, wired like ResilientTrainer's
        checkpoint-before-death: a REAL SIGTERM closes admission in the
        handler, drains on a worker thread, journals the preempt marker
        and flushes the journal."""
        import signal as _signal

        prev_handler = _signal.getsignal(_signal.SIGTERM)
        eng = ServingEngine(model=small_net(), max_wait_ms=5,
                            handle_signals=True).start()
        try:
            _post(eng.url, "/predict", {"record": [0.1, 0.2, 0.3, 0.4]}, 30)
            os.kill(os.getpid(), _signal.SIGTERM)
            deadline = time.monotonic() + 10
            while not eng._draining and time.monotonic() < deadline:
                time.sleep(0.01)
            assert eng._draining
            # the drain thread finishes shutdown; the journal holds the
            # preempt marker + drain completion
            deadline = time.monotonic() + 10
            while (not obs.default_journal().events("serve.drain_complete")
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            assert obs.default_journal().events("serve.preempt")
            assert obs.default_journal().events("serve.drain_complete")
        finally:
            eng.stop(drain=False)
        # the engine restored the previous SIGTERM disposition (the
        # drain thread's stop() cannot restore — not the main thread —
        # so this stop() from the test's main thread did)
        assert _signal.getsignal(_signal.SIGTERM) == prev_handler


# ---------------------------------------------------------------------------
# registry failure isolation: a bad rollout never takes down the old model
# ---------------------------------------------------------------------------


class TestRegistryIsolation:
    def test_load_failure_lands_broken_prior_version_keeps_serving(self):
        chaos = ServingChaos(ServingChaosConfig(load_fail_name="v2"))
        eng = ServingEngine(model=small_net(), max_wait_ms=5,
                            chaos=chaos).start()
        try:
            code, body, _ = _code_of(
                _post, eng.url, "/models",
                {"action": "load", "name": "v2", "path": "/nope.zip"}, 30)
            assert code == 400 and "injected load failure" in body["error"]
            # the failed rollout is AUDITABLE, not vanished: a broken
            # record with the error preserved
            models = {f"{d['name']}@v{d['version']}": d
                      for d in _get(eng.url, "/models")["models"]}
            assert models["v2@v1"]["state"] == "broken"
            assert "injected" in models["v2@v1"]["error"]
            # THE contract: the prior serving version is untouched
            out = _post(eng.url, "/predict",
                        {"record": [0.1, 0.2, 0.3, 0.4]}, 30)
            assert len(out["output"]) == 3
            h = _get(eng.url, "/health")
            assert h["ok"] and h["health"]["default@v1"] == "serving"
            assert h["health"]["v2@v1"] == "broken"
            assert eng.metrics()["serving"]["load_failures"] == 1
            # traffic explicitly aimed at the broken record sheds 503
            code, _, _ = _code_of(
                _post, eng.url, "/predict",
                {"record": [0.1] * 4, "model": "v2"}, 30)
            assert code == 503
        finally:
            eng.stop()

    def test_warmup_failure_isolates_and_serve_refuses(self):
        chaos = ServingChaos(ServingChaosConfig(warmup_fail_name="v2"))
        eng = ServingEngine(model=small_net(), max_wait_ms=5,
                            chaos=chaos).start()
        try:
            eng.registry.load("v2", model=small_net(seed=9),
                              input_shape=(4,))
            code, body, _ = _code_of(
                _post, eng.url, "/models",
                {"action": "warmup", "name": "v2", "max_batch": 4}, 30)
            assert code == 400 and "injected warmup failure" in body["error"]
            assert eng.registry.get("v2").state == "broken"
            # a broken record cannot be promoted onto traffic
            with pytest.raises(ValueError, match="refusing to serve"):
                eng.registry.serve("v2")
            assert eng.registry.default().key == "default@v1"
            out = _post(eng.url, "/predict",
                        {"record": [0.1, 0.2, 0.3, 0.4]}, 30)
            assert len(out["output"]) == 3
            assert eng.metrics()["serving"]["warmup_failures"] == 1
        finally:
            eng.stop()

    def test_warmup_rehabilitates_broken_record(self):
        """A record broken at warmup that later warms clean is
        rehabilitated (the operator's re-warm IS the probe)."""
        from deeplearning4j_tpu.serving import ModelRegistry

        net = small_net()
        state = {"fail": True}

        class Flaky:
            def output(self, x):
                if state["fail"]:
                    raise RuntimeError("first warmup dies")
                return net.output(x)

        reg = ModelRegistry()
        reg.load("m", model=Flaky(), input_shape=(4,))
        with pytest.raises(RuntimeError):
            reg.warmup("m", max_batch=2)
        rec = reg.get("m")
        assert rec.state == "broken" and "first warmup" in rec.error
        state["fail"] = False
        reg.warmup("m", max_batch=2)
        assert rec.state == "warm" and rec.error is None


# ---------------------------------------------------------------------------
# decode-slot crash: evicted + failed without poisoning co-residents
# ---------------------------------------------------------------------------


def tiny_lm(**over):
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    kw = dict(vocab_size=29, d_model=16, n_layers=2, n_heads=2, d_ff=32,
              max_len=32, use_flash=False)
    kw.update(over)
    return TransformerLM(TransformerConfig(**kw))


class TestSlotCrash:
    def test_crashed_admission_preserves_coresident_tokens(self):
        """The slot-independence contract under failure: admission k
        crashes, ONLY its future fails, and a co-resident's greedy
        tokens equal its solo baseline — the crash neither poisons the
        pool nor kills the decoder."""
        from deeplearning4j_tpu.serving.decode import ContinuousDecoder

        lm = tiny_lm()
        # admissions: 1 = solo baseline, 2 = the long co-resident,
        # 3 = the crasher
        chaos = ServingChaos(ServingChaosConfig(admit_raise_at=3))
        d = ContinuousDecoder(lm, slots=2, chaos=chaos)
        try:
            prompt = [1, 5, 2, 9]
            # solo baseline decoded first (admission 1 is clean)
            solo = d.generate(np.asarray([prompt]), 8, temperature=0.0)[0]
            long_fut = d.submit(prompt, 8, temperature=0.0)
            time.sleep(0.05)  # let admission 1 land before the crasher
            crash_fut = d.submit([3, 3, 4], 6, temperature=0.0)
            with pytest.raises(InjectedServingFault):
                crash_fut.result(timeout=60)
            cosched = long_fut.result(timeout=120)
            np.testing.assert_array_equal(solo, cosched)
            assert d.stats.slot_crashes == 1
            # the pool is still alive: a fresh prompt decodes fine
            again = d.generate(np.asarray([prompt]), 8, temperature=0.0)[0]
            np.testing.assert_array_equal(solo, again)
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# equivalence guard: the plane is accounting, never arithmetic
# ---------------------------------------------------------------------------


class TestEquivalence:
    def test_batcher_equals_direct_output_with_plane_armed(self):
        """DL4J_TPU_OBS=0 byte-equivalence (the acceptance criterion):
        with the watchdog armed and breakers live, batcher outputs remain
        byte-identical to direct net.output() — the resilience plane is
        host-side accounting around the dispatch, never inside it."""
        obs.set_enabled(False)
        try:
            net = small_net()
            eng = ServingEngine(model=net, max_wait_ms=60,
                                watchdog_s=10.0, breaker_fails=3).start()
            try:
                rng = np.random.default_rng(11)
                rows = rng.normal(size=(6, 4)).astype(np.float32)
                futs = [eng._batcher_for(eng.registry.default())
                        .submit(rows[i:i + 1]) for i in range(6)]
                got = np.concatenate([f.result(timeout=60) for f in futs])
                direct = np.asarray(net.output(rows))
                np.testing.assert_array_equal(got, direct)
            finally:
                eng.stop()
        finally:
            obs.set_enabled(None)


# ---------------------------------------------------------------------------
# conventions: ledger registration (PR 7) + bench-leg registration
# ---------------------------------------------------------------------------


class TestConventions:
    def test_serving_stats_ledger_carries_resilience_counters(self):
        """The breaker/watchdog/drain counters ride the engine's
        registered serving_stats ledger (the PR 7 registration
        convention) and flatten into the central Prometheus scrape."""
        from deeplearning4j_tpu.obs import registry as obs_registry

        eng = ServingEngine(model=small_net())
        try:
            reg = obs_registry.default_registry()
            assert reg.ledgers(eng).get("serving_stats") is eng.stats
            snap = eng.stats.snapshot()
            for key in ("breaker_opens", "breaker_closes", "fast_fails_503",
                        "wedged_batches", "watchdog_restarts",
                        "worker_deaths", "slot_crashes", "load_failures",
                        "warmup_failures", "drains_started",
                        "drains_completed"):
                assert key in snap, key
            page = reg.render_prometheus()
            assert "dl4j_serving_wedged_batches" in page
            assert "dl4j_serving_breaker_opens" in page
            assert "dl4j_serving_drains_started" in page
        finally:
            eng.stop(drain=False)

    def test_serving_resilience_leg_registered(self):
        """The serving_resilience bench leg is in the expected set — live
        parse of bench.py and the EXPECTED fallback — so the watcher's
        completeness check demands the overhead/recovery evidence row."""
        from scripts.bench_state import EXPECTED, expected_legs

        src = open(os.path.join(REPO, "bench.py")).read()
        legs_direct = re.findall(r'^\s*run\("([a-z0-9_]+)"', src, re.M)
        assert "serving_resilience" in legs_direct
        assert "serving_resilience" in EXPECTED
        assert "serving_resilience" in expected_legs()

    def test_chaos_never_ambient(self):
        """The zero-behavior-change contract: an engine WITHOUT a chaos
        object has no injection hook anywhere on its dispatch path."""
        eng = ServingEngine(model=small_net())
        try:
            assert eng.chaos is None
            assert eng.registry.chaos is None
            out = eng.predict(np.zeros((1, 4), np.float32))
            assert out.shape == (1, 3)
        finally:
            eng.stop()
