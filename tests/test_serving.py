"""Serving-engine tests: dynamic batching equivalence (the serving twin of
the distributed==serial convention), flow control (429/504), continuous
LM decode (slot independence, mid-loop admission), registry lifecycle,
and telemetry.

Reference anchors: the route being replaced
(dl4j-streaming/.../routes/DL4jServeRouteBuilder.java, one output() per
record) and the reference's route test (Dl4jServingRouteTest) — here the
equivalence bar is stronger: batcher outputs must be byte-identical to
direct ``net.output()`` rows for the same records (pad rows inert).
"""

import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serving import (
    DynamicBatcher,
    ModelRegistry,
    QueueFullError,
    RequestTimeoutError,
    ServingEngine,
    ServingStats,
)
from deeplearning4j_tpu.serving.registry import bucket_ladder


def small_net(seed=7, n_in=4, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.1)
            .list()
            .layer(0, DenseLayer(n_in=n_in, n_out=8, activation="tanh"))
            .layer(1, OutputLayer(n_in=8, n_out=n_out, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    net.fit(rng.normal(size=(32, n_in)).astype(np.float32),
            np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, 32)])
    return net


def _post(url, path, payload, timeout=60):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url, path, timeout=30):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return json.loads(r.read())


# ---------------------------------------------------------------------------
# DynamicBatcher core
# ---------------------------------------------------------------------------


class TestDynamicBatcher:
    def test_coalesces_and_matches_direct_output_bytes(self):
        """Rows submitted concurrently coalesce into ONE batch whose
        per-request outputs are byte-identical to direct net.output() on
        the same stacked records — the serving equivalence contract."""
        net = small_net()
        rng = np.random.default_rng(1)
        rows = rng.normal(size=(5, 4)).astype(np.float32)
        stats = ServingStats()
        b = DynamicBatcher(lambda x: np.asarray(net.output(x)),
                           max_batch=64, max_wait_ms=120, stats=stats)
        try:
            futs = [b.submit(rows[i:i + 1]) for i in range(5)]
            got = np.concatenate([f.result(timeout=60) for f in futs])
        finally:
            b.stop()
        direct = np.asarray(net.output(rows))
        # byte-identical: the batcher dispatched the same bucket-padded
        # program output() itself runs for this batch shape, and pad rows
        # are provably inert (test_pad_rows_inert below)
        np.testing.assert_array_equal(got, direct)
        assert stats.batches == 1  # coalesced, not 5 dispatches
        assert stats.batched_rows == 5
        # 5 rows pad to the 6-bucket (ops/dispatch.bucket_size)
        assert stats.padded_rows == 1
        assert stats.batch_fill_ratio() == pytest.approx(5 / 6, abs=1e-3)

    def test_pad_rows_inert(self):
        """The bucket pad rows the batcher's dispatch carries do not leak
        into real rows: a 5-row batch (padded to 6) returns the same bytes
        as the same 5 rows inside a full 6-row batch with a REAL 6th row."""
        net = small_net()
        rng = np.random.default_rng(2)
        six = rng.normal(size=(6, 4)).astype(np.float32)
        out_five = np.asarray(net.output(six[:5]))   # pads row 5 with zeros
        out_six = np.asarray(net.output(six))        # real row 5
        np.testing.assert_array_equal(out_five, out_six[:5])

    def test_bucket_full_flush_before_deadline(self):
        net = small_net()
        b = DynamicBatcher(lambda x: np.asarray(net.output(x)),
                           max_batch=4, max_wait_ms=10_000)
        try:
            t0 = time.monotonic()
            futs = [b.submit(np.zeros((1, 4), np.float32)) for _ in range(4)]
            for f in futs:
                f.result(timeout=60)
            # flushed on bucket-full, NOT after the 10s deadline
            assert time.monotonic() - t0 < 8.0
        finally:
            b.stop()

    def test_backpressure_queue_full(self):
        release = threading.Event()

        def slow(x):
            release.wait(timeout=30)
            return np.asarray(x)

        b = DynamicBatcher(slow, max_batch=2, max_wait_ms=1,
                           queue_capacity=3)
        try:
            futs = [b.submit(np.zeros((1, 2))) for _ in range(3)]
            # worker holds <=2 rows; queue holds the rest up to capacity 3
            with pytest.raises(QueueFullError):
                for _ in range(4):
                    futs.append(b.submit(np.zeros((1, 2))))
            assert b.stats.rejected >= 1
        finally:
            release.set()
            b.stop()

    def test_per_request_timeout(self):
        hold = threading.Event()

        def slow(x):
            hold.wait(timeout=30)
            return np.asarray(x)

        b = DynamicBatcher(slow, max_batch=1, max_wait_ms=1)
        try:
            b.submit(np.zeros((1, 2)))          # occupies the worker
            with pytest.raises(RequestTimeoutError):
                b.predict(np.zeros((1, 2)), timeout_s=0.2)
            assert b.stats.timeouts >= 1
        finally:
            hold.set()
            b.stop()

    def test_mixed_shape_requests_do_not_poison_batch(self):
        """A malformed (odd-shaped) request must fail alone: the worker
        splits the batch at a row-shape boundary instead of feeding one
        np.concatenate that would fail every request in the window."""
        b = DynamicBatcher(lambda x: np.asarray(x) * 2.0,
                           max_batch=8, max_wait_ms=60)
        try:
            fa = b.submit(np.ones((1, 4), np.float32))
            fb = b.submit(np.ones((2, 5), np.float32))  # different width
            fc = b.submit(np.full((1, 4), 3.0, np.float32))
            np.testing.assert_array_equal(fa.result(timeout=30),
                                          np.full((1, 4), 2.0))
            np.testing.assert_array_equal(fb.result(timeout=30),
                                          np.full((2, 5), 2.0))
            np.testing.assert_array_equal(fc.result(timeout=30),
                                          np.full((1, 4), 6.0))
        finally:
            b.stop()

    def test_oversize_request_admitted_when_idle(self):
        """A single request larger than queue_capacity passes through as
        its own batch on an idle server (a hard reject would 429 it
        forever — no amount of retrying shrinks the request)."""
        b = DynamicBatcher(lambda x: np.asarray(x), max_batch=4,
                           max_wait_ms=5, queue_capacity=8)
        try:
            out = b.predict(np.ones((16, 2), np.float32), timeout_s=30)
            assert out.shape == (16, 2)
        finally:
            b.stop()

    def test_timeout_counted_once(self):
        hold = threading.Event()

        def slow(x):
            hold.wait(timeout=30)
            return np.asarray(x)

        b = DynamicBatcher(slow, max_batch=1, max_wait_ms=1)
        try:
            b.submit(np.zeros((1, 2)))          # occupies the worker
            with pytest.raises(RequestTimeoutError):
                b.predict(np.zeros((1, 2)), timeout_s=0.2)
            assert b.stats.timeouts == 1  # not double-counted
        finally:
            hold.set()
            b.stop()

    def test_multi_row_requests_sliced_back(self):
        net = small_net()
        rng = np.random.default_rng(3)
        a = rng.normal(size=(2, 4)).astype(np.float32)
        c = rng.normal(size=(3, 4)).astype(np.float32)
        b = DynamicBatcher(lambda x: np.asarray(net.output(x)),
                           max_batch=16, max_wait_ms=80)
        try:
            fa, fc = b.submit(a), b.submit(c)
            ra, rc = fa.result(timeout=60), fc.result(timeout=60)
        finally:
            b.stop()
        direct = np.asarray(net.output(np.concatenate([a, c])))
        np.testing.assert_array_equal(ra, direct[:2])
        np.testing.assert_array_equal(rc, direct[2:5])


# ---------------------------------------------------------------------------
# Engine over HTTP: equivalence under concurrency, 429, metrics
# ---------------------------------------------------------------------------


class TestEngineHTTP:
    @pytest.fixture()
    def served(self):
        net = small_net()
        eng = ServingEngine(model=net, max_wait_ms=60).start()
        yield net, eng
        eng.stop()

    def test_concurrent_predicts_equal_direct_output(self, served):
        net, eng = served
        rng = np.random.default_rng(4)
        rows = rng.normal(size=(12, 4)).astype(np.float32)

        def one(i):
            out = _post(eng.url, "/predict",
                        {"record": rows[i].tolist()})["output"]
            return np.asarray(out, np.float32)

        with ThreadPoolExecutor(max_workers=12) as ex:
            got = np.stack(list(ex.map(one, range(12))))
        # each concurrent request's floats equal its row of a direct
        # output() on the same records (JSON round-trips f32 exactly)
        direct = np.asarray(net.output(rows), np.float32)
        np.testing.assert_array_equal(got, direct)
        m = eng.metrics()["serving"]
        assert m["requests"] == 12 and m["completed"] == 12
        assert m["batches"] <= 12  # at least some coalescing happened
        assert m["latency_ms"]["p50"] is not None

    def test_http_429_on_queue_full(self):
        release = threading.Event()

        class Slow:
            def output(self, x):
                release.wait(timeout=30)
                return np.asarray(x)

        eng = ServingEngine(model=Slow(), max_batch=1, max_wait_ms=1,
                            queue_capacity=1).start()
        try:
            with ThreadPoolExecutor(max_workers=6) as ex:
                futs = [ex.submit(_post, eng.url, "/predict",
                                  {"record": [0.0, 0.0]}, 30)
                        for _ in range(6)]
                time.sleep(0.5)
                release.set()
                codes = []
                for f in futs:
                    try:
                        f.result()
                        codes.append(200)
                    except urllib.error.HTTPError as e:
                        codes.append(e.code)
            assert 429 in codes  # backpressure reached the wire
        finally:
            release.set()
            eng.stop()

    def test_metrics_endpoint_shape(self, served):
        net, eng = served
        _post(eng.url, "/predict", {"record": [0.1, 0.2, 0.3, 0.4]})
        m = _get(eng.url, "/metrics")
        s = m["serving"]
        for key in ("requests", "completed", "rejected_429", "timeouts",
                    "latency_ms", "batch_fill_ratio", "queue_depth"):
            assert key in s
        assert m["models"][0]["state"] == "serving"
        # per-model dispatch_stats ride along (traces == XLA compiles)
        assert m["models"][0]["dispatch_stats"]["calls"]["output"] >= 1

    def test_health_lists_models(self, served):
        net, eng = served
        h = _get(eng.url, "/health")
        assert h["ok"] and "MultiLayerNetwork" in h["model"]
        assert h["models"] == ["default@v1"]


# ---------------------------------------------------------------------------
# Model registry lifecycle
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_bucket_ladder(self):
        assert bucket_ladder(64) == [1, 2, 3, 4, 6, 8, 12, 16, 24, 32,
                                     48, 64]

    def test_load_warmup_serve_unload(self):
        reg = ModelRegistry()
        net = small_net()
        rec = reg.load("iris", model=net, input_shape=(4,))
        assert rec.state == "loaded" and rec.version == 1
        report = reg.warmup("iris", max_batch=8)
        assert report["buckets"] == [1, 2, 3, 4, 6, 8]
        assert reg.get("iris").state == "warm"
        # warmup compiled one program per bucket; a post-warmup request at
        # any size <= max_batch is a compiled-cache hit, not a trace
        traces = dict(net.dispatch_stats.traces)
        np.asarray(net.output(np.zeros((5, 4), np.float32)))  # pads to 6
        assert net.dispatch_stats.traces == traces
        reg.serve("iris")
        assert reg.get("iris").state == "serving"
        assert reg.default().key == "iris@v1"
        reg.unload("iris")
        assert reg.get("iris").state == "unloaded"
        assert reg.get("iris").model is None and reg.default() is None

    def test_versioning_and_serve_switch(self):
        reg = ModelRegistry()
        r1 = reg.load("m", model=small_net(seed=1), input_shape=(4,))
        r2 = reg.load("m", model=small_net(seed=2), input_shape=(4,))
        assert (r1.version, r2.version) == (1, 2)
        reg.serve("m", 1)
        assert reg.default().version == 1
        reg.serve("m", 2)
        assert reg.default().version == 2
        assert reg.get("m", 1).state == "warm"  # demoted, still loaded

    def test_engine_models_endpoint_lifecycle(self, tmp_path):
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        net = small_net()
        p = str(tmp_path / "m.zip")
        ModelSerializer.write_model(net, p)
        eng = ServingEngine(model=net, input_shape=(4,)).start()
        try:
            out = _post(eng.url, "/models",
                        {"action": "load", "name": "v2", "path": p,
                         "input_shape": [4]})
            assert out["state"] == "loaded" and out["version"] == 1
            out = _post(eng.url, "/models",
                        {"action": "warmup", "name": "v2", "max_batch": 4})
            assert out["buckets"] == [1, 2, 3, 4]
            _post(eng.url, "/models", {"action": "serve", "name": "v2"})
            assert _get(eng.url, "/models")["default"] == "v2@v1"
            # traffic with an explicit model key still reaches default@v1
            out = _post(eng.url, "/predict",
                        {"record": [0.1, 0.2, 0.3, 0.4],
                         "model": "default"})
            assert len(out["output"]) == 3
            out = _post(eng.url, "/models", {"action": "unload",
                                             "name": "v2"})
            assert out["state"] == "unloaded"
            with pytest.raises(urllib.error.HTTPError):
                _post(eng.url, "/predict", {"record": [0.1] * 4,
                                            "model": "v2"})
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# Continuous LM decode
# ---------------------------------------------------------------------------


def tiny_lm(**over):
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    kw = dict(vocab_size=29, d_model=16, n_layers=2, n_heads=2, d_ff=32,
              max_len=32, use_flash=False)
    kw.update(over)
    return TransformerLM(TransformerConfig(**kw))


class TestContinuousDecode:
    def test_decode_step_slots_matches_decode_step(self):
        """Uniform per-slot positions reduce decode_step_slots to the
        scalar-pos decode_step (models/transformer.py:710) exactly."""
        import jax.numpy as jnp

        from deeplearning4j_tpu.models.transformer import (
            decode_step,
            prefill_cache,
        )
        from deeplearning4j_tpu.serving.decode import decode_step_slots

        lm = tiny_lm()
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(0, 29, (3, 8)), jnp.int32)
        cache, _ = prefill_cache(lm.params, toks, lm.cfg)
        tok = jnp.asarray(toks[:, -1])
        c1, l1 = decode_step(lm.params, cache, tok,
                             jnp.asarray(7, jnp.int32), lm.cfg)
        c2, l2 = decode_step_slots(lm.params, cache, tok,
                                   jnp.full((3,), 7, jnp.int32), lm.cfg)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(c1["k"]), np.asarray(c2["k"]),
                                   rtol=1e-5, atol=1e-6)

    def test_solo_equals_coscheduled_greedy(self):
        """A sequence's greedy tokens do not depend on which other
        sequences share the slot pool — slot independence, the serving
        twin of distributed==serial."""
        from deeplearning4j_tpu.serving.decode import ContinuousDecoder

        lm = tiny_lm()
        d = ContinuousDecoder(lm, slots=3)
        try:
            prompt = [1, 5, 2, 9]
            solo = d.generate(np.asarray([prompt]), 6, temperature=0.0)[0]
            futs = [d.submit(prompt, 6, temperature=0.0),
                    d.submit([3, 3, 4], 10, temperature=0.0),
                    d.submit([7, 1], 4, temperature=0.0)]
            cosched = futs[0].result(timeout=120)
            for f in futs[1:]:
                f.result(timeout=120)
        finally:
            d.stop()
        np.testing.assert_array_equal(solo, cosched)

    def test_mid_loop_admission_and_eviction(self):
        """A long generation keeps running while short prompts are
        admitted into freed slots mid-loop; everyone completes and the
        long sequence is unaffected by churn around it."""
        from deeplearning4j_tpu.serving.decode import ContinuousDecoder

        lm = tiny_lm()
        d = ContinuousDecoder(lm, slots=2)
        try:
            baseline = d.generate(np.asarray([[2, 4, 6]]), 16,
                                  temperature=0.0)[0]
            long_fut = d.submit([2, 4, 6], 16, temperature=0.0)
            # staggered short requests churn the second slot while the
            # long one runs (each eviction frees the slot for the next)
            shorts = []
            for i in range(3):
                time.sleep(0.05)
                shorts.append(d.submit([i + 1, i + 2], 3, temperature=0.0))
            long_toks = long_fut.result(timeout=180)
            for s in shorts:
                out = s.result(timeout=180)
                assert out.shape == (3,)
            assert d.stats.generated_tokens >= 16 + 9
        finally:
            d.stop()
        np.testing.assert_array_equal(baseline, long_toks)

    def test_seed_determinism_under_pool(self):
        """Sampling is a function of the request's own seed, not of pool
        scheduling: same seed twice -> same tokens."""
        from deeplearning4j_tpu.serving.decode import ContinuousDecoder

        lm = tiny_lm()
        d = ContinuousDecoder(lm, slots=2)
        try:
            a = d.submit([4, 4, 8], 8, temperature=0.9, seed=123)
            b = d.submit([4, 4, 8], 8, temperature=0.9, seed=123)
            c = d.submit([4, 4, 8], 8, temperature=0.9, seed=124)
            ra, rb, rc = (f.result(timeout=120) for f in (a, b, c))
        finally:
            d.stop()
        np.testing.assert_array_equal(ra, rb)
        assert not np.array_equal(ra, rc)  # different seed, different draw

    def test_generate_endpoint_uses_continuous_path(self):
        lm = tiny_lm()
        eng = ServingEngine(model=lm).start()
        try:
            out = _post(eng.url, "/generate",
                        {"tokens": [[1, 2, 3], [4, 5, 6]], "n_new": 5,
                         "temperature": 0.7, "seed": 3}, timeout=180)
            toks = np.asarray(out["tokens"])
            assert toks.shape == (2, 5)
            assert ((0 <= toks) & (toks < 29)).all()
            assert "default@v1" in eng._decoders  # continuous path taken
            assert eng.metrics()["serving"]["generated_tokens"] >= 10
            # static top_k filter routes to lm.generate (per-call compile)
            out = _post(eng.url, "/generate",
                        {"tokens": [[1, 2, 3]], "n_new": 4, "top_k": 5},
                        timeout=180)
            assert len(out["tokens"][0]) == 4
        finally:
            eng.stop()

    def test_moe_and_mesh_fall_back(self):
        from deeplearning4j_tpu.serving.decode import ContinuousDecoder

        moe_lm = tiny_lm(moe_experts=2, d_ff=16)
        with pytest.raises(ValueError):
            ContinuousDecoder(moe_lm)
        eng = ServingEngine(model=moe_lm).start()
        try:
            out = _post(eng.url, "/generate",
                        {"tokens": [[1, 2]], "n_new": 3}, timeout=180)
            assert len(out["tokens"][0]) == 3
            assert eng._decoders == {}  # fell back to lm.generate
        finally:
            eng.stop()
