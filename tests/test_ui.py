"""UI tests — mirrors the reference UI test strategy (SURVEY.md section 4:
TestComponentSerialization, TestRendering, ApiTest server smoke)."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import (
    ChartHistogram,
    ChartHorizontalBar,
    ChartLine,
    ChartScatter,
    ChartStackedArea,
    ChartTimeline,
    ComponentTable,
    ComponentText,
    FlowIterationListener,
    HistogramIterationListener,
    HistoryStorage,
    UiServer,
    component_from_dict,
    render_page,
)


def all_components():
    line = ChartLine(title="L").add_series("a", [0, 1, 2], [1.0, 0.5, 0.2])
    line.add_series("b", [0, 1, 2], [0.2, 0.3, 0.4])
    scatter = ChartScatter(title="S").add_series("pts", [0, 1], [1, 0])
    hist = ChartHistogram(title="H").add_bin(0, 1, 5).add_bin(1, 2, 3)
    stacked = ChartStackedArea(title="SA")
    stacked.add_series("x", [0, 1, 2], [1, 1, 1])
    stacked.add_series("y", [0, 1, 2], [2, 1, 0.5])
    bars = ChartHorizontalBar(title="B").add_bar("w", 3.0).add_bar("b", 1.5)
    tl = ChartTimeline(title="T").add_lane("w0", [(0, 10, "fit"), (10, 12, "avg")])
    table = ComponentTable(title="tab", header=["a", "b"], rows=[["1", "2"]])
    text = ComponentText(title="", text="hello")
    return [line, scatter, hist, stacked, bars, tl, table, text]


class TestComponentSerde:
    def test_json_roundtrip_all(self):
        for comp in all_components():
            d = json.loads(comp.to_json())
            restored = component_from_dict(d)
            assert restored.to_dict() == comp.to_dict(), type(comp).__name__

    def test_render_all_produce_markup(self):
        for comp in all_components():
            markup = comp.render()
            assert ("<svg" in markup) or ("<table" in markup) or ("<p" in markup)

    def test_static_page_export(self, tmp_path):
        page = render_page(all_components(), title="export test")
        assert page.count("<svg") >= 6
        assert "export test" in page
        # self-contained: no external scripts/stylesheets/images
        assert "<script" not in page and "<link" not in page
        assert "src=" not in page


class TestUiServer:
    @pytest.fixture()
    def server(self):
        s = UiServer(port=0).start()
        yield s
        s.stop()

    def _post(self, server, payload):
        req = urllib.request.Request(
            server.url + "/train/update",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status

    def test_post_and_summary(self, server):
        assert self._post(server, {"type": "score", "iteration": 0,
                                   "score": 1.5}) == 200
        with urllib.request.urlopen(server.url + "/train/summary", timeout=5) as r:
            summary = json.loads(r.read())
        assert summary["score"]["score"] == 1.5

    def test_dashboard_renders(self, server):
        self._post(server, {"type": "score", "iteration": 0, "score": 2.0})
        self._post(server, {"type": "score", "iteration": 1, "score": 1.0})
        with urllib.request.urlopen(server.url + "/", timeout=5) as r:
            page = r.read().decode()
        assert "Score vs iteration" in page and "<svg" in page

    def test_404(self, server):
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(server.url + "/nope", timeout=5)


def small_net():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(1)
        .learning_rate(0.1)
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


class TestListeners:
    def test_histogram_listener_local_storage(self):
        net = small_net()
        listener = HistogramIterationListener(frequency=1)
        net.set_listeners(listener)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(x, y)
        hist = listener.storage.latest("histogram")
        assert hist is not None
        assert "0_W" in hist["params"]
        assert len(hist["params"]["0_W"]["counts"]) == 20

    def test_flow_listener_topology(self):
        net = small_net()
        listener = FlowIterationListener(frequency=1)
        net.set_listeners(listener)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        net.fit(x, y)
        flow = listener.storage.latest("flow")
        assert [l["layer_type"] for l in flow["layers"]] == [
            "DenseLayer", "OutputLayer",
        ]

    def test_listener_posts_to_server(self):
        server = UiServer(port=0).start()
        try:
            net = small_net()
            net.set_listeners(
                HistogramIterationListener(frequency=1, server_url=server.url)
            )
            rng = np.random.default_rng(0)
            x = rng.normal(size=(8, 4)).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
            net.fit(x, y)
            with urllib.request.urlopen(server.url + "/train/summary",
                                        timeout=5) as r:
                summary = json.loads(r.read())
            assert "histogram" in summary and "score" in summary
            with urllib.request.urlopen(server.url + "/", timeout=5) as r:
                page = r.read().decode()
            assert "<svg" in page
        finally:
            server.stop()
