"""UI tests — mirrors the reference UI test strategy (SURVEY.md section 4:
TestComponentSerialization, TestRendering, ApiTest server smoke)."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import (
    ChartHistogram,
    ChartHorizontalBar,
    ChartLine,
    ChartScatter,
    ChartStackedArea,
    ChartTimeline,
    ComponentImage,
    ComponentTable,
    ComponentText,
    FlowIterationListener,
    HistogramIterationListener,
    HistoryStorage,
    UiServer,
    component_from_dict,
    render_page,
)


def all_components():
    line = ChartLine(title="L").add_series("a", [0, 1, 2], [1.0, 0.5, 0.2])
    line.add_series("b", [0, 1, 2], [0.2, 0.3, 0.4])
    scatter = ChartScatter(title="S").add_series("pts", [0, 1], [1, 0])
    hist = ChartHistogram(title="H").add_bin(0, 1, 5).add_bin(1, 2, 3)
    stacked = ChartStackedArea(title="SA")
    stacked.add_series("x", [0, 1, 2], [1, 1, 1])
    stacked.add_series("y", [0, 1, 2], [2, 1, 0.5])
    bars = ChartHorizontalBar(title="B").add_bar("w", 3.0).add_bar("b", 1.5)
    tl = ChartTimeline(title="T").add_lane("w0", [(0, 10, "fit"), (10, 12, "avg")])
    table = ComponentTable(title="tab", header=["a", "b"], rows=[["1", "2"]])
    text = ComponentText(title="", text="hello")
    img = ComponentImage.from_array(
        np.linspace(0, 1, 16).reshape(4, 4), title="filters", scale=8)
    return [line, scatter, hist, stacked, bars, tl, table, text, img]


class TestComponentSerde:
    def test_json_roundtrip_all(self):
        for comp in all_components():
            d = json.loads(comp.to_json())
            restored = component_from_dict(d)
            assert restored.to_dict() == comp.to_dict(), type(comp).__name__

    def test_render_all_produce_markup(self):
        for comp in all_components():
            markup = comp.render()
            assert ("<svg" in markup) or ("<table" in markup) \
                or ("<p" in markup) or ("<img" in markup)

    def test_static_page_export(self, tmp_path):
        page = render_page(all_components(), title="export test")
        assert page.count("<svg") >= 6
        assert "export test" in page
        # self-contained: no external scripts/stylesheets/images (inline
        # data: URIs — ComponentImage — are fine; http(s) refs are not)
        assert "<script" not in page and "<link" not in page
        assert 'src="http' not in page
        assert page.count('src="data:image/png;base64,') == 1


class TestUiServer:
    @pytest.fixture()
    def server(self):
        s = UiServer(port=0).start()
        yield s
        s.stop()

    def _post(self, server, payload):
        req = urllib.request.Request(
            server.url + "/train/update",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status

    def test_post_and_summary(self, server):
        assert self._post(server, {"type": "score", "iteration": 0,
                                   "score": 1.5}) == 200
        with urllib.request.urlopen(server.url + "/train/summary", timeout=5) as r:
            summary = json.loads(r.read())
        assert summary["score"]["score"] == 1.5

    def test_dashboard_renders(self, server):
        self._post(server, {"type": "score", "iteration": 0, "score": 2.0})
        self._post(server, {"type": "score", "iteration": 1, "score": 1.0})
        with urllib.request.urlopen(server.url + "/", timeout=5) as r:
            page = r.read().decode()
        assert "Score vs iteration" in page and "<svg" in page

    def test_404(self, server):
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(server.url + "/nope", timeout=5)


def small_net():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(1)
        .learning_rate(0.1)
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


class TestListeners:
    def test_histogram_listener_local_storage(self):
        net = small_net()
        listener = HistogramIterationListener(frequency=1)
        net.set_listeners(listener)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(x, y)
        hist = listener.storage.latest("histogram")
        assert hist is not None
        assert "0_W" in hist["params"]
        assert len(hist["params"]["0_W"]["counts"]) == 20

    def test_flow_listener_topology(self):
        net = small_net()
        listener = FlowIterationListener(frequency=1)
        net.set_listeners(listener)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        net.fit(x, y)
        flow = listener.storage.latest("flow")
        assert [l["layer_type"] for l in flow["layers"]] == [
            "DenseLayer", "OutputLayer",
        ]

    def test_listener_posts_to_server(self):
        server = UiServer(port=0).start()
        try:
            net = small_net()
            net.set_listeners(
                HistogramIterationListener(frequency=1, server_url=server.url)
            )
            rng = np.random.default_rng(0)
            x = rng.normal(size=(8, 4)).astype(np.float32)
            y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
            net.fit(x, y)
            with urllib.request.urlopen(server.url + "/train/summary",
                                        timeout=5) as r:
                summary = json.loads(r.read())
            assert "histogram" in summary and "score" in summary
            with urllib.request.urlopen(server.url + "/", timeout=5) as r:
                page = r.read().decode()
            assert "<svg" in page
        finally:
            server.stop()


# ------------------------------------------------------- explorer resources
class TestExplorers:
    """t-SNE scatter + VPTree nearest-neighbors explorers (reference
    TsneResource.java / NearestNeighborsResource.java; VERDICT round-1
    missing #5)."""

    def _post(self, url, path, obj):
        import json as _json
        import urllib.request

        req = urllib.request.Request(
            url + path, data=_json.dumps(obj).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return _json.loads(r.read())

    def _get(self, url, path):
        import json as _json
        import urllib.request

        with urllib.request.urlopen(url + path, timeout=10) as r:
            body = r.read()
            ctype = r.headers.get("Content-Type", "")
        return _json.loads(body) if "json" in ctype else body.decode()

    @pytest.fixture()
    def server(self):
        s = UiServer().start()
        yield s
        s.stop()

    def _embeddings(self, n=30, d=8, clusters=2):
        rng = np.random.default_rng(0)
        words, vecs = [], []
        for c in range(clusters):
            center = rng.standard_normal(d) * 5
            for i in range(n // clusters):
                words.append(f"c{c}_w{i}")
                vecs.append(center + 0.1 * rng.standard_normal(d))
        return words, np.asarray(vecs, np.float32).tolist()

    def test_nearest_neighbors_round_trip(self, server):
        words, vecs = self._embeddings()
        res = self._post(server.url, "/word2vec/upload",
                         {"words": words, "vectors": vecs})
        assert res["words"] == len(words)
        vocab = self._get(server.url, "/word2vec/words")
        assert vocab["words"] == words
        out = self._post(server.url, "/word2vec/nearest",
                         {"word": "c0_w0", "k": 5})
        names = [n["word"] for n in out["neighbors"]]
        assert len(names) == 5
        assert all(n.startswith("c0_") for n in names), names
        assert "c0_w0" not in names  # query word excluded
        # query by raw vector too
        out2 = self._post(server.url, "/word2vec/nearest",
                          {"vector": vecs[0], "k": 3})
        assert len(out2["neighbors"]) == 3

    def test_nearest_unknown_word_400(self, server):
        import urllib.error

        self._post(server.url, "/word2vec/upload",
                   {"words": ["a", "b"], "vectors": [[1, 0], [0, 1]]})
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(server.url, "/word2vec/nearest", {"word": "zzz"})
        assert ei.value.code == 400

    def test_tsne_upload_and_render(self, server):
        words, vecs = self._embeddings(n=24)
        res = self._post(server.url, "/tsne/upload",
                         {"words": words, "vectors": vecs,
                          "iterations": 50})
        assert res["points"] == len(words)
        coords = self._get(server.url, "/tsne/coords")
        assert len(coords["coords"]) == len(words)
        assert all(len(c) == 2 for c in coords["coords"])
        page = self._get(server.url, "/tsne")
        assert "svg" in page.lower()

    def test_tsne_update_precomputed(self, server):
        self._post(server.url, "/tsne/update",
                   {"words": ["x", "y"], "coords": [[0, 1], [2, 3]]})
        coords = self._get(server.url, "/tsne/coords")
        assert coords == {"words": ["x", "y"], "coords": [[0.0, 1.0], [2.0, 3.0]]}
        page = self._get(server.url, "/tsne")
        assert "svg" in page.lower()
