"""Layer unit tests: tiny fixed inputs, numpy-verified forwards
(reference pattern: ConvolutionLayerTest, GravesLSTMTest,
BatchNormalizationTest, EmbeddingLayerTest — SURVEY.md section 4)."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    EmbeddingLayer,
    GravesBidirectionalLSTM,
    GravesLSTM,
    GRU,
    LocalResponseNormalization,
    SubsamplingLayer,
    resolve,
)
from deeplearning4j_tpu.nn.layers.factory import create_layer

KEY = jax.random.PRNGKey(0)


def build(conf, input_shape):
    layer = create_layer(resolve(conf))
    params, state, out_shape = layer.initialize(KEY, input_shape)
    return layer, params, state, out_shape


def test_dense_forward_matches_numpy():
    layer, params, state, out_shape = build(
        DenseLayer(n_in=3, n_out=4, activation="tanh"), (3,)
    )
    x = np.random.default_rng(0).standard_normal((5, 3)).astype(np.float32)
    y, _ = layer.apply(params, state, jnp.asarray(x))
    expected = np.tanh(x @ np.asarray(params["W"]) + np.asarray(params["b"]))
    np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-5)
    assert out_shape == (4,)


def test_dense_dropout_train_vs_inference():
    layer, params, state, _ = build(
        DenseLayer(n_in=10, n_out=10, activation="identity", dropout=0.5), (10,)
    )
    x = jnp.ones((4, 10))
    y_inf, _ = layer.apply(params, state, x, train=False)
    y_tr, _ = layer.apply(params, state, x, train=True, rng=jax.random.PRNGKey(1))
    assert not np.allclose(np.asarray(y_inf), np.asarray(y_tr))


def test_conv_shapes_and_identity_kernel():
    layer, params, state, out_shape = build(
        ConvolutionLayer(
            n_in=1, n_out=1, kernel_size=(3, 3), stride=(1, 1), padding=(1, 1),
            activation="identity", weight_init="zero",
        ),
        (5, 5, 1),
    )
    assert out_shape == (5, 5, 1)
    # delta kernel -> identity map
    W = np.zeros((3, 3, 1, 1), np.float32)
    W[1, 1, 0, 0] = 1.0
    params = {"W": jnp.asarray(W), "b": params["b"]}
    x = np.random.default_rng(0).standard_normal((2, 5, 5, 1)).astype(np.float32)
    y, _ = layer.apply(params, state, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(y), x, rtol=1e-5, atol=1e-6)


def test_conv_stride_no_padding_shape():
    _, _, _, out_shape = build(
        ConvolutionLayer(n_in=1, n_out=6, kernel_size=(5, 5), stride=(1, 1)),
        (28, 28, 1),
    )
    assert out_shape == (24, 24, 6)


def test_max_pooling_values():
    layer, params, state, out_shape = build(
        SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)),
        (4, 4, 1),
    )
    assert out_shape == (2, 2, 1)
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    y, _ = layer.apply(params, state, x)
    np.testing.assert_allclose(
        np.asarray(y)[0, :, :, 0], [[5.0, 7.0], [13.0, 15.0]]
    )


def test_avg_pooling_values():
    layer, params, state, _ = build(
        SubsamplingLayer(pooling_type="avg", kernel_size=(2, 2), stride=(2, 2)),
        (2, 2, 1),
    )
    x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]]).reshape(1, 2, 2, 1)
    y, _ = layer.apply(params, state, x)
    np.testing.assert_allclose(np.asarray(y).ravel(), [2.5])


def test_batchnorm_normalizes_and_tracks_stats():
    layer, params, state, _ = build(BatchNormalization(), (8,))
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((64, 8)) * 5 + 3.0
    )
    y, new_state = layer.apply(params, state, x, train=True)
    assert abs(float(jnp.mean(y))) < 0.1
    assert abs(float(jnp.std(y)) - 1.0) < 0.1
    # running stats moved toward batch stats
    assert float(jnp.max(jnp.abs(new_state["mean"]))) > 0
    # inference path uses running stats (different result than train path)
    y_inf, st2 = layer.apply(params, new_state, x, train=False)
    assert np.all(np.asarray(st2["mean"]) == np.asarray(new_state["mean"]))


def test_lrn_shape_preserved():
    layer, params, state, out_shape = build(
        LocalResponseNormalization(), (6, 6, 10)
    )
    assert out_shape == (6, 6, 10)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 6, 6, 10)))
    y, _ = layer.apply(params, state, x)
    assert y.shape == x.shape
    # normalization shrinks magnitudes
    assert float(jnp.max(jnp.abs(y))) <= float(jnp.max(jnp.abs(x)))


def test_embedding_lookup():
    layer, params, state, _ = build(
        EmbeddingLayer(n_in=7, n_out=4, activation="identity"), (1,)
    )
    idx = jnp.asarray([[0], [3], [6]])
    y, _ = layer.apply(params, state, idx)
    expected = np.asarray(params["W"])[[0, 3, 6]] + np.asarray(params["b"])
    np.testing.assert_allclose(np.asarray(y), expected, rtol=1e-6)


def test_lstm_forward_shapes_and_forget_bias():
    layer, params, state, out_shape = build(
        GravesLSTM(n_in=3, n_out=5, activation="tanh"), (-1, 3)
    )
    assert out_shape == (-1, 5)
    b = np.asarray(params["b"])
    np.testing.assert_allclose(b[5:10], np.ones(5))  # forget gate bias = 1
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 7, 3)))
    y, st = layer.apply(params, state, x)
    assert y.shape == (2, 7, 5)
    assert st["h"].shape == (2, 5) and st["c"].shape == (2, 5)


def test_lstm_masking_freezes_state_and_zeroes_output():
    layer, params, state, _ = build(
        GravesLSTM(n_in=3, n_out=4, activation="tanh"), (-1, 3)
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((1, 6, 3)).astype(np.float32))
    mask = jnp.asarray([[1, 1, 1, 0, 0, 0]], dtype=np.float32)
    y, st = layer.apply(params, state, x, mask=mask)
    np.testing.assert_allclose(np.asarray(y)[0, 3:], 0.0)
    # state after masked tail == state at t=2
    y3, st3 = layer.apply(params, state, x[:, :3])
    np.testing.assert_allclose(np.asarray(st["h"]), np.asarray(st3["h"]), rtol=1e-5)


def test_lstm_step_matches_scan():
    layer, params, state, _ = build(
        GravesLSTM(n_in=3, n_out=4, activation="tanh"), (-1, 3)
    )
    x = jnp.asarray(np.random.default_rng(1).standard_normal((2, 5, 3)).astype(np.float32))
    y_scan, _ = layer.apply(params, state, x)
    st = state
    outs = []
    for t in range(5):
        o, st = layer.step(params, st, x[:, t])
        outs.append(o)
    y_step = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(y_scan), np.asarray(y_step), rtol=1e-5, atol=1e-6)


def test_bidirectional_lstm_uses_future_context():
    layer, params, state, _ = build(
        GravesBidirectionalLSTM(n_in=2, n_out=3, activation="tanh"), (-1, 2)
    )
    rng = np.random.default_rng(0)
    x1 = rng.standard_normal((1, 5, 2)).astype(np.float32)
    x2 = x1.copy()
    x2[0, 4] += 1.0  # change only the LAST timestep
    y1, _ = layer.apply(params, state, jnp.asarray(x1))
    y2, _ = layer.apply(params, state, jnp.asarray(x2))
    # output at t=0 must differ (backward pass sees the future)
    assert not np.allclose(np.asarray(y1)[0, 0], np.asarray(y2)[0, 0])


def test_gru_shapes_and_step_consistency():
    layer, params, state, out_shape = build(
        GRU(n_in=3, n_out=4, activation="tanh"), (-1, 3)
    )
    assert out_shape == (-1, 4)
    x = jnp.asarray(np.random.default_rng(2).standard_normal((2, 6, 3)).astype(np.float32))
    y_scan, _ = layer.apply(params, state, x)
    st = state
    outs = []
    for t in range(6):
        o, st = layer.step(params, st, x[:, t])
        outs.append(o)
    np.testing.assert_allclose(
        np.asarray(y_scan), np.asarray(jnp.stack(outs, axis=1)), rtol=1e-5, atol=1e-6
    )


def test_lstm_carry_state_resumes():
    """TBPTT window chaining: two half-windows with carry == one full window
    (reference doTruncatedBPTT state carry)."""
    layer, params, state, _ = build(
        GravesLSTM(n_in=3, n_out=4, activation="tanh"), (-1, 3)
    )
    x = jnp.asarray(np.random.default_rng(3).standard_normal((2, 8, 3)).astype(np.float32))
    y_full, _ = layer.apply(params, state, x)
    y1, st1 = layer.apply(params, state, x[:, :4])
    y2, _ = layer.apply(params, st1, x[:, 4:], carry_state=True)
    np.testing.assert_allclose(
        np.asarray(y_full), np.asarray(jnp.concatenate([y1, y2], axis=1)),
        rtol=1e-5, atol=1e-6,
    )


def test_gru_carry_state_resumes():
    layer, params, state, _ = build(GRU(n_in=3, n_out=4, activation="tanh"), (-1, 3))
    x = jnp.asarray(np.random.default_rng(4).standard_normal((2, 8, 3)).astype(np.float32))
    y_full, _ = layer.apply(params, state, x)
    y1, st1 = layer.apply(params, state, x[:, :4])
    y2, _ = layer.apply(params, st1, x[:, 4:], carry_state=True)
    np.testing.assert_allclose(
        np.asarray(y_full), np.asarray(jnp.concatenate([y1, y2], axis=1)),
        rtol=1e-5, atol=1e-6,
    )


def test_tbptt_backprop_window_truncates_input_grads():
    """backprop_window=B: gradients flow only through the last B timesteps
    (reference LSTMHelpers.backpropGradientHelper:255 endIdx truncation);
    earlier steps contribute values but zero gradient."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, GRU
    from deeplearning4j_tpu.nn.layers.factory import create_layer

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.random((2, 6, 4)).astype(np.float32))
    for conf in (
        GravesLSTM(n_in=4, n_out=3, activation="tanh", weight_init="xavier"),
        GRU(n_in=4, n_out=3, activation="tanh", weight_init="xavier"),
    ):
        layer = create_layer(conf)
        params, state, _ = layer.initialize(jax.random.PRNGKey(0), (6, 4))

        def loss(xx, bw):
            y, _ = layer.apply(params, state, xx, backprop_window=bw)
            return jnp.sum(y * y)

        g_full = jax.grad(lambda xx: loss(xx, None))(x)
        g_trunc = jax.grad(lambda xx: loss(xx, 2))(x)
        # early-step input grads are exactly zero under truncation
        np.testing.assert_array_equal(np.asarray(g_trunc[:, :4]), 0.0)
        assert np.abs(np.asarray(g_trunc[:, 4:])).max() > 0
        # full-window grads are generally nonzero at early steps
        assert np.abs(np.asarray(g_full[:, :4])).max() > 0
        # forward values are unchanged by the truncation
        y_full, _ = layer.apply(params, state, x)
        y_trunc, _ = layer.apply(params, state, x, backprop_window=2)
        np.testing.assert_allclose(
            np.asarray(y_full), np.asarray(y_trunc), rtol=1e-6
        )
