"""Network integration tests (reference MultiLayerTest/BackPropMLPTest
pattern: small nets on Iris/synthetic, assert score decreases, evaluation,
serialization round-trip — SURVEY.md section 4)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.fetchers import IrisDataSetIterator, load_iris
from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator,
    DataSet,
    ListDataSetIterator,
)
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import CollectScoresIterationListener
from deeplearning4j_tpu.utils.serialization import ModelSerializer


def iris_net(seed=42, lr=0.1, updater="sgd"):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(lr)
        .updater(updater)
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=10, activation="tanh"))
        .layer(
            1,
            OutputLayer(
                n_in=10, n_out=3, activation="softmax", loss_function="mcxent"
            ),
        )
        .build()
    )
    return MultiLayerNetwork(conf).init()


def test_score_decreases_on_iris():
    net = iris_net()
    x, y = load_iris()
    s0 = net.score(x, y)
    for _ in range(30):
        net.fit(x, y)
    s1 = net.score(x, y)
    assert s1 < s0 * 0.7, f"score did not decrease enough: {s0} -> {s1}"


def test_iris_accuracy_after_training():
    net = iris_net(updater="adam", lr=0.05)
    it = IrisDataSetIterator(batch=50)
    net.fit_iterator(it, num_epochs=60)
    ev = net.evaluate(it)
    assert ev.accuracy() > 0.9, ev.stats()


def test_listeners_invoked():
    net = iris_net()
    collector = CollectScoresIterationListener(frequency=1)
    net.set_listeners(collector)
    x, y = load_iris()
    for _ in range(5):
        net.fit(x, y)
    assert len(collector.scores) == 5
    assert collector.scores[0][1] > collector.scores[-1][1]


def test_deterministic_same_seed():
    x, y = load_iris()
    n1, n2 = iris_net(seed=7), iris_net(seed=7)
    for _ in range(3):
        n1.fit(x, y)
        n2.fit(x, y)
    for p1, p2 in zip(n1.params, n2.params):
        for k in p1:
            np.testing.assert_array_equal(np.asarray(p1[k]), np.asarray(p2[k]))


def test_different_seed_differs():
    x, y = load_iris()
    n1, n2 = iris_net(seed=1), iris_net(seed=2)
    assert not np.allclose(
        np.asarray(n1.params[0]["W"]), np.asarray(n2.params[0]["W"])
    )


def test_async_iterator_equivalent():
    x, y = load_iris()
    base = ListDataSetIterator(x, y, batch=50)
    a = iris_net(seed=3)
    b = iris_net(seed=3)
    a.fit_iterator(base, num_epochs=2)
    b.fit_iterator(AsyncDataSetIterator(ListDataSetIterator(x, y, batch=50), device_put=False), num_epochs=2)
    for p1, p2 in zip(a.params, b.params):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=1e-6
            )


def test_output_shape_and_probabilities():
    net = iris_net()
    x, _ = load_iris()
    out = np.asarray(net.output(x[:10]))
    assert out.shape == (10, 3)
    np.testing.assert_allclose(out.sum(axis=1), np.ones(10), rtol=1e-5)


def test_num_params():
    net = iris_net()
    # 4*10 + 10 + 10*3 + 3 = 83
    assert net.num_params() == 83


def test_model_serializer_round_trip(tmp_path):
    net = iris_net(updater="adam")
    x, y = load_iris()
    for _ in range(5):
        net.fit(x, y)
    path = str(tmp_path / "model.zip")
    ModelSerializer.write_model(net, path)
    net2 = ModelSerializer.restore_multi_layer_network(path)
    np.testing.assert_allclose(
        np.asarray(net.output(x[:8])), np.asarray(net2.output(x[:8])), rtol=1e-6
    )
    assert net2.iteration == net.iteration
    # training continues identically (updater state restored)
    net.fit(x, y)
    net2.fit(x, y)
    for p1, p2 in zip(net.params, net2.params):
        for k in p1:
            np.testing.assert_allclose(
                np.asarray(p1[k]), np.asarray(p2[k]), rtol=1e-5
            )


def test_clone_independent():
    net = iris_net()
    x, y = load_iris()
    c = net.clone()
    net.fit(x, y)
    assert not np.allclose(
        np.asarray(net.params[0]["W"]), np.asarray(c.params[0]["W"])
    )


# ---------------------------------------------------------------- streaming
def lstm_net(seed=7):
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(0.1)
        .weight_init("xavier")
        .list()
        .layer(0, GravesLSTM(n_in=6, n_out=8, activation="tanh"))
        .layer(
            1,
            RnnOutputLayer(
                n_in=8, n_out=4, activation="softmax", loss_function="mcxent"
            ),
        )
        .build()
    )
    return MultiLayerNetwork(conf).init(input_shape=(1, 6))


def test_rnn_time_step_matches_batch_forward():
    """Streaming stepwise inference == batch forward at every timestep
    (reference rnnTimeStep :2152 contract)."""
    net = lstm_net()
    rng = np.random.default_rng(0)
    x = rng.random((3, 5, 6)).astype(np.float32)
    batch_out = np.asarray(net.output(x))  # [3,5,4]
    net.rnn_clear_previous_state()
    for t in range(5):
        step_out = np.asarray(net.rnn_time_step(x[:, t]))
        np.testing.assert_allclose(step_out, batch_out[:, t], rtol=2e-5, atol=1e-6)


def test_rnn_time_step_seq_path_matches_stepwise():
    """[N,T,F] input runs the scanned path; equals repeated single steps and
    carries state across calls."""
    net = lstm_net()
    rng = np.random.default_rng(1)
    x = rng.random((2, 6, 6)).astype(np.float32)
    net.rnn_clear_previous_state()
    seq_out = np.asarray(net.rnn_time_step(x))  # scan path
    h_after_seq = np.asarray(net.states[0]["h"])
    net.rnn_clear_previous_state()
    steps = [np.asarray(net.rnn_time_step(x[:, t])) for t in range(6)]
    np.testing.assert_allclose(seq_out, np.stack(steps, axis=1), rtol=2e-5, atol=1e-6)
    np.testing.assert_allclose(h_after_seq, np.asarray(net.states[0]["h"]), rtol=2e-5, atol=1e-6)


def test_rnn_clear_previous_state_keeps_params():
    net = lstm_net()
    w_before = np.asarray(net.params[0]["W"]).copy()
    rng = np.random.default_rng(2)
    net.rnn_time_step(rng.random((2, 6)).astype(np.float32))
    assert np.asarray(net.states[0]["h"]).shape == (2, 8)
    net.rnn_clear_previous_state()
    assert np.asarray(net.states[0]["h"]).shape[0] == 0
    np.testing.assert_array_equal(w_before, np.asarray(net.params[0]["W"]))


# ---------------------------------------------------------------------------
# fit_batches: K steps fused in one lax.scan == K serial fit() calls
# ---------------------------------------------------------------------------


def _dropout_net(seed=11):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(0.05)
        .updater("adam")
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=16, activation="relu", dropout=0.3))
        .layer(
            1,
            OutputLayer(
                n_in=16, n_out=3, activation="softmax", loss_function="mcxent"
            ),
        )
        .build()
    )
    return MultiLayerNetwork(conf).init()


def test_fit_batches_equals_serial_fits():
    x, y = load_iris()
    K, N = 4, 30
    xs = np.stack([x[i * N:(i + 1) * N] for i in range(K)])
    ys = np.stack([y[i * N:(i + 1) * N] for i in range(K)])

    serial = iris_net(seed=5, updater="adam")
    serial_losses = [float(serial.fit(xs[k], ys[k])) for k in range(K)]

    fused = iris_net(seed=5, updater="adam")
    fused_losses = fused.fit_batches(xs, ys)

    np.testing.assert_allclose(fused_losses, serial_losses, rtol=1e-6)
    for p_s, p_f in zip(serial.params, fused.params):
        for name in p_s:
            np.testing.assert_allclose(
                np.asarray(p_f[name]), np.asarray(p_s[name]),
                rtol=1e-6, atol=1e-7, err_msg=name,
            )
    assert fused.iteration == serial.iteration == K


def test_fit_batches_matches_serial_with_dropout_rng():
    """Per-step dropout streams must line up with the serial path."""
    x, y = load_iris()
    K, N = 3, 40
    xs = np.stack([x[i * N:(i + 1) * N] for i in range(K)])
    ys = np.stack([y[i * N:(i + 1) * N] for i in range(K)])

    serial = _dropout_net()
    serial_losses = [float(serial.fit(xs[k], ys[k])) for k in range(K)]
    fused = _dropout_net()
    fused_losses = fused.fit_batches(xs, ys)
    np.testing.assert_allclose(fused_losses, serial_losses, rtol=1e-6)
    for p_s, p_f in zip(serial.params, fused.params):
        for name in p_s:
            np.testing.assert_allclose(
                np.asarray(p_f[name]), np.asarray(p_s[name]),
                rtol=1e-6, atol=1e-7,
            )


def test_fit_batches_listeners_and_guards():
    x, y = load_iris()
    xs, ys = np.stack([x[:20], x[20:40]]), np.stack([y[:20], y[20:40]])
    net = iris_net(seed=9)
    lst = CollectScoresIterationListener()
    net.listeners.append(lst)
    losses = net.fit_batches(xs, ys)
    assert len(losses) == 2 and len(lst.scores) == 2
    assert lst.scores[0][1] == pytest.approx(losses[0], rel=1e-6)


def test_fit_batches_respects_conf_iterations():
    """conf.iterations > 1: fused path == serial fit()s (which run
    `iterations` optimizer steps per batch)."""
    x, y = load_iris()
    K, N = 2, 30
    xs = np.stack([x[i * N:(i + 1) * N] for i in range(K)])
    ys = np.stack([y[i * N:(i + 1) * N] for i in range(K)])

    def build():
        conf = (
            NeuralNetConfiguration.builder()
            .seed(21)
            .learning_rate(0.05)
            .updater("nesterovs")
            .iterations(3)
            .list()
            .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build()
        )
        return MultiLayerNetwork(conf).init()

    serial = build()
    for k in range(K):
        serial.fit(xs[k], ys[k])
    fused = build()
    losses = fused.fit_batches(xs, ys)
    assert losses.shape == (K * 3,)
    assert fused.iteration == serial.iteration == K * 3
    for p_s, p_f in zip(serial.params, fused.params):
        for name in p_s:
            np.testing.assert_allclose(
                np.asarray(p_f[name]), np.asarray(p_s[name]),
                rtol=1e-6, atol=1e-7, err_msg=name,
            )


def test_gradient_checkpointing_matches_plain():
    """remat changes memory use, never values: losses + params after
    training must match the non-checkpointed run exactly."""
    x, y = load_iris()

    def build(ckpt):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(13)
            .learning_rate(0.05)
            .updater("adam")
            .list()
            .gradient_checkpointing(ckpt)
            .layer(0, DenseLayer(n_in=4, n_out=16, activation="tanh",
                                 dropout=0.2))
            .layer(1, DenseLayer(n_in=16, n_out=8, activation="relu"))
            .layer(2, OutputLayer(n_in=8, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build()
        )
        assert conf.gradient_checkpointing is ckpt
        return MultiLayerNetwork(conf).init()

    plain, ckpt = build(False), build(True)
    for _ in range(4):
        lp = float(plain.fit(x, y))
        lc = float(ckpt.fit(x, y))
        assert lp == pytest.approx(lc, rel=1e-6)
    for p_s, p_f in zip(plain.params, ckpt.params):
        for name in p_s:
            np.testing.assert_allclose(
                np.asarray(p_f[name]), np.asarray(p_s[name]),
                rtol=1e-6, atol=1e-7,
            )


def test_gradient_checkpointing_serde_round_trip():
    from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration

    conf = (
        NeuralNetConfiguration.builder().seed(1).list()
        .layer(0, DenseLayer(n_in=4, n_out=4))
        .layer(1, OutputLayer(n_in=4, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .gradient_checkpointing(True)
        .build()
    )
    rt = MultiLayerConfiguration.from_dict(conf.to_dict())
    assert rt.gradient_checkpointing is True


def test_performance_dtype_policy_trains():
    """Mixed precision (bf16 compute / f32 masters): training converges,
    master params stay f32, conf round-trips."""
    from deeplearning4j_tpu.nn.conf.multi_layer import MultiLayerConfiguration

    x, y = load_iris()
    conf = (
        NeuralNetConfiguration.builder()
        .seed(23).learning_rate(0.1).updater("adam")
        .list()
        .dtype_policy("performance")
        .layer(0, DenseLayer(n_in=4, n_out=16, activation="tanh"))
        .layer(1, OutputLayer(n_in=16, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    assert conf.dtype_policy == "performance"
    assert MultiLayerConfiguration.from_dict(conf.to_dict()).dtype_policy == "performance"
    net = MultiLayerNetwork(conf).init()
    first = float(net.fit(x, y))
    for _ in range(40):
        loss = float(net.fit(x, y))
    assert loss < first * 0.7, (first, loss)
    # master params remain f32
    import jax.numpy as jnp

    for p in net.params:
        for a in p.values():
            assert a.dtype == jnp.float32, a.dtype
    # accuracy sanity on the training set
    from deeplearning4j_tpu.eval.evaluation import Evaluation

    ev = Evaluation(3)
    ev.eval(np.asarray(y), np.asarray(net.output(x)))
    assert ev.accuracy() > 0.8


def test_performance_policy_close_to_strict():
    """bf16 compute tracks the strict-f32 loss curve within bf16 tolerance."""
    x, y = load_iris()

    def build(policy):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(29).learning_rate(0.05).updater("sgd")
            .list()
            .dtype_policy(policy)
            .layer(0, DenseLayer(n_in=4, n_out=12, activation="relu"))
            .layer(1, OutputLayer(n_in=12, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build()
        )
        return MultiLayerNetwork(conf).init()

    strict, perf = build("strict"), build("performance")
    for _ in range(10):
        ls = float(strict.fit(x, y))
        lp = float(perf.fit(x, y))
    assert abs(ls - lp) / max(ls, 1e-6) < 0.05, (ls, lp)


def test_performance_policy_preserves_embedding_indices():
    """Integer embedding indices must NOT be bf16-cast (bf16 only
    represents integers exactly up to 256)."""
    from deeplearning4j_tpu.nn.conf.layers import EmbeddingLayer

    vocab = 2000
    conf = (
        NeuralNetConfiguration.builder()
        .seed(3).learning_rate(0.05).updater("sgd")
        .list()
        .dtype_policy("performance")
        .layer(0, EmbeddingLayer(n_in=vocab, n_out=8))
        .layer(1, OutputLayer(n_in=8, n_out=2, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    idx = np.array([[1001], [1999], [5]], np.int32)
    out = np.asarray(net.output(idx))
    # distinct high indices must hit distinct embedding rows: outputs differ
    assert not np.allclose(out[0], out[1]), "indices collapsed (bf16 cast?)"
    y = np.eye(2, dtype=np.float32)[[0, 1, 0]]
    loss = float(net.fit(idx, y))
    assert np.isfinite(loss)


def test_performance_policy_bn_and_lstm_state_dtypes():
    """Norm layers are excluded from bf16 casting (f32 batch statistics)
    and recurrent states stay f32 across mixed-precision training, so
    fit/fit_batches/rnn_time_step can interleave without dtype flips."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf.layers import (
        BatchNormalization, GravesLSTM, RnnOutputLayer,
    )

    vocab = 12
    conf = (
        NeuralNetConfiguration.builder()
        .seed(5).learning_rate(0.01).updater("adam")
        .list()
        .dtype_policy("performance")
        .layer(0, GravesLSTM(n_in=vocab, n_out=16, activation="tanh"))
        .layer(1, RnnOutputLayer(n_in=16, n_out=vocab, activation="softmax",
                                 loss_function="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init(input_shape=(1, vocab))
    eye = np.eye(vocab, dtype=np.float32)
    ids = np.stack([(np.arange(9) + o) % vocab for o in range(4)])
    x, y = eye[ids[:, :8]], eye[ids[:, 1:]]
    float(net.fit(x, y))
    for s in net.states:
        for a in s.values():
            assert a.dtype == jnp.float32, a.dtype
    # fused path immediately after per-step path: scan carry stays stable
    xs, ys = np.stack([x, x]), np.stack([y, y])
    losses = net.fit_batches(xs, ys)
    assert np.isfinite(losses).all()

    # BN under performance policy: stats state stays f32, training is finite
    conf_bn = (
        NeuralNetConfiguration.builder()
        .seed(5).learning_rate(0.01).updater("adam")
        .list()
        .dtype_policy("performance")
        .layer(0, DenseLayer(n_in=4, n_out=8, activation="relu"))
        .layer(1, BatchNormalization(n_out=8))
        .layer(2, OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    xb, yb = load_iris()
    net_bn = MultiLayerNetwork(conf_bn).init()
    loss = float(net_bn.fit(xb, yb))
    assert np.isfinite(loss)
    assert net_bn.states[1]["mean"].dtype == jnp.float32
    assert net_bn.states[1]["var"].dtype == jnp.float32


def test_fused_fit_iterator_equals_per_step():
    """fit_iterator(fused_batches=K) stacks K DataSets into one
    fit_batches program; parameters must match the per-step loop exactly
    (fit_batches is serially equivalent), including the ragged tail."""
    x, y = load_iris()
    x, y = x[:130], y[:130]  # 5 batches of 26: K=2 leaves a tail of 1
    a = iris_net(seed=9)
    b = iris_net(seed=9)
    a.fit_iterator(ListDataSetIterator(x, y, batch=26), num_epochs=2)
    b.fit_iterator(ListDataSetIterator(x, y, batch=26), num_epochs=2,
                   fused_batches=2)
    for p1, p2 in zip(a.params, b.params):
        for k in p1:
            np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                       rtol=1e-6, atol=1e-7)
    assert a.iteration == b.iteration


def test_fused_fit_iterator_shape_change_falls_back():
    """A shape change mid-stream flushes the buffer per-step instead of
    crashing the stack."""
    x, y = load_iris()
    ds_list = [
        DataSet(x[:32], y[:32]), DataSet(x[32:64], y[32:64]),
        DataSet(x[64:80], y[64:80]),  # different batch size
        DataSet(x[80:96], y[80:96]),
    ]
    net = iris_net(seed=11)
    net.fit_iterator(ds_list, fused_batches=2)
    assert net.iteration == 4
