"""Replicated serving fleet tests (ISSUE 12): router+replicas byte-
identical to a single engine, chaos-killed replica => zero failed
admitted requests, rolling rollout with injected warmup failure never
moves a serving default, fleet-wide SLO shed, replica-breaker ejection +
half-open re-admission, the liveness/readiness split, and the
seal-on-drain rollout/SIGTERM race fix.

The training fleet proved loss==replay (tests/test_fleet.py, PR 6); this
file is the SERVING side of that convention over the same membership
authority (parallel/fleet.FileMembershipBoard). Every fault is provoked
deterministically through resilience/chaos.RouterChaosConfig /
ServingChaosConfig (never ambient).

Reference anchor: the reference's scaleout tree
(deeplearning4j-scaleout spark/akka/zookeeper — SURVEY) never grew a
serving twin; DL4jServeRouteBuilder.java is one process with no failover
— every contract here is beyond-reference.
"""

import json
import os
import re
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.resilience import (
    RouterChaos,
    RouterChaosConfig,
    ServingChaos,
    ServingChaosConfig,
)
from deeplearning4j_tpu.serving import DrainingError, ServingEngine
from deeplearning4j_tpu.serving.fleet import ServingFleet
from deeplearning4j_tpu.serving.router import (
    FleetOverloadError,
    FleetRouter,
)
from deeplearning4j_tpu.utils.serialization import ModelSerializer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def small_net(seed=7, n_in=4, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.1)
            .list()
            .layer(0, DenseLayer(n_in=n_in, n_out=8, activation="tanh"))
            .layer(1, OutputLayer(n_in=8, n_out=n_out, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(seed)
    net.fit(rng.normal(size=(32, n_in)).astype(np.float32),
            np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, 32)])
    return net


@pytest.fixture(scope="module")
def net():
    return small_net()


@pytest.fixture(scope="module")
def rows():
    rng = np.random.default_rng(3)
    return rng.normal(size=(16, 4)).astype(np.float32)


def _post_raw(url, path, payload, timeout=60):
    """(status, raw body bytes) — byte-level for the identity contract;
    4xx/5xx answered bodies are returned, not raised."""
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _get(url, path, timeout=30):
    try:
        with urllib.request.urlopen(url + path, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _fleet(net, n=2, **kw):
    kw.setdefault("heartbeat_s", 0.5)
    return ServingFleet(model=net, replicas=n, **kw).start()


# ---------------------------------------------------------------------------
# byte identity: the acceptance contract
# ---------------------------------------------------------------------------


class TestByteIdentity:
    def test_router_plus_replicas_equals_single_engine(self, net, rows):
        """The same request stream through router+2 replicas and through
        one solo engine must produce BYTE-identical response bodies."""
        solo = ServingEngine(model=net).start()
        fleet = _fleet(net, 2)
        try:
            stream = [rows[:1], rows[1:4], rows[4:9], rows[2:3],
                      rows[:8], rows[9:16]]
            for batch in stream:
                payload = {"batch": batch.tolist()}
                s_code, s_body = _post_raw(solo.url, "/predict", payload)
                f_code, f_body = _post_raw(fleet.url, "/predict", payload)
                assert (s_code, f_code) == (200, 200)
                assert s_body == f_body  # bytes, not parsed floats
        finally:
            fleet.stop()
            solo.stop()


# ---------------------------------------------------------------------------
# chaos kill: zero failed admitted requests
# ---------------------------------------------------------------------------


class TestChaosKill:
    def test_killed_replica_loses_no_admitted_request(self, net, rows):
        """A replica hard-killed mid-stream (RouterChaos verdict, enacted
        through the fleet's kill hook — no drain, no goodbye): every
        /predict in the stream still answers 200 with byte-correct
        output, retried on the survivor."""
        chaos = RouterChaos(RouterChaosConfig(
            kill_replica={"replica": "r0", "after_proxied": 3}))
        # slow the background poll so the REQUEST path (connect failure
        # -> breaker vote -> retry-on-survivor) is the detector — with
        # the default fast poll the readiness probe wins the race and
        # the corpse is skipped before any request touches it
        fleet = _fleet(net, 2, chaos=chaos,
                       router_kwargs={"poll_s": 30.0})
        try:
            expect = np.asarray(net.output(rows[:2]))
            for i in range(20):
                code, body = _post_raw(fleet.url, "/predict",
                                       {"batch": rows[:2].tolist()})
                assert code == 200, f"request {i} failed: {body!r}"
                out = np.asarray(json.loads(body)["outputs"],
                                 np.float32)
                np.testing.assert_array_equal(
                    out, np.asarray(expect, np.float32))
            # the kill really happened and really was detected
            assert any("kill_replica" in str(f) for _, f in chaos.log)
            assert not fleet._handles["r0"].alive
            snap = fleet.router.stats.snapshot()
            assert snap["replica_failures"] >= 1
            assert snap["retries"] >= 1
            # board expiry scrubs the corpse from membership
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                fleet.router.refresh()
                if sorted(fleet.router.describe_replicas()) == ["r1"]:
                    break
                time.sleep(0.1)
            assert sorted(fleet.router.describe_replicas()) == ["r1"]
            code, body = _get(fleet.url, "/health")
            assert code == 200 and body["routable"] == ["r1"]
        finally:
            fleet.stop()

    def test_announced_departure_is_a_clean_leave(self, net, rows):
        fleet = _fleet(net, 2)
        try:
            fleet.depart_replica("r1")
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                fleet.router.refresh()
                if sorted(fleet.router.describe_replicas()) == ["r0"]:
                    break
                time.sleep(0.1)
            assert sorted(fleet.router.describe_replicas()) == ["r0"]
            code, _ = _post_raw(fleet.url, "/predict",
                                {"batch": rows[:2].tolist()})
            assert code == 200
            # a goodbye is not a failure: no breaker activity
            assert fleet.router.stats.snapshot()["breaker_opens"] == 0
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# replica breaker: ejection + half-open re-admission
# ---------------------------------------------------------------------------


class TestReplicaBreaker:
    def test_partition_ejects_then_halfopen_readmits(self, net, rows):
        """A router->replica partition (connect failures, process alive):
        consecutive failures eject the replica; once the partition heals
        the half-open probe re-admits it. The CLIENT sees 200 for every
        request throughout — retried on the survivor."""
        e0 = ServingEngine(model=net).start()
        e1 = ServingEngine(model=net).start()
        chaos = RouterChaos(RouterChaosConfig(
            partition_replica={"replica": "r0", "calls": 2}))
        router = FleetRouter(
            replicas={"r0": e0.url, "r1": e1.url},
            replica_fails=2, breaker_cooldown_s=0.2, poll_s=30.0,
            chaos=chaos)
        try:
            body = json.dumps({"batch": rows[:2].tolist()}).encode()
            for _ in range(4):
                status, _, _ = router.proxy_predict(body)
                assert status == 200
            assert (router.describe_replicas()["r0"]["breaker"]["state"]
                    == "broken")
            assert router.stats.snapshot()["breaker_opens"] == 1
            time.sleep(0.25)  # past the cooldown: probe time
            deadline = time.monotonic() + 5.0
            while (router.describe_replicas()["r0"]["breaker"]["state"]
                   != "serving" and time.monotonic() < deadline):
                status, _, _ = router.proxy_predict(body)
                assert status == 200
                time.sleep(0.05)
            assert (router.describe_replicas()["r0"]["breaker"]["state"]
                    == "serving")
            assert router.stats.snapshot()["breaker_closes"] >= 1
        finally:
            router.stop()
            e0.stop()
            e1.stop()


# ---------------------------------------------------------------------------
# rolling rollout
# ---------------------------------------------------------------------------


class TestRollout:
    def test_rolling_rollout_shifts_every_replica(self, net, rows,
                                                  tmp_path):
        net2 = small_net(seed=11)
        path = str(tmp_path / "m2.zip")
        ModelSerializer.write_model(net2, path)
        fleet = _fleet(net, 2)
        try:
            code, report = _post_raw(fleet.url, "/rollout",
                                     {"name": "m2", "path": path,
                                      "input_shape": [4]})
            report = json.loads(report)
            assert code == 200 and report["ok"], report
            for eng in fleet.engines().values():
                assert eng.registry.default().key == "m2@v1"
            expect = np.asarray(net2.output(rows[:3]), np.float32)
            code, body = _post_raw(fleet.url, "/predict",
                                   {"batch": rows[:3].tolist()})
            assert code == 200
            np.testing.assert_array_equal(
                np.asarray(json.loads(body)["outputs"], np.float32),
                expect)
            assert fleet.router.stats.snapshot()["rollouts"] == 1
        finally:
            fleet.stop()

    def test_warmup_failure_rolls_back_and_moves_no_default(self, net,
                                                            rows,
                                                            tmp_path):
        """Injected warmup failure on the SECOND replica: the roll stops,
        the first replica is rolled back to its prior default, the
        failing replica's default never moved (registry isolation), and
        traffic through the router still serves the OLD model
        byte-identically."""
        net2 = small_net(seed=11)
        path = str(tmp_path / "m2.zip")
        ModelSerializer.write_model(net2, path)
        fleet = _fleet(net, 2)
        try:
            fleet.engines()["r1"].registry.chaos = ServingChaos(
                ServingChaosConfig(warmup_fail_name="m2"))
            code, report = _post_raw(fleet.url, "/rollout",
                                     {"name": "m2", "path": path,
                                      "input_shape": [4]})
            report = json.loads(report)
            assert code == 409 and not report["ok"]
            assert report["failed_replica"] == "r1"
            assert report["rolled_back"] == ["r0"]
            for eng in fleet.engines().values():
                assert eng.registry.default().key == "default@v1"
            # the half-warmed record is isolated as broken, not serving
            assert (fleet.engines()["r1"].registry.get("m2").state
                    == "broken")
            expect = np.asarray(net.output(rows[:3]), np.float32)
            code, body = _post_raw(fleet.url, "/predict",
                                   {"batch": rows[:3].tolist()})
            assert code == 200
            np.testing.assert_array_equal(
                np.asarray(json.loads(body)["outputs"], np.float32),
                expect)
            assert fleet.router.stats.snapshot()["rollbacks"] == 1
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# fleet-wide SLO shed
# ---------------------------------------------------------------------------


class TestSLOShed:
    def test_low_class_sheds_while_high_class_admits(self, net, rows):
        fleet = _fleet(net, 1, router_kwargs={
            "slo_classes": "interactive:5,batch:60", "queue_cap": 2})
        router = fleet.router
        try:
            # batch (priority 1 of 2) gets ceil(2 * 1/2) = 1 slot;
            # interactive keeps the full cap of 2
            assert router._admit({"slo": "batch"}) == "batch"
            with pytest.raises(FleetOverloadError):
                router._admit({"slo": "batch"})
            assert router._admit({"slo": "interactive"}) == "interactive"
            router._release()
            router._release()
            assert router.stats.snapshot()["shed_by_class"] == {"batch": 1}
            # unlabeled traffic rides the lowest class
            assert router._class_of({}) == ("batch", 1)
            # and the shed is visible on the wire: hold one slot, then a
            # batch-class request 429s with Retry-After while an
            # interactive one still answers
            router._admit({"slo": "batch"})
            try:
                req = urllib.request.Request(
                    fleet.url + "/predict",
                    data=json.dumps({"batch": rows[:1].tolist(),
                                     "slo": "batch"}).encode(),
                    headers={"Content-Type": "application/json"})
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req, timeout=30)
                assert ei.value.code == 429
                assert ei.value.headers["Retry-After"] == "1"
                code, _ = _post_raw(fleet.url, "/predict",
                                    {"batch": rows[:1].tolist(),
                                     "slo": "interactive"})
                assert code == 200
            finally:
                router._release()
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# liveness vs readiness (satellite 1)
# ---------------------------------------------------------------------------


class TestReadinessSplit:
    def test_plain_health_contract_is_byte_unchanged(self, net):
        eng = ServingEngine(model=net).start()
        try:
            code, body = _get(eng.url, "/health")
            assert code == 200
            # the PRE-split body: no live/ready keys on the plain path
            assert set(body) == {"ok", "draining", "model", "models",
                                 "health"}
            code, body = _get(eng.url, "/health?ready=1")
            assert code == 200
            assert body["live"] is True and body["ready"] is True
        finally:
            eng.stop()

    def test_draining_is_alive_but_not_ready(self, net):
        eng = ServingEngine(model=net).start()
        try:
            eng.drain()
            code, body = _get(eng.url, "/health")
            assert code == 503 and body["draining"] is True
            assert "live" not in body  # plain contract untouched
            code, body = _get(eng.url, "/health?ready=1")
            assert code == 503
            assert body["live"] is True and body["ready"] is False
        finally:
            eng.stop()

    def test_drain_stops_admission_without_breaker_vote(self, net, rows):
        fleet = _fleet(net, 2)
        try:
            fleet.engines()["r0"].drain()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                fleet.router.refresh()
                desc = fleet.router.describe_replicas()
                if not desc["r0"]["ready"]:
                    break
                time.sleep(0.05)
            desc = fleet.router.describe_replicas()
            assert desc["r0"]["ready"] is False
            # alive-but-not-ready: NOT death — no breaker vote
            assert desc["r0"]["breaker"]["state"] == "serving"
            code, _ = _post_raw(fleet.url, "/predict",
                                {"batch": rows[:2].tolist()})
            assert code == 200  # routed to r1
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# seal-on-drain (satellite 2): rollout racing shutdown
# ---------------------------------------------------------------------------


class TestSealOnDrain:
    def test_drain_seals_lifecycle_so_no_halfwarmed_default(self, net):
        eng = ServingEngine(model=net).start()
        try:
            # a rollout in progress: v2 loaded but not yet warm
            eng.registry.load("m2", model=small_net(seed=11))
            eng.drain()
            # the racing rollout thread's next steps are REFUSED…
            with pytest.raises(DrainingError):
                eng.registry.warmup("m2")
            with pytest.raises(DrainingError):
                eng.registry.serve("m2")
            # …and over HTTP they answer 503 like any drain-time admission
            code, _ = _post_raw(eng.url, "/models",
                                {"action": "serve", "name": "m2"})
            assert code == 503
            # the serving default never moved off the stable version
            assert eng.registry.default().key == "default@v1"
            # unload stays legal: teardown must still free buffers
            eng.registry.unload("m2")
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


class TestRouterLedger:
    def test_router_stats_rides_the_central_registry(self, net, rows):
        fleet = _fleet(net, 1)
        try:
            _post_raw(fleet.url, "/predict", {"batch": rows[:2].tolist()})
            reg = obs.default_registry()
            assert "router_stats" in reg.ledgers(fleet.router)
            text = reg.render_prometheus()
            # the registry strips the _stats suffix at scrape time
            assert "dl4j_router_requests" in text
            assert "dl4j_router_proxied_ok" in text
            # and the router's own /metrics carries the JSON ledger
            code, body = _get(fleet.url, "/metrics")
            assert code == 200
            assert body["router"]["requests"] >= 1
            assert body["router"]["proxied_ok"] >= 1
        finally:
            fleet.stop()

    def test_serving_fleet_leg_registered(self):
        """bench.py defines the serving_fleet leg, bench_state expects
        it, and it is pinned CPU-only (router accounting + failover are
        host-side machinery, not a chip benchmark)."""
        from scripts.bench_state import EXPECTED

        assert "serving_fleet" in EXPECTED
        src = open(os.path.join(REPO, "bench.py")).read()
        legs = set(re.findall(r'^\s*run\("([a-z0-9_]+)"', src, re.M))
        assert "serving_fleet" in legs
        cpu_only = re.search(r"_CPU_ONLY_LEGS\s*=\s*\{([^}]*)\}", src)
        assert cpu_only and "serving_fleet" in cpu_only.group(1)
