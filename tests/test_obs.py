"""Unified observability plane (ISSUE 7 — deeplearning4j_tpu/obs/).

Contracts under test:

  * obs DISABLED (the default) => training is BIT-exact vs obs enabled —
    spans are host-side events that never enter the numerics (the
    acceptance bar's equivalence clause);
  * span tracer: monotonic spans with ids + parent ids + attrs, nested
    parenting, null-path no-ops, after-the-fact waits;
  * MetricsRegistry: counters/gauges/histograms, ledger adoption (every
    ``net.*_stats`` ledger on MLN/CG registers — a new ledger added
    without registration fails LOUDLY here), Prometheus text exposition
    pinned by a golden file (label escaping, histogram buckets) plus
    counter monotonicity across two scrapes;
  * one scrape covers all five ledgers (dispatch/memory/pipeline/
    resilience/serving) through the serving engine's /metrics;
  * flight recorder: bounded ring, crash-safe flush, fsync-on-preemption
    through the ResilientTrainer SIGTERM path, checkpoint/membership
    correlation events;
  * instrumented seams emit the expected spans (dispatch trace-vs-cache-
    hit, serve.request -> serve.batch -> dispatch parenting with the
    request id threading through the batcher, etl waits, ckpt phases);
  * bench: the obs_overhead leg is registered in scripts/bench_state.py
    EXPECTED (the watcher's completeness contract).

Reference provenance: the listener/UI plane these tests grow from is
deeplearning4j-core/.../optimize/api/IterationListener.java and
deeplearning4j-ui-parent (UiServer.java) — see PARITY.md.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu import obs
from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.obs.registry import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data",
                      "prometheus_golden.txt")


@pytest.fixture
def obs_on():
    """Force the gate on with a FRESH tracer/journal (the module
    singletons are process-wide; tests must not read each other's
    spans)."""
    obs.set_enabled(True)
    obs.tracer().clear()
    try:
        yield
    finally:
        obs.set_enabled(None)


def mlp(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.05)
            .updater("adam").list()
            .layer(0, DenseLayer(n_in=6, n_out=12, activation="relu"))
            .layer(1, OutputLayer(n_in=12, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


# ---------------------------------------------------------------------------
# tracer
# ---------------------------------------------------------------------------


def test_spans_nest_with_parent_ids(obs_on):
    with obs.span("outer", a=1) as sp_outer:
        with obs.span("inner") as sp_inner:
            sp_inner.set_attr("x", "y")
        assert sp_inner.parent_id == sp_outer.span_id
    spans = obs.tracer().spans()
    by_name = {s["name"]: s for s in spans}
    assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
    assert by_name["outer"]["parent_id"] is None
    assert by_name["inner"]["attrs"] == {"x": "y"}
    assert by_name["outer"]["attrs"] == {"a": 1}
    assert by_name["outer"]["duration_s"] >= by_name["inner"]["duration_s"]


def test_disabled_tracer_records_nothing():
    obs.set_enabled(False)
    try:
        obs.tracer().clear()
        with obs.span("nope", k=1) as sp:
            sp.set_attr("still", "a no-op")  # null span: same call shape
        obs.record_span("nope2", 0.5)
        assert obs.tracer().spans() == []
    finally:
        obs.set_enabled(None)


def test_env_gate_default_off(monkeypatch):
    monkeypatch.delenv(obs.ENV_OBS, raising=False)
    assert not obs.obs_enabled()
    monkeypatch.setenv(obs.ENV_OBS, "1")
    assert obs.obs_enabled()
    monkeypatch.setenv(obs.ENV_OBS, "0")
    assert not obs.obs_enabled()


def test_record_span_backdates_start(obs_on):
    obs.record_span("wait", 0.25, seq=3)
    (s,) = obs.tracer().spans("wait")
    assert abs(s["duration_s"] - 0.25) < 1e-6
    assert s["attrs"]["seq"] == 3


def test_span_ring_is_bounded():
    tr = obs.Tracer(capacity=8)
    for i in range(50):
        with tr.span(f"s{i}"):
            pass
    spans = tr.spans()
    assert len(spans) == 8
    assert spans[-1]["name"] == "s49"


# ---------------------------------------------------------------------------
# the acceptance equivalence: obs on vs off is BIT-exact
# ---------------------------------------------------------------------------


def test_training_bit_exact_with_obs_on_vs_off():
    """Spans/journal/registry are host-side observers: the same seed with
    DL4J_TPU_OBS flipped must produce bit-identical params and losses —
    the contract that makes default-off obs equal to pre-PR behavior."""
    x, y = data(48)

    def run():
        net = mlp()
        losses = [net.fit(x, y) for _ in range(5)]
        return losses, net.params

    obs.set_enabled(False)
    try:
        losses_off, params_off = run()
    finally:
        obs.set_enabled(None)
    obs.set_enabled(True)
    try:
        losses_on, params_on = run()
    finally:
        obs.set_enabled(None)
    assert losses_off == losses_on
    for a, b in zip(jax.tree_util.tree_leaves(params_off),
                    jax.tree_util.tree_leaves(params_on)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    r = MetricsRegistry()
    r.counter("dl4j_c", 2, k="a")
    r.counter("dl4j_c", 3, k="a")
    r.gauge("dl4j_g", 1.5)
    r.gauge("dl4j_g", 2.5)  # last write wins
    for v in (0.001, 0.2):
        r.histogram("dl4j_h", v, buckets=(0.01, 0.1))
    snap = r.snapshot()
    assert snap["counters"]["dl4j_c"]["k=a"] == 5
    assert snap["gauges"]["dl4j_g"]["_"] == 2.5
    h = snap["histograms"]["dl4j_h"]["_"]
    assert h["count"] == 2 and h["counts"] == [1, 0, 1]
    with pytest.raises(ValueError):
        r.counter("dl4j_c", -1)  # counters are monotonic by construction


def test_prometheus_exposition_matches_golden_file():
    """The exact text exposition is pinned: label escaping (backslash,
    quote, newline), sorted labels, histogram buckets with +Inf/_sum/
    _count, counter _total naming, HELP/TYPE metadata."""
    r = MetricsRegistry()
    r.set_help("dl4j_requests", "serving requests accepted")
    r.counter("dl4j_requests", 3, model="mnist@v1", path="/predict")
    r.counter("dl4j_requests", 1, model='with"quote\\and\nnewline',
              path="/predict")
    r.gauge("dl4j_queue_depth", 7)
    for v in (0.003, 0.02, 0.33, 0.5055):
        r.histogram("dl4j_latency_seconds", v, buckets=(0.005, 0.05, 0.5),
                    model="mnist@v1")
    with open(GOLDEN) as f:
        assert r.render_prometheus() == f.read()


def test_counter_monotonicity_across_two_scrapes():
    r = MetricsRegistry()
    r.counter("dl4j_events", 2)
    first = {line.split(" ")[0]: float(line.split(" ")[1])
             for line in r.render_prometheus().splitlines()
             if not line.startswith("#")}
    r.counter("dl4j_events", 1)
    second = {line.split(" ")[0]: float(line.split(" ")[1])
              for line in r.render_prometheus().splitlines()
              if not line.startswith("#")}
    for name, v in first.items():
        assert second[name] >= v, f"{name} went backwards"
    assert second["dl4j_events_total"] == 3


def _assert_all_ledgers_registered(net, registry) -> None:
    """THE registration convention: every non-None ``*_stats`` attribute
    on a container must be a registered registry view."""
    registered = registry.ledgers(net)
    for attr, val in vars(net).items():
        if attr.endswith("_stats") and val is not None:
            assert registered.get(attr) is val, (
                f"net.{attr} is not registered in the MetricsRegistry — "
                "new ledgers must go through obs.registry.register_net "
                "at their attach point")


def test_every_mln_ledger_registers():
    net = mlp()
    _assert_all_ledgers_registered(net, obs.default_registry())


def test_every_cg_ledger_registers():
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    conf = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.1)
            .graph_builder()
            .add_inputs("in")
            .add_layer("out", OutputLayer(
                n_in=6, n_out=3, activation="softmax",
                loss_function="mcxent"), "in")
            .set_outputs("out").build())
    net = ComputationGraph(conf).init()
    _assert_all_ledgers_registered(net, obs.default_registry())


def test_unregistered_new_ledger_fails_loudly():
    """The guard has teeth: a hypothetical new ledger attached WITHOUT
    registration trips the convention check."""
    net = mlp()
    net.shiny_new_stats = {"things": 1}
    with pytest.raises(AssertionError, match="shiny_new_stats"):
        _assert_all_ledgers_registered(net, obs.default_registry())


def test_dead_owner_is_pruned():
    r = MetricsRegistry()

    class Owner:
        pass

    o = Owner()
    r.register_ledger(o, "x_stats", {"n": 1})
    assert r.collect_ledger_samples()
    del o
    assert r.collect_ledger_samples() == []


# ---------------------------------------------------------------------------
# one scrape, five ledgers (the acceptance bar's export clause)
# ---------------------------------------------------------------------------


def test_one_scrape_covers_all_five_ledgers(obs_on, tmp_path):
    """dispatch + memory + pipeline + resilience + serving counters in a
    single /metrics scrape of the serving engine (Prometheus form)."""
    from deeplearning4j_tpu.etl.pipeline import InputPipeline
    from deeplearning4j_tpu.resilience import ResilientTrainer
    from deeplearning4j_tpu.serving.engine import ServingEngine

    x, y = data(32)
    net = mlp()
    net.measure_memory(x[:16], y[:16])  # populates the memory ledger
    pipe = InputPipeline(ListDataSetIterator(x, y, batch=16), workers=1,
                         shard=None)
    trainer = ResilientTrainer(net, handle_signals=False)
    trainer.fit(pipe, num_epochs=1)
    net.pipeline_stats = pipe.pipeline_stats
    obs.register_net(net)
    eng = ServingEngine(model=net).start()
    try:
        eng.predict(x[:4])
        req = urllib.request.Request(
            eng.url + "/metrics", headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert "text/plain" in r.headers.get("Content-Type", "")
            page = r.read().decode()
    finally:
        eng.stop()
    for family in ("dl4j_dispatch_", "dl4j_memory_", "dl4j_pipeline_",
                   "dl4j_resilience_", "dl4j_serving_"):
        assert any(line.startswith(family)
                   for line in page.splitlines()), f"{family} missing"


def test_metrics_json_contract_unchanged(obs_on):
    from deeplearning4j_tpu.serving.engine import ServingEngine

    x, y = data(8)
    eng = ServingEngine(model=mlp()).start()
    try:
        eng.predict(x[:2])
        with urllib.request.urlopen(eng.url + "/metrics", timeout=10) as r:
            m = json.loads(r.read())
        assert "serving" in m and "models" in m
        req = urllib.request.Request(
            eng.url + "/metrics?format=prometheus")
        with urllib.request.urlopen(req, timeout=10) as r:
            page = r.read().decode()
        assert any(line.startswith("dl4j_serving_")
                   for line in page.splitlines())
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_journal_ring_bounded_and_loadable(tmp_path):
    j = obs.FlightRecorder(path=str(tmp_path / "j.jsonl"), capacity=5,
                           flush_interval_s=1e9)
    for i in range(12):
        j.record("tick", i=i)
    path = j.flush(fsync=True)
    events = obs.FlightRecorder.load(path)
    assert [e["i"] for e in events] == list(range(7, 12))
    assert all(e["kind"] == "tick" for e in events)
    # seq is globally increasing even though the ring dropped the head
    assert [e["seq"] for e in events] == list(range(8, 13))


def test_marker_events_survive_span_floods(tmp_path):
    """Per-dispatch spans enter the journal at hundreds/sec and turn the
    main ring over fast; checkpoint/membership/preempt markers must
    survive the flood (the pinned side ring) or the post-mortem loses
    its anchors."""
    j = obs.FlightRecorder(path=str(tmp_path / "j.jsonl"), capacity=64,
                           flush_interval_s=1e9)
    j.record("checkpoint", step=7)
    j.record("membership", epoch=2)
    for i in range(500):  # > 7x ring turnover of span traffic
        j.append({"kind": "span", "name": f"dispatch.x{i}"})
    events = obs.FlightRecorder.load(j.flush(fsync=True))
    kinds = [e["kind"] for e in events]
    assert "checkpoint" in kinds and "membership" in kinds
    assert [e for e in events if e["kind"] == "checkpoint"][0]["step"] == 7
    # markers also stay visible on the live read surface
    assert j.events("membership")[0]["epoch"] == 2
    # the timeline stays seq-ordered despite the two-ring merge
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs)


def test_journal_flush_is_atomic_no_tmp_litter(tmp_path):
    j = obs.FlightRecorder(path=str(tmp_path / "j.jsonl"), capacity=4)
    j.record("a")
    j.flush()
    j.record("b")
    j.flush(fsync=True)
    assert sorted(p.name for p in tmp_path.iterdir()) == ["j.jsonl"]


def test_preemption_fsyncs_journal(obs_on, tmp_path, monkeypatch):
    """The SIGTERM path: checkpoint-before-death also flushes the flight
    recorder with fsync, and the on-disk timeline carries the preempt
    marker + the checkpoint correlation id."""
    import deeplearning4j_tpu.obs.journal as journal_mod
    from deeplearning4j_tpu.resilience import (
        CheckpointManager,
        Preempted,
        ResilientTrainer,
    )

    jr = obs.FlightRecorder(path=str(tmp_path / "flight.jsonl"),
                            capacity=64, flush_interval_s=1e9)
    monkeypatch.setattr(journal_mod, "_DEFAULT", jr)
    x, y = data(32)
    it = ListDataSetIterator(x, y, batch=16)
    mgr = CheckpointManager(str(tmp_path / "ckpt"), every_steps=1,
                            async_save=False)
    trainer = ResilientTrainer(mlp(), mgr, handle_signals=False)

    class _PreemptAfterFirstStep:  # the signal handler's flag, scripted
        def before_step(self, step):
            pass

        def after_step(self, step):
            trainer._preempt_requested = True

    trainer.chaos = _PreemptAfterFirstStep()
    with pytest.raises(Preempted):
        trainer.fit(it, num_epochs=1)
    events = obs.FlightRecorder.load(str(tmp_path / "flight.jsonl"))
    kinds = [e["kind"] for e in events]
    assert "preempt" in kinds and "checkpoint" in kinds
    preempt = [e for e in events if e["kind"] == "preempt"][-1]
    assert preempt["path"] and preempt["step"] == 1
    assert trainer.resilience_stats["last_checkpoint_step"] == 1


def test_checkpoint_spans_and_journal_event(obs_on, tmp_path):
    from deeplearning4j_tpu.resilience import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=False)
    mgr.save(mlp(), step=3)
    names = {s["name"] for s in obs.tracer().spans()}
    assert {"ckpt.snapshot", "ckpt.write", "ckpt.commit"} <= names
    write = obs.tracer().spans("ckpt.write")[-1]
    assert write["attrs"]["step"] == 3


# ---------------------------------------------------------------------------
# instrumented seams
# ---------------------------------------------------------------------------


def test_dispatch_spans_mark_trace_vs_cache_hit(obs_on):
    net = mlp()
    x, y = data(16)
    net.fit(x, y)
    net.fit(x, y)
    spans = obs.tracer().spans("dispatch.train_step")
    assert len(spans) == 2
    assert spans[0]["attrs"].get("traced") is True
    assert "traced" not in spans[1]["attrs"]  # compiled-cache hit
    assert spans[0]["duration_s"] > spans[1]["duration_s"]


def test_request_id_threads_through_batcher_to_jit(obs_on):
    """request -> batch -> jit: the serve.request span carries the rid,
    the serve.batch span lists it in request_ids, and the jit dispatch
    span is a CHILD of the batch span (worker-thread parenting)."""
    from deeplearning4j_tpu.serving.engine import ServingEngine

    x, y = data(8)
    eng = ServingEngine(model=mlp()).start()
    try:
        eng.predict(x[:2])
    finally:
        eng.stop()
    requests = obs.tracer().spans("serve.request")
    batches = obs.tracer().spans("serve.batch")
    assert requests and batches
    rid = requests[-1]["attrs"]["rid"]
    batch = batches[-1]
    assert rid in batch["attrs"]["request_ids"]
    children = [s for s in obs.tracer().spans("dispatch.output")
                if s["parent_id"] == batch["span_id"]]
    assert children, "jit dispatch span did not parent under serve.batch"


def test_etl_spans(obs_on):
    from deeplearning4j_tpu.etl.pipeline import InputPipeline

    x, y = data(48)
    pipe = InputPipeline(ListDataSetIterator(x, y, batch=16), workers=1,
                         shard=None)
    assert sum(1 for _ in pipe) == 3
    waits = obs.tracer().spans("etl.wait")
    stages = obs.tracer().spans("etl.stage")
    assert len(waits) == 3 and len(stages) == 3
    assert all(w["attrs"]["records"] == 16 for w in waits)


def test_fleet_round_span_carries_membership_epoch(obs_on):
    from deeplearning4j_tpu.parallel.fleet import (
        ElasticParameterAveragingTrainer,
    )

    x, y = data(32, seed=2)
    net = mlp(seed=11)
    fleet = ElasticParameterAveragingTrainer(net, num_workers=2,
                                             heartbeat_s=2.0)
    try:
        fleet.fit(x, y)
    finally:
        fleet.close()
    rounds = obs.tracer().spans("fleet.round")
    assert rounds and rounds[-1]["attrs"]["membership_epoch"] >= 1
    assert rounds[-1]["attrs"]["workers"] == 2
    splits = obs.tracer().spans("fleet.split")
    assert {s["attrs"]["split"] for s in splits} == {0, 1}
    # the membership journal event correlates with the same epoch
    members = [e for e in obs.default_journal().events("membership")]
    assert members and members[-1]["epoch"] == \
        rounds[-1]["attrs"]["membership_epoch"]


# ---------------------------------------------------------------------------
# exporter + listener + bench registration
# ---------------------------------------------------------------------------


def test_exporter_endpoints(obs_on, tmp_path):
    reg = MetricsRegistry()
    reg.counter("dl4j_things", 4)
    jr = obs.FlightRecorder(path=str(tmp_path / "j.jsonl"))
    jr.record("hello", x=1)
    exp = obs.MetricsExporter(registry=reg, journal=jr).start()
    try:
        with urllib.request.urlopen(exp.url + "/metrics", timeout=10) as r:
            assert b"dl4j_things_total 4" in r.read()
        with urllib.request.urlopen(exp.url + "/metrics.json",
                                    timeout=10) as r:
            snap = json.loads(r.read())
            assert snap["counters"]["dl4j_things"]["_"] == 4
        with urllib.request.urlopen(exp.url + "/journal", timeout=10) as r:
            lines = r.read().decode().strip().splitlines()
            assert json.loads(lines[-1])["kind"] == "hello"
        with urllib.request.urlopen(exp.url + "/health", timeout=10) as r:
            assert json.loads(r.read())["ok"] is True
    finally:
        exp.stop()


def test_stats_listeners_share_uniform_renderer():
    """Satellite: Dispatch/Resilience listeners are ONE StatsListener
    base — same snapshot shape as before, same log format for any
    ledger."""
    from deeplearning4j_tpu.optimize.listeners import (
        DispatchStatsListener,
        PipelineStatsListener,
        ResilienceStatsListener,
        StatsListener,
    )

    assert issubclass(DispatchStatsListener, StatsListener)
    assert issubclass(ResilienceStatsListener, StatsListener)
    assert issubclass(PipelineStatsListener, StatsListener)
    net = mlp()
    x, y = data(16)
    net.resilience_stats = {"retries": 2, "backoff_seconds": 0.5}
    dl = DispatchStatsListener(frequency=1)
    rl = ResilienceStatsListener(frequency=1)
    net.set_listeners(dl, rl)
    net.fit(x, y)
    # stored snapshot shape is backward-compatible (iteration rides along)
    assert dl.snapshots[-1]["traces"]["train_step"] == 1
    assert rl.snapshots[-1]["retries"] == 2
    # ONE render format: sorted key=value, dicts collapsed to sums
    out = dl.render(dl.snapshots[-1])
    assert "traces=1" in out and "donated_steps=" in out
    out = rl.render(rl.snapshots[-1])
    assert "retries=2" in out and "backoff_seconds=0.500" in out


def test_obs_overhead_leg_registered():
    """ISSUE 7: the obs_overhead leg is in the expected set — both the
    live parse of bench.py's run() calls and the EXPECTED fallback — so
    the watcher's completeness check demands the overhead evidence row
    every round."""
    import re

    from scripts.bench_state import EXPECTED, expected_legs

    src = open(os.path.join(REPO, "bench.py")).read()
    legs_direct = re.findall(r'^\s*run\("([a-z0-9_]+)"', src, re.M)
    assert "obs_overhead" in legs_direct
    assert "obs_overhead" in EXPECTED
    assert "obs_overhead" in expected_legs()
