"""Distributed control-plane tests — mirrors the reference Spark test
strategy (SURVEY.md section 4 "Distributed-without-a-cluster"): local-mode
masters on the 8-device CPU mesh, stats collection
(TestTrainingStatsCollection), repartitioning invariants
(TestRepartitioning), distributed eval merge, distributed early stopping
(TestEarlyStoppingSpark)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.iterator import DataSet, ListDataSetIterator
from deeplearning4j_tpu.earlystopping.config import EarlyStoppingConfiguration
from deeplearning4j_tpu.earlystopping.distributed import (
    DistributedEarlyStoppingTrainer,
)
from deeplearning4j_tpu.earlystopping.terminations import (
    MaxEpochsTerminationCondition,
)
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.multihost import (
    MultiHostConfig,
    initialize_multihost,
    local_batch_slice,
    process_info,
)
from deeplearning4j_tpu.parallel.stats import (
    NTPTimeSource,
    SystemClockTimeSource,
    TrainingStats,
)
from deeplearning4j_tpu.parallel.training_master import (
    DistributedEvaluator,
    ParameterAveragingTrainingMaster,
    Repartition,
    SparkStyleNetwork,
    balanced_splits,
)


def small_net(seed=12345, lr=0.1):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(lr)
        .updater("sgd")
        .weight_init("xavier")
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=16, activation="tanh"))
        .layer(1, OutputLayer(n_in=16, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def iris_like(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    # fixed labeling rule so train/val come from the same task
    w = np.random.default_rng(42).normal(size=(4, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


def datasets_of(n, batch, seed=0):
    x, y = iris_like(n, seed)
    return [DataSet(x[i : i + batch], y[i : i + batch])
            for i in range(0, n, batch)]


class TestBalancedSplits:
    def test_exact_balance(self):
        sls = balanced_splits(10, 3)
        sizes = [s.stop - s.start for s in sls]
        assert sizes == [4, 3, 3]
        assert sls[-1].stop == 10

    def test_more_workers_than_items(self):
        sls = balanced_splits(2, 4)
        assert [s.stop - s.start for s in sls] == [1, 1, 0, 0]


class TestExportedSplitFit:
    """Export-then-fit-from-path (reference export plumbing,
    ParameterAveragingTrainingMaster.java:148-168 +
    SparkDl4jMultiLayer.fit(String path) :217): saving an iterator's
    minibatches as files and fitting from the path must train the SAME
    model as fitting the iterator directly."""

    def test_round_trip_preserves_datasets(self, tmp_path):
        from deeplearning4j_tpu.parallel.training_master import (
            export_datasets,
            load_exported_datasets,
        )

        data = datasets_of(64, 16, seed=3)
        # give one batch masks to prove they survive the round trip
        data[1] = DataSet(data[1].features, data[1].labels,
                          np.ones_like(data[1].features),
                          np.ones_like(data[1].labels))
        paths = export_datasets(data, str(tmp_path / "exp"))
        assert len(paths) == 4
        back = list(load_exported_datasets(str(tmp_path / "exp")))
        assert len(back) == 4
        for orig, re in zip(data, back):
            np.testing.assert_array_equal(orig.features, re.features)
            np.testing.assert_array_equal(orig.labels, re.labels)
        assert back[0].features_mask is None
        np.testing.assert_array_equal(back[1].features_mask,
                                      np.ones_like(data[1].features))

    def test_fit_paths_equals_direct_fit(self, tmp_path):
        data = datasets_of(4 * 8 * 2 * 2, 32, seed=5)

        def run(fit):
            net = small_net()
            master = ParameterAveragingTrainingMaster(
                num_workers=4, batch_size_per_worker=8,
                averaging_frequency=2,
            )
            fit(SparkStyleNetwork(net, master))
            return net

        from deeplearning4j_tpu.parallel.training_master import (
            export_datasets,
        )

        export_datasets(data, str(tmp_path / "splits"))
        net_direct = run(lambda s: s.fit(data))
        net_paths = run(lambda s: s.fit_paths(str(tmp_path / "splits")))
        for pd, pp in zip(net_direct.params, net_paths.params):
            for k in pd:
                np.testing.assert_allclose(
                    np.asarray(pd[k]), np.asarray(pp[k]), atol=1e-7,
                    err_msg=k)

    def test_fit_paths_accepts_file_list(self, tmp_path):
        from deeplearning4j_tpu.parallel.training_master import (
            export_datasets,
            load_exported_datasets,
        )

        paths = export_datasets(datasets_of(32, 16, seed=7),
                                str(tmp_path / "lst"))
        assert len(list(load_exported_datasets(paths))) == 2

    def test_empty_path_raises(self, tmp_path):
        from deeplearning4j_tpu.parallel.training_master import (
            load_exported_datasets,
        )

        with pytest.raises(ValueError, match="no exported"):
            list(load_exported_datasets(str(tmp_path)))

    def test_gs_export_stages_and_uploads(self, tmp_path):
        """gs:// destination goes through GcsUploader (fake runner — the
        provision tests' pattern; no network)."""
        import deeplearning4j_tpu.provision.gcs as gcs_mod
        from deeplearning4j_tpu.parallel.training_master import (
            export_datasets,
        )

        calls = []

        class FakeUploader:
            def upload(self, local, uri):
                calls.append((local, uri))

        orig = gcs_mod.GcsUploader
        gcs_mod.GcsUploader = FakeUploader
        try:
            out = export_datasets(datasets_of(32, 16, seed=8),
                                  "gs://bucket/exp")
        finally:
            gcs_mod.GcsUploader = orig
        assert out == ["gs://bucket/exp/dataset_00000.npz",
                       "gs://bucket/exp/dataset_00001.npz"]
        assert len(calls) == 2
        assert all(c[0].endswith(".npz") for c in calls)


class TestParameterAveragingMaster:
    def test_training_reduces_score(self):
        net = small_net()
        master = ParameterAveragingTrainingMaster(
            num_workers=4, batch_size_per_worker=8, averaging_frequency=2,
        )
        data = datasets_of(4 * 8 * 2 * 3, 32)
        before = net.score(*iris_like(64, seed=9))
        SparkStyleNetwork(net, master).fit(data)
        after = net.score(*iris_like(64, seed=9))
        assert after < before

    def test_stats_collection(self):
        net = small_net()
        master = ParameterAveragingTrainingMaster(
            num_workers=2, batch_size_per_worker=8, averaging_frequency=2,
            collect_training_stats=True,
        )
        master.execute_training(net, datasets_of(2 * 8 * 2 * 2, 16))
        stats = master.get_training_stats()
        summary = stats.summary()
        assert "split" in summary and "fit" in summary
        assert summary["fit"]["count"] == 2  # two averaging rounds

    def test_insufficient_data_raises(self):
        net = small_net()
        master = ParameterAveragingTrainingMaster(
            num_workers=8, batch_size_per_worker=16, averaging_frequency=5,
        )
        with pytest.raises(ValueError, match="averaging round"):
            master.execute_training(net, datasets_of(32, 16))

    def test_repartition_never_preserves_order(self):
        master = ParameterAveragingTrainingMaster(
            num_workers=2, batch_size_per_worker=4, averaging_frequency=1,
            repartition=Repartition.NEVER,
        )
        data = datasets_of(16, 8, seed=3)
        splits = list(master._splits(data))
        x0 = np.concatenate([np.asarray(d.features) for d in data])[:8]
        np.testing.assert_array_equal(splits[0][0], x0)


class TestDistributedEval:
    def test_merge_equals_serial(self):
        net = small_net()
        data = datasets_of(96, 16, seed=5)
        dist = DistributedEvaluator(num_shards=4).evaluate(net, data)
        serial = DistributedEvaluator(num_shards=1).evaluate(net, data)
        assert dist.accuracy() == pytest.approx(serial.accuracy())
        assert dist.f1() == pytest.approx(serial.f1())


class TestStats:
    def test_timeline_export(self, tmp_path):
        stats = TrainingStats()
        with stats.timed("fit", worker_id="w0", example_count=32):
            pass
        with stats.timed("aggregate", worker_id="w1"):
            pass
        html_path = str(tmp_path / "timeline.html")
        stats.export_html(html_path)
        content = open(html_path).read()
        assert "timeline" in content and "fit" in content and "aggregate" in content
        json_path = str(tmp_path / "stats.json")
        stats.export_json(json_path)
        assert "fit" in open(json_path).read()

    def test_time_sources(self):
        assert abs(
            SystemClockTimeSource().current_time_millis()
            - NTPTimeSource(offset_millis=0).current_time_millis()
        ) < 1000
        assert (
            NTPTimeSource(offset_millis=100_000).current_time_millis()
            > SystemClockTimeSource().current_time_millis() + 50_000
        )


class TestMultiHost:
    def test_single_process_defaults(self):
        assert initialize_multihost(MultiHostConfig()) is False
        info = process_info()
        assert info["process_count"] == 1
        assert info["process_index"] == 0

    def test_local_batch_slice_single(self):
        sl = local_batch_slice(64)
        assert sl == slice(0, 64)

    def test_config_from_env(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_COORDINATOR", "10.0.0.1:1234")
        monkeypatch.setenv("DL4J_TPU_NUM_PROCESSES", "4")
        monkeypatch.setenv("DL4J_TPU_PROCESS_ID", "2")
        cfg = MultiHostConfig.from_env()
        assert cfg.is_configured()
        assert cfg.num_processes == 4 and cfg.process_id == 2


class TestDistributedEarlyStopping:
    def test_stops_at_max_epochs(self):
        net = small_net()
        master = ParameterAveragingTrainingMaster(
            num_workers=2, batch_size_per_worker=8, averaging_frequency=1,
        )
        data = datasets_of(2 * 8 * 1 * 2, 16)
        cfg = EarlyStoppingConfiguration(
            epoch_terminations=[MaxEpochsTerminationCondition(3)],
        )
        trainer = DistributedEarlyStoppingTrainer(cfg, master, net, data)
        result = trainer.fit(max_epochs=50)
        assert result.total_epochs <= 4
        assert result.best_model is not None
