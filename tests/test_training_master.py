"""Distributed control-plane tests — mirrors the reference Spark test
strategy (SURVEY.md section 4 "Distributed-without-a-cluster"): local-mode
masters on the 8-device CPU mesh, stats collection
(TestTrainingStatsCollection), repartitioning invariants
(TestRepartitioning), distributed eval merge, distributed early stopping
(TestEarlyStoppingSpark)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.iterator import DataSet, ListDataSetIterator
from deeplearning4j_tpu.earlystopping.config import EarlyStoppingConfiguration
from deeplearning4j_tpu.earlystopping.distributed import (
    DistributedEarlyStoppingTrainer,
)
from deeplearning4j_tpu.earlystopping.terminations import (
    MaxEpochsTerminationCondition,
)
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.multihost import (
    MultiHostConfig,
    initialize_multihost,
    local_batch_slice,
    process_info,
)
from deeplearning4j_tpu.parallel.stats import (
    NTPTimeSource,
    SystemClockTimeSource,
    TrainingStats,
)
from deeplearning4j_tpu.parallel.training_master import (
    DistributedEvaluator,
    ParameterAveragingTrainingMaster,
    Repartition,
    SparkStyleNetwork,
    balanced_splits,
)


def small_net(seed=12345, lr=0.1):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(lr)
        .updater("sgd")
        .weight_init("xavier")
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=16, activation="tanh"))
        .layer(1, OutputLayer(n_in=16, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def iris_like(n, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    # fixed labeling rule so train/val come from the same task
    w = np.random.default_rng(42).normal(size=(4, 3))
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


def datasets_of(n, batch, seed=0):
    x, y = iris_like(n, seed)
    return [DataSet(x[i : i + batch], y[i : i + batch])
            for i in range(0, n, batch)]


class TestBalancedSplits:
    def test_exact_balance(self):
        sls = balanced_splits(10, 3)
        sizes = [s.stop - s.start for s in sls]
        assert sizes == [4, 3, 3]
        assert sls[-1].stop == 10

    def test_more_workers_than_items(self):
        sls = balanced_splits(2, 4)
        assert [s.stop - s.start for s in sls] == [1, 1, 0, 0]


class TestParameterAveragingMaster:
    def test_training_reduces_score(self):
        net = small_net()
        master = ParameterAveragingTrainingMaster(
            num_workers=4, batch_size_per_worker=8, averaging_frequency=2,
        )
        data = datasets_of(4 * 8 * 2 * 3, 32)
        before = net.score(*iris_like(64, seed=9))
        SparkStyleNetwork(net, master).fit(data)
        after = net.score(*iris_like(64, seed=9))
        assert after < before

    def test_stats_collection(self):
        net = small_net()
        master = ParameterAveragingTrainingMaster(
            num_workers=2, batch_size_per_worker=8, averaging_frequency=2,
            collect_training_stats=True,
        )
        master.execute_training(net, datasets_of(2 * 8 * 2 * 2, 16))
        stats = master.get_training_stats()
        summary = stats.summary()
        assert "split" in summary and "fit" in summary
        assert summary["fit"]["count"] == 2  # two averaging rounds

    def test_insufficient_data_raises(self):
        net = small_net()
        master = ParameterAveragingTrainingMaster(
            num_workers=8, batch_size_per_worker=16, averaging_frequency=5,
        )
        with pytest.raises(ValueError, match="averaging round"):
            master.execute_training(net, datasets_of(32, 16))

    def test_repartition_never_preserves_order(self):
        master = ParameterAveragingTrainingMaster(
            num_workers=2, batch_size_per_worker=4, averaging_frequency=1,
            repartition=Repartition.NEVER,
        )
        data = datasets_of(16, 8, seed=3)
        splits = list(master._splits(data))
        x0 = np.concatenate([np.asarray(d.features) for d in data])[:8]
        np.testing.assert_array_equal(splits[0][0], x0)


class TestDistributedEval:
    def test_merge_equals_serial(self):
        net = small_net()
        data = datasets_of(96, 16, seed=5)
        dist = DistributedEvaluator(num_shards=4).evaluate(net, data)
        serial = DistributedEvaluator(num_shards=1).evaluate(net, data)
        assert dist.accuracy() == pytest.approx(serial.accuracy())
        assert dist.f1() == pytest.approx(serial.f1())


class TestStats:
    def test_timeline_export(self, tmp_path):
        stats = TrainingStats()
        with stats.timed("fit", worker_id="w0", example_count=32):
            pass
        with stats.timed("aggregate", worker_id="w1"):
            pass
        html_path = str(tmp_path / "timeline.html")
        stats.export_html(html_path)
        content = open(html_path).read()
        assert "timeline" in content and "fit" in content and "aggregate" in content
        json_path = str(tmp_path / "stats.json")
        stats.export_json(json_path)
        assert "fit" in open(json_path).read()

    def test_time_sources(self):
        assert abs(
            SystemClockTimeSource().current_time_millis()
            - NTPTimeSource(offset_millis=0).current_time_millis()
        ) < 1000
        assert (
            NTPTimeSource(offset_millis=100_000).current_time_millis()
            > SystemClockTimeSource().current_time_millis() + 50_000
        )


class TestMultiHost:
    def test_single_process_defaults(self):
        assert initialize_multihost(MultiHostConfig()) is False
        info = process_info()
        assert info["process_count"] == 1
        assert info["process_index"] == 0

    def test_local_batch_slice_single(self):
        sl = local_batch_slice(64)
        assert sl == slice(0, 64)

    def test_config_from_env(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_COORDINATOR", "10.0.0.1:1234")
        monkeypatch.setenv("DL4J_TPU_NUM_PROCESSES", "4")
        monkeypatch.setenv("DL4J_TPU_PROCESS_ID", "2")
        cfg = MultiHostConfig.from_env()
        assert cfg.is_configured()
        assert cfg.num_processes == 4 and cfg.process_id == 2


class TestDistributedEarlyStopping:
    def test_stops_at_max_epochs(self):
        net = small_net()
        master = ParameterAveragingTrainingMaster(
            num_workers=2, batch_size_per_worker=8, averaging_frequency=1,
        )
        data = datasets_of(2 * 8 * 1 * 2, 16)
        cfg = EarlyStoppingConfiguration(
            epoch_terminations=[MaxEpochsTerminationCondition(3)],
        )
        trainer = DistributedEarlyStoppingTrainer(cfg, master, net, data)
        result = trainer.fit(max_epochs=50)
        assert result.total_epochs <= 4
        assert result.best_model is not None
