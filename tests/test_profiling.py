"""Xplane trace hook (utils/profiling.py — SURVEY section 5 profiling
mapping): traces capture on the CPU backend too, so the plumbing is
testable without the chip."""

import glob
import os

import numpy as np

from deeplearning4j_tpu.parallel.stats import TrainingStats
from deeplearning4j_tpu.utils.profiling import (
    XplaneTraceListener,
    link_stats,
    xplane_trace,
)


def _net():
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(1).learning_rate(0.1).list()
        .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _data(n=32):
    rng = np.random.default_rng(0)
    return (rng.normal(size=(n, 4)).astype(np.float32),
            np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)])


def test_xplane_trace_writes_artifacts(tmp_path):
    import jax.numpy as jnp

    logdir = str(tmp_path / "trace")
    with xplane_trace(logdir):
        (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
    # the profiler writes <logdir>/plugins/profile/<run>/*.xplane.pb
    found = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    assert found, f"no xplane artifact under {logdir}"


def test_trace_listener_captures_iteration_window(tmp_path):
    net = _net()
    x, y = _data()
    stats = TrainingStats()
    logdir = str(tmp_path / "fit_trace")
    lst = XplaneTraceListener(logdir, start_iteration=1, num_iterations=2,
                              stats=stats)
    net.set_listeners(lst)
    for _ in range(6):
        net.fit(x, y)
    lst.stop()  # idempotent; ensures closed even if window ran past end
    found = glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                      recursive=True)
    assert found, "listener window captured no trace"
    # the stats timeline links the trace directory
    assert any(e.event_type.startswith("xplane_trace:")
               for e in stats.events)


def test_link_stats_records_event():
    stats = TrainingStats()
    link_stats(stats, "/tmp/some_trace")
    assert stats.events[-1].event_type == "xplane_trace:/tmp/some_trace"


def test_xplane_trace_disabled_noop(tmp_path):
    with xplane_trace(str(tmp_path / "x"), enabled=False):
        pass
    assert not (tmp_path / "x").exists()
