"""Elastic fleet runtime (parallel/fleet.py — ISSUE 6).

The headline contract extends PR 3's resilience bar across MEMBERSHIP
changes: a run that loses worker k at round s (chaos kill, detected by
heartbeat expiry, in-flight split reclaimed and re-executed) and
re-admits a replacement at round s+m produces BIT-exact params and loss
curve versus a deterministic replay of the same membership schedule
(scripted evict/admit at the same round boundaries), and matches the
serial big-batch run to 1e-5 (the
TestCompareParameterAveragingSparkVsSingleMachine.java:115-262 bar).
Plus: fenced completions under stalled heartbeats (no split
double-counted), partitioned-coordinator retry/fallback, poisoned-split
loudness, the file membership transport, checkpoint/restore through
ResilientTrainer with the coordinator owning the single authoritative
checkpoint, and the cross-process (OS-process worker) path including
corrupt-checkpoint fallback under fleet restore.
"""

import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.fleet import (
    ElasticParameterAveragingTrainer,
    FileMembershipBoard,
    shard_for,
)
from deeplearning4j_tpu.resilience import (
    ChaosConfig,
    ChaosMonkey,
    CheckpointManager,
    FleetChaos,
    FleetChaosConfig,
    InjectedKill,
    ResilientTrainer,
)
from deeplearning4j_tpu.resilience import chaos as chaos_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# deterministic shared data; 12 examples/round divides by 1..4 workers
_RNG = np.random.default_rng(0)
ROUNDS, GB = 6, 12
X = _RNG.standard_normal((ROUNDS * GB, 4)).astype(np.float32)
Y = np.eye(3, dtype=np.float32)[_RNG.integers(0, 3, ROUNDS * GB)]


def build_mln() -> MultiLayerNetwork:
    conf = (
        NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf)


def round_batch(r: int):
    return X[r * GB:(r + 1) * GB], Y[r * GB:(r + 1) * GB]


def serial_run(rounds=ROUNDS):
    net = build_mln()
    for r in range(rounds):
        net.fit(*round_batch(r))
    return net


def params_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


def max_dev(a, b) -> float:
    return max(
        float(np.max(np.abs(np.asarray(x) - np.asarray(y))))
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)))


# ------------------------------------------------------------- equivalence
class TestElasticEquivalence:
    def test_static_fleet_matches_serial(self):
        """Sanity floor: no membership change — freq-1 SGD averaging over
        3 workers == serial big-batch (host-side averaging variant of the
        shard_map trainer's contract)."""
        fleet = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=3, averaging_frequency=1,
            heartbeat_s=1.0)
        try:
            for r in range(ROUNDS):
                fleet.fit(*round_batch(r))
        finally:
            fleet.close()
        assert max_dev(fleet.net.params, serial_run().params) < 1e-5
        assert fleet.resilience_stats["rounds"] == ROUNDS
        assert fleet.resilience_stats["reclaims"] == 0

    def test_worker_loss_and_rejoin_bit_exact_vs_replay_and_serial(self):
        """HEADLINE: lose a worker mid-round 2 (dies HOLDING its split —
        reclaimed, re-executed by a survivor), re-admit a replacement
        before round 4. Bit-exact vs the scripted replay of the same
        membership schedule; == serial to 1e-5."""
        chaos = FleetChaos(FleetChaosConfig(
            kill_split={"round": 2, "split": 1}))
        f1 = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=3, averaging_frequency=1,
            heartbeat_s=1.0, chaos=chaos)
        l1 = []
        try:
            for r in range(ROUNDS):
                if r == 3:
                    f1.admit_worker("replacement")
                l1.append(float(f1.fit(*round_batch(r))))
        finally:
            f1.close()
        assert f1.resilience_stats["reclaims"] == 1
        assert chaos.log and chaos.log[0][0] == 2

        # deterministic replay: same membership schedule, no faults —
        # evict at the round-2 boundary, admit before round 4
        f2 = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=3, averaging_frequency=1,
            heartbeat_s=1.0)
        l2 = []
        try:
            for r in range(ROUNDS):
                if r == 2:
                    f2.evict_worker("w1")
                if r == 3:
                    f2.admit_worker("replacement")
                l2.append(float(f2.fit(*round_batch(r))))
        finally:
            f2.close()
        assert l1 == l2, "loss curve diverged from the membership replay"
        assert params_equal(f1.net.params, f2.net.params)
        assert params_equal(f1.net.updater_state, f2.net.updater_state)
        assert max_dev(f1.net.params, serial_run().params) < 1e-5
        # membership really changed: 3 -> 2 -> 3 workers
        assert f1.epoch >= 3

    def test_worker_join_reforms_rounds(self):
        """Fleet GROWS mid-run: rounds re-form over the enlarged set and
        the run still matches serial (split count is membership-driven,
        numerics membership-schedule-deterministic)."""
        fleet = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=2, averaging_frequency=1,
            heartbeat_s=1.0)
        try:
            for r in range(ROUNDS):
                if r == 2:
                    fleet.admit_worker()
                    fleet.admit_worker()
                fleet.fit(*round_batch(r))
        finally:
            fleet.close()
        assert max_dev(fleet.net.params, serial_run().params) < 1e-5
        assert fleet.epoch >= 2

    def test_uneven_split_raises_loud(self):
        """Satellite: a round that does not divide across the live
        membership fails LOUDLY instead of silently truncating the tail
        (the multihost.local_batch_slice rule)."""
        fleet = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=3, averaging_frequency=1,
            heartbeat_s=1.0)
        try:
            with pytest.raises(ValueError, match="not divisible by 3 live"):
                fleet.fit(X[:10], Y[:10])
        finally:
            fleet.close()

    def test_elastic_training_master(self):
        """ElasticParameterAveragingTrainingMaster: the Spark-style
        master's split/average loop over the fleet trainer == the base
        (shard_map) master on the same data/seed to 1e-5."""
        from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
        from deeplearning4j_tpu.parallel.training_master import (
            ElasticParameterAveragingTrainingMaster,
            ParameterAveragingTrainingMaster,
        )

        mk_it = lambda: ListDataSetIterator(X[:48], Y[:48], batch=12)
        base_net, elastic_net = build_mln(), build_mln()
        ParameterAveragingTrainingMaster(
            num_workers=2, batch_size_per_worker=6, averaging_frequency=1,
        ).execute_training(base_net, mk_it())
        with ElasticParameterAveragingTrainingMaster(
                num_workers=2, batch_size_per_worker=6,
                averaging_frequency=1,
                fleet_kwargs={"heartbeat_s": 1.0}) as master:
            master.execute_training(elastic_net, mk_it())
        assert master.fleet is None  # close() owned the fleet lifecycle
        assert max_dev(base_net.params, elastic_net.params) < 1e-5

    def test_admit_after_evict_gets_fresh_id(self):
        """Generated member ids never collide with a live member after
        an eviction (a collision would orphan the old thread and make
        the admit a membership no-op)."""
        fleet = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=3, averaging_frequency=1,
            heartbeat_s=1.0)
        try:
            fleet.fit(*round_batch(0))
            fleet.evict_worker("w0")
            wid = fleet.admit_worker()
            assert wid not in ("w1", "w2")
            deadline = time.time() + 5
            while len(fleet.tracker.live_workers()) < 3:
                assert time.time() < deadline
                time.sleep(0.01)
            with pytest.raises(ValueError, match="already a live member"):
                fleet.admit_worker("w1")
        finally:
            fleet.close()


# ------------------------------------------------------------ fleet faults
class TestFleetFaults:
    def test_stalled_heartbeat_fenced_no_double_count(self):
        """A zombie (alive, heartbeat stalled past the timeout) loses its
        split to reclaim; its LATE completion is fenced out by the
        attempt number (counted, never applied), it re-registers, and the
        round's numerics equal the fault-free run — no split dropped, no
        split double-counted."""
        chaos = FleetChaos(FleetChaosConfig(
            stall_heartbeat={"round": 1, "split": 0, "sleep_s": 2.5}))
        f1 = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=2, averaging_frequency=1,
            heartbeat_s=0.4, chaos=chaos)
        try:
            for r in range(2):
                f1.fit(*round_batch(r))
            # the zombie wakes AFTER its round completed: wait for its
            # late completion to hit the fence before asserting
            deadline = time.monotonic() + 10.0
            while (f1.tracker.stale_completions < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
        finally:
            f1.close()
        assert f1.resilience_stats["reclaims"] >= 1
        assert f1.tracker.stale_completions >= 1
        # replay of the detected schedule: the zombie was deregistered at
        # reclaim, so round 2 formed over ONE worker — script the same
        f2 = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=2, averaging_frequency=1,
            heartbeat_s=0.4)
        try:
            f2.fit(*round_batch(0))
            f2.evict_worker("w1")
            f2.fit(*round_batch(1))
        finally:
            f2.close()
        assert params_equal(f1.net.params, f2.net.params), \
            "zombie completion leaked into the average"

    def test_partitioned_coordinator_retries(self):
        """Membership-plane partition (CoordinatorPartitioned on the
        first polls of round 2): the coordinator retries with backoff and
        the run completes bit-identical to the unpartitioned one."""
        chaos = FleetChaos(FleetChaosConfig(
            partition_coordinator={"at_round": 2, "polls": 3}))
        f1 = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=2, averaging_frequency=1,
            heartbeat_s=1.0, chaos=chaos)
        try:
            for r in range(3):
                f1.fit(*round_batch(r))
        finally:
            f1.close()
        assert f1.resilience_stats["membership_retries"] == 3
        f2 = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=2, averaging_frequency=1,
            heartbeat_s=1.0)
        try:
            for r in range(3):
                f2.fit(*round_batch(r))
        finally:
            f2.close()
        assert params_equal(f1.net.params, f2.net.params)

    def test_poisoned_split_is_loud(self, monkeypatch):
        """A split that fails every attempt routes to the dead-letter
        list and the round raises — a batch may not silently vanish."""
        fleet = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=2, averaging_frequency=1,
            heartbeat_s=1.0, job_max_attempts=2, round_timeout_s=30.0)
        monkeypatch.setattr(
            fleet, "_execute_split",
            lambda payload: (_ for _ in ()).throw(RuntimeError("boom")))
        try:
            with pytest.raises(RuntimeError, match="poisoned"):
                fleet.fit(*round_batch(0))
        finally:
            fleet.close()


# ----------------------------------------------------- membership transports
class TestMembershipTransports:
    def test_file_membership_board(self, tmp_path):
        board = FileMembershipBoard(str(tmp_path), heartbeat_timeout=0.2)
        board.register_worker("a")
        board.register_worker("b")
        assert sorted(board.live_workers()) == ["a", "b"]
        board.deregister_worker("a")  # announced departure
        assert board.live_workers() == ["b"]
        time.sleep(0.3)  # b's heartbeat goes stale
        assert board.live_workers() == []
        board.heartbeat("b")
        assert board.live_workers() == ["b"]

    def test_fleet_over_file_board(self, tmp_path):
        """The file transport as the fleet's membership authority: rounds
        form over the board's live set, == serial."""
        board = FileMembershipBoard(str(tmp_path), heartbeat_timeout=1.0)
        fleet = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=2, averaging_frequency=1,
            heartbeat_s=1.0, membership_board=board)
        try:
            for r in range(2):
                fleet.fit(*round_batch(r))
        finally:
            fleet.close()
        assert max_dev(fleet.net.params, serial_run(2).params) < 1e-5

    def test_board_outage_reads_as_partition_not_empty_fleet(self,
                                                             tmp_path,
                                                             monkeypatch):
        """A shared-mount blip must surface as ConnectionError (the
        coordinator's retry/fallback path), never as an empty live set
        that runs the round-timeout clock out."""
        board = FileMembershipBoard(str(tmp_path), heartbeat_timeout=1.0)
        board.register_worker("a")
        monkeypatch.setattr(os, "listdir",
                            lambda p: (_ for _ in ()).throw(OSError("nfs")))
        with pytest.raises(ConnectionError, match="membership board"):
            board.live_workers()

    def test_shard_for(self):
        assert shard_for("b", ["c", "a", "b"]) == (1, 3)
        assert shard_for("gone", ["a"]) is None

    def test_membership_listener_reshards_pipeline(self):
        """Live ETL resharding hook: on a membership change the attached
        pipeline is re-partitioned to this member's (rank, count) at the
        agreed boundary."""
        from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
        from deeplearning4j_tpu.etl.pipeline import InputPipeline

        pipe = InputPipeline(ListDataSetIterator(X[:32], Y[:32], 4),
                             workers=1, device_put=False, shard=(0, 3))
        fleet = ElasticParameterAveragingTrainer(
            build_mln(), num_workers=3, averaging_frequency=1,
            heartbeat_s=1.0)
        fleet.attach_pipeline(pipe, "w0", boundary_fn=lambda: 100)
        from deeplearning4j_tpu.etl.pipeline import DROP_SHARD

        gone = InputPipeline(ListDataSetIterator(X[:32], Y[:32], 4),
                             workers=1, device_put=False, shard=(2, 3))
        fleet.attach_pipeline(gone, "w2", boundary_fn=lambda: 100)
        try:
            fleet.fit(*round_batch(0))  # first membership note: 3 workers
            fleet.evict_worker("w2")
            fleet.fit(*round_batch(1))  # re-forms over 2, reshards at 100
        finally:
            fleet.close()
        sched = pipe._shard_schedule_snapshot()
        assert sched[-1] == [100, [0, 2]], sched
        # the DEPARTED member's pipeline owns NOTHING from the boundary
        # (None would mean "everything" and double-feed the survivors)
        assert gone._shard_schedule_snapshot()[-1] == [100, DROP_SHARD]


# -------------------------------------------------- resilience integration
class TestFleetResilience:
    def test_fleet_kill_resume_bit_exact(self, tmp_path):
        """PR 3's crash-recovery bar over the ELASTIC trainer: the
        coordinator is killed at round 3 (chaos), a fresh coordinator +
        fleet restores the authoritative checkpoint and finishes —
        params and loss curve bit-identical to uninterrupted."""
        from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator

        mk_it = lambda: ListDataSetIterator(X, Y, batch=GB)
        mk_fleet = lambda chaos=None: ElasticParameterAveragingTrainer(
            build_mln(), num_workers=2, averaging_frequency=1,
            heartbeat_s=1.0)

        baseline = ResilientTrainer(mk_fleet())
        baseline.fit(mk_it(), num_epochs=1)
        baseline.trainee.close()

        mgr = CheckpointManager(str(tmp_path), every_steps=2, keep_last=2)
        killed_fleet = mk_fleet()
        killed = ResilientTrainer(
            killed_fleet, mgr,
            chaos=ChaosMonkey(ChaosConfig(kill_at_step=3)))
        with pytest.raises(InjectedKill):
            killed.fit(mk_it(), num_epochs=1)
        mgr.close()
        killed_fleet.close()

        mgr2 = CheckpointManager(str(tmp_path), every_steps=2, keep_last=2)
        resumed_fleet = mk_fleet()
        resumed = ResilientTrainer(resumed_fleet, mgr2)
        resumed.fit(mk_it(), num_epochs=1)
        mgr2.close()
        resumed_fleet.close()

        assert resumed.resumed_step == 2
        stitched = killed.losses[:2] + resumed.losses
        assert stitched == baseline.losses
        assert params_equal(baseline.net.params, resumed.net.params)
        # the shared fault-plane ledger: fleet counters + trainer counters
        # in ONE dict on the net (beside dispatch_stats)
        assert resumed.net.resilience_stats["resumes"] == 1
        assert resumed.net.resilience_stats["rounds"] == 4

    def _spawn_worker(self, addr, wid, spool):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
        return subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "fleet_worker.py"),
             addr, wid, spool],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)

    def _await_member(self, fleet, wid, proc, timeout=90.0):
        deadline = time.monotonic() + timeout
        while wid not in fleet.tracker.live_workers():
            if proc.poll() is not None:
                raise AssertionError(
                    f"worker died: {proc.stderr.read()[-800:]}")
            assert time.monotonic() < deadline, "worker never registered"
            time.sleep(0.05)

    def test_corrupt_checkpoint_fleet_restore_multiprocess(self, tmp_path):
        """Satellite: verify-then-trust under FLEET restore on the
        multi-process path. A coordinator driving a real OS-process
        worker checkpoints per round; the latest checkpoint is
        chaos-truncated; the restoring coordinator falls back to the
        prior VERIFIED checkpoint and the fleet resumes — final params
        bit-identical to the uninterrupted multi-process run."""
        rounds = 4

        def run(tag, kill_at=None, resume=False):
            spool = str(tmp_path / f"spool-{tag}")
            ck = str(tmp_path / "ckpt")
            fleet = ElasticParameterAveragingTrainer(
                build_mln(), num_workers=0, averaging_frequency=1,
                heartbeat_s=2.0, min_workers=1, spool_dir=spool)
            addr = fleet.serve()
            proc = self._spawn_worker(addr, "ext0", spool)
            try:
                self._await_member(fleet, "ext0", proc)
                mgr = CheckpointManager(
                    ck, every_steps=1, keep_last=3,
                    async_save=False) if (kill_at or resume) else None
                chaos = (ChaosMonkey(ChaosConfig(kill_at_step=kill_at))
                         if kill_at else None)
                trainer = ResilientTrainer(fleet, mgr, chaos=chaos,
                                           resume=resume)
                from deeplearning4j_tpu.datasets.iterator import (
                    ListDataSetIterator,
                )

                it = ListDataSetIterator(X[:rounds * GB], Y[:rounds * GB],
                                         batch=GB)
                if kill_at:
                    with pytest.raises(InjectedKill):
                        trainer.fit(it, num_epochs=1)
                else:
                    trainer.fit(it, num_epochs=1)
                if mgr:
                    mgr.close()
                return trainer
            finally:
                fleet.close()
                proc.terminate()
                proc.wait(timeout=30)

        baseline = run("base")
        killed = run("killed", kill_at=3)
        # chaos-truncate the LATEST checkpoint (step 3): restore must
        # fall back to the prior verified one (step 2), not load garbage
        mgr_probe = CheckpointManager(str(tmp_path / "ckpt"))
        (_, newest) = mgr_probe.checkpoints()[-1]
        chaos_mod.truncate_file(os.path.join(newest, "model.zip"), keep=12)
        resumed = run("resumed", resume=True)
        assert resumed.resumed_step == 2  # fell back past the corrupt 3
        stitched = killed.losses[:2] + resumed.losses
        assert stitched == baseline.losses
        assert params_equal(baseline.net.params, resumed.net.params)
