"""Multi-host runtime exercised across REAL OS processes.

The reference's distributed plane is genuinely multi-process (Spark
executors + Aeron broadcast; SURVEY.md section 2.3/2.7). Until round 4
`parallel/multihost.py` was validated only single-process; this harness
spawns a 2-process jax.distributed CPU cluster (2 local devices each, 4
global, collectives over Gloo) wired through the SAME env-var contract
the TPU pod provisioner injects, and asserts the framework's actual DP
training path (ParallelWrapper.fit and the fused fit_batches scan) is
bit-identical to serial training — the
TestCompareParameterAveragingSparkVsSingleMachine property, across
process boundaries.
"""
import os
import socket
import subprocess
import sys


from deeplearning4j_tpu.parallel import multihost

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_dp_training_matches_serial():
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env[multihost.COORDINATOR_ENV] = f"127.0.0.1:{port}"
        env[multihost.NUM_PROCESSES_ENV] = "2"
        env[multihost.PROCESS_ID_ENV] = str(pid)
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    if any("MH_SKIP" in out for _, out, _ in outs):
        import pytest

        pytest.skip("this jaxlib cannot run multi-process computations on "
                    "the CPU backend (worker capability probe)")
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
        assert "MH_OK" in out, out
        assert "max_param_dev=0.0" in out, out
    # both processes saw the same replicated final loss
    losses = {line.split("loss=")[1].split()[0]
              for _, out, _ in outs for line in out.splitlines()
              if "MH_OK" in line}
    assert len(losses) == 1, losses
