"""Distributed NLP training tests (VERDICT round-1 missing #4).

Mirrors the reference dl4j-spark-nlp surface: TextPipeline partitioned vocab
build (spark/text/functions/TextPipeline.java) and data-parallel
Word2Vec/GloVe (spark/models/embeddings/word2vec/Word2Vec.java:65) — on the
virtual 8-device CPU mesh, following the distributed==serial test strategy
(SURVEY.md §4)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.text_pipeline import TextPipeline
from deeplearning4j_tpu.nlp.word2vec import Word2Vec

from tests.test_nlp import make_corpus


class TestTextPipeline:
    def test_counts_match_serial(self):
        corpus = make_corpus(n=200)
        tp8 = TextPipeline(min_word_frequency=1, num_partitions=8).fit(corpus)
        tp1 = TextPipeline(min_word_frequency=1, num_partitions=1).fit(corpus)
        assert tp8.word_counts == tp1.word_counts

    def test_vocab_matches_word2vec_build(self):
        corpus = make_corpus(n=200)
        tp = TextPipeline(min_word_frequency=2, num_partitions=8).fit(corpus)
        w2v = Word2Vec(layer_size=8, min_word_frequency=2)
        w2v.build_vocab(w2v._tokenize_corpus(corpus))
        words_tp = {w.word for w in tp.vocab.vocab_words()}
        words_w2v = {w.word for w in w2v.vocab.vocab_words()}
        assert words_tp == words_w2v

    def test_min_frequency_filter(self):
        tp = TextPipeline(min_word_frequency=3, num_partitions=4).fit(
            ["a a a b b c"]
        )
        assert set(tp.filtered_counts()) == {"a"}


class TestDistributedWord2Vec:
    def test_8dev_matches_serial_exactly(self):
        """Sharded batches + GSPMD psum of the scatter updates compute the
        SAME math as the serial step — tables must match (tolerance covers
        reduction-order-sensitive float sums)."""
        corpus = make_corpus(n=120)
        kw = dict(layer_size=16, window=3, epochs=1, seed=4, negative=5,
                  batch_size=256)
        serial = Word2Vec(**kw).fit(corpus)
        dist = Word2Vec(num_workers=8, **kw).fit(corpus)
        np.testing.assert_allclose(
            serial.lookup_table.syn0, dist.lookup_table.syn0,
            rtol=5e-4, atol=5e-6,
        )

    def test_8dev_similarity_quality(self):
        """The distributed model passes the same topical-similarity bar as
        the serial tests (reference Word2VecTests pattern)."""
        vec = Word2Vec(layer_size=32, window=3, epochs=3, seed=11,
                       negative=5, batch_size=512, num_workers=8)
        vec.fit(make_corpus(n=300))
        in_cluster = vec.similarity("day", "night")
        cross = vec.similarity("day", "cat")
        assert in_cluster > cross, (in_cluster, cross)

    def test_batch_size_divisibility_validated(self):
        with pytest.raises(ValueError, match="divisible"):
            Word2Vec(batch_size=100, num_workers=8)


class TestDistributedGlove:
    def test_8dev_matches_serial(self):
        corpus = make_corpus(n=150)
        kw = dict(layer_size=16, epochs=2, min_word_frequency=1, seed=5,
                  batch_size=512)
        serial = Glove(**kw).fit(corpus)
        dist = Glove(num_workers=8, **kw).fit(corpus)
        np.testing.assert_allclose(serial.W, dist.W, rtol=5e-4, atol=5e-6)

    def test_batch_size_divisibility_validated(self):
        with pytest.raises(ValueError, match="divisible"):
            Glove(batch_size=100, num_workers=8)
