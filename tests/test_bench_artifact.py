"""Bench artifact plumbing: merge-across-passes persistence + the
watcher's completeness checker.

Round-4 regression cover: the tunnel died ~3 minutes into first contact
and a timed-out retry leg OVERWROTE the measured rows in
BENCH_PARTIAL.json (observed 2026-07-31 04:08). The reference keeps
long-lived benchmark state out of scope (it publishes no numbers —
BASELINE.md), so this contract is ours: an error row must never clobber a
measured row; a fresh measured row always replaces an older one.
"""
import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "benchmod", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_persist_partial_merges_across_passes(tmp_path):
    m = _load_bench()
    m._PARTIAL_PATH = str(tmp_path / "partial.json")
    # pass 1: a measured row
    m._persist_partial({"lenet5": {"samples_per_sec": 100.0, "ts": "t1"}})
    # pass 2: the tunnel died — error rows for both legs
    m._persist_partial({"lenet5": {"error": "tunnel died", "ts": "t2"},
                        "char_rnn": {"error": "down", "ts": "t2"}})
    # pass 3: char_rnn measured on a later contact
    m._persist_partial({"char_rnn": {"tokens_per_sec": 5.0, "ts": "t3"}})
    legs = json.load(open(m._PARTIAL_PATH))["legs"]
    # measured row survived the error pass, annotated not clobbered
    assert legs["lenet5"]["samples_per_sec"] == 100.0
    assert "error" not in legs["lenet5"]
    assert legs["lenet5"]["last_error"] == "tunnel died"
    assert legs["lenet5"]["last_error_ts"] == "t2"
    # error row was upgraded to the later measured row
    assert legs["char_rnn"] == {"tokens_per_sec": 5.0, "ts": "t3"}


def test_fill_skip_semantics():
    m = _load_bench()
    measured_full = {"samples_per_sec": 10.0, "quick": False}
    measured_quick = {"samples_per_sec": 10.0, "quick": True}
    errored = {"error": "tunnel"}
    # quick --fill: any measured row is good enough
    assert m._fill_skip(measured_full, quick=True)
    assert m._fill_skip(measured_quick, quick=True)
    # full --fill: quick rows get re-measured at full length
    assert m._fill_skip(measured_full, quick=False)
    assert not m._fill_skip(measured_quick, quick=False)
    # errors and gaps always re-run
    assert not m._fill_skip(errored, quick=True)
    assert not m._fill_skip(None, quick=True)
    # legacy rows without the quick stamp count as full-length
    assert m._fill_skip({"samples_per_sec": 1.0}, quick=False)


def test_persist_partial_measured_replaces_measured(tmp_path):
    m = _load_bench()
    m._PARTIAL_PATH = str(tmp_path / "partial.json")
    m._persist_partial({"lenet5": {"samples_per_sec": 100.0, "ts": "t1"}})
    m._persist_partial({"lenet5": {"samples_per_sec": 250.0, "ts": "t2"}})
    legs = json.load(open(m._PARTIAL_PATH))["legs"]
    assert legs["lenet5"] == {"samples_per_sec": 250.0, "ts": "t2"}


def _run_state(path):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_state.py"),
         str(path)], capture_output=True, text=True)


def test_bench_state_checker(tmp_path):
    from scripts.bench_state import EXPECTED

    p = tmp_path / "partial.json"
    legs = {name: {"x": 1.0} for name in EXPECTED}
    p.write_text(json.dumps({"legs": legs}))
    assert _run_state(p).returncode == 0
    # one leg errored -> incomplete
    legs["resnet50"] = {"error": "oom"}
    p.write_text(json.dumps({"legs": legs}))
    r = _run_state(p)
    assert r.returncode == 1 and "resnet50" in r.stdout
    # one leg missing entirely -> incomplete
    del legs["north_star"]
    legs["resnet50"] = {"x": 1.0}
    p.write_text(json.dumps({"legs": legs}))
    r = _run_state(p)
    assert r.returncode == 1 and "north_star" in r.stdout
    # extras schema (BENCH_WATCH.json shape) is readable too
    p.write_text(json.dumps(
        {"metric": "m", "extras": {name: {"x": 1.0} for name in EXPECTED}}))
    assert _run_state(p).returncode == 0


def test_bench_state_warns_on_time_skew(tmp_path):
    """Rows measured >6h apart (a multi-window capture) get a WARN line
    without changing the completeness verdict (VERDICT r5 ask #9)."""
    from scripts.bench_state import EXPECTED

    legs = {name: {"x": 1.0, "ts": "2026-08-04T01:00:00"}
            for name in EXPECTED}
    legs["lenet5"]["ts"] = "2026-08-04T09:30:00"  # 8.5h after the rest
    p = tmp_path / "partial.json"
    p.write_text(json.dumps({"legs": legs}))
    r = _run_state(p)
    assert r.returncode == 0  # complete — warnings don't fail
    assert "WARN:" in r.stdout and "span" in r.stdout
    assert "lenet5" in r.stdout


def test_bench_state_warns_on_load_regime_skew(tmp_path):
    from scripts.bench_state import EXPECTED

    legs = {name: {"x": 1.0, "load1": 0.2} for name in EXPECTED}
    legs["resnet50"]["load1"] = 3.4  # contended-host row among quiet rows
    p = tmp_path / "partial.json"
    p.write_text(json.dumps({"legs": legs}))
    r = _run_state(p)
    assert r.returncode == 0
    assert "WARN:" in r.stdout and "load1" in r.stdout
    assert "resnet50" in r.stdout


def test_bench_state_quiet_when_conditions_match(tmp_path):
    from scripts.bench_state import EXPECTED

    legs = {name: {"x": 1.0, "ts": "2026-08-04T01:00:00", "load1": 0.5}
            for name in EXPECTED}
    p = tmp_path / "partial.json"
    p.write_text(json.dumps({"legs": legs}))
    r = _run_state(p)
    assert r.returncode == 0 and "WARN" not in r.stdout
    # error rows are excluded from skew analysis (their ts is outage
    # bookkeeping, not a measurement condition)
    legs["north_star"] = {"error": "down", "ts": "2026-08-05T23:00:00"}
    p.write_text(json.dumps({"legs": legs}))
    r = _run_state(p)
    assert r.returncode == 1 and "WARN" not in r.stdout


def test_bench_state_expected_matches_bench_legs():
    """Three-way pin: an INDEPENDENT parse of bench.py's run() calls must
    be non-empty (else the checker's regex broke and expected_legs() is
    silently running on the frozen fallback), must match the EXPECTED
    fallback (leg-list drift), and must be what expected_legs() returns."""
    import re

    from scripts.bench_state import EXPECTED, expected_legs

    src = open(os.path.join(REPO, "bench.py")).read()
    legs_direct = re.findall(r'^\s*run\("([a-z0-9_]+)"', src, re.M)
    assert legs_direct, "leg regex no longer matches bench.py"
    assert sorted(legs_direct) == sorted(EXPECTED)
    legs = expected_legs()
    # identity check: the fallback path returns the EXPECTED list OBJECT
    # itself, so a broken checker regex can't hide behind equal contents
    assert legs is not EXPECTED, "expected_legs() fell back to EXPECTED"
    assert legs == legs_direct


def test_remat_memory_leg_registered():
    """ISSUE 4: the remat_memory leg (AOT memory ladder evidence) is in
    the expected set — both the live parse of bench.py's run() calls and
    the EXPECTED fallback — so the watcher's completeness check demands
    the HBM-lean evidence row every round."""
    from scripts.bench_state import EXPECTED, expected_legs

    assert "remat_memory" in EXPECTED
    assert "remat_memory" in expected_legs()


def test_input_pipeline_leg_registered():
    """ISSUE 5: the input_pipeline leg (naive single-thread feed vs the
    overlapped InputPipeline, CPU-measurable) is in the expected set AND
    in bench.py's CPU-only set — the ingest proof must run (and persist)
    even with the tunnel dead."""
    from scripts.bench_state import EXPECTED, expected_legs

    assert "input_pipeline" in EXPECTED
    assert "input_pipeline" in expected_legs()
    m = _load_bench()
    assert "input_pipeline" in m._CPU_ONLY_LEGS


def test_elastic_dp_leg_registered():
    """ISSUE 6: the elastic_dp leg (averaging-round overhead of the
    elastic fleet at N workers, with/without one lost worker) is in the
    expected set AND in bench.py's CPU-only set — the fleet control
    plane is host-side work, so its proof must run (and persist) even
    with the tunnel dead."""
    from scripts.bench_state import EXPECTED, expected_legs

    assert "elastic_dp" in EXPECTED
    assert "elastic_dp" in expected_legs()
    m = _load_bench()
    assert "elastic_dp" in m._CPU_ONLY_LEGS


def test_online_loop_leg_registered():
    """ISSUE 14: the online_loop leg (ingest -> fit round -> candidate
    export -> shadow stage -> gated promotion cycle time + the
    shadow-mirror /predict overhead bar) is in the expected set AND in
    bench.py's CPU-only set — the loop is host-side orchestration, so
    its proof must run (and persist) even with the tunnel dead."""
    from scripts.bench_state import EXPECTED, expected_legs

    assert "online_loop" in EXPECTED
    assert "online_loop" in expected_legs()
    m = _load_bench()
    assert "online_loop" in m._CPU_ONLY_LEGS


def test_kernel_legs_registered():
    """ISSUE 13: the paged_kernel / sgns_kernel legs (interpret-mode CPU
    equivalence when the tunnel is dead, compiled real-chip measured-win
    rows at contact) are in the expected set AND in bench.py's CPU-only
    set — the watcher demands an honest row every round either way."""
    from scripts.bench_state import EXPECTED, expected_legs

    m = _load_bench()
    for leg in ("paged_kernel", "sgns_kernel"):
        assert leg in EXPECTED
        assert leg in expected_legs()
        assert leg in m._CPU_ONLY_LEGS


def test_bench_state_warns_on_interpret_gate_rows(tmp_path):
    """ISSUE 13: a CPU/interpret-mode row inside PALLAS_BENCH.json gets
    a WARN naming the kernel (NOT chip evidence; the measured-win gate
    ignores it) — and a real-chip row stays quiet."""
    from scripts.bench_state import kernel_gate_warnings

    art = tmp_path / "pallas.json"
    art.write_text(json.dumps({
        "paged": {"d8_h16": {"speedup": 3.0, "backend": "cpu",
                             "interpret": True}},
        "sgns": {"v100k": {"speedup": 1.4, "backend": "tpu",
                           "interpret": False}},
        "verdicts": {"paged": "smoke only"},
    }))
    warns = kernel_gate_warnings(str(art))
    assert len(warns) == 1
    assert "paged.d8_h16" in warns[0] and "NOT" in warns[0]
    # the real committed artifact must carry no interpret-mode rows
    assert kernel_gate_warnings() == []
