"""Measured-win gate (ops/kernel_gate.py): default-on requires a committed
on-chip PALLAS_BENCH.json row beating the XLA twin."""

import json

import pytest

from deeplearning4j_tpu.ops import kernel_gate


@pytest.fixture
def artifact(tmp_path, monkeypatch):
    path = tmp_path / "PALLAS_BENCH.json"
    monkeypatch.setattr(kernel_gate, "_ARTIFACT", str(path))
    kernel_gate.reload()
    yield path
    kernel_gate.reload()


def test_no_artifact_defaults_off(artifact):
    assert not kernel_gate.measured_win("attention", "ring_local_flash")
    assert kernel_gate.measured_win("attention", "x", default=True)


def test_tpu_win_row_enables(artifact):
    artifact.write_text(json.dumps(
        {"attention": {"ring_local_flash":
                       {"speedup": 1.4, "backend": "tpu"}}}))
    kernel_gate.reload()
    assert kernel_gate.measured_win("attention", "ring_local_flash")


def test_loss_row_disables(artifact):
    artifact.write_text(json.dumps(
        {"attention": {"ring_local_flash":
                       {"speedup": 0.9, "backend": "tpu"}}}))
    kernel_gate.reload()
    assert not kernel_gate.measured_win("attention", "ring_local_flash")


def test_cpu_or_interpret_rows_do_not_count(artifact):
    artifact.write_text(json.dumps(
        {"attention": {"a": {"speedup": 2.0, "backend": "cpu"},
                       "b": {"speedup": 2.0, "interpret": True,
                             "backend": "tpu"}}}))
    kernel_gate.reload()
    assert not kernel_gate.measured_win("attention", "a")
    assert not kernel_gate.measured_win("attention", "b")


def test_record_win_merges_and_enables(artifact):
    artifact.write_text(json.dumps(
        {"lstm_legacy": {"keep": {"speedup": 9.9}}}))
    kernel_gate.reload()
    kernel_gate.record_win("attention", "masked_flash",
                           {"speedup": 1.2, "backend": "tpu"})
    assert kernel_gate.measured_win("attention", "masked_flash")
    data = json.loads(artifact.read_text())
    assert data["lstm_legacy"]["keep"]["speedup"] == 9.9  # preserved


def test_force_env_overrides(artifact, monkeypatch):
    monkeypatch.setenv("DL4J_TPU_PALLAS_FORCE", "1")
    assert kernel_gate.measured_win("attention", "anything")


class TestLstmWinTable:
    def test_nearest_shape_class_decides(self, artifact):
        artifact.write_text(json.dumps({"lstm": {
            "small": {"n": 32, "t": 128, "h": 128, "speedup": 0.93,
                      "backend": "tpu", "interpret": False},
            "large": {"n": 128, "t": 512, "h": 512, "speedup": 2.2,
                      "backend": "tpu", "interpret": False},
        }}))
        kernel_gate.reload()
        from deeplearning4j_tpu.ops.pallas_kernels import lstm_kernel_wins

        assert not lstm_kernel_wins(32, 128, 128)   # nearest: losing row
        assert lstm_kernel_wins(128, 512, 512)      # nearest: winning row
        assert lstm_kernel_wins(256, 512, 1024)     # beyond largest: wins

    def test_legacy_cases_rows_parse(self, artifact):
        artifact.write_text(json.dumps({"cases": [
            {"n": 64, "t": 256, "h": 256, "scan_ms": 2.4, "pallas_ms": 1.5,
             "pallas_interpret_mode": False,
             "scan_speedup_over_pallas": 0.63},
        ]}))
        kernel_gate.reload()
        from deeplearning4j_tpu.ops.pallas_kernels import lstm_kernel_wins

        assert lstm_kernel_wins(64, 256, 256)

    def test_no_rows_defaults_off(self, artifact):
        from deeplearning4j_tpu.ops.pallas_kernels import lstm_kernel_wins

        assert not lstm_kernel_wins(64, 256, 256)

    def test_committed_artifact_small_class_off_large_on(self):
        """The REAL committed artifact (round-2 chip rows): scan won the
        smallest class (ratio 1.07), kernel won the larger two."""
        from deeplearning4j_tpu.ops.pallas_kernels import lstm_kernel_wins

        kernel_gate.reload()
        assert not lstm_kernel_wins(32, 128, 128)
        assert lstm_kernel_wins(64, 256, 256)
        assert lstm_kernel_wins(128, 512, 512)


def test_bench_ring_attention_leg_executes():
    """The on-chip ring bench leg has ONE shot when the tunnel returns —
    smoke it here (interpret kernel, tiny shapes, CPU) so a code bug can't
    burn it. The recorded row is redirected to a temp artifact."""
    import sys

    sys.path.insert(0, "/root/repo")
    import bench

    import tempfile

    with tempfile.TemporaryDirectory() as d:
        import deeplearning4j_tpu.ops.kernel_gate as kg

        old = kg._ARTIFACT
        kg._ARTIFACT = f"{d}/PALLAS_BENCH.json"
        kg.reload()
        try:
            out = bench.bench_ring_attention(n=1, t=256, h=2, d=32, steps=1,
                                             interpret=True)
        finally:
            kg._ARTIFACT = old
            kg.reload()
    assert "ring_einsum_ms" in out and "ring_flash_ms" in out
    assert out["flash_speedup"] > 0
