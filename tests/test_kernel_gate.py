"""Measured-win gate (ops/kernel_gate.py): default-on requires a committed
on-chip PALLAS_BENCH.json row beating the XLA twin."""

import json

import pytest

from deeplearning4j_tpu.ops import kernel_gate


@pytest.fixture
def artifact(tmp_path, monkeypatch):
    path = tmp_path / "PALLAS_BENCH.json"
    monkeypatch.setattr(kernel_gate, "_ARTIFACT", str(path))
    kernel_gate.reload()
    yield path
    kernel_gate.reload()


def test_no_artifact_defaults_off(artifact):
    assert not kernel_gate.measured_win("attention", "ring_local_flash")
    assert kernel_gate.measured_win("attention", "x", default=True)


def test_tpu_win_row_enables(artifact):
    artifact.write_text(json.dumps(
        {"attention": {"ring_local_flash":
                       {"speedup": 1.4, "backend": "tpu"}}}))
    kernel_gate.reload()
    assert kernel_gate.measured_win("attention", "ring_local_flash")


def test_loss_row_disables(artifact):
    artifact.write_text(json.dumps(
        {"attention": {"ring_local_flash":
                       {"speedup": 0.9, "backend": "tpu"}}}))
    kernel_gate.reload()
    assert not kernel_gate.measured_win("attention", "ring_local_flash")


def test_cpu_or_interpret_rows_do_not_count(artifact):
    artifact.write_text(json.dumps(
        {"attention": {"a": {"speedup": 2.0, "backend": "cpu"},
                       "b": {"speedup": 2.0, "interpret": True,
                             "backend": "tpu"}}}))
    kernel_gate.reload()
    assert not kernel_gate.measured_win("attention", "a")
    assert not kernel_gate.measured_win("attention", "b")


def test_record_win_merges_and_enables(artifact):
    artifact.write_text(json.dumps(
        {"lstm_legacy": {"keep": {"speedup": 9.9}}}))
    kernel_gate.reload()
    kernel_gate.record_win("attention", "masked_flash",
                           {"speedup": 1.2, "backend": "tpu"})
    assert kernel_gate.measured_win("attention", "masked_flash")
    data = json.loads(artifact.read_text())
    assert data["lstm_legacy"]["keep"]["speedup"] == 9.9  # preserved


def test_force_env_overrides(artifact, monkeypatch):
    monkeypatch.setenv("DL4J_TPU_PALLAS_FORCE", "1")
    assert kernel_gate.measured_win("attention", "anything")
