"""ComputationGraph tests.

Mirrors the reference's graph test strategy (SURVEY.md section 4):
TestComputationGraphNetwork (build/fit/output/score), JSON round-trip
(ComputationGraphConfigurationTest), vertex behavior, multi-input/multi-output,
rnn vertices (ComputationGraphTestRNN), and gradient checking
(GradientCheckTestsComputationGraph).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import (
    ComputationGraphConfiguration,
    DuplicateToTimeSeriesVertex,
    ElementWiseVertex,
    LastTimeStepVertex,
    MergeVertex,
    ScaleVertex,
    SubsetVertex,
)
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer,
    GravesLSTM,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.datasets.iterator import DataSet
from deeplearning4j_tpu.nn.graph import ComputationGraph


def _simple_graph_conf(seed=12345, lr=0.1):
    return (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(lr)
        .graph_builder()
        .add_inputs("in")
        .add_layer("d1", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
        .add_layer(
            "out",
            OutputLayer(n_in=8, n_out=3, activation="softmax", loss_function="mcxent"),
            "d1",
        )
        .set_outputs("out")
        .build()
    )


def _iris_like(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


class TestBuildAndValidate:
    def test_topological_order(self):
        conf = _simple_graph_conf()
        assert conf.topological_order() == ["d1", "out"]

    def test_cycle_detection(self):
        conf = ComputationGraphConfiguration(
            inputs=["in"],
            vertices={"a": MergeVertex(), "b": MergeVertex()},
            vertex_inputs={"a": ["b"], "b": ["a"]},
            outputs=["a"],
        )
        with pytest.raises(ValueError, match="cycle"):
            conf.topological_order()

    def test_unknown_input_rejected(self):
        with pytest.raises(ValueError, match="unknown input"):
            (
                NeuralNetConfiguration.builder()
                .graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=2, n_out=2), "nope")
                .set_outputs("d")
                .build()
            )

    def test_duplicate_name_rejected(self):
        gb = (
            NeuralNetConfiguration.builder()
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=2, n_out=2), "in")
        )
        with pytest.raises(ValueError, match="duplicate"):
            gb.add_layer("d", DenseLayer(n_in=2, n_out=2), "in")


class TestJsonRoundTrip:
    def test_simple(self):
        conf = _simple_graph_conf()
        j = conf.to_json()
        conf2 = ComputationGraphConfiguration.from_json(j)
        assert conf2.to_json() == j
        assert conf2.topological_order() == conf.topological_order()

    def test_vertices_survive(self):
        conf = (
            NeuralNetConfiguration.builder()
            .graph_builder()
            .add_inputs("a", "b")
            .add_layer("d1", DenseLayer(n_in=4, n_out=4), "a")
            .add_vertex("ew", ElementWiseVertex(op="product"), "d1", "b")
            .add_vertex("sub", SubsetVertex(from_index=0, to_index=1), "ew")
            .add_vertex("sc", ScaleVertex(scale=0.5), "sub")
            .add_layer(
                "out",
                OutputLayer(n_in=2, n_out=2, activation="softmax", loss_function="mcxent"),
                "sc",
            )
            .set_outputs("out")
            .build()
        )
        conf2 = ComputationGraphConfiguration.from_json(conf.to_json())
        assert isinstance(conf2.vertices["ew"], ElementWiseVertex)
        assert conf2.vertices["ew"].op == "product"
        assert conf2.vertices["sub"].to_index == 1
        assert conf2.vertices["sc"].scale == 0.5


class TestFitAndOutput:
    def test_fit_reduces_score(self):
        conf = _simple_graph_conf()
        net = ComputationGraph(conf).init()
        x, y = _iris_like(64)
        first = float(net.fit(x, y))
        for _ in range(30):
            last = float(net.fit(x, y))
        assert last < first

    def test_output_shape_and_softmax(self):
        net = ComputationGraph(_simple_graph_conf()).init()
        x, _ = _iris_like(8)
        (out,) = net.output(x)
        assert out.shape == (8, 3)
        np.testing.assert_allclose(np.sum(np.asarray(out), axis=1), 1.0, atol=1e-5)

    def test_equivalent_to_multilayer(self):
        """A linear graph must match the sequential container exactly
        (same seed, same layers) — the graph generalizes, not diverges."""
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        mln_conf = (
            NeuralNetConfiguration.builder()
            .seed(777)
            .learning_rate(0.1)
            .list()
            .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(
                1,
                OutputLayer(
                    n_in=8, n_out=3, activation="softmax", loss_function="mcxent"
                ),
            )
            .build()
        )
        mln = MultiLayerNetwork(mln_conf).init()
        cg = ComputationGraph(_simple_graph_conf(seed=777)).init()
        x, y = _iris_like(16)
        l_m = float(mln.fit(x, y))
        l_g = float(cg.fit(x, y))
        # same loss function and data; init RNG streams differ by layer
        # keying so allow loose agreement on the first loss magnitude
        assert abs(l_m - l_g) < 1.0
        for _ in range(10):
            l_m = float(mln.fit(x, y))
            l_g = float(cg.fit(x, y))
        assert l_g < 1.2  # both learn


class TestVertices:
    def test_merge_concatenates(self):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(1)
            .learning_rate(0.1)
            .graph_builder()
            .add_inputs("a", "b")
            .add_layer("da", DenseLayer(n_in=3, n_out=4, activation="relu"), "a")
            .add_layer("db", DenseLayer(n_in=5, n_out=6, activation="relu"), "b")
            .add_vertex("m", MergeVertex(), "da", "db")
            .add_layer(
                "out",
                OutputLayer(n_in=10, n_out=2, activation="softmax", loss_function="mcxent"),
                "m",
            )
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(0)
        a = rng.normal(size=(4, 3)).astype(np.float32)
        b = rng.normal(size=(4, 5)).astype(np.float32)
        acts = net.feed_forward(a, b)
        assert acts["m"].shape == (4, 10)
        np.testing.assert_allclose(
            np.asarray(acts["m"]),
            np.concatenate([np.asarray(acts["da"]), np.asarray(acts["db"])], axis=1),
            rtol=1e-6,
        )
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 4)]
        net.fit([a, b], y)  # trains without error

    def test_elementwise_add_and_subset(self):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(1)
            .graph_builder()
            .add_inputs("x")
            .add_layer("d1", DenseLayer(n_in=4, n_out=4, activation="identity"), "x")
            .add_vertex("sum", ElementWiseVertex(op="add"), "d1", "x")
            .add_vertex("first2", SubsetVertex(from_index=0, to_index=1), "sum")
            .add_layer(
                "out",
                OutputLayer(n_in=2, n_out=2, activation="softmax", loss_function="mcxent"),
                "first2",
            )
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init()
        x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
        acts = net.feed_forward(x)
        np.testing.assert_allclose(
            np.asarray(acts["sum"]), np.asarray(acts["d1"]) + x, rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(acts["first2"]), np.asarray(acts["sum"])[:, :2], rtol=1e-6
        )

    def test_residual_block_trains(self):
        """ElementWiseVertex add = the residual-connection pattern."""
        conf = (
            NeuralNetConfiguration.builder()
            .seed(3)
            .learning_rate(0.05)
            .updater("adam")
            .graph_builder()
            .add_inputs("x")
            .add_layer("d1", DenseLayer(n_in=8, n_out=8, activation="relu"), "x")
            .add_layer("d2", DenseLayer(n_in=8, n_out=8, activation="identity"), "d1")
            .add_vertex("res", ElementWiseVertex(op="add"), "d2", "x")
            .add_layer(
                "out",
                OutputLayer(n_in=8, n_out=2, activation="softmax", loss_function="mcxent"),
                "res",
            )
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(5)
        x = rng.normal(size=(32, 8)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(axis=1) > 0).astype(int)]
        first = float(net.fit(x, y))
        for _ in range(40):
            last = float(net.fit(x, y))
        assert last < first


class TestMultiOutput:
    def test_two_outputs_sum_losses(self):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(9)
            .learning_rate(0.1)
            .graph_builder()
            .add_inputs("x")
            .add_layer("trunk", DenseLayer(n_in=4, n_out=8, activation="tanh"), "x")
            .add_layer(
                "out1",
                OutputLayer(n_in=8, n_out=3, activation="softmax", loss_function="mcxent"),
                "trunk",
            )
            .add_layer(
                "out2",
                OutputLayer(n_in=8, n_out=2, activation="softmax", loss_function="mcxent"),
                "trunk",
            )
            .set_outputs("out1", "out2")
            .build()
        )
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(2)
        x = rng.normal(size=(16, 4)).astype(np.float32)
        y1 = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        y2 = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
        first = float(net.fit(x, [y1, y2]))
        for _ in range(20):
            last = float(net.fit(x, [y1, y2]))
        assert last < first
        o1, o2 = net.output(x)
        assert o1.shape == (16, 3) and o2.shape == (16, 2)
        # score == sum of the two losses (computeGradientAndScore :894-907)
        s = net.score(x, [y1, y2])
        assert s > 0


class TestRnnVertices:
    def test_last_time_step_vertex(self):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(4)
            .learning_rate(0.1)
            .graph_builder()
            .add_inputs("seq")
            .add_layer("lstm", GravesLSTM(n_in=3, n_out=5, activation="tanh"), "seq")
            .add_vertex("last", LastTimeStepVertex(mask_input="seq"), "lstm")
            .add_layer(
                "out",
                OutputLayer(n_in=5, n_out=2, activation="softmax", loss_function="mcxent"),
                "last",
            )
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init(input_shapes={"seq": (-1, 3)})
        rng = np.random.default_rng(0)
        x = rng.normal(size=(6, 7, 3)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 6)]
        acts = net.feed_forward(x)
        assert acts["last"].shape == (6, 5)
        np.testing.assert_allclose(
            np.asarray(acts["last"]), np.asarray(acts["lstm"])[:, -1, :], rtol=1e-6
        )
        net.fit(x, y)

    def test_last_time_step_vertex_masked(self):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(4)
            .graph_builder()
            .add_inputs("seq")
            .add_layer("lstm", GravesLSTM(n_in=3, n_out=5, activation="tanh"), "seq")
            .add_vertex("last", LastTimeStepVertex(mask_input="seq"), "lstm")
            .add_layer(
                "out",
                OutputLayer(n_in=5, n_out=2, activation="softmax", loss_function="mcxent"),
                "last",
            )
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init(input_shapes={"seq": (-1, 3)})
        rng = np.random.default_rng(1)
        x = rng.normal(size=(2, 5, 3)).astype(np.float32)
        mask = np.array(
            [[1, 1, 1, 0, 0], [1, 1, 1, 1, 1]], dtype=np.float32
        )
        inputs = {"seq": np.asarray(x)}
        acts, _ = net._forward(
            net.params,
            net.states,
            {k: v for k, v in inputs.items()},
            train=False,
            masks={"seq": mask},
        )
        # row 0: last unmasked step is index 2
        np.testing.assert_allclose(
            np.asarray(acts["last"])[0], np.asarray(acts["lstm"])[0, 2, :], rtol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(acts["last"])[1], np.asarray(acts["lstm"])[1, 4, :], rtol=1e-6
        )

    def test_duplicate_to_time_series_seq2seq(self):
        """Encoder LastTimeStep -> DuplicateToTimeSeries decoder-conditioning
        (the reference's seq2seq vertex pair)."""
        conf = (
            NeuralNetConfiguration.builder()
            .seed(6)
            .learning_rate(0.1)
            .graph_builder()
            .add_inputs("seq")
            .add_layer("enc", GravesLSTM(n_in=2, n_out=4, activation="tanh"), "seq")
            .add_vertex("last", LastTimeStepVertex(), "enc")
            .add_vertex("dup", DuplicateToTimeSeriesVertex(reference_input="seq"), "last")
            .add_layer("dec", GravesLSTM(n_in=4, n_out=4, activation="tanh"), "dup")
            .add_layer(
                "out",
                RnnOutputLayer(n_in=4, n_out=2, activation="softmax", loss_function="mcxent"),
                "dec",
            )
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init(input_shapes={"seq": (-1, 2)})
        rng = np.random.default_rng(0)
        x = rng.normal(size=(3, 6, 2)).astype(np.float32)
        y = np.tile(
            np.eye(2, dtype=np.float32)[rng.integers(0, 2, 3)][:, None, :], (1, 6, 1)
        )
        acts = net.feed_forward(x)
        assert acts["dup"].shape == (3, 6, 4)
        # every timestep of dup equals the encoder's last step
        np.testing.assert_allclose(
            np.asarray(acts["dup"])[:, 0, :], np.asarray(acts["last"]), rtol=1e-6
        )
        first = float(net.fit(x, y))
        for _ in range(10):
            last = float(net.fit(x, y))
        assert last < first

    def test_rnn_time_step(self):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(4)
            .graph_builder()
            .add_inputs("seq")
            .add_layer("lstm", GravesLSTM(n_in=3, n_out=4, activation="tanh"), "seq")
            .add_layer(
                "out",
                RnnOutputLayer(n_in=4, n_out=3, activation="softmax", loss_function="mcxent"),
                "lstm",
            )
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init(input_shapes={"seq": (-1, 3)})
        rng = np.random.default_rng(0)
        seq = rng.normal(size=(2, 4, 3)).astype(np.float32)
        # full-sequence output
        (full,) = net.output(seq)
        # step-by-step must match (stateful streaming, rnnTimeStep :1601)
        net.rnn_clear_previous_state()
        outs = []
        for t in range(4):
            (o,) = net.rnn_time_step(seq[:, t, :])
            outs.append(np.asarray(o))
        np.testing.assert_allclose(
            np.stack(outs, axis=1), np.asarray(full), rtol=1e-4, atol=1e-5
        )


class TestGraphGradients:
    def test_gradient_check_merge_graph(self):
        """Central-difference check through Merge + ElementWise vertices
        (GradientCheckTestsComputationGraph equivalent)."""
        from deeplearning4j_tpu.utils.gradient_check import check_graph_gradients

        conf = (
            NeuralNetConfiguration.builder()
            .seed(11)
            .graph_builder()
            .add_inputs("a", "b")
            .add_layer("da", DenseLayer(n_in=3, n_out=4, activation="tanh"), "a")
            .add_layer("db", DenseLayer(n_in=3, n_out=4, activation="sigmoid"), "b")
            .add_vertex("m", MergeVertex(), "da", "db")
            .add_layer(
                "out",
                OutputLayer(n_in=8, n_out=2, activation="softmax", loss_function="mcxent"),
                "m",
            )
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(0)
        a = rng.normal(size=(4, 3))
        b = rng.normal(size=(4, 3))
        y = np.eye(2)[rng.integers(0, 2, 4)]
        ok, max_rel = check_graph_gradients(
            net, [a, b], [y], max_params_per_leaf=10
        )
        assert ok, f"max relative error {max_rel}"


class TestGraphPersistence:
    def test_model_serializer_roundtrip(self, tmp_path):
        """ModelSerializer handles graphs (reference restoreComputationGraph)."""
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        net = ComputationGraph(_simple_graph_conf()).init()
        x, y = _iris_like(16)
        net.fit(x, y)
        p = str(tmp_path / "graph.zip")
        ModelSerializer.write_model(net, p)
        restored = ModelSerializer.restore(p)
        assert isinstance(restored, ComputationGraph)
        (o1,) = net.output(x)
        (o2,) = restored.output(x)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), rtol=1e-6)
        assert restored.iteration == net.iteration

    def test_clone_preserves_iteration(self):
        net = ComputationGraph(_simple_graph_conf()).init()
        x, y = _iris_like(8)
        net.fit(x, y)
        net.fit(x, y)
        c = net.clone()
        assert c.iteration == net.iteration


class TestGraphSolver:
    def test_lbfgs_graph_training(self):
        """conf.optimization_algo is honored by the graph container too."""
        conf = (
            NeuralNetConfiguration.builder()
            .seed(5)
            .optimization_algo("lbfgs")
            .iterations(25)
            .max_num_line_search_iterations(10)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d1", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
            .add_layer(
                "out",
                OutputLayer(n_in=8, n_out=3, activation="softmax", loss_function="mcxent"),
                "d1",
            )
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init()
        x, y = _iris_like(32)
        before = net.score(x, y)
        net.fit(x, y)
        after = net.score(x, y)
        assert after < before * 0.7


class TestGraphMasking:
    def test_feature_mask_reaches_rnn_output_loss(self):
        """Feature mask must mask the RnnOutputLayer loss when no label mask
        is given (MLN parity: lmask falls back to the feature mask)."""
        conf = (
            NeuralNetConfiguration.builder()
            .seed(8)
            .graph_builder()
            .add_inputs("seq")
            .add_layer("lstm", GravesLSTM(n_in=2, n_out=4, activation="tanh"), "seq")
            .add_layer(
                "out",
                RnnOutputLayer(n_in=4, n_out=2, activation="softmax", loss_function="mcxent"),
                "lstm",
            )
            .set_outputs("out")
            .build()
        )
        net = ComputationGraph(conf).init(input_shapes={"seq": (-1, 2)})
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 6, 2)).astype(np.float32)
        y = np.tile(np.array([[1.0, 0.0]], np.float32), (2, 6, 1)).astype(np.float32)
        full = net.score(x, y)
        mask = np.ones((2, 6), np.float32)
        mask[:, 3:] = 0.0
        # corrupt the masked-out region of the labels; score must not change
        y2 = y.copy()
        y2[:, 3:, :] = np.array([0.0, 1.0], np.float32)
        import jax.numpy as jnp

        s_masked_clean, _ = net._loss(
            net.params, net.states,
            {"seq": jnp.asarray(x)}, [jnp.asarray(y)],
            train=False, rng=None, masks={"seq": jnp.asarray(mask)},
        )
        s_masked_corrupt, _ = net._loss(
            net.params, net.states,
            {"seq": jnp.asarray(x)}, [jnp.asarray(y2)],
            train=False, rng=None, masks={"seq": jnp.asarray(mask)},
        )
        np.testing.assert_allclose(
            float(s_masked_clean), float(s_masked_corrupt), rtol=1e-6
        )
        assert abs(float(s_masked_clean) - float(full)) > 1e-9


class TestGraphTbptt:
    def test_truncated_bptt_fit_carries_state_across_windows(self):
        """ComputationGraph honors BackpropType.TruncatedBPTT (reference
        supports TBPTT on graphs the same as on MLN :1162-1233): the time
        axis is sliced into fwd-length windows, one optimizer iteration per
        window."""
        conf = (
            NeuralNetConfiguration.builder()
            .seed(7)
            .learning_rate(0.05)
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", GravesLSTM(n_in=3, n_out=6, activation="tanh"), "in")
            .add_layer(
                "out",
                RnnOutputLayer(
                    n_in=6, n_out=2, activation="softmax", loss_function="mcxent"
                ),
                "lstm",
            )
            .set_outputs("out")
            .backprop_type("truncated_bptt")
            .t_bptt_forward_length(4)
            .t_bptt_backward_length(4)
            .build()
        )
        net = ComputationGraph(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(5, 12, 3)).astype(np.float32)  # T=12 -> 3 windows
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (5, 12))]
        it0 = net.iteration
        loss = net.fit(x, y)
        assert np.isfinite(float(loss))
        assert net.iteration - it0 == 3  # one iteration per window
        # training should reduce the loss over repeats
        for _ in range(10):
            loss2 = net.fit(x, y)
        assert float(loss2) < float(loss)


def test_graph_fit_batches_equals_serial():
    """K-step fused scan == K serial fits (params + losses), graph container."""
    import numpy as np

    from deeplearning4j_tpu.datasets.fetchers import load_iris

    x, y = load_iris()
    K, N = 3, 30
    xs = np.stack([x[i * N:(i + 1) * N] for i in range(K)])
    ys = np.stack([y[i * N:(i + 1) * N] for i in range(K)])

    def build():
        conf = (
            NeuralNetConfiguration.builder()
            .seed(3)
            .learning_rate(0.1)
            .updater("adam")
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                          loss_function="mcxent"), "d")
            .set_outputs("out")
            .build()
        )
        return ComputationGraph(conf).init()

    serial = build()
    serial_losses = [float(serial.fit(xs[k], ys[k])) for k in range(K)]
    fused = build()
    fused_losses = fused.fit_batches(xs, ys)
    np.testing.assert_allclose(fused_losses, serial_losses, rtol=1e-6)
    for name in serial.params:
        for pn in serial.params[name]:
            np.testing.assert_allclose(
                np.asarray(fused.params[name][pn]),
                np.asarray(serial.params[name][pn]),
                rtol=1e-6, atol=1e-7, err_msg=f"{name}.{pn}",
            )
    assert fused.iteration == serial.iteration == K


def test_graph_gradient_checkpointing_matches_plain():
    import numpy as np

    from deeplearning4j_tpu.datasets.fetchers import load_iris

    x, y = load_iris()

    def build(ckpt):
        conf = (
            NeuralNetConfiguration.builder()
            .seed(17).learning_rate(0.05).updater("adam")
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=4, n_out=12, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=12, n_out=3, activation="softmax",
                                          loss_function="mcxent"), "d")
            .set_outputs("out")
            .gradient_checkpointing(ckpt)
            .build()
        )
        assert conf.gradient_checkpointing is ckpt
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        return ComputationGraph(conf).init()

    plain, ckpt = build(False), build(True)
    for _ in range(3):
        assert float(plain.fit(x, y)) == pytest.approx(float(ckpt.fit(x, y)), rel=1e-6)
    for name in plain.params:
        for pn in plain.params[name]:
            np.testing.assert_allclose(
                np.asarray(ckpt.params[name][pn]),
                np.asarray(plain.params[name][pn]), rtol=1e-6, atol=1e-7)
    # serde keeps the flag
    from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration

    rt = ComputationGraphConfiguration.from_dict(build(True).conf.to_dict())
    assert rt.gradient_checkpointing is True


def test_graph_performance_dtype_policy_trains():
    import numpy as np

    from deeplearning4j_tpu.datasets.fetchers import load_iris

    x, y = load_iris()
    conf = (
        NeuralNetConfiguration.builder()
        .seed(19).learning_rate(0.1).updater("adam")
        .graph_builder()
        .add_inputs("in")
        .add_layer("d", DenseLayer(n_in=4, n_out=12, activation="tanh"), "in")
        .add_layer("out", OutputLayer(n_in=12, n_out=3, activation="softmax",
                                      loss_function="mcxent"), "d")
        .set_outputs("out")
        .dtype_policy("performance")
        .build()
    )
    assert conf.dtype_policy == "performance"
    net = ComputationGraph(conf).init()
    first = float(net.fit(x, y))
    for _ in range(40):
        loss = float(net.fit(x, y))
    assert loss < first * 0.7
    import jax.numpy as jnp

    for lp in net.params.values():
        for a in lp.values():
            assert a.dtype == jnp.float32


class TestFusedFitIterator:
    def test_fused_equals_per_step(self):
        """fit_iterator(fused_batches=K) on a graph == the per-step loop
        exactly (fit_batches serial equivalence), incl. the ragged tail."""
        x, y = _iris_like(n=80, seed=3)
        ds_list = [DataSet(x[i:i + 16], y[i:i + 16])
                   for i in range(0, 80, 16)]
        a = ComputationGraph(_simple_graph_conf(seed=31)).init()
        b = ComputationGraph(_simple_graph_conf(seed=31)).init()
        a.fit_iterator(list(ds_list), num_epochs=2)
        b.fit_iterator(list(ds_list), num_epochs=2, fused_batches=2)
        for name in a.params:
            for k in a.params[name]:
                np.testing.assert_allclose(
                    np.asarray(a.params[name][k]),
                    np.asarray(b.params[name][k]), rtol=1e-6, atol=1e-7)
        assert a.iteration == b.iteration

    def test_masked_datasets_fall_back(self):
        """Masked DataSets can't stack through the mask-free fit_batches —
        they run per-step (and still train)."""
        rng = np.random.default_rng(0)
        conf = (
            NeuralNetConfiguration.builder().seed(4).learning_rate(0.1)
            .graph_builder().add_inputs("in")
            .add_layer("l", GravesLSTM(n_in=3, n_out=8), "in")
            .add_layer("out", RnnOutputLayer(n_in=8, n_out=2,
                                             loss_function="mcxent",
                                             activation="softmax"), "l")
            .set_outputs("out").build()
        )
        net = ComputationGraph(conf).init(input_shapes={"in": (-1, 3)})
        x = rng.normal(size=(4, 6, 3)).astype(np.float32)
        yy = np.zeros((4, 6, 2), np.float32)
        yy[..., 0] = 1.0
        m = np.ones((4, 6), np.float32)
        m[:, 4:] = 0.0
        ds = [DataSet(x, yy, m, m) for _ in range(4)]
        net.fit_iterator(ds, fused_batches=2)
        assert net.iteration == 4
