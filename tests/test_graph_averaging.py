"""ComputationGraph under the parameter-averaging master/trainer.

The reference trains graphs on Spark through the SAME
ParameterAveragingTrainingMaster as MLNs (SparkComputationGraph.java:68
fit(JavaRDD<DataSet>)); its equivalence bar is
TestCompareParameterAveragingSparkVsSingleMachine.java:115-262 — N-worker
freq-1 SGD averaging equals the serial big-batch step. This suite mirrors
both for the graph container, including multi-input/multi-output graphs
(MultiDataSet) and the ResNet-50 flagship in averaging-compatibility mode.
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.graph import MergeVertex
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.parallel.data_parallel import ParameterAveragingTrainer
from deeplearning4j_tpu.parallel.training_master import (
    ParameterAveragingTrainingMaster,
    SparkStyleNetwork,
)
from deeplearning4j_tpu.datasets.iterator import DataSet


def _graph(seed=12345, lr=0.1, updater="sgd"):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(lr)
        .updater(updater)
        .graph_builder()
        .add_inputs("in")
        .add_layer("d1", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
        .add_layer(
            "out",
            OutputLayer(n_in=8, n_out=3, activation="softmax",
                        loss_function="mcxent"),
            "d1",
        )
        .set_outputs("out")
        .build()
    )
    return ComputationGraph(conf).init()


def _data(n=144, seed=0):
    from deeplearning4j_tpu.datasets.fetchers import load_iris

    x, y = load_iris()
    if seed:
        order = np.random.default_rng(seed).permutation(len(x))
        x, y = x[order], y[order]
    return x[:n], y[:n]


def assert_params_close(p1, p2, rtol=1e-5, atol=1e-6):
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=rtol, atol=atol)


class TestGraphAveragingTrainer:
    def test_freq1_sgd_equals_big_batch(self):
        """The reference equivalence assertion (:115-262), graph edition:
        averaging 8 independent one-step workers == one big-batch step."""
        x, y = _data()
        avg = _graph(seed=11)
        ParameterAveragingTrainer(avg, num_workers=8,
                                  averaging_frequency=1).fit(x, y)
        serial = _graph(seed=11)
        serial.fit(x, y)
        assert_params_close(serial.params, avg.params)

    def test_multi_round_trains(self):
        x, y = _data()
        net = _graph(seed=13, updater="adam", lr=0.05)
        trainer = ParameterAveragingTrainer(net, num_workers=8,
                                            averaging_frequency=3)
        s0 = net.score(x, y)
        for _ in range(15):
            trainer.fit(x, y)
        assert net.score(x, y) < s0 * 0.8

    @staticmethod
    def _multi_conf():
        return (
            NeuralNetConfiguration.builder()
            .seed(7)
            .learning_rate(0.1)
            .graph_builder()
            .add_inputs("a", "b")
            .add_vertex("m", MergeVertex(), "a", "b")
            .add_layer("d", DenseLayer(n_in=6, n_out=8, activation="tanh"),
                       "m")
            .add_layer("o1", OutputLayer(n_in=8, n_out=3,
                                         activation="softmax",
                                         loss_function="mcxent"), "d")
            .add_layer("o2", OutputLayer(n_in=8, n_out=2,
                                         activation="softmax",
                                         loss_function="mcxent"), "d")
            .set_outputs("o1", "o2")
            .build()
        )

    def test_multi_input_output_graph(self):
        """MultiDataSet analog: two inputs merged, two outputs — the
        dict/list containers must round-trip the worker loop."""
        rng = np.random.default_rng(0)
        n = 64
        xa = rng.normal(size=(n, 4)).astype(np.float32)
        xb = rng.normal(size=(n, 2)).astype(np.float32)
        y1 = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
        y2 = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]

        shapes = {"a": (-1, 4), "b": (-1, 2)}
        avg = ComputationGraph(self._multi_conf()).init(input_shapes=shapes)
        ParameterAveragingTrainer(avg, num_workers=8,
                                  averaging_frequency=1).fit(
            [xa, xb], [y1, y2])
        serial = ComputationGraph(self._multi_conf()).init(input_shapes=shapes)
        serial.fit([xa, xb], [y1, y2])
        assert_params_close(serial.params, avg.params)


class TestGraphUnderMaster:
    def test_spark_style_graph_fit(self):
        """SparkComputationGraph.fit(JavaRDD<DataSet>) analog end-to-end:
        master splits, trainer averages, score drops."""
        x, y = _data(n=144, seed=3)
        net = _graph(seed=21, updater="adam", lr=0.05)
        master = ParameterAveragingTrainingMaster(
            num_workers=8, batch_size_per_worker=2, averaging_frequency=3,
            collect_training_stats=True,
        )
        spark_net = SparkStyleNetwork(net, master)
        datasets = [DataSet(x[i:i + 16], y[i:i + 16])
                    for i in range(0, 144, 16)]
        s0 = net.score(x, y)
        for _ in range(6):
            spark_net.fit(datasets)
        assert net.score(x, y) < s0
        stats = master.get_training_stats()
        assert stats is not None and len(stats.events) > 0

    def test_master_multi_component_split(self):
        """Master splitting with list features/labels (MultiDataSet)."""
        rng = np.random.default_rng(0)
        n = 32
        xa = rng.normal(size=(n, 4)).astype(np.float32)
        xb = rng.normal(size=(n, 2)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
        master = ParameterAveragingTrainingMaster(
            num_workers=2, batch_size_per_worker=4, averaging_frequency=2)
        ds = [DataSet([xa, xb], [y])]
        splits = list(master._splits(ds))
        assert len(splits) == 2  # 32 // (2*4*2)
        (fx, fy) = splits[0]
        assert isinstance(fx, list) and fx[0].shape == (16, 4) \
            and fx[1].shape == (16, 2)
        assert isinstance(fy, list) and fy[0].shape == (16, 3)


class TestResNet50AveragingMode:
    def test_resnet50_averaging_round(self):
        """The flagship CNN in averaging-compatibility mode (VERDICT round-2
        missing #1): one full averaging round on the 8-worker mesh, params
        move, BN running stats averaged."""
        from deeplearning4j_tpu.models.resnet import build_resnet50

        net = build_resnet50(input_size=32, num_classes=10,
                             learning_rate=0.01, updater="nesterovs")
        rng = np.random.default_rng(0)
        x = rng.random((16, 32, 32, 3)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)]
        trainer = ParameterAveragingTrainer(net, num_workers=8,
                                            averaging_frequency=2)
        loss = float(trainer.fit(x, y))
        assert np.isfinite(loss)
        loss2 = float(trainer.fit(x, y))
        assert np.isfinite(loss2)
