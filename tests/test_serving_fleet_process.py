"""OS-process serving-fleet replicas (ISSUE 12, full tier): the
production shape of serving/fleet.run_replica — real processes started
via ``python -m deeplearning4j_tpu.serving.fleet --cpu``, joining the
membership board from separate PIDs, answering traffic through the
router, SIGTERM -> engine drain -> deregister GOODBYE, SIGKILL -> board
expiry. The in-process contracts live in tests/test_serving_fleet.py
(quick tier); this file proves the same semantics hold across process
boundaries, like tests/test_fleet.py's OS-process-worker leg does for
the training fleet (reference anchor: the scaleout tree per SURVEY —
the serving side never existed there).
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np

from deeplearning4j_tpu.parallel.fleet import FileMembershipBoard
from deeplearning4j_tpu.serving.router import (
    FleetRouter,
    read_replica_addr,
)
from deeplearning4j_tpu.utils.serialization import ModelSerializer

from test_serving_fleet import small_net

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn_replica(fleet_dir, rid, model_path, heartbeat_s=0.5):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "deeplearning4j_tpu.serving.fleet",
         "--cpu", "--fleet-dir", str(fleet_dir), "--replica-id", rid,
         "--model-path", str(model_path),
         "--heartbeat-s", str(heartbeat_s)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)


def _wait_addr(fleet_dir, rid, deadline_s=90.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        url = read_replica_addr(str(fleet_dir), rid)
        if url is not None:
            try:
                with urllib.request.urlopen(url + "/health",
                                            timeout=5) as r:
                    if r.status == 200:
                        return url
            except OSError:
                pass
        time.sleep(0.2)
    raise AssertionError(f"replica {rid} never came up")


def test_process_replicas_goodbye_and_expiry(tmp_path):
    net = small_net()
    model_path = tmp_path / "model.zip"
    ModelSerializer.write_model(net, str(model_path))
    fleet_dir = tmp_path / "fleet"
    fleet_dir.mkdir()

    procs = {rid: _spawn_replica(fleet_dir, rid, model_path)
             for rid in ("r0", "r1")}
    router = None
    try:
        for rid in procs:
            _wait_addr(fleet_dir, rid)
        router = FleetRouter(
            board=FileMembershipBoard(str(fleet_dir),
                                      heartbeat_timeout=0.5),
            poll_s=0.2)
        router.start()
        assert sorted(router.describe_replicas()) == ["r0", "r1"]

        rng = np.random.default_rng(5)
        rows = rng.normal(size=(4, 4)).astype(np.float32)
        body = json.dumps({"batch": rows.tolist()}).encode()
        # both OS processes answer byte-identically (same zip, same
        # substrate) — collect enough round-robin turns to hit both
        bodies = set()
        for _ in range(4):
            status, _, data = router.proxy_predict(body)
            assert status == 200
            bodies.add(data)
        assert len(bodies) == 1
        out = np.asarray(json.loads(bodies.pop())["outputs"], np.float32)
        assert out.shape == (4, 3)
        np.testing.assert_allclose(
            out, np.asarray(net.output(rows), np.float32),
            rtol=0, atol=1e-6)

        # SIGTERM r1: engine drain, then the deregister GOODBYE — a
        # clean leave with NO breaker evidence
        procs["r1"].send_signal(signal.SIGTERM)
        assert procs["r1"].wait(timeout=60) == 0
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            router.refresh()
            if sorted(router.describe_replicas()) == ["r0"]:
                break
            time.sleep(0.1)
        assert sorted(router.describe_replicas()) == ["r0"]
        assert read_replica_addr(str(fleet_dir), "r1") is None
        status, _, _ = router.proxy_predict(body)
        assert status == 200
        assert router.stats.snapshot()["breaker_opens"] == 0

        # SIGKILL r0: no goodbye possible — board expiry is the only
        # witness, and the router's poll scrubs the corpse
        procs["r0"].kill()
        procs["r0"].wait(timeout=30)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            router.refresh()
            if not router.describe_replicas():
                break
            time.sleep(0.1)
        assert not router.describe_replicas()
    finally:
        if router is not None:
            router.stop()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
