"""ETL subsystem (deeplearning4j_tpu/etl/): schema + TransformProcess,
fitted normalizers, checkpoint-zip serde, and the normalizer-aware
serving path.

DataVec-parity contracts: a TransformProcess compiles its declarative
steps into ONE record function whose output schema was validated at
build time; fitted normalizers produce the SAME statistics streaming
over an iterator as a single full-array pass, revert() inverts
transform(), and the statistics round-trip through the ModelSerializer
zip's optional normalizer.json section so serving and resume apply
exactly what training fitted. CSVRecordReader satellites: RFC-4180
quoting and the loud ragged-row error.
"""

import json
import os
import zipfile

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
from deeplearning4j_tpu.datasets.records import (
    CollectionRecordReader,
    CSVRecordReader,
    RecordReaderDataSetIterator,
)
from deeplearning4j_tpu.etl import (
    ColumnType,
    ImagePreProcessingScaler,
    NormalizerMinMaxScaler,
    NormalizerStandardize,
    Schema,
    TransformProcess,
    normalizer_from_json,
)
from deeplearning4j_tpu.etl.transforms import TransformProcessRecordReader
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.utils.serialization import (
    ModelSerializer,
    read_normalizer,
)


def base_schema() -> Schema:
    return (Schema.builder()
            .add_numeric_column("a", "b")
            .add_categorical_column("cat", ["x", "y", "z"])
            .add_integer_column("label")
            .build())


class TestSchema:
    def test_builder_and_queries(self):
        s = base_schema()
        assert s.names() == ["a", "b", "cat", "label"]
        assert s.index_of("cat") == 2
        assert s.column("cat").categories == ["x", "y", "z"]
        assert s.column("label").type == ColumnType.INTEGER

    def test_duplicate_and_missing_columns_loud(self):
        with pytest.raises(ValueError, match="duplicate"):
            Schema.builder().add_numeric_column("a", "a").build()
        with pytest.raises(KeyError, match="no column"):
            base_schema().index_of("nope")
        with pytest.raises(ValueError, match="category list"):
            Schema.builder().add_categorical_column("c", []).build()

    def test_json_round_trip(self):
        s = base_schema()
        assert Schema.from_json(s.to_json()) == s


class TestTransformProcess:
    def test_steps_compose_and_schema_tracks(self):
        tp = (TransformProcess(base_schema())
              .math_op("a", "mul", 2.0)
              .one_hot("cat")
              .remove_columns("b")
              .derive("s", ["a", "cat[y]"], "sum")
              .rolling_window("a", 2, "mean"))
        assert tp.final_schema().names() == [
            "a", "cat[x]", "cat[y]", "cat[z]", "label", "s", "a_mean2"]
        out = list(tp.execute([["1", "9", "y", "0"], ["2", "8", "x", "1"]]))
        assert out[0] == [2.0, 0.0, 1.0, 0.0, "0", 3.0, 2.0]
        # rolling mean over records 1..2 of (already doubled) column a
        assert out[1] == [4.0, 1.0, 0.0, 0.0, "1", 4.0, 3.0]

    def test_filters_drop_records(self):
        tp = (TransformProcess(base_schema())
              .condition_filter("a", "lt", 0.0)
              .filter_invalid(["b"]))
        recs = [["1", "2", "x", "0"],
                ["-1", "2", "x", "0"],   # a < 0 -> dropped
                ["1", "junk", "x", "0"],  # b unparseable -> dropped
                ["3", "4", "y", "1"]]
        out = list(tp.execute(recs))
        assert [r[0] for r in out] == ["1", "3"]

    def test_unknown_category_is_loud(self):
        tp = TransformProcess(base_schema()).one_hot("cat")
        with pytest.raises(ValueError, match="not in categories"):
            list(tp.execute([["1", "2", "w", "0"]]))

    def test_categorical_to_integer_and_string_to_time(self):
        s = (Schema.builder().add_categorical_column("c", ["lo", "hi"])
             .add_string_column("t").build())
        tp = (TransformProcess(s).categorical_to_integer("c")
              .string_to_time("t", "%Y-%m-%d"))
        (rec,) = tp.execute([["hi", "1970-01-02"]])
        assert rec == [1, 86400.0]
        assert tp.final_schema().column("t").type == ColumnType.TIME

    def test_build_time_validation(self):
        tp = TransformProcess(base_schema())
        with pytest.raises(KeyError):
            tp.math_op("nope", "add", 1.0)
        with pytest.raises(ValueError, match="not categorical"):
            tp.one_hot("a")
        assert tp.steps == []  # the failed step was never appended

    def test_json_round_trip_executes_identically(self):
        tp = (TransformProcess(base_schema())
              .math_op("a", "log1p")
              .condition_filter("b", "gt", 5.0)
              .one_hot("cat")
              .rolling_window("a", 3, "max")
              .derive("d", ["a", "b"], "mean"))
        tp2 = TransformProcess.from_json(tp.to_json())
        recs = [[str(i), str(i % 7), ["x", "y", "z"][i % 3], str(i % 2)]
                for i in range(20)]
        assert list(tp.execute(recs)) == list(tp2.execute(recs))
        assert tp2.final_schema() == tp.final_schema()

    def test_map_column_works_but_rejects_serde(self):
        tp = TransformProcess(base_schema()).map_column("a", lambda v: 7.0)
        (rec,) = tp.execute([["1", "2", "x", "0"]])
        assert rec[0] == 7.0
        with pytest.raises(NotImplementedError, match="not serializable"):
            tp.to_json()

    def test_split_for_pipeline_semantics(self):
        tp = (TransformProcess(base_schema())
              .math_op("a", "mul", 2.0)          # stateless
              .condition_filter("a", "gt", 50.0)  # filter -> head boundary
              .one_hot("cat"))                    # stateless tail
        head, tail = tp.split_for_pipeline()
        assert len(head.steps) == 2 and len(tail.steps) == 1
        assert not any(s.is_filter or s.is_stateful for s in tail.steps)
        recs = [[str(i), "0", "x", "0"] for i in range(40)]
        serial = list(tp.execute(recs))
        composed = list(tail.execute(head.execute(recs)))
        assert serial == composed
        # pure process: no head at all
        pure = TransformProcess(base_schema()).math_op("a", "add", 1.0)
        h, t = pure.split_for_pipeline()
        assert h is None and len(t.steps) == 1
        assert pure.is_record_parallel_safe and not tp.is_record_parallel_safe

    def test_record_reader_bridge_feeds_iterator(self):
        recs = [[str(i), str(i + 1), ["x", "y", "z"][i % 3], str(i % 3)]
                for i in range(10)]
        tp = TransformProcess(base_schema()).one_hot("cat")
        li = tp.final_schema().index_of("label")
        it = RecordReaderDataSetIterator(
            TransformProcessRecordReader(CollectionRecordReader(recs), tp),
            batch_size=4, label_index=li, num_possible_labels=3)
        batches = list(it)
        assert [b.features.shape for b in batches] == [(4, 5), (4, 5), (2, 5)]
        assert batches[0].labels.shape == (4, 3)
        # second pass identical (stateful steps recompile fresh)
        again = list(it)
        assert all(np.array_equal(a.features, b.features)
                   for a, b in zip(batches, again))


class TestCSVRecordReaderRFC4180:
    def test_quoted_delimiters_escapes_and_newlines(self, tmp_path):
        p = tmp_path / "q.csv"
        p.write_text('a,"b,c","say ""hi""","line1\nline2"\n'
                     '1,2,3,4\n')
        rows = list(CSVRecordReader(str(p)))
        assert rows[0] == ["a", "b,c", 'say "hi"', "line1\nline2"]
        assert rows[1] == ["1", "2", "3", "4"]

    def test_ragged_row_raises_with_location(self, tmp_path):
        p = tmp_path / "ragged.csv"
        p.write_text("1,2,3\n4,5,6\n7,8\n")
        with pytest.raises(ValueError) as ei:
            list(CSVRecordReader(str(p)))
        msg = str(ei.value)
        assert "ragged" in msg and str(p) in msg and ":3" in msg
        assert "2 fields, expected 3" in msg

    def test_skip_lines_and_blank_lines(self, tmp_path):
        p = tmp_path / "s.csv"
        p.write_text("header,row\n\n1,2\n\n3,4\n")
        rows = list(CSVRecordReader(str(p), skip_lines=1))
        assert rows == [["1", "2"], ["3", "4"]]


def _iter(x, y, batch=10):
    return ListDataSetIterator(x, y, batch)


class TestNormalizers:
    def setup_method(self):
        rng = np.random.default_rng(3)
        self.x = (rng.standard_normal((64, 5)) * [1, 5, 0.1, 10, 2]
                  + [0, 3, -2, 100, 0]).astype(np.float32)
        self.y = (rng.standard_normal((64, 2)) * 4 + 7).astype(np.float32)

    def test_standardize_streaming_equals_full_pass(self):
        n = NormalizerStandardize().fit(_iter(self.x, self.y, batch=7))
        x64 = np.asarray(self.x, np.float64)
        np.testing.assert_allclose(n.mean, x64.mean(0), rtol=1e-12)
        np.testing.assert_allclose(n.std, x64.std(0), rtol=1e-9)
        xt = n.transform_array(self.x)
        assert abs(xt.mean(0)).max() < 1e-5 and abs(xt.std(0) - 1).max() < 1e-4

    def test_transform_revert_round_trip(self):
        for n in (NormalizerStandardize(),
                  NormalizerMinMaxScaler(),
                  NormalizerMinMaxScaler(-1.0, 1.0)):
            n.fit(self.x)
            back = n.revert_array(n.transform_array(self.x))
            np.testing.assert_allclose(back, self.x, atol=1e-4)

    def test_minmax_hits_range_and_constant_column_safe(self):
        x = self.x.copy()
        x[:, 2] = 5.0  # constant column
        n = NormalizerMinMaxScaler().fit(x)
        xt = n.transform_array(x)
        np.testing.assert_allclose(xt.min(0)[[0, 1, 3, 4]], 0.0, atol=1e-6)
        np.testing.assert_allclose(xt.max(0)[[0, 1, 3, 4]], 1.0, atol=1e-6)
        assert np.all(xt[:, 2] == 0.0)

    def test_image_scaler_closed_form(self):
        img = np.arange(0, 256, dtype=np.float32).reshape(1, 16, 16, 1)
        n = ImagePreProcessingScaler()
        out = n.transform_array(img)
        assert out.min() == 0.0 and out.max() == 1.0
        np.testing.assert_allclose(n.revert_array(out), img, atol=1e-3)

    def test_fit_labels_regression(self):
        n = (NormalizerStandardize().fit_label(True)
             .fit(_iter(self.x, self.y, batch=16)))
        yt = n.transform_array(self.y, labels=True)
        assert abs(yt.mean(0)).max() < 1e-5
        np.testing.assert_allclose(
            n.revert_array(yt, labels=True), self.y, atol=1e-4)

    def test_dataset_transform_in_place_and_pre_process_alias(self):
        from deeplearning4j_tpu.datasets.iterator import DataSet

        n = NormalizerStandardize().fit(self.x)
        ds = DataSet(self.x.copy(), self.y.copy())
        out = n.pre_process(ds)
        assert out is ds
        assert abs(np.asarray(ds.features).mean(0)).max() < 1e-5
        assert ds.features.dtype == np.float32  # dtype preserved

    def test_unfitted_use_is_loud(self):
        with pytest.raises(RuntimeError, match="before fit"):
            NormalizerStandardize().transform_array(self.x)

    def test_json_round_trip(self):
        n = NormalizerMinMaxScaler(-2.0, 2.0).fit(self.x)
        n2 = normalizer_from_json(n.to_json())
        np.testing.assert_array_equal(n2.transform_array(self.x),
                                      n.transform_array(self.x))
        with pytest.raises(ValueError, match="unknown normalizer"):
            normalizer_from_json(json.dumps({"class": "Nope"}))


def _small_net() -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
            .updater("adam").list()
            .layer(0, DenseLayer(n_in=5, n_out=8, activation="tanh"))
            .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


class TestNormalizerZipSerde:
    def test_zip_section_round_trip(self, tmp_path):
        rng = np.random.default_rng(5)
        x = (rng.standard_normal((32, 5)) * 3 + 1).astype(np.float32)
        norm = NormalizerStandardize().fit(x)
        net = _small_net()
        path = str(tmp_path / "model.zip")
        ModelSerializer.write_model(net, path, normalizer=norm)
        with zipfile.ZipFile(path) as z:
            assert "normalizer.json" in z.namelist()
        n2 = read_normalizer(path)
        assert isinstance(n2, NormalizerStandardize)
        np.testing.assert_array_equal(n2.transform_array(x),
                                      norm.transform_array(x))
        # the model itself restores unchanged alongside
        net2 = ModelSerializer.restore(path)
        assert type(net2).__name__ == "MultiLayerNetwork"

    def test_old_zip_without_section_returns_none(self, tmp_path):
        net = _small_net()
        path = str(tmp_path / "plain.zip")
        ModelSerializer.write_model(net, path)
        assert read_normalizer(path) is None


class TestServingNormalizerAware:
    def test_predict_applies_fitted_statistics(self, tmp_path):
        """ISSUE 5 satellite: /predict through a zip with a normalizer
        section == output(normalizer.transform_array(x)), byte-identical
        — on both the dynamic-batcher path and the naive locked path."""
        import json as _json
        import urllib.request

        from deeplearning4j_tpu.serving.engine import ServingEngine

        rng = np.random.default_rng(11)
        x = (rng.standard_normal((24, 5)) * 7 + 3).astype(np.float32)
        norm = NormalizerStandardize().fit(x)
        net = _small_net()
        path = str(tmp_path / "m.zip")
        ModelSerializer.write_model(net, path, normalizer=norm)

        engine = ServingEngine(model_path=path).start()
        try:
            rec = engine.registry.default()
            assert isinstance(rec.normalizer, NormalizerStandardize)
            assert rec.describe()["normalizer"] == "NormalizerStandardize"
            want = np.asarray(
                rec.model.output(norm.transform_array(x)))
            got = engine.predict(x)
            assert got.tobytes() == want.tobytes()
            # the HTTP surface agrees
            req = urllib.request.Request(
                engine.url + "/predict",
                data=_json.dumps({"batch": x.tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as resp:
                outs = np.asarray(_json.loads(resp.read())["outputs"],
                                  np.float32)
            np.testing.assert_allclose(outs, want, rtol=1e-5, atol=1e-6)
        finally:
            engine.stop()

    def test_live_model_without_normalizer_unchanged(self):
        from deeplearning4j_tpu.serving.engine import ServingEngine

        rng = np.random.default_rng(13)
        x = rng.standard_normal((8, 5)).astype(np.float32)
        net = _small_net()
        # start() matters: stop()'s HTTPServer.shutdown blocks forever
        # when serve_forever was never entered
        engine = ServingEngine(model=net).start()
        try:
            want = np.asarray(net.output(x))
            assert engine.predict(x).tobytes() == want.tobytes()
        finally:
            engine.stop()
