"""Ring attention / sequence parallelism tests on the 8-device CPU mesh —
the distributed==serial equivalence pattern from SURVEY.md section 4 applied
to long-context: the ring result must EXACTLY match single-device attention."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.parallel.sequence_parallel import (
    SEQ_AXIS,
    mha_apply,
    multi_head_attention,
    ring_attention_sharded,
    ulysses_attention_sharded,
)


def make_qkv(n=2, t=32, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(0, 1, (n, t, h, d)).astype(np.float32))
        for _ in range(3)
    )


def seq_mesh(n_dev=8):
    devs = jax.devices()[:n_dev]
    return Mesh(np.array(devs), (SEQ_AXIS,))


class TestRingAttention:
    def test_matches_single_device_full(self):
        q, k, v = make_qkv()
        mesh = seq_mesh()
        out_ring = ring_attention_sharded(q, k, v, mesh, causal=False)
        out_ref = multi_head_attention(q, k, v, causal=False)
        np.testing.assert_allclose(out_ring, out_ref, rtol=2e-5, atol=2e-6)

    def test_matches_single_device_causal(self):
        q, k, v = make_qkv(seed=3)
        mesh = seq_mesh()
        out_ring = ring_attention_sharded(q, k, v, mesh, causal=True)
        out_ref = multi_head_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out_ring, out_ref, rtol=2e-5, atol=2e-6)

    def test_two_device_ring(self):
        q, k, v = make_qkv(t=16, seed=5)
        mesh = seq_mesh(2)
        out_ring = ring_attention_sharded(q, k, v, mesh, causal=True)
        out_ref = multi_head_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out_ring, out_ref, rtol=2e-5, atol=2e-6)

    def test_indivisible_length_rejected(self):
        q, k, v = make_qkv(t=30)
        with pytest.raises(ValueError, match="not divisible"):
            ring_attention_sharded(q, k, v, seq_mesh(8))

    def test_gradients_flow_through_ring(self):
        q, k, v = make_qkv(t=16, seed=7)
        mesh = seq_mesh(4)

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(multi_head_attention(q, k, v, causal=True) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-5)


class TestAttentionLayer:
    def test_layer_in_network_trains(self):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import (
            MultiHeadAttention,
            RnnOutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (
            NeuralNetConfiguration.builder().seed(1).learning_rate(0.01)
            .updater("adam").list()
            .layer(0, MultiHeadAttention(n_in=6, n_out=8, num_heads=2,
                                         causal=True, activation="identity"))
            .layer(1, RnnOutputLayer(n_in=8, n_out=3, activation="softmax",
                                     loss_function="mcxent"))
            .build()
        )
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 10, 6)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 10))]
        first = net.fit(x, y)
        for _ in range(10):
            last = net.fit(x, y)
        assert float(last) < float(first)

    def test_ulysses_matches_single_device(self):
        q, k, v = make_qkv(t=32, h=8)
        mesh = seq_mesh()
        for causal in (False, True):
            out = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
            ref = multi_head_attention(q, k, v, causal=causal)
            np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-6)

    def test_ulysses_matches_ring(self):
        q, k, v = make_qkv(t=32, h=8, seed=3)
        mesh = seq_mesh()
        out_u = ulysses_attention_sharded(q, k, v, mesh, causal=True)
        out_r = ring_attention_sharded(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(out_u, out_r, rtol=2e-5, atol=2e-6)

    def test_ulysses_head_divisibility_rejected(self):
        q, k, v = make_qkv(t=32, h=4)  # 4 heads on 8 devices
        with pytest.raises(ValueError):
            ulysses_attention_sharded(q, k, v, seq_mesh(), causal=False)

    def test_ulysses_gradients_flow(self):
        q, k, v = make_qkv(t=16, h=8, seed=5)
        mesh = seq_mesh()

        def loss_u(q, k, v):
            return jnp.sum(
                ulysses_attention_sharded(q, k, v, mesh, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(multi_head_attention(q, k, v, causal=True) ** 2)

        gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gu, gr):
            np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5)

    def test_heads_divisibility_validated(self):
        from deeplearning4j_tpu.nn.conf.layers import MultiHeadAttention

        with pytest.raises(ValueError, match="divisible"):
            MultiHeadAttention(n_in=6, n_out=7, num_heads=2)

    def test_mha_apply_causal_prefix_property(self):
        """Causal attention output at position t must not change when future
        positions change."""
        rng = np.random.default_rng(1)
        x1 = rng.normal(size=(1, 8, 4)).astype(np.float32)
        x2 = x1.copy()
        x2[:, 5:] += 1.0  # perturb the future
        params = {
            "Wq": jnp.asarray(rng.normal(0, 0.3, (4, 8)).astype(np.float32)),
            "Wk": jnp.asarray(rng.normal(0, 0.3, (4, 8)).astype(np.float32)),
            "Wv": jnp.asarray(rng.normal(0, 0.3, (4, 8)).astype(np.float32)),
            "Wo": jnp.asarray(rng.normal(0, 0.3, (8, 4)).astype(np.float32)),
        }
        y1 = mha_apply(params, jnp.asarray(x1), 2, causal=True)
        y2 = mha_apply(params, jnp.asarray(x2), 2, causal=True)
        np.testing.assert_allclose(y1[:, :5], y2[:, :5], rtol=1e-5)
        assert not np.allclose(y1[:, 5:], y2[:, 5:])

    def test_padded_keys_excluded_by_mask(self):
        """A padded timestep must not influence valid positions' outputs
        (the finding the LSTM path already guarantees via state freezing)."""
        rng = np.random.default_rng(2)
        x_short = rng.normal(size=(1, 3, 4)).astype(np.float32)
        x_padded = np.zeros((1, 5, 4), np.float32)
        x_padded[:, :3] = x_short
        x_padded[:, 3:] = 99.0  # garbage in the padding
        mask = np.array([[1, 1, 1, 0, 0]], np.float32)
        params = {
            "Wq": jnp.asarray(rng.normal(0, 0.3, (4, 8)).astype(np.float32)),
            "Wk": jnp.asarray(rng.normal(0, 0.3, (4, 8)).astype(np.float32)),
            "Wv": jnp.asarray(rng.normal(0, 0.3, (4, 8)).astype(np.float32)),
            "Wo": jnp.asarray(rng.normal(0, 0.3, (8, 4)).astype(np.float32)),
        }
        y_short = mha_apply(params, jnp.asarray(x_short), 2)
        y_padded = mha_apply(params, jnp.asarray(x_padded), 2,
                             key_mask=jnp.asarray(mask))
        np.testing.assert_allclose(y_padded[:, :3], y_short, rtol=1e-5,
                                   atol=1e-6)

    def test_streaming_step_matches_batch_causal(self):
        """KV-cache streaming (rnnTimeStep analog) equals batch causal
        attention position by position."""
        from deeplearning4j_tpu.nn.conf.layers import MultiHeadAttention
        from deeplearning4j_tpu.nn.layers.factory import create_layer

        conf = MultiHeadAttention(n_in=4, n_out=8, num_heads=2, causal=True,
                                  weight_init="xavier", activation="identity")
        impl = create_layer(conf)
        params, state, _ = impl.initialize(jax.random.PRNGKey(0), (6, 4))
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(2, 6, 4)).astype(np.float32))
        y_batch, _ = impl.apply(params, state, x)
        st = {}
        outs = []
        for t in range(6):
            y_t, st = impl.step(params, st, x[:, t])
            outs.append(y_t)
        y_stream = jnp.stack(outs, axis=1)
        np.testing.assert_allclose(y_stream, y_batch, rtol=1e-4, atol=1e-5)


class TestRingFlashComposition:
    """VERDICT round-2 weak #5: the flash kernel engaged INSIDE the ring
    (local block product through pallas, interpret mode on the CPU mesh)."""

    def _qkv(self, n=2, t=512, h=2, d=32, seed=0):
        rng = np.random.default_rng(seed)
        return [jnp.asarray(rng.standard_normal((n, t, h, d)), jnp.float32)
                for _ in range(3)]

    def test_ring_flash_matches_dense(self):
        from jax.sharding import Mesh

        q, k, v = self._qkv()
        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        for causal in (False, True):
            ring = ring_attention_sharded(q, k, v, mesh, causal=causal,
                                          use_flash=True, interpret=True)
            ref = multi_head_attention(q, k, v, causal=causal)
            np.testing.assert_allclose(np.asarray(ring), np.asarray(ref),
                                       atol=5e-5,
                                       err_msg=f"causal={causal}")

    def test_ring_flash_with_key_mask(self):
        from jax.sharding import Mesh

        q, k, v = self._qkv(seed=2)
        rng = np.random.default_rng(3)
        km = rng.random((2, 512)) > 0.25
        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        ring = ring_attention_sharded(q, k, v, mesh, causal=True,
                                      key_mask=km, use_flash=True,
                                      interpret=True)
        ref = multi_head_attention(q, k, v, causal=True,
                                   key_mask=jnp.asarray(km))
        np.testing.assert_allclose(np.asarray(ring), np.asarray(ref),
                                   atol=5e-5)

    def test_ring_einsum_with_key_mask(self):
        """The non-flash ring path also honors the rotating mask shard."""
        from jax.sharding import Mesh

        q, k, v = self._qkv(t=64, seed=4)
        rng = np.random.default_rng(5)
        km = rng.random((2, 64)) > 0.25
        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        ring = ring_attention_sharded(q, k, v, mesh, causal=True,
                                      key_mask=km, use_flash=False)
        ref = multi_head_attention(q, k, v, causal=True,
                                   key_mask=jnp.asarray(km))
        np.testing.assert_allclose(np.asarray(ring), np.asarray(ref),
                                   atol=1e-5)

    def test_ring_flash_gradients_match_dense(self):
        from jax.sharding import Mesh

        q, k, v = self._qkv(n=1, t=256, h=1, d=32, seed=6)
        mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))

        def f_ring(q, k, v):
            return (ring_attention_sharded(
                q, k, v, mesh, causal=True, use_flash=True,
                interpret=True) ** 2).mean()

        def f_ref(q, k, v):
            return (multi_head_attention(q, k, v, causal=True) ** 2).mean()

        g = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, err_msg=f"d{name}")

    @pytest.mark.parametrize("use_flash", [True, False])
    def test_all_masked_rows_zero_output_finite_grads(self, use_flash):
        """Regression: a query row whose visible keys are ALL masked must
        output exactly 0 with finite gradients. Guards two coupled fixes:
        the ext kernel's lse = -inf (not a finite ~-69 sentinel) for
        no-visible-key rows, and the ring combiner's where-based safe
        denominator (maximum(l, 1e-30) NaNs the backward via (1e-30)^2
        f32 underflow in -o/denom^2 when l = 0)."""
        q, k, v = self._qkv(seed=8)
        t = q.shape[1]
        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        t_local = t // 4
        km = np.ones((2, t), bool)
        km[:, :t_local] = False  # first shard fully masked: causal rows
        # 0..t_local-1 see no key at all
        km = jnp.asarray(km)

        out = ring_attention_sharded(q, k, v, mesh, causal=True,
                                     key_mask=km, use_flash=use_flash,
                                     interpret=use_flash)
        out = np.asarray(out)
        assert np.isfinite(out).all()
        np.testing.assert_array_equal(out[:, :t_local], 0.0)

        def loss(q, k, v):
            o = ring_attention_sharded(q, k, v, mesh, causal=True,
                                       key_mask=km, use_flash=use_flash,
                                       interpret=use_flash)
            return (o.astype(jnp.float32) ** 2).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for gi, name in zip(g, "qkv"):
            assert np.isfinite(np.asarray(gi)).all(), f"d{name} non-finite"

    def test_mha_apply_ring_with_mask(self):
        """mha_apply on a seq mesh now supports key_mask (previously a
        ValueError): padded garbage cannot leak into valid positions."""
        from jax.sharding import Mesh

        rng = np.random.default_rng(7)
        x = np.zeros((2, 64, 8), np.float32)
        x[:, :48] = rng.normal(size=(2, 48, 8)).astype(np.float32)
        x[:, 48:] = 99.0
        mask = np.zeros((2, 64), np.float32)
        mask[:, :48] = 1.0
        params = {
            "Wq": jnp.asarray(rng.normal(0, 0.3, (8, 8)), jnp.float32),
            "Wk": jnp.asarray(rng.normal(0, 0.3, (8, 8)), jnp.float32),
            "Wv": jnp.asarray(rng.normal(0, 0.3, (8, 8)), jnp.float32),
            "Wo": jnp.asarray(rng.normal(0, 0.3, (8, 8)), jnp.float32),
        }
        mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
        y_ring = mha_apply(params, jnp.asarray(x), 2, mesh=mesh,
                           key_mask=jnp.asarray(mask))
        y_serial = mha_apply(params, jnp.asarray(x), 2,
                             key_mask=jnp.asarray(mask))
        np.testing.assert_allclose(np.asarray(y_ring[:, :48]),
                                   np.asarray(y_serial[:, :48]),
                                   rtol=1e-4, atol=1e-5)
