"""Updater closed-form tests (reference TestUpdaters.java pattern:
hand-computed expected update per rule — SURVEY.md section 4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.layers import DenseLayer, resolve
from deeplearning4j_tpu.optimize.updaters import (
    LayerUpdater,
    apply_updates,
    lr_at,
    normalize_gradients,
)


def make_updater(**kw):
    conf = resolve(DenseLayer(n_in=2, n_out=2, **kw))
    return LayerUpdater(conf), conf


G = {"W": jnp.array([[1.0, -2.0], [0.5, 3.0]]), "b": jnp.array([0.1, -0.1])}
P = {"W": jnp.zeros((2, 2)), "b": jnp.zeros(2)}


def test_sgd():
    u, conf = make_updater(updater="sgd", learning_rate=0.5)
    upd, _ = u.update(G, u.init(P), P, 0)
    np.testing.assert_allclose(upd["W"], 0.5 * np.asarray(G["W"]))
    np.testing.assert_allclose(upd["b"], 0.5 * np.asarray(G["b"]))


def test_bias_learning_rate():
    u, _ = make_updater(updater="sgd", learning_rate=0.5, bias_learning_rate=0.1)
    upd, _ = u.update(G, u.init(P), P, 0)
    np.testing.assert_allclose(upd["W"], 0.5 * np.asarray(G["W"]))
    np.testing.assert_allclose(upd["b"], 0.1 * np.asarray(G["b"]))


def test_nesterov_two_steps():
    lr, mu = 0.1, 0.9
    u, _ = make_updater(updater="nesterovs", learning_rate=lr, momentum=mu)
    state = u.init(P)
    g = np.asarray(G["W"])
    # step 1: v1 = -lr*g ; upd = mu*0 - (1+mu)*v1
    upd1, state = u.update(G, state, P, 0)
    v1 = -lr * g
    np.testing.assert_allclose(upd1["W"], -(1 + mu) * v1, rtol=1e-6)
    # step 2 with same gradient
    upd2, state = u.update(G, state, P, 1)
    v2 = mu * v1 - lr * g
    np.testing.assert_allclose(upd2["W"], mu * v1 - (1 + mu) * v2, rtol=1e-6)


def test_adagrad():
    lr, eps = 0.5, 1e-8
    u, _ = make_updater(updater="adagrad", learning_rate=lr, epsilon=eps)
    upd, state = u.update(G, u.init(P), P, 0)
    g = np.asarray(G["W"])
    np.testing.assert_allclose(
        upd["W"], lr * g / (np.sqrt(g * g) + eps), rtol=1e-6
    )
    # second step accumulates history
    upd2, _ = u.update(G, state, P, 1)
    np.testing.assert_allclose(
        upd2["W"], lr * g / (np.sqrt(2 * g * g) + eps), rtol=1e-6
    )


def test_rmsprop():
    lr, d, eps = 0.2, 0.95, 1e-8
    u, _ = make_updater(updater="rmsprop", learning_rate=lr, rms_decay=d, epsilon=eps)
    upd, _ = u.update(G, u.init(P), P, 0)
    g = np.asarray(G["W"])
    cache = (1 - d) * g * g
    np.testing.assert_allclose(upd["W"], lr * g / np.sqrt(cache + eps), rtol=1e-6)


def test_adadelta_first_step():
    rho, eps = 0.95, 1e-6
    u, _ = make_updater(updater="adadelta", rho=rho, epsilon=eps)
    upd, _ = u.update(G, u.init(P), P, 0)
    g = np.asarray(G["W"])
    msg = (1 - rho) * g * g
    expected = g * np.sqrt(eps) / np.sqrt(msg + eps)
    np.testing.assert_allclose(upd["W"], expected, rtol=1e-5)


def test_adam_first_step():
    lr, b1, b2, eps = 0.1, 0.9, 0.999, 1e-8
    u, _ = make_updater(
        updater="adam",
        learning_rate=lr,
        adam_mean_decay=b1,
        adam_var_decay=b2,
        epsilon=eps,
    )
    upd, _ = u.update(G, u.init(P), P, 0)
    g = np.asarray(G["W"])
    m = (1 - b1) * g
    v = (1 - b2) * g * g
    alpha = np.sqrt(1 - b2) / (1 - b1)
    np.testing.assert_allclose(
        upd["W"], lr * alpha * m / (np.sqrt(v) + eps), rtol=1e-5
    )


def test_noop():
    u, _ = make_updater(updater="none")
    upd, _ = u.update(G, u.init(P), P, 0)
    np.testing.assert_allclose(upd["W"], np.asarray(G["W"]))


def test_apply_updates_minimize():
    p2 = apply_updates([P], [G], minimize=True)
    np.testing.assert_allclose(p2[0]["W"], -np.asarray(G["W"]))


# -- LR policies (reference TestDecayPolicies.java pattern) ------------------


class _Conf:
    def __init__(self, **kw):
        self.lr_policy = kw.get("lr_policy", "none")
        self.lr_policy_decay_rate = kw.get("decay")
        self.lr_policy_steps = kw.get("steps")
        self.lr_policy_power = kw.get("power")
        self.lr_schedule = kw.get("schedule")
        self.momentum_schedule = None


@pytest.mark.parametrize(
    "conf,it,expected",
    [
        (_Conf(), 10, 0.1),
        (_Conf(lr_policy="exponential", decay=0.9), 2, 0.1 * 0.9**2),
        (_Conf(lr_policy="inverse", decay=0.5, power=2.0), 3, 0.1 / (1 + 0.5 * 3) ** 2),
        (_Conf(lr_policy="step", decay=0.5, steps=10.0), 25, 0.1 * 0.5**2),
        (_Conf(lr_policy="poly", power=2.0, steps=100.0), 50, 0.1 * 0.25),
        (_Conf(lr_policy="schedule", schedule={5: 0.01, 10: 0.001}), 3, 0.1),
        (_Conf(lr_policy="schedule", schedule={5: 0.01, 10: 0.001}), 7, 0.01),
        (_Conf(lr_policy="schedule", schedule={5: 0.01, 10: 0.001}), 11, 0.001),
    ],
)
def test_lr_policies(conf, it, expected):
    np.testing.assert_allclose(float(lr_at(conf, 0.1, it)), expected, rtol=1e-6)


# -- gradient normalization (reference TestGradientNormalization.java) ------


def test_clip_elementwise():
    out = normalize_gradients(G, "clip_elementwise_absolute_value", 1.0)
    assert np.abs(np.asarray(out["W"])).max() <= 1.0


def test_renormalize_l2_per_layer():
    out = normalize_gradients(G, "renormalize_l2_per_layer", 1.0)
    total = sum(np.sum(np.square(np.asarray(v))) for v in out.values())
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


def test_clip_l2_per_param_type():
    out = normalize_gradients(G, "clip_l2_per_param_type", 1.0)
    for v in out.values():
        assert np.linalg.norm(np.asarray(v).ravel()) <= 1.0 + 1e-5


def test_clip_l2_noop_when_under_threshold():
    out = normalize_gradients(G, "clip_l2_per_layer", 1e9)
    np.testing.assert_allclose(out["W"], np.asarray(G["W"]))


def test_score_lr_policy_decay():
    """'score' LR policy: event-driven decay via apply_lr_score_decay
    (reference BaseOptimizer.checkTerminalConditions:239 +
    Model.applyLearningRateScoreDecay)."""
    import numpy as np

    from deeplearning4j_tpu.datasets.fetchers import load_iris
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(42)
        .learning_rate(0.5)
        .learning_rate_policy("score")
        .lr_policy_decay_rate(0.1)
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    assert float(net.updater_state[0].get("lr_scale", -1)) == 1.0
    x, y = load_iris()
    net.fit(x, y)
    p_before = np.asarray(net.params[0]["W"]).copy()
    net.fit(x, y)
    full_step = np.abs(np.asarray(net.params[0]["W"]) - p_before).max()
    net.apply_lr_score_decay()
    assert abs(float(net.updater_state[0]["lr_scale"]) - 0.1) < 1e-6
    p_before = np.asarray(net.params[0]["W"]).copy()
    net.fit(x, y)
    decayed_step = np.abs(np.asarray(net.params[0]["W"]) - p_before).max()
    assert decayed_step < full_step * 0.5, (full_step, decayed_step)
