"""Activation registry unit tests (reference-style: tiny fixed inputs,
hand-computed expectations — SURVEY.md section 4 'Layer unit tests')."""

import numpy as np
import pytest

from deeplearning4j_tpu.ops.activations import ACTIVATIONS, activation


X = np.array([-2.0, -0.5, 0.0, 0.5, 2.0], dtype=np.float32)


@pytest.mark.parametrize(
    "name,expected",
    [
        ("relu", np.maximum(X, 0)),
        ("identity", X),
        ("tanh", np.tanh(X)),
        ("sigmoid", 1 / (1 + np.exp(-X))),
        ("hardtanh", np.clip(X, -1, 1)),
        ("cube", X**3),
        ("softplus", np.log1p(np.exp(X))),
        ("softsign", X / (1 + np.abs(X))),
        ("leakyrelu", np.where(X > 0, X, 0.01 * X)),
        ("step", (X > 0).astype(np.float32)),
    ],
)
def test_pointwise_values(name, expected):
    # rtol 1e-4: XLA's vectorized transcendental approximations (e.g. tanh)
    # differ from libm at ~2e-5 relative
    np.testing.assert_allclose(activation(name)(X), expected, rtol=1e-4, atol=1e-6)


def test_softmax_rows_sum_to_one():
    x = np.random.default_rng(0).standard_normal((4, 7)).astype(np.float32)
    y = np.asarray(activation("softmax")(x))
    np.testing.assert_allclose(y.sum(axis=-1), np.ones(4), rtol=1e-6)
    assert (y > 0).all()


def test_unknown_activation_raises():
    with pytest.raises(ValueError):
        activation("nope")


def test_registry_contains_reference_era_set():
    for name in [
        "sigmoid",
        "tanh",
        "relu",
        "leakyrelu",
        "softmax",
        "identity",
        "softsign",
        "softplus",
        "hardtanh",
        "cube",
        "elu",
        "rectifiedtanh",
    ]:
        assert name in ACTIVATIONS
