"""Worker process for the multi-host CPU harness (test_multihost_cpu.py).

Runs as ONE process of a 2-process jax.distributed cluster, wired through
the SAME env-var contract the pod provisioner injects
(multihost.COORDINATOR_ENV et al.) — the cross-process analogue of the
reference's Spark executor role (SURVEY.md section 2.3: one worker JVM per
partition feeding ParameterAveragingTrainingMaster; here one OS process
per host feeding XLA collectives over Gloo/ICI).

Each worker:
  1. initializes jax.distributed from the env contract,
  2. trains a serial reference net on its own full copy of the data,
  3. trains the SAME net via ParallelWrapper on the global 2-process x
     2-device mesh, feeding only its process-local batch slice,
  4. asserts bit-identical parameters and prints `MH_OK ...` for the
     parent test to collect.
"""
import os
import sys

# the pytest parent forces an 8-device host platform via XLA_FLAGS; this
# worker wants 2 local devices per process (2 procs x 2 = 4 global).
# Replace (not just strip) the flag BEFORE jax import: this environment's
# jax (0.4.x) has no jax_num_cpu_devices config, so XLA_FLAGS — read at
# CPU-client creation — is the only device-count mechanism (same fallback
# as tests/conftest.py).
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if "xla_force_host_platform_device_count" not in f]
flags.append("--xla_force_host_platform_device_count=2")
os.environ["XLA_FLAGS"] = " ".join(flags)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # 0.4.x: the XLA_FLAGS fallback above provides the 2 devices
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer  # noqa: E402
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.parallel import multihost  # noqa: E402
from deeplearning4j_tpu.parallel.data_parallel import ParallelWrapper  # noqa: E402


def build_net(seed=7):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(0.1)
        .updater("sgd")
        .list()
        .layer(0, DenseLayer(n_in=8, n_out=16, activation="tanh"))
        .layer(1, OutputLayer(n_in=16, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def main() -> None:
    assert multihost.initialize_multihost(), "env contract not configured"
    info = multihost.process_info()
    assert info["process_count"] == 2, info
    assert info["global_device_count"] == 4, info
    assert multihost.is_multihost()

    # an uneven global batch must raise CONSISTENTLY on every process —
    # a per-process divergence here would deadlock the collectives
    try:
        multihost.local_batch_slice(17)
    except ValueError as e:
        assert "17" in str(e), e
    else:
        raise AssertionError("uneven global batch must raise")

    # capability probe: this jaxlib generation (0.4.x) cannot RUN
    # multi-process computations on the CPU backend at all (Gloo-backed
    # cross-host CPU collectives landed later) — the cluster forms and
    # process_info is correct, but the first collective raises. Report the
    # missing capability explicitly so the parent test can SKIP instead of
    # failing on an environment limit no code change here can lift.
    try:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("mh_probe")
    except Exception as e:  # noqa: BLE001 — filtered to the capability case
        # ONLY the known capability gap becomes a skip ("Multiprocess
        # computations aren't implemented on the CPU backend"); any other
        # collective failure is a real regression and must stay loud.
        if "Multiprocess computations" not in str(e):
            raise
        print(f"MH_SKIP multiprocess CPU collectives unavailable: {e}",
              flush=True)
        return

    rng = np.random.RandomState(0)
    X = rng.randn(16, 8)
    Y = np.eye(3)[rng.randint(0, 3, size=16)]

    serial = build_net()
    for _ in range(5):
        serial.fit(X, Y)

    net = build_net()
    mesh = Mesh(np.array(jax.devices()).reshape(4), ("data",))
    pw = ParallelWrapper(net, mesh=mesh)
    sl = multihost.local_batch_slice(16)
    for _ in range(5):
        loss = pw.fit(X[sl], Y[sl])

    # fused multi-step path too (fit_batches: [K, N, ...] per-process
    # shard of the stacked batches through one lax.scan program)
    Xs = np.stack([X, X[::-1]])
    Ys = np.stack([Y, Y[::-1]])
    serial.fit_batches(Xs, Ys)
    pw.fit_batches(Xs[:, sl], Ys[:, sl])

    dev = max(
        float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
        for a, b in zip(jax.tree_util.tree_leaves(serial.params),
                        jax.tree_util.tree_leaves(net.params))
    )
    assert dev == 0.0, f"param deviation {dev}"
    print(f"MH_OK proc={info['process_index']} loss={float(loss):.6f} "
          f"max_param_dev={dev}", flush=True)


if __name__ == "__main__":
    main()
