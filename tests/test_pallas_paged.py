"""Paged-decode attention kernel contracts (ISSUE 13, quick tier).

The rent the kernel pays before it may ever go default-on
(ops/pallas_paged.py — vLLM PagedAttention + flash online softmax over
the PR 11 block arena; no reference twin, provenance in the module
docstring):

  * value contract — ``paged_attention`` (interpret mode on this CPU
    substrate) matches the serving gather path's masked softmax
    attention to f32 rounding, including trash-block invisibility
    (poisoned block 0 cannot move the output);
  * tick contract — ``paged_decode_step(attention='kernel')`` ==
    ``attention='gather'`` logits and arena to 1e-6, with layer 0's
    pre-attention scatter write BIT-identical (shared code);
  * transcript contract — the full prefix-sharing and preemption
    scenarios from tests/test_serving_paged.py produce byte-identical
    greedy transcripts with DL4J_TPU_PALLAS_PAGED=force vs =0 (the
    kernel slots under every scheduling behavior, not just a lone tick);
  * gate contract — knob 0 always gathers, force always kernels (within
    the VMEM budget), and '' auto stays on the gather fallback on this
    substrate (no real-chip measured-win row).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def tiny_lm(**over):
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    kw = dict(vocab_size=29, d_model=16, n_layers=2, n_heads=2, d_ff=32,
              max_len=32, use_flash=False)
    kw.update(over)
    return TransformerLM(TransformerConfig(**kw))


def _arena_case(seed=0, s=4, h=2, hd=16, bt=4, m=4):
    """A hand-built block-table scene: lane ``i`` owns ``used[i]``
    distinct arena blocks (allocated from 1; 0 is trash), its table is
    padded out to ``m`` with trash entries, and ``pos`` sits mid-window
    so both fully-visible and partially-visible blocks occur."""
    rng = np.random.default_rng(seed)
    n_blocks = s * m
    q = jnp.asarray(rng.standard_normal((s, h, hd)), jnp.float32)
    ck = jnp.asarray(rng.standard_normal((n_blocks + 1, bt, h, hd)),
                     jnp.float32)
    cv = jnp.asarray(rng.standard_normal((n_blocks + 1, bt, h, hd)),
                     jnp.float32)
    perm = rng.permutation(np.arange(1, n_blocks + 1))
    tables = np.zeros((s, m), np.int32)
    pos = np.zeros((s,), np.int32)
    nxt = 0
    for i in range(s):
        used = 1 + (i % m)                # 1..m allocated blocks
        tables[i, :used] = perm[nxt:nxt + used]
        nxt += used
        pos[i] = used * bt - 1 - (i % bt)  # last block partially filled
    return q, ck, cv, jnp.asarray(tables), jnp.asarray(pos)


def _gather_oracle(q, ck, cv, tables, pos):
    """serving/paged.py's gather-path attention math, verbatim."""
    s, h, hd = q.shape
    bt = ck.shape[1]
    t_total = tables.shape[1] * bt
    kg = ck[tables].reshape(s, t_total, h, hd)
    vg = cv[tables].reshape(s, t_total, h, hd)
    sc = jnp.einsum("nhd,nthd->nht", q.astype(jnp.float32),
                    kg.astype(jnp.float32)) / float(np.sqrt(hd))
    visible = jnp.arange(t_total)[None, :] <= pos[:, None]
    sc = jnp.where(visible[:, None, :], sc, -jnp.inf)
    p = jax.nn.softmax(sc, axis=-1)
    return jnp.einsum("nht,nthd->nhd", p, vg.astype(jnp.float32))


# ---------------------------------------------------------------------------
# kernel value contracts (interpret mode — Mosaic only compiles on chip)
# ---------------------------------------------------------------------------


class TestPagedAttentionKernel:
    def test_kernel_matches_gather_oracle(self):
        from deeplearning4j_tpu.ops.pallas_paged import paged_attention

        q, ck, cv, tables, pos = _arena_case()
        out = paged_attention(q, ck, cv, tables, pos, interpret=True)
        ref = _gather_oracle(q, ck, cv, tables, pos)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-6

    def test_single_block_and_full_window_lanes(self):
        """Edge positions: a lane on its very first token (pos 0) and a
        lane with every table entry allocated and full (pos == T-1)."""
        from deeplearning4j_tpu.ops.pallas_paged import paged_attention

        q, ck, cv, tables, pos = _arena_case(seed=1)
        tables = tables.at[0].set(jnp.arange(1, tables.shape[1] + 1))
        pos = pos.at[0].set(tables.shape[1] * ck.shape[1] - 1)
        pos = pos.at[1].set(0)
        out = paged_attention(q, ck, cv, tables, pos, interpret=True)
        ref = _gather_oracle(q, ck, cv, tables, pos)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-6

    def test_trash_block_content_is_invisible(self):
        """Physical block 0 backs every unallocated table entry; the
        ``t <= pos`` mask must make its CONTENT unobservable — poisoning
        it with huge values cannot move any lane's output."""
        from deeplearning4j_tpu.ops.pallas_paged import paged_attention

        q, ck, cv, tables, pos = _arena_case(seed=2)
        clean = paged_attention(q, ck, cv, tables, pos, interpret=True)
        ck = ck.at[0].set(1e6)
        cv = cv.at[0].set(-1e6)
        poisoned = paged_attention(q, ck, cv, tables, pos, interpret=True)
        np.testing.assert_array_equal(np.asarray(clean),
                                      np.asarray(poisoned))


# ---------------------------------------------------------------------------
# tick contract: paged_decode_step kernel == gather
# ---------------------------------------------------------------------------


class TestPagedDecodeStep:
    def test_kernel_tick_equals_gather_tick(self):
        from deeplearning4j_tpu.serving.paged import paged_decode_step

        lm = tiny_lm()
        cfg = lm.cfg
        bt, n_blocks = 8, 12
        s, m = 3, cfg.max_len // bt
        hd = cfg.d_model // cfg.n_heads
        rng = np.random.default_rng(7)
        shape = (cfg.n_layers, n_blocks + 1, bt, cfg.n_heads, hd)
        arena = {
            "k": jnp.asarray(rng.standard_normal(shape), cfg.compute_dtype),
            "v": jnp.asarray(rng.standard_normal(shape), cfg.compute_dtype),
        }
        tables = np.zeros((s, m), np.int32)
        perm = rng.permutation(np.arange(1, n_blocks + 1))
        nxt = 0
        pos = np.zeros((s,), np.int32)
        for i in range(s):
            used = 1 + i
            tables[i, :used] = perm[nxt:nxt + used]
            nxt += used
            pos[i] = used * bt - 2 - i
        tok = jnp.asarray([3, 11, 27], jnp.int32)
        tables = jnp.asarray(tables)
        pos = jnp.asarray(pos)

        a_g, logits_g = paged_decode_step(lm.params, arena, tok, pos,
                                          tables, cfg, attention="gather")
        a_k, logits_k = paged_decode_step(lm.params, arena, tok, pos,
                                          tables, cfg, attention="kernel")
        # layer 0's (block, offset) scatter write happens BEFORE any
        # attention runs: it must be BIT-identical between the paths;
        # deeper layers write values downstream of the previous layer's
        # attention and inherit its f32 rounding
        np.testing.assert_array_equal(np.asarray(a_g["k"][0]),
                                      np.asarray(a_k["k"][0]))
        np.testing.assert_array_equal(np.asarray(a_g["v"][0]),
                                      np.asarray(a_k["v"][0]))
        assert float(jnp.max(jnp.abs(a_g["k"] - a_k["k"]))) < 1e-6
        assert float(jnp.max(jnp.abs(a_g["v"] - a_k["v"]))) < 1e-6
        assert float(jnp.max(jnp.abs(logits_g - logits_k))) < 1e-6


# ---------------------------------------------------------------------------
# transcript contracts: full scheduling scenarios, kernel forced
# ---------------------------------------------------------------------------


class TestForcedKernelTranscripts:
    def _decode(self, lm, monkeypatch, knob, scenario):
        monkeypatch.setenv("DL4J_TPU_PALLAS_PAGED", knob)
        from deeplearning4j_tpu.serving.paged import PagedDecoder, \
            attention_path

        want = "kernel" if knob == "force" else "gather"
        assert attention_path(lm.cfg, 8) == want
        return scenario(PagedDecoder)

    def test_prefix_sharing_transcripts_identical(self, monkeypatch):
        """The tests/test_serving_paged.py prefix-sharing scenario —
        shared read tables, trash-pointed write tables, a third
        co-resident — replayed with the kernel forced: greedy
        transcripts byte-identical to the gather path, and the share
        still registers as prefix-cache hits."""
        lm = tiny_lm()
        shared = [2, 4, 6, 8, 10, 12, 14, 16, 3, 5]

        def scenario(PagedDecoder):
            d = PagedDecoder(lm, block_tokens=8, n_blocks=16)
            try:
                before = d.stats.prefix_hits
                f1 = d.submit(shared + [7], 5, temperature=0.0)
                f2 = d.submit(shared + [9], 5, temperature=0.0)
                f3 = d.submit([3, 3, 4], 8, temperature=0.0)
                outs = [f.result(timeout=120) for f in (f1, f2, f3)]
                assert d.stats.prefix_hits > before
                return outs
            finally:
                d.stop()

        base = self._decode(lm, monkeypatch, "0", scenario)
        forced = self._decode(lm, monkeypatch, "force", scenario)
        for b, f in zip(base, forced):
            np.testing.assert_array_equal(b, f)

    def test_preemption_transcripts_identical(self, monkeypatch):
        """The block-starvation scenario (7 blocks cannot hold three
        23/24-token sequences): preemption + recompute-from-window must
        fire under BOTH paths and the transcripts must agree byte-wise
        — the kernel's mask honors a re-admitted lane's rebuilt table
        exactly like the gather."""
        lm = tiny_lm()
        prompts = ([2, 4, 6], [1, 1, 1, 1], [9, 8, 7])

        def scenario(PagedDecoder):
            d = PagedDecoder(lm, block_tokens=8, n_blocks=7)
            try:
                futs = [d.submit(list(p), 20, temperature=0.0)
                        for p in prompts]
                outs = [f.result(timeout=240) for f in futs]
                assert d.stats.preemptions >= 1
                return outs
            finally:
                d.stop()

        base = self._decode(lm, monkeypatch, "0", scenario)
        forced = self._decode(lm, monkeypatch, "force", scenario)
        for b, f in zip(base, forced):
            np.testing.assert_array_equal(b, f)


# ---------------------------------------------------------------------------
# gate contract
# ---------------------------------------------------------------------------


class TestPagedGate:
    def test_knob_zero_disables(self, monkeypatch):
        from deeplearning4j_tpu.ops.pallas_paged import paged_kernel_enabled

        monkeypatch.setenv("DL4J_TPU_PALLAS_PAGED", "0")
        assert not paged_kernel_enabled(16, 128, 16)

    def test_force_respects_vmem_budget(self, monkeypatch):
        from deeplearning4j_tpu.ops.pallas_paged import (
            _VMEM_BUDGET_FLOATS,
            paged_kernel_enabled,
        )

        monkeypatch.setenv("DL4J_TPU_PALLAS_PAGED", "force")
        assert paged_kernel_enabled(2, 8, 8)
        # force bypasses the measured-win table, never the VMEM fit
        too_big = _VMEM_BUDGET_FLOATS  # 2 * bt * H * hd over budget
        assert not paged_kernel_enabled(too_big, 1, 1)

    def test_auto_stays_on_gather_without_chip_row(self, monkeypatch):
        """'' auto on this CPU substrate: no real-chip measured-win row
        for the paged group exists, so the tick must resolve to the XLA
        gather fallback (the default-off half of the rent contract)."""
        from deeplearning4j_tpu.serving.paged import attention_path

        monkeypatch.delenv("DL4J_TPU_PALLAS_PAGED", raising=False)
        assert attention_path(tiny_lm().cfg, 8) == "gather"

    def test_interpret_on_cpu(self):
        from deeplearning4j_tpu.ops.pallas_paged import paged_interpret

        assert paged_interpret()
