"""Ingest-layer tests: idx/CIFAR binary parsing against known checksums,
ImageLoader, ImageRecordReader directory-label semantics (reference
MnistDataFetcher idx readers, CifarDataSetIterator, util/ImageLoader.java,
Canova ImageRecordReader)."""

import gzip
import hashlib
import struct

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import fetchers
from deeplearning4j_tpu.datasets.fetchers import (
    load_curves,
    load_lfw_info,
    read_cifar_batch,
    read_idx_images,
    read_idx_labels,
)
from deeplearning4j_tpu.datasets.image import ImageLoader, ImageRecordReader
from deeplearning4j_tpu.datasets.records import RecordReaderDataSetIterator


# ---------------------------------------------------------------- idx files
def write_idx(tmp_path, imgs: np.ndarray, lbls: np.ndarray, gz=False):
    n, rows, cols = imgs.shape
    ipath = tmp_path / ("imgs.idx3-ubyte" + (".gz" if gz else ""))
    lpath = tmp_path / ("lbls.idx1-ubyte" + (".gz" if gz else ""))
    iopen = gzip.open if gz else open
    with iopen(ipath, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, rows, cols))
        f.write(imgs.astype(np.uint8).tobytes())
    with iopen(lpath, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(lbls.astype(np.uint8).tobytes())
    return ipath, lpath


def test_idx_round_trip_and_checksum(tmp_path):
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (32, 28, 28)).astype(np.uint8)
    lbls = rng.integers(0, 10, 32).astype(np.uint8)
    ipath, lpath = write_idx(tmp_path, imgs, lbls)
    # the serialized idx bytes are deterministic: checksum pins the format
    digest = hashlib.md5(ipath.read_bytes()).hexdigest()
    assert digest == hashlib.md5(
        struct.pack(">IIII", 2051, 32, 28, 28) + imgs.tobytes()
    ).hexdigest()
    np.testing.assert_array_equal(read_idx_images(ipath), imgs)
    np.testing.assert_array_equal(read_idx_labels(lpath), lbls)


def test_idx_gzip_transparent(tmp_path):
    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, (8, 28, 28)).astype(np.uint8)
    lbls = rng.integers(0, 10, 8).astype(np.uint8)
    ipath, lpath = write_idx(tmp_path, imgs, lbls, gz=True)
    np.testing.assert_array_equal(read_idx_images(ipath), imgs)
    np.testing.assert_array_equal(read_idx_labels(lpath), lbls)


def test_idx_bad_magic_raises(tmp_path):
    p = tmp_path / "bad.idx3-ubyte"
    p.write_bytes(struct.pack(">IIII", 1234, 1, 2, 2) + b"\x00" * 4)
    with pytest.raises(ValueError, match="magic"):
        read_idx_images(p)


def test_load_mnist_from_local_idx(tmp_path, monkeypatch):
    """load_mnist prefers real local idx files and reports provenance."""
    rng = np.random.default_rng(2)
    imgs = rng.integers(0, 256, (16, 28, 28)).astype(np.uint8)
    lbls = rng.integers(0, 10, 16).astype(np.uint8)
    mdir = tmp_path / "MNIST"
    mdir.mkdir()
    for stem in ("train", "t10k"):
        ip, lp = write_idx(tmp_path, imgs, lbls)
        (mdir / f"{stem}-images-idx3-ubyte").write_bytes(ip.read_bytes())
        (mdir / f"{stem}-labels-idx1-ubyte").write_bytes(lp.read_bytes())
    monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
    x, y, prov = fetchers.load_mnist_info(train=True, download=False)
    assert prov == "local"
    assert x.shape == (16, 28, 28, 1) and y.shape == (16, 10)
    np.testing.assert_allclose(
        x[:, :, :, 0], imgs.astype(np.float32) / 255.0, atol=1e-7
    )
    # binarize option (MnistDataFetcher.java:43-70)
    xb, _, _ = fetchers.load_mnist_info(train=True, binarize=True, download=False)
    assert set(np.unique(xb)) <= {0.0, 1.0}


def test_load_mnist_synthetic_fallback(tmp_path, monkeypatch):
    monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path / "empty"))
    x, y, prov = fetchers.load_mnist_info(train=True, num_examples=64, download=False)
    assert prov == "synthetic"
    assert x.shape == (64, 28, 28, 1)


# ------------------------------------------------------------------- CIFAR
def test_cifar_batch_parse(tmp_path):
    rng = np.random.default_rng(3)
    n = 10
    labels = rng.integers(0, 10, n).astype(np.uint8)
    imgs_chw = rng.integers(0, 256, (n, 3, 32, 32)).astype(np.uint8)
    raw = b"".join(
        bytes([labels[i]]) + imgs_chw[i].tobytes() for i in range(n)
    )
    p = tmp_path / "data_batch_1.bin"
    p.write_bytes(raw)
    assert hashlib.md5(p.read_bytes()).hexdigest() == hashlib.md5(raw).hexdigest()
    imgs, lbls = read_cifar_batch(p)
    assert imgs.shape == (n, 32, 32, 3)
    np.testing.assert_array_equal(lbls, labels)
    # HWC conversion: channel c, row y, col x comes from CHW layout
    np.testing.assert_array_equal(imgs[0, :, :, 0], imgs_chw[0, 0])
    np.testing.assert_array_equal(imgs[0, :, :, 2], imgs_chw[0, 2])


def test_cifar_truncated_raises(tmp_path):
    p = tmp_path / "trunc.bin"
    p.write_bytes(b"\x00" * 100)
    with pytest.raises(ValueError, match="multiple"):
        read_cifar_batch(p)


def test_load_cifar10_local(tmp_path, monkeypatch):
    rng = np.random.default_rng(4)
    d = tmp_path / "cifar-10-batches-bin"
    d.mkdir()
    for name in [f"data_batch_{i}.bin" for i in range(1, 6)] + ["test_batch.bin"]:
        labels = rng.integers(0, 10, 4).astype(np.uint8)
        imgs = rng.integers(0, 256, (4, 3, 32, 32)).astype(np.uint8)
        (d / name).write_bytes(
            b"".join(bytes([labels[i]]) + imgs[i].tobytes() for i in range(4))
        )
    monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
    x, y, prov = fetchers.load_cifar10_info(train=True, download=False)
    assert prov == "local"
    assert x.shape == (20, 32, 32, 3) and y.shape == (20, 10)
    x, y, prov = fetchers.load_cifar10_info(train=False, download=False)
    assert x.shape == (4, 32, 32, 3)


# ------------------------------------------------------------- ImageLoader
def _write_png(path, arr):
    from PIL import Image

    Image.fromarray(arr).save(path)


def test_image_loader_matrix_and_resize(tmp_path):
    rng = np.random.default_rng(5)
    arr = rng.integers(0, 256, (16, 12, 3)).astype(np.uint8)
    p = tmp_path / "img.png"
    _write_png(p, arr)
    loader = ImageLoader()
    out = loader.as_matrix(p)
    assert out.shape == (16, 12, 3)
    np.testing.assert_array_equal(out.astype(np.uint8), arr)
    resized = ImageLoader(height=8, width=6, channels=3).as_matrix(p)
    assert resized.shape == (8, 6, 3)
    gray = ImageLoader(channels=1).as_matrix(p)
    assert gray.shape == (16, 12)
    row = ImageLoader(height=4, width=4, channels=1).as_row_vector(p)
    assert row.shape == (1, 16)


def test_image_loader_to_image_round_trip(tmp_path):
    rng = np.random.default_rng(6)
    arr = rng.integers(0, 256, (10, 10, 3)).astype(np.uint8)
    img = ImageLoader.to_image(arr.astype(np.float32))
    np.testing.assert_array_equal(np.asarray(img), arr)


def test_image_record_reader_directory_labels(tmp_path):
    """Parent-directory name is the label (Canova ImageRecordReader)."""
    rng = np.random.default_rng(7)
    for ci, cls in enumerate(["cat", "dog"]):
        d = tmp_path / cls
        d.mkdir()
        for j in range(3):
            _write_png(
                d / f"{j}.png", rng.integers(0, 256, (8, 8)).astype(np.uint8)
            )
    rr = ImageRecordReader(str(tmp_path), height=8, width=8, channels=1)
    assert rr.labels == ["cat", "dog"]
    recs = list(rr)
    assert len(recs) == 6
    assert all(r.shape == (65,) for r in recs)  # 64 pixels + label
    assert sorted({int(r[-1]) for r in recs}) == [0, 1]

    # assembles into a classification DataSet through the standard iterator
    it = RecordReaderDataSetIterator(
        rr, batch_size=4, label_index=-1, num_possible_labels=2
    )
    batches = list(it)
    assert batches[0].features.shape == (4, 64)
    assert batches[0].labels.shape == (4, 2)
    np.testing.assert_allclose(batches[0].labels.sum(axis=1), 1.0)


# ------------------------------------------------------------- LFW / Curves
def test_lfw_local_directory(tmp_path, monkeypatch):
    rng = np.random.default_rng(8)
    lfw = tmp_path / "lfw"
    for person in ["alice", "bob"]:
        d = lfw / person
        d.mkdir(parents=True)
        for j in range(2):
            _write_png(
                d / f"{person}_{j}.png",
                rng.integers(0, 256, (32, 32)).astype(np.uint8),
            )
    monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
    x, y, names, prov = load_lfw_info(height=16, width=16)
    assert prov == "local"
    assert x.shape == (4, 16, 16, 1)
    assert names == ["alice", "bob"]
    assert y.shape == (4, 2)


def test_lfw_synthetic_fallback(tmp_path, monkeypatch):
    monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
    x, y, names, prov = load_lfw_info(num_examples=32)
    assert prov == "synthetic"
    assert x.shape == (32, 28, 28, 1)


def test_curves_deterministic():
    x1, y1 = load_curves(n=16)
    x2, _ = load_curves(n=16)
    np.testing.assert_array_equal(x1, x2)
    assert x1.shape == (16, 784)
    assert y1 is x1 or np.array_equal(y1, x1)
    assert x1.max() == 1.0 and x1.min() == 0.0
