"""Filter-grid / reconstruction renders (reference plot/PlotFilters.java,
ImageRender.java, MultiLayerNetworkReconstructionRender.java,
plot/iterationlistener/PlotFiltersIterationListener.java) — the last
SURVEY §2.1 plot row: mosaic assembly semantics, PNG round trip, the
AE/RBM reconstruction path, and the periodic listener."""

import numpy as np
import pytest

from deeplearning4j_tpu.plot import (
    PlotFilters,
    PlotFiltersIterationListener,
    ReconstructionRender,
    reconstruct,
    render_image,
)


class TestPlotFilters:
    def test_mosaic_shape_no_spacing(self):
        filt = np.random.default_rng(0).random((6, 16))
        pf = PlotFilters(filt, tile_shape=(2, 3), image_shape=(4, 4))
        plot = pf.plot()
        assert plot.shape == (8, 12)

    def test_mosaic_tile_placement(self):
        """Tile (r, c) holds filter r*cols + c, scaled to [0, 1] per tile
        (PlotFilters.plotSection row-major order + scale :63-66)."""
        filt = np.arange(12, dtype=np.float64).reshape(3, 4)  # 3 filters 2x2
        pf = PlotFilters(filt, tile_shape=(2, 2), image_shape=(2, 2))
        plot = pf.plot()
        for i in range(3):
            r, c = divmod(i, 2)
            tile = plot[2 * r: 2 * r + 2, 2 * c: 2 * c + 2]
            expect = (filt[i] - filt[i].min())
            expect = (expect / expect.max()).reshape(2, 2)
            np.testing.assert_allclose(tile, expect)
        # unfilled 4th tile is zeros
        np.testing.assert_array_equal(plot[2:, 2:], 0.0)

    def test_spacing_inserts_gaps(self):
        filt = np.ones((4, 4))
        pf = PlotFilters(filt, tile_shape=(2, 2), image_shape=(2, 2),
                         tile_spacing=(1, 1), scale_rows=False)
        plot = pf.plot()
        assert plot.shape == (5, 5)  # (2+1)*2-1
        np.testing.assert_array_equal(plot[2, :], 0.0)  # gap row
        np.testing.assert_array_equal(plot[:, 2], 0.0)  # gap col

    def test_4d_input_stacks_channels(self):
        x = np.random.default_rng(1).random((3, 4, 2, 2))
        pf = PlotFilters(x, tile_shape=(2, 2), image_shape=(2, 2))
        plot = pf.plot()
        assert plot.shape == (4, 4, 3)

    @pytest.mark.parametrize("channels,shape", [(1, (4, 4)), (2, (4, 4, 3)),
                                                (4, (4, 4, 4))])
    def test_4d_every_channel_count_renderable(self, channels, shape,
                                               tmp_path):
        """Every plot() result must feed render_image: 1 channel (the
        MNIST conv case) squeezes to grayscale, 2 pads to RGB."""
        x = np.random.default_rng(2).random((channels, 4, 2, 2))
        pf = PlotFilters(x, tile_shape=(2, 2), image_shape=(2, 2))
        plot = pf.plot()
        assert plot.shape == shape
        render_image(plot, str(tmp_path / "p.png"))

    def test_get_plot_before_plot_raises(self):
        pf = PlotFilters(np.ones((2, 4)), tile_shape=(1, 2),
                         image_shape=(2, 2))
        with pytest.raises(ValueError, match="plot"):
            pf.get_plot()


class TestRenderImage:
    def test_png_round_trip_grayscale(self, tmp_path):
        from PIL import Image

        img = np.linspace(0, 1, 64).reshape(8, 8)
        path = str(tmp_path / "g.png")
        render_image(img, path)
        back = np.asarray(Image.open(path))
        assert back.shape == (8, 8)
        np.testing.assert_array_equal(
            back, np.clip(img * 255, 0, 255).astype(np.uint8))

    def test_png_rgb(self, tmp_path):
        from PIL import Image

        img = np.random.default_rng(2).random((4, 4, 3))
        path = str(tmp_path / "c.png")
        render_image(img, path)
        assert np.asarray(Image.open(path)).shape == (4, 4, 3)

    def test_bad_shape_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="renderable"):
            render_image(np.ones((2, 2, 2)), str(tmp_path / "x.png"))


def _pretrain_net(layer_cls_kwargs):
    from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .learning_rate(0.05)
        .list()
        .layer(0, layer_cls_kwargs)
        .layer(1, OutputLayer(n_in=8, n_out=4, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf)
    net.init()
    return net


class TestReconstruction:
    @pytest.mark.parametrize("kind", ["ae", "rbm"])
    def test_reconstruct_through_pretrain_layer(self, kind):
        from deeplearning4j_tpu.nn.conf.layers import AutoEncoder, RBM

        layer = (AutoEncoder(n_in=16, n_out=8, activation="sigmoid")
                 if kind == "ae" else
                 RBM(n_in=16, n_out=8, visible_unit="binary",
                     hidden_unit="binary"))
        net = _pretrain_net(layer)
        x = np.random.default_rng(3).random((5, 16)).astype(np.float32)
        recon = reconstruct(net, x, 0)
        assert recon.shape == (5, 16)
        assert np.isfinite(recon).all()

    def test_reconstruct_dense_layer_rejected(self):
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer

        net = _pretrain_net(DenseLayer(n_in=16, n_out=8, activation="relu"))
        with pytest.raises(ValueError, match="visible model"):
            reconstruct(net, np.ones((2, 16), np.float32), 0)

    def test_render_draw_writes_real_vs_recon(self, tmp_path):
        from PIL import Image

        from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
        from deeplearning4j_tpu.nn.conf.layers import AutoEncoder

        net = _pretrain_net(AutoEncoder(n_in=16, n_out=8,
                                        activation="sigmoid"))
        x = np.random.default_rng(4).random((6, 16)).astype(np.float32)
        y = np.eye(4, dtype=np.float32)[np.arange(6) % 4]
        it = ListDataSetIterator(x, y, batch=6)
        rr = ReconstructionRender(it, net, recon_layer=0, image_shape=(4, 4),
                                  max_examples=6)
        path = str(tmp_path / "recon.png")
        mosaic = rr.draw(path)
        assert mosaic.shape == (8, 24)  # 2 rows of six 4x4 images
        assert np.asarray(Image.open(path)).shape == (8, 24)
        # top row is the (scaled) real data, not all zeros
        assert mosaic[:4].max() > 0

    def test_draw_walks_the_iterator(self, tmp_path):
        """Successive draw() calls render successive batches (reference
        draw() walks iter.next() :46), and exhaustion raises."""
        from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
        from deeplearning4j_tpu.nn.conf.layers import AutoEncoder

        net = _pretrain_net(AutoEncoder(n_in=16, n_out=8,
                                        activation="sigmoid"))
        rng = np.random.default_rng(5)
        x = np.concatenate([np.zeros((2, 16), np.float32),
                            rng.random((2, 16)).astype(np.float32)])
        y = np.eye(4, dtype=np.float32)[np.arange(4) % 4]
        rr = ReconstructionRender(ListDataSetIterator(x, y, batch=2), net,
                                  recon_layer=0, image_shape=(4, 4))
        m1 = rr.draw(str(tmp_path / "b0.png"))
        m2 = rr.draw(str(tmp_path / "b1.png"))
        # batch 0's real row is all-zero input; batch 1's is not
        assert m1[:4].max() == 0.0
        assert m2[:4].max() > 0.0
        with pytest.raises(StopIteration):
            rr.draw(str(tmp_path / "b2.png"))


class TestPlotFiltersListener:
    def test_listener_renders_every_n_iterations(self, tmp_path):
        from deeplearning4j_tpu.datasets.fetchers import load_iris
        from deeplearning4j_tpu.nn.conf.builder import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (
            NeuralNetConfiguration.builder()
            .seed(1)
            .learning_rate(0.1)
            .list()
            .layer(0, DenseLayer(n_in=4, n_out=4, activation="tanh"))
            .layer(1, OutputLayer(n_in=4, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build()
        )
        net = MultiLayerNetwork(conf)
        net.init()
        out = tmp_path / "render.png"
        pf = PlotFilters(None, tile_shape=(2, 2), image_shape=(2, 2))
        net.set_listeners(PlotFiltersIterationListener(
            pf, layer=0, param="W", frequency=2, output_path=str(out)))
        X, Y = load_iris()
        for _ in range(2):
            net.fit(X[:32], Y[:32])
        assert out.exists()
        # grid of layer-0 W^T: 4 filters of 4 weights as 2x2 tiles
        from PIL import Image

        assert np.asarray(Image.open(str(out))).shape == (4, 4)
