"""Flash-attention pallas kernel == dense attention (interpret mode on CPU).

Same strategy as tests/test_pallas.py for the LSTM kernel: the kernel runs
under interpret=True on the CPU mesh and must reproduce the dense XLA
attention bit-for-bit-ish (f32 accumulation in both paths)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.pallas_attention import (
    dense_attention,
    flash_attention,
    flash_fits,
)


def _qkv(n=2, t=256, h=2, d=64, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return [
        jnp.asarray(rng.standard_normal((n, t, h, d)), dtype)
        for _ in range(3)
    ]


def _dense_nthd(q, k, v, causal):
    return dense_attention(q, k, v, causal=causal)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = _dense_nthd(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_multiple_k_blocks():
    q, k, v = _qkv(t=512, d=32)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = _dense_nthd(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_bf16_io():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = _dense_nthd(q, k, v, True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=2e-2)


def test_flash_gradients_match_dense():
    # t=256 = two K blocks: exercises the lax.scan accumulation, the
    # cross-block causal masking, and the dK/dV unstack in _flash_bwd
    q, k, v = _qkv(t=256, d=32)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, interpret=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_nthd(q, k, v, True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   err_msg=f"grad d{name}")


def test_fits_gate():
    assert flash_fits(1024, 64)
    assert not flash_fits(1000, 64)       # not a block multiple
    assert not flash_fits(65536, 128)     # k/v would blow VMEM


def test_attention_auto_dense_fallback():
    """Off-TPU (pallas disabled) attention_auto must take the dense path and
    still be correct."""
    from deeplearning4j_tpu.ops.pallas_attention import attention_auto

    q, k, v = _qkv(t=64)  # 64 not a block multiple -> dense even if enabled
    out = attention_auto(q, k, v, causal=True)
    ref = _dense_nthd(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


# ---------------------------------------------------------------------------
# Extended kernel: key padding mask + traced visibility offset
# ---------------------------------------------------------------------------


def test_ext_masked_matches_dense_masked():
    from deeplearning4j_tpu.ops.pallas_attention import (
        _dense_masked,
        flash_attention_masked,
    )

    q, k, v = _qkv()
    rng = np.random.default_rng(3)
    km = rng.random((2, 256)) > 0.3
    for causal in (False, True):
        out = flash_attention_masked(q, k, v, km, causal=causal,
                                     interpret=True)
        ref = _dense_masked(q, k, v, km, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)


def test_ext_offset_visibility():
    """offset generalizes causality to shards: off=0 == causal, off>=T ==
    full, off<=-T == nothing visible (zero output)."""
    from deeplearning4j_tpu.ops.pallas_attention import (
        flash_attention_block,
    )

    rng = np.random.default_rng(0)
    b, t, d = 4, 256, 32
    q, k, v = (jnp.asarray(rng.standard_normal((b, t, d)), jnp.float32)
               for _ in range(3))
    from deeplearning4j_tpu.ops.pallas_attention import _dense_reference

    o0, _ = flash_attention_block(q, k, v, offset=0, interpret=True)
    np.testing.assert_allclose(
        np.asarray(o0), np.asarray(_dense_reference(q, k, v, causal=True)),
        atol=2e-5)
    of, _ = flash_attention_block(q, k, v, offset=t, interpret=True)
    np.testing.assert_allclose(
        np.asarray(of), np.asarray(_dense_reference(q, k, v, causal=False)),
        atol=2e-5)
    oh, _ = flash_attention_block(q, k, v, offset=-t, interpret=True)
    assert float(jnp.max(jnp.abs(oh))) == 0.0


def test_ext_gradients_include_lse_cotangent():
    """Gradients through BOTH outputs (o and lse) match the dense oracle —
    the lse cotangent is what ring combination differentiates through."""
    from deeplearning4j_tpu.ops.pallas_attention import (
        flash_attention_block,
    )

    rng = np.random.default_rng(1)
    b, t, d = 2, 256, 32
    q, k, v = (jnp.asarray(rng.standard_normal((b, t, d)), jnp.float32)
               for _ in range(3))

    def f(q, k, v):
        o, lse = flash_attention_block(q, k, v, offset=0, interpret=True)
        return (o ** 2).mean() + 0.01 * lse.mean()

    def f_ref(q, k, v):
        s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(d)
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool))[None], s, -jnp.inf)
        p = jax.nn.softmax(s, -1)
        o = jnp.einsum("bqk,bkd->bqd", p, v)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        return (o ** 2).mean() + 0.01 * lse.mean()

    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(g, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-5,
                                   err_msg=f"d{name}")


def test_attention_auto_masked_dispatch():
    """attention_auto with a key_mask must agree between its two backends
    (ext kernel vs dense fallback)."""
    from deeplearning4j_tpu.ops.pallas_attention import (
        _dense_masked,
        attention_auto,
    )

    q, k, v = _qkv()
    rng = np.random.default_rng(5)
    km = rng.random((2, 256)) > 0.4
    out = attention_auto(q, k, v, causal=True, key_mask=km)
    ref = _dense_masked(q, k, v, km, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
