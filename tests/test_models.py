"""Flagship model tests: ResNet-50 graph assembly + train step on tiny
shapes; char-RNN LSTM training + sampling (mirrors reference example-driven
integration tests, SURVEY.md section 4 "Network integration")."""

import numpy as np
import pytest

from deeplearning4j_tpu.models.char_rnn import CharRnn
from deeplearning4j_tpu.models.resnet import build_resnet50, resnet50_conf


class TestResNet50:
    def test_conf_shape_and_param_count(self):
        conf = resnet50_conf(num_classes=1000, input_size=224)
        # 16 bottleneck blocks -> 16 add vertices
        adds = [n for n in conf.vertices if n.endswith("_add")]
        assert len(adds) == 16
        net_small = build_resnet50(input_size=64, num_classes=10)
        n_params = net_small.num_params()
        # ResNet-50 has ~25.5M params at 1000 classes; at 10 classes the fc
        # shrinks but the conv trunk (~23.5M) remains
        assert 20e6 < n_params < 30e6

    def test_train_step_decreases_loss_tiny(self):
        net = build_resnet50(input_size=32, num_classes=5, learning_rate=1e-3,
                             updater="adam", momentum=0.9)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 32, 32, 3)).astype(np.float32)
        y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 4)]
        first = net.fit(x, y)
        for _ in range(6):
            last = net.fit(x, y)
        assert np.isfinite(float(first))
        assert float(last) < float(first)

    def test_output_shape(self):
        net = build_resnet50(input_size=32, num_classes=5)
        x = np.random.default_rng(1).normal(size=(2, 32, 32, 3)).astype(np.float32)
        out = net.output(x)
        assert out[0].shape == (2, 5)
        np.testing.assert_allclose(np.asarray(out[0]).sum(axis=1), 1.0, rtol=1e-4)


TEXT = ("the quick brown fox jumps over the lazy dog. " * 30)


class TestCharRnn:
    def test_fit_and_sample(self):
        model = CharRnn(TEXT, lstm_size=32, num_layers=1, tbptt_length=16,
                        learning_rate=0.05)
        losses = model.fit_text(TEXT, epochs=3, batch=4, seq_len=32)
        assert losses[-1] < losses[0]
        out = model.sample("the ", length=40, seed=1)
        assert len(out) == 44
        assert set(out) <= set(model.chars)

    def test_tbptt_window_count(self):
        model = CharRnn(TEXT, lstm_size=16, num_layers=1, tbptt_length=8)
        it0 = model.net.iteration
        x, y = next(model.batches(TEXT, batch=2, seq_len=32))
        model.net.fit(x, y)
        assert model.net.iteration - it0 == 4  # 32/8 windows


class TestAlexNetVgg:
    def test_alexnet_builds_and_steps(self):
        from deeplearning4j_tpu.models.alexnet import build_alexnet

        # small spatial variant for CPU test speed: 67 -> conv1 15 -> pool 7
        net = build_alexnet(input_size=67, num_classes=10)
        rng = np.random.default_rng(0)
        x = rng.random((2, 67, 67, 3)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 2)]
        loss = float(net.fit(x, y))
        assert np.isfinite(loss)
        out = np.asarray(net.output(x))
        assert out.shape == (2, 10)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)

    def test_alexnet_227_param_count(self):
        from deeplearning4j_tpu.models.alexnet import alexnet_conf
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        net = MultiLayerNetwork(alexnet_conf(num_classes=1000)).init(
            input_shape=(227, 227, 3)
        )
        # canonical single-tower AlexNet ~= 62.3M params
        assert abs(net.num_params() - 62_378_344) < 1_000_000, net.num_params()

    def test_vgg16_builds_and_steps(self):
        from deeplearning4j_tpu.models.vgg import build_vgg16

        net = build_vgg16(input_size=32, num_classes=10,
                          gradient_checkpointing=True)
        rng = np.random.default_rng(0)
        x = rng.random((2, 32, 32, 3)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 2)]
        l1 = float(net.fit(x, y))
        l2 = float(net.fit(x, y))
        assert np.isfinite(l1) and np.isfinite(l2)
        assert net.output(x).shape == (2, 10)

    def test_vgg16_224_param_count(self):
        from deeplearning4j_tpu.models.vgg import vgg16_conf
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        net = MultiLayerNetwork(vgg16_conf(num_classes=1000)).init(
            input_shape=(224, 224, 3)
        )
        # canonical VGG-16: ~138.36M params
        assert abs(net.num_params() - 138_357_544) < 1_000_000, net.num_params()


class TestDbn:
    def test_pretrain_then_finetune(self):
        import numpy as np

        from deeplearning4j_tpu.models.dbn import build_dbn

        net = build_dbn(n_in=20, hidden=(16, 12), num_classes=3,
                        learning_rate=0.05)
        rng = np.random.default_rng(0)
        x = (rng.random((32, 20)) > 0.5).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        net.pretrain(x, num_epochs=2)       # layerwise CD-k
        first = net.fit(x, y)
        for _ in range(15):
            last = net.fit(x, y)
        assert last < first

    def test_stacked_autoencoder(self):
        import numpy as np

        from deeplearning4j_tpu.models.dbn import build_stacked_autoencoder

        net = build_stacked_autoencoder(n_in=20, hidden=(16,), num_classes=3,
                                        learning_rate=0.05)
        rng = np.random.default_rng(1)
        x = rng.random((32, 20)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
        net.pretrain(x, num_epochs=1)
        first = net.fit(x, y)
        for _ in range(15):
            last = net.fit(x, y)
        assert last < first

    def test_conf_roundtrip(self):
        from deeplearning4j_tpu.models.dbn import dbn_conf
        from deeplearning4j_tpu.nn.conf.multi_layer import (
            MultiLayerConfiguration,
        )

        conf = dbn_conf(n_in=20, hidden=(16, 12), num_classes=3)
        assert conf.pretrain is True
        rt = MultiLayerConfiguration.from_json(conf.to_json())
        assert rt.to_json() == conf.to_json()


class TestGoogLeNet:
    def test_param_count_matches_canonical(self):
        """Inception-v1 at 224px without aux heads: canonical ~6.99M
        params (Szegedy et al. 2014 Table 1)."""
        from deeplearning4j_tpu.models.googlenet import googlenet_conf
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        net = ComputationGraph(googlenet_conf())
        net.init(input_shapes={"in": (224, 224, 3)})
        n = net.num_params()
        assert 6.5e6 < n < 7.5e6, f"{n/1e6:.2f}M"

    def test_trains_and_merges_towers(self):
        from deeplearning4j_tpu.models.googlenet import build_googlenet

        rng = np.random.default_rng(0)
        net = build_googlenet(input_size=64, num_classes=10)
        x = rng.random((4, 64, 64, 3)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 4)]
        l1 = float(net.fit(x, y))
        l2 = float(net.fit(x, y))
        assert np.isfinite(l1) and l2 < l1

    def test_aux_heads_three_output_training(self):
        """The paper's auxiliary classifiers as extra graph OUTPUTS — the
        reference's multi-output fit path (one label array per output)."""
        from deeplearning4j_tpu.models.googlenet import build_googlenet

        rng = np.random.default_rng(1)
        net = build_googlenet(input_size=64, num_classes=10, aux_heads=True)
        assert len(net.conf.outputs) == 3
        x = rng.random((4, 64, 64, 3)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 4)]
        loss = float(net.fit(x, [y, y, y]))
        assert np.isfinite(loss)
        outs = net.output(x)
        assert len(outs) == 3 and outs[0].shape == (4, 10)


def test_char_rnn_top_k_sampling():
    """top_k=1 sampling is deterministic greedy regardless of seed."""
    from deeplearning4j_tpu.models.char_rnn import CharRnn

    text = "hello world, hello there! " * 8
    m = CharRnn(text, lstm_size=16, num_layers=1, tbptt_length=8)
    m.fit_text(text, epochs=1, batch=4, seq_len=16)
    a = m.sample("he", length=20, top_k=1, seed=0)
    b = m.sample("he", length=20, top_k=1, seed=99)
    assert a == b
    assert len(a) == 22
