"""CLI + streaming tests — mirrors the reference CLI subcommand tests
(deeplearning4j-cli TrainTest) and streaming route tests
(Dl4jServingRouteTest with embedded broker; here in-process HTTP)."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.cli import main as cli_main
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.streaming import (
    ModelServer,
    StreamingTrainingPipeline,
    decode_record_base64,
    encode_record_base64,
    record_to_array,
)


def write_conf(path):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .learning_rate(0.1)
        .updater("sgd")
        .list()
        .layer(0, DenseLayer(n_in=4, n_out=16, activation="tanh"))
        .layer(1, OutputLayer(n_in=16, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    path.write_text(conf.to_json())
    return conf


def write_csv(path, n=96, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4))
    w = np.random.default_rng(42).normal(size=(4, 3))
    labels = np.argmax(x @ w, axis=1)
    np.savetxt(path, np.column_stack([x, labels]), delimiter=",", fmt="%.6f")
    return x, labels


class TestCli:
    def test_train_test_predict_roundtrip(self, tmp_path, capsys):
        conf_path = tmp_path / "conf.json"
        train_csv = tmp_path / "train.csv"
        model_zip = tmp_path / "model.zip"
        write_conf(conf_path)
        write_csv(train_csv, n=192, seed=0)

        rc = cli_main([
            "train", "--conf", str(conf_path), "--input", str(train_csv),
            "--output", str(model_zip), "--epochs", "15", "--batch", "32",
        ])
        assert rc == 0 and model_zip.exists()

        test_csv = tmp_path / "test.csv"
        write_csv(test_csv, n=48, seed=5)
        rc = cli_main(["test", "--model", str(model_zip), "--input", str(test_csv)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Accuracy" in out

        # predict consumes UNLABELED input (features only)
        x_only_csv = tmp_path / "x_only.csv"
        x_test = np.loadtxt(test_csv, delimiter=",")[:, :-1]
        np.savetxt(x_only_csv, x_test, delimiter=",", fmt="%.6f")
        pred_csv = tmp_path / "preds.csv"
        rc = cli_main([
            "predict", "--model", str(model_zip), "--input", str(x_only_csv),
            "--output", str(pred_csv),
        ])
        assert rc == 0
        preds = np.loadtxt(pred_csv, delimiter=",")
        assert preds.shape == (48, 3)
        np.testing.assert_allclose(preds.sum(axis=1), 1.0, rtol=1e-3)

    def test_npz_input(self, tmp_path):
        conf_path = tmp_path / "conf.json"
        write_conf(conf_path)
        x = np.random.default_rng(0).normal(size=(64, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[np.random.default_rng(1).integers(0, 3, 64)]
        npz = tmp_path / "data.npz"
        np.savez(npz, features=x, labels=y)
        model_zip = tmp_path / "m.zip"
        rc = cli_main([
            "train", "--conf", str(conf_path), "--input", str(npz),
            "--output", str(model_zip), "--epochs", "1",
        ])
        assert rc == 0 and model_zip.exists()


class TestConversion:
    def test_record_roundtrip(self):
        rec = [1.5, -2.0, 3.25]
        b64 = encode_record_base64(rec)
        back = decode_record_base64(b64)
        np.testing.assert_allclose(back, record_to_array(rec))

    def test_bad_payload_rejected(self):
        with pytest.raises(ValueError):
            decode_record_base64("AAA=")  # 3 bytes, not float32-aligned


def trained_net():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(7).learning_rate(0.1).list()
        .layer(0, DenseLayer(n_in=4, n_out=16, activation="tanh"))
        .layer(1, OutputLayer(n_in=16, n_out=3, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
    net.fit(x, y)
    return net


class TestModelServer:
    @pytest.fixture(scope="class")
    def server(self):
        s = ModelServer(model=trained_net(), port=0).start()
        yield s
        s.stop()

    def _post(self, server, payload):
        req = urllib.request.Request(
            server.url + "/predict", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    def test_health(self, server):
        with urllib.request.urlopen(server.url + "/health", timeout=5) as r:
            h = json.loads(r.read())
        assert h["ok"] and "MultiLayerNetwork" in h["model"]

    def test_predict_record(self, server):
        out = self._post(server, {"record": [0.1, -0.2, 0.3, 0.4]})
        assert len(out["output"]) == 3
        assert abs(sum(out["output"]) - 1.0) < 1e-3

    def test_predict_base64(self, server):
        payload = {"record_base64": encode_record_base64([0.1, -0.2, 0.3, 0.4])}
        out = self._post(server, payload)
        assert len(out["output"]) == 3

    def test_predict_batch(self, server):
        out = self._post(server, {"batch": [[0.1] * 4, [0.2] * 4]})
        assert len(out["outputs"]) == 2

    def test_bad_request(self, server):
        with pytest.raises(urllib.error.HTTPError):
            self._post(server, {"nope": 1})

    def test_restore_from_checkpoint(self, tmp_path):
        from deeplearning4j_tpu.utils.serialization import ModelSerializer

        net = trained_net()
        p = str(tmp_path / "m.zip")
        ModelSerializer.write_model(net, p)
        s = ModelServer(model_path=p, port=0).start()
        try:
            out = self._post(s, {"record": [0.1, -0.2, 0.3, 0.4]})
            direct = np.asarray(net.output(np.array([[0.1, -0.2, 0.3, 0.4]],
                                                    np.float32)))[0]
            np.testing.assert_allclose(out["output"], direct, rtol=1e-4)
        finally:
            s.stop()

    def test_serve_transformer_checkpoint(self, tmp_path):
        """The generic restore dispatch serves TransformerLM checkpoints
        through the same /predict surface (token ids in, logits out)."""
        from deeplearning4j_tpu.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )

        lm = TransformerLM(TransformerConfig(
            vocab_size=20, d_model=16, n_layers=1, n_heads=2, d_ff=32,
            max_len=8))
        p = str(tmp_path / "lm.zip")
        lm.save(p)
        s = ModelServer(model_path=p, port=0).start()
        try:
            out = self._post(s, {"record": [1, 2, 3, 4]})
            direct = np.asarray(lm.output(np.array([[1, 2, 3, 4]])))[0]
            np.testing.assert_allclose(np.asarray(out["output"]),
                                       direct, rtol=1e-4)
        finally:
            s.stop()


class TestStreamingPipeline:
    def test_stream_training(self):
        net_conf = (
            NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.1).list()
            .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .build()
        )
        net = MultiLayerNetwork(net_conf)
        pipe = StreamingTrainingPipeline(net, num_classes=3, batch_size=16)
        pipe.start()
        rng = np.random.default_rng(0)
        w = np.random.default_rng(42).normal(size=(4, 3))
        for _ in range(64):
            rec = rng.normal(size=4)
            pipe.publish(rec, int(np.argmax(rec @ w)))
        pipe.stop()
        assert pipe.batches_fit == 4
        assert all(np.isfinite(l) for l in pipe.losses)


class TestGenerateEndpoint:
    def test_generate_route(self):
        """POST /generate drives TransformerLM.generate (KV-cache decode)
        through the serving surface."""
        import json
        import urllib.request

        from deeplearning4j_tpu.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )
        from deeplearning4j_tpu.streaming.serving import ModelServer

        lm = TransformerLM(TransformerConfig(
            vocab_size=32, d_model=16, n_layers=1, n_heads=2, d_ff=32,
            max_len=16, use_flash=False))
        srv = ModelServer(model=lm).start()
        try:
            body = json.dumps({"tokens": [1, 2, 3], "n_new": 4,
                               "temperature": 0.7, "top_k": 5,
                               "seed": 1}).encode()
            req = urllib.request.Request(
                srv.url + "/generate", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                out = json.loads(r.read())
            assert len(out["tokens"][0]) == 4
            assert all(0 <= t < 32 for t in out["tokens"][0])
        finally:
            srv.stop()

    def test_generate_rejected_for_non_lm(self):
        import json
        import urllib.error
        import urllib.request

        from deeplearning4j_tpu.nn.conf import (
            DenseLayer,
            NeuralNetConfiguration,
            OutputLayer,
        )
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.streaming.serving import ModelServer

        conf = (NeuralNetConfiguration.builder().seed(1).learning_rate(0.1)
                .list()
                .layer(0, DenseLayer(n_in=4, n_out=8, activation="tanh"))
                .layer(1, OutputLayer(n_in=8, n_out=3, activation="softmax"))
                .build())
        srv = ModelServer(model=MultiLayerNetwork(conf).init()).start()
        try:
            body = json.dumps({"tokens": [1], "n_new": 2}).encode()
            req = urllib.request.Request(
                srv.url + "/generate", data=body,
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=30)
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
                assert "generate" in json.loads(e.read())["error"]
        finally:
            srv.stop()
