"""Tests for util extras (VERDICT round-1 coverage rows 31/36):
MovingWindowMatrix, DiskBasedQueue, moving-window text context,
inverted index."""

import numpy as np

from deeplearning4j_tpu.nlp.invertedindex import InvertedIndex
from deeplearning4j_tpu.nlp.movingwindow import (
    Window,
    WindowConverter,
    strip_context_labels,
    window_for_word_in_position,
    windows,
)
from deeplearning4j_tpu.utils.disk_queue import DiskBasedQueue
from deeplearning4j_tpu.utils.moving_window import MovingWindowMatrix


# --------------------------------------------------------- MovingWindowMatrix
def test_moving_window_matrix_chunks():
    m = np.arange(24).reshape(4, 6)
    wins = MovingWindowMatrix(m, 2, 3).windows()
    assert len(wins) == 4
    np.testing.assert_array_equal(wins[0], [[0, 1, 2], [3, 4, 5]])
    np.testing.assert_array_equal(wins[-1], [[18, 19, 20], [21, 22, 23]])


def test_moving_window_matrix_flattened_and_rotate():
    m = np.arange(8)
    flat = MovingWindowMatrix(m, 2, 2).windows(flattened=True)
    assert len(flat) == 2 and flat[0].shape == (4,)
    rot = MovingWindowMatrix(m, 2, 2, add_rotate=True).windows()
    assert len(rot) == 8  # each window + 3 rotations
    # the last entry of each group of 4 is the unrotated window
    np.testing.assert_array_equal(rot[3], [[0, 1], [2, 3]])


# -------------------------------------------------------------- DiskBasedQueue
def test_disk_queue_fifo(tmp_path):
    q = DiskBasedQueue(str(tmp_path))
    assert q.is_empty() and q.poll() is None
    q.add({"a": 1})
    q.add(np.arange(3))
    assert len(q) == 2
    assert q.peek() == {"a": 1}
    assert q.poll() == {"a": 1}
    np.testing.assert_array_equal(q.poll(), np.arange(3))
    assert q.poll() is None
    # spill files cleaned up
    q.add(1)
    q.clear()
    assert q.is_empty()
    assert not list(tmp_path.glob("*.pkl"))


# --------------------------------------------------------------- movingwindow
def test_windows_padding_and_focus():
    toks = "the quick brown fox jumps".split()
    ws = windows(toks, window_size=5)
    assert len(ws) == 5
    w0 = ws[0]
    assert w0.words == ["<s>", "<s>", "the", "quick", "brown"]
    assert w0.focus_word == "the"
    assert w0.is_begin_label()
    w_last = ws[-1]
    assert w_last.words == ["brown", "fox", "jumps", "</s>", "</s>"]
    assert ws[2].words == toks
    assert ws[2].focus_word == "brown"


def test_window_converter_concatenates_vectors():
    vecs = {"a": np.ones(3, np.float32), "b": 2 * np.ones(3, np.float32)}
    w = window_for_word_in_position(3, 0, ["a", "b"])
    ex = WindowConverter.as_example(w, vecs, 3)
    assert ex.shape == (9,)
    np.testing.assert_array_equal(ex[:3], 0)  # <s> has no vector
    np.testing.assert_array_equal(ex[3:6], 1)
    np.testing.assert_array_equal(ex[6:], 2)


def test_strip_context_labels():
    plain, spans = strip_context_labels(
        "went to <LOC> new york </LOC> with <PER>alice</PER>"
    )
    assert plain == "went to new york with alice"
    assert spans == [("LOC", "new york"), ("PER", "alice")]


# --------------------------------------------------------------- invertedindex
def test_inverted_index_postings_and_sample():
    ix = InvertedIndex()
    d0 = ix.add_words_to_doc("the cat sat".split(), label="x")
    d1 = ix.add_words_to_doc("the dog ran".split())
    assert (d0, d1) == (0, 1)
    assert ix.num_documents() == 2
    assert ix.documents("the") == [0, 1]
    assert ix.documents("cat") == [0]
    assert ix.doc_frequency("dog") == 1
    assert ix.document(1) == ["the", "dog", "ran"]
    assert ix.document_label(0) == "x"
    assert len(ix.sample(5)) == 5
    seen = []
    ix.eachDoc(seen.append)
    assert len(seen) == 2
