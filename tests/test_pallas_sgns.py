"""Fused SGNS kernel contracts (ISSUE 13, quick tier).

The rent ops/pallas_sgns.py pays before it may ever go default-on:

  * f64 gradcheck — ``sgns_fused_step`` (interpret mode on this CPU
    substrate) matches nlp/word2vec._neg_body to 1e-8 in float64 on a
    batch with DELIBERATE row collisions (repeated context rows and
    repeated target rows), pinning the two-phase stale-gather /
    sequential-RMW design to XLA's exact ``.at[].add()`` semantics;
  * epoch contract — ``_skipgram_epoch(sgns_kernel=True)`` ==
    ``sgns_kernel=False`` through the full stacked-batch scan at the
    production f32 dtype (1e-5; syn1 — the HS table the kernel never
    touches — stays BIT-identical);
  * gate contract — knob 0 always off, force = VMEM fit only, '' auto
    stays off on this substrate (no real-chip measured-win row in
    PALLAS_BENCH.json's sgns group).
"""

import numpy as np

import jax
import jax.numpy as jnp


def _case(seed=3, v=50, d=36, b=16, k1=6, dtype=jnp.float64):
    """A pair batch with forced collisions: contexts[5] == contexts[4]
    (colliding syn0 rows), targets[3] == targets[2] row-wise (colliding
    syn1neg rows), plus dead negatives (live == 0, the reference's
    ``continue`` on target == center) and one fully-dead pair row."""
    rng = np.random.default_rng(seed)
    syn0 = jnp.asarray(rng.standard_normal((v, d)) * 0.1, dtype)
    syn1neg = jnp.asarray(rng.standard_normal((v, d)) * 0.1, dtype)
    contexts = rng.integers(0, v, size=(b,)).astype(np.int32)
    contexts[5] = contexts[4]
    targets = rng.integers(0, v, size=(b, k1)).astype(np.int32)
    targets[3] = targets[2]
    labels = np.zeros((b, k1), np.float64)
    labels[:, 0] = 1.0
    live = np.ones((b, k1), np.float64)
    live[1, 2] = 0.0                      # a dead negative
    live[7, :] = 0.0                      # a fully-padded pair row
    return (syn0, syn1neg, jnp.asarray(contexts), jnp.asarray(targets),
            jnp.asarray(labels, dtype), jnp.asarray(live, dtype))


class TestSgnsFusedStep:
    def test_f64_gradcheck_vs_neg_body(self):
        from deeplearning4j_tpu.nlp.word2vec import _neg_body
        from deeplearning4j_tpu.ops.pallas_sgns import sgns_fused_step

        syn0, syn1neg, cx, tgt, lbl, live = _case()
        alpha = 0.025
        # both the XLA step and the aliased kernel donate their tables:
        # hand each its own copy
        r0, r1 = _neg_body(jnp.array(syn0), jnp.array(syn1neg),
                           cx, tgt, lbl, live, alpha)
        k0, k1_ = sgns_fused_step(jnp.array(syn0), jnp.array(syn1neg),
                                  cx, tgt, lbl, live, alpha,
                                  interpret=True)
        assert float(jnp.max(jnp.abs(r0 - k0))) < 1e-8
        assert float(jnp.max(jnp.abs(r1 - k1_))) < 1e-8

    def test_f64_gradcheck_saturated_dots(self):
        """The MAX_EXP saturation branches (dot > 6 -> labels-1,
        dot < -6 -> labels) — scale the tables up so saturation actually
        fires on a meaningful fraction of the pairs."""
        from deeplearning4j_tpu.nlp.word2vec import _neg_body
        from deeplearning4j_tpu.ops.pallas_sgns import sgns_fused_step

        syn0, syn1neg, cx, tgt, lbl, live = _case(seed=11)
        syn0, syn1neg = syn0 * 40.0, syn1neg * 40.0
        dots = jnp.einsum("bd,bkd->bk", syn0[cx], syn1neg[tgt])
        assert bool(jnp.any(jnp.abs(dots) > 6.0))  # the branch is live
        alpha = 0.025
        r0, r1 = _neg_body(jnp.array(syn0), jnp.array(syn1neg),
                           cx, tgt, lbl, live, alpha)
        k0, k1_ = sgns_fused_step(jnp.array(syn0), jnp.array(syn1neg),
                                  cx, tgt, lbl, live, alpha,
                                  interpret=True)
        assert float(jnp.max(jnp.abs(r0 - k0))) < 1e-8
        assert float(jnp.max(jnp.abs(r1 - k1_))) < 1e-8


class TestSgnsEpochScan:
    def test_epoch_kernel_equals_xla(self):
        """The full production surface: _skipgram_epoch's stacked-batch
        scan with the kernel swapped in for _neg_body, f32 tables,
        device-drawn negatives — embeddings agree to 1e-5 and the HS
        table (untouched by the NS branch) is bit-identical."""
        from deeplearning4j_tpu.nlp.word2vec import _skipgram_epoch

        rng = np.random.default_rng(5)
        v, vh, d, l = 30, 40, 24, 4
        nb, b, k = 3, 8, 5
        syn0 = rng.standard_normal((v, d)).astype(np.float32) * 0.1
        syn1 = rng.standard_normal((vh, d)).astype(np.float32) * 0.1
        syn1neg = rng.standard_normal((v, d)).astype(np.float32) * 0.1
        P = jnp.asarray(rng.integers(0, vh, size=(v, l)), jnp.int32)
        C = jnp.asarray(rng.integers(0, 2, size=(v, l)), jnp.float32)
        M = jnp.asarray(rng.integers(0, 2, size=(v, l)), jnp.float32)
        table = jnp.asarray(rng.integers(0, v, size=(64,)), jnp.int32)
        cens = jnp.asarray(rng.integers(0, v, size=(nb, b)), jnp.int32)
        cxs = jnp.asarray(rng.integers(0, v, size=(nb, b)), jnp.int32)
        plive = jnp.ones((nb, b), jnp.float32).at[2, 6:].set(0.0)
        keys = jnp.stack([jax.random.PRNGKey(i) for i in range(nb)])
        alphas = jnp.full((nb,), 0.025, jnp.float32)

        def run(use_kernel):
            # the epoch jit donates the tables: fresh copies per run
            return _skipgram_epoch(
                jnp.array(syn0), jnp.array(syn1), jnp.array(syn1neg),
                P, C, M, table, cens, cxs, plive, keys, alphas,
                use_neg=True, negative_k=k,
                sgns_kernel=use_kernel, sgns_interpret=use_kernel)

        x0, x1, xn = run(False)
        p0, p1, pn = run(True)
        np.testing.assert_array_equal(np.asarray(x1), np.asarray(p1))
        assert float(jnp.max(jnp.abs(x0 - p0))) < 1e-5
        assert float(jnp.max(jnp.abs(xn - pn))) < 1e-5


class TestSgnsGate:
    def test_knob_zero_disables(self, monkeypatch):
        from deeplearning4j_tpu.ops.pallas_sgns import sgns_kernel_enabled

        monkeypatch.setenv("DL4J_TPU_PALLAS_SGNS", "0")
        assert not sgns_kernel_enabled(128, 6, 100)

    def test_force_respects_vmem_budget(self, monkeypatch):
        from deeplearning4j_tpu.ops.pallas_sgns import (
            _VMEM_BUDGET_FLOATS,
            sgns_kernel_enabled,
        )

        monkeypatch.setenv("DL4J_TPU_PALLAS_SGNS", "force")
        assert sgns_kernel_enabled(128, 6, 100)
        # force bypasses the measured-win table, never the VMEM fit
        assert not sgns_kernel_enabled(_VMEM_BUDGET_FLOATS, 6, 100)

    def test_auto_stays_off_without_chip_row(self, monkeypatch):
        """'' auto on this CPU substrate: PALLAS_BENCH.json's sgns group
        has no real-chip row, so word2vec must keep the XLA _neg_body
        step (the default-off half of the rent contract)."""
        from deeplearning4j_tpu.ops.pallas_sgns import sgns_kernel_enabled

        monkeypatch.delenv("DL4J_TPU_PALLAS_SGNS", raising=False)
        assert not sgns_kernel_enabled(128, 6, 100)

    def test_interpret_on_cpu(self):
        from deeplearning4j_tpu.ops.pallas_sgns import sgns_interpret

        assert sgns_interpret()
