"""Autoscaling multi-model fleet tests (ISSUE 20): deterministic
signal-driven scale decisions (same snapshots => same decisions,
bit-exact, twice), HBM-aware first-fit-decreasing placement with
model-affinity routing (a model on zero ready replicas is a LOUD 503,
never a silent wrong-replica answer), per-tenant token-bucket fairness
(one tenant's burst never starves another's admission), the goodbye
ordering fix (addr unlink BEFORE board deregister), the /signals +
/placement + /replicas-HBM surfaces, and the headline chaos contract:
a scripted load wave triggers scale-up, then scale-down races live
/predict and streaming /generate traffic with ZERO failed admitted
requests.

Reference anchor: the reference's scaleout tree provisioned a STATIC
Spark worker set by hand (SURVEY L6 spark/zookeeper) — there is no
component that sizes the fleet or decides where a model runs; every
contract here is beyond-reference.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.resilience import AutoscaleChaos, AutoscaleChaosConfig
from deeplearning4j_tpu.serving.autoscale import (
    FleetAutoscaler,
    ScaleConfig,
)
from deeplearning4j_tpu.serving.fleet import (
    ServingFleet,
    goodbye_replica,
)
from deeplearning4j_tpu.serving.placement import (
    ModelFootprint,
    PlacementPlan,
    model_footprint,
    pack_models,
)
from deeplearning4j_tpu.serving.router import (
    FleetRouter,
    ModelUnplacedError,
    TenantQuotaError,
    publish_replica_addr,
    read_replica_addr,
)
from deeplearning4j_tpu.serving.slo import (
    TenantBucket,
    parse_tenant_quotas,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def small_net(seed=7, n_in=4, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.1)
            .list()
            .layer(0, DenseLayer(n_in=n_in, n_out=8, activation="tanh"))
            .layer(1, OutputLayer(n_in=8, n_out=n_out, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(seed)
    net.fit(rng.normal(size=(32, n_in)).astype(np.float32),
            np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, 32)])
    return net


def tiny_lm(**over):
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    kw = dict(vocab_size=29, d_model=16, n_layers=2, n_heads=2, d_ff=32,
              max_len=32, use_flash=False)
    kw.update(over)
    return TransformerLM(TransformerConfig(**kw))


@pytest.fixture(scope="module")
def net():
    return small_net()


def _post_raw(url, path, payload, timeout=60):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _get(url, path, timeout=30):
    try:
        with urllib.request.urlopen(url + path, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _fleet(net, n=2, **kw):
    kw.setdefault("heartbeat_s", 0.5)
    return ServingFleet(model=net, replicas=n, **kw).start()


def _wait_ready(router, n, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if len(router.signals()["ready_replicas"]) >= n:
            return
        time.sleep(0.05)
    raise AssertionError(f"fleet never reached {n} ready replicas")


def _stripped(decisions):
    """Decisions minus the enactment fields tick() adds after decide()
    — the pure-decision view replay() reproduces."""
    return [{k: v for k, v in d.items()
             if k not in ("enacted", "enact_error")} for d in decisions]


# ---------------------------------------------------------------------------
# tenant quotas: parsing, the bucket, and admission fairness
# ---------------------------------------------------------------------------


class TestTenantQuotas:
    def test_parse(self):
        qs = parse_tenant_quotas("a:2:5, b:10")
        assert [(q.name, q.rate_per_s, q.burst) for q in qs] == \
            [("a", 2.0, 5.0), ("b", 10.0, 10.0)]
        assert parse_tenant_quotas("") == []
        for bad in ("a", "a:0", "a:-1:2", "a:1:0.5", "a:1,a:2"):
            with pytest.raises(ValueError):
                parse_tenant_quotas(bad)

    def test_bucket_deterministic_clock(self):
        (q,) = parse_tenant_quotas("t:2:2")
        clock = [0.0]
        b = TenantBucket(q, now_fn=lambda: clock[0])
        assert b.try_take() == (True, 0.0)
        assert b.try_take() == (True, 0.0)
        ok, retry = b.try_take()
        assert not ok and retry == pytest.approx(0.5)
        clock[0] = 0.5  # refill one token at 2/s
        assert b.try_take() == (True, 0.0)

    def test_burst_tenant_never_starves_the_other(self, net):
        """The acceptance counter-proof: tenant a's burst exhausts its
        OWN bucket (429 + Retry-After) while tenant b's admission is
        untouched — and a's sheds never consume in-flight headroom."""
        fleet = _fleet(net, 1, router_kwargs={
            "tenant_quotas": "a:0.001:3,b:1000:1000"})
        try:
            router = fleet.router
            a_shed = 0
            for _ in range(10):
                try:
                    router._admit({"tenant": "a"})
                    router._release()
                except TenantQuotaError as e:
                    a_shed += 1
                    assert e.retry_after_s > 0
            assert a_shed == 7  # burst 3 admitted, the rest shed
            for _ in range(20):  # b rides through a's burst untouched
                router._admit({"tenant": "b"})
                router._release()
            snap = router.stats.snapshot()
            assert snap["tenant_admitted"] == {"a": 3, "b": 20}
            assert snap["tenant_shed"] == {"a": 7}
            # tenant sheds are their own ledger, not the SLO shed
            assert snap["fleet_429"] == 0
        finally:
            fleet.stop()

    def test_http_shed_carries_retry_after(self, net):
        fleet = _fleet(net, 1, router_kwargs={"tenant_quotas": "a:0.5:1"})
        try:
            rows = [[0.1, 0.2, 0.3, 0.4]]
            code, _, _ = _post_raw(fleet.url, "/predict",
                                   {"batch": rows, "tenant": "a"})
            assert code == 200
            code, body, headers = _post_raw(
                fleet.url, "/predict", {"batch": rows, "tenant": "a"})
            assert code == 429
            assert int(headers.get("Retry-After")) >= 1
            assert "tenant" in json.loads(body)["error"]
            # unmetered traffic still flows
            code, _, _ = _post_raw(fleet.url, "/predict", {"batch": rows})
            assert code == 200
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# placement: FFD determinism, unplaced loudness, affinity routing
# ---------------------------------------------------------------------------


class TestPlacement:
    def test_ffd_deterministic_and_unplaced(self):
        GB = 2 ** 30
        fps = [ModelFootprint("big", 6 * GB),
               ModelFootprint("mid", 3 * GB, kv_bytes=1 * GB),
               ModelFootprint("small", 1 * GB),
               ModelFootprint("huge", 40 * GB)]
        plans = [pack_models(fps, ["r1", "r0"], hbm_gb=8.0)
                 for _ in range(2)]
        assert plans[0].describe() == plans[1].describe()
        plan = plans[0]
        # FFD: big (6G) -> r0; mid (4G) won't fit r0 -> r1; small-> r0
        assert plan.assignments == {"r0": ["big", "small"],
                                    "r1": ["mid"]}
        assert plan.unplaced == ["huge"]
        assert plan.replicas_of("small") == ["r0"]
        assert plan.replicas_of("huge") == []
        desc = plan.describe()
        assert desc["utilization"]["r0"] == pytest.approx(0.875)
        assert "huge" in desc["footprints"]

    def test_model_footprint_prices_params_and_kv(self):
        lm = tiny_lm()
        fp = model_footprint("lm", lm, ann_bytes=123, hbm_gb=0.25)
        assert fp.param_bytes > 0
        assert fp.kv_bytes > 0  # decode-eligible => a KV arena is priced
        assert fp.ann_bytes == 123
        assert fp.total_bytes == fp.param_bytes + fp.kv_bytes + 123
        net = small_net()
        fp2 = model_footprint("mlp", net)
        assert fp2.kv_bytes == 0  # no generate surface, no arena

    def test_affinity_routes_only_to_holders(self, net):
        fleet = _fleet(net, 2)
        try:
            _wait_ready(fleet.router, 2)
            plan = PlacementPlan(budget_bytes=2 ** 30,
                                 assignments={"r0": ["default"], "r1": []},
                                 used_bytes={"r0": 100, "r1": 0})
            fleet.router.set_placement(plan)
            rows = [[0.1, 0.2, 0.3, 0.4]]
            for _ in range(6):
                code, _, _ = _post_raw(fleet.url, "/predict",
                                       {"batch": rows, "model": "default"})
                assert code == 200
            engines = fleet.engines()
            assert engines["r0"].stats.snapshot()["requests"] == 6
            assert engines["r1"].stats.snapshot()["requests"] == 0
        finally:
            fleet.stop()

    def test_zero_ready_holders_is_a_loud_503(self, net):
        """A model placed nowhere (or on dead holders) answers 503
        naming the model — never a silent wrong-replica 500."""
        fleet = _fleet(net, 1)
        try:
            _wait_ready(fleet.router, 1)
            plan = PlacementPlan(budget_bytes=2 ** 30,
                                 assignments={"r0": []},
                                 used_bytes={"r0": 0},
                                 unplaced=["default"])
            fleet.router.set_placement(plan)
            with pytest.raises(ModelUnplacedError, match="default"):
                fleet.router._candidates(model="default")
            rows = [[0.1, 0.2, 0.3, 0.4]]
            code, body, _ = _post_raw(fleet.url, "/predict",
                                      {"batch": rows, "model": "default"})
            assert code == 503
            assert "default" in json.loads(body)["error"]
            assert fleet.router.stats.snapshot()["affinity_503"] >= 2
            # an UNKNOWN model keeps the fleet-wide walk (the plan only
            # constrains models it priced)
            code, _, _ = _post_raw(fleet.url, "/predict", {"batch": rows})
            assert code == 200
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# surfaces: /signals, /placement, /replicas HBM
# ---------------------------------------------------------------------------


class TestSurfaces:
    def test_signals_and_placement_and_hbm(self, net):
        fleet = _fleet(net, 2)
        try:
            _wait_ready(fleet.router, 2)
            code, sig = _get(fleet.url, "/signals")
            assert code == 200
            assert sorted(sig["replicas"]) == ["r0", "r1"]
            for entry in sig["replicas"].values():
                assert set(entry) >= {"ready", "role", "breaker",
                                      "queue_depth", "cordoned"}
            assert sig["ready_replicas"] == ["r0", "r1"]
            for key in ("queue_depth", "inflight", "shed_total",
                        "shed_by_class", "per_class_latency_ms",
                        "slo_classes", "tenant_admitted", "tenant_shed",
                        "affinity_503"):
                assert key in sig
            code, rep = _get(fleet.url, "/placement")
            assert code == 200 and rep == {"placement": None}
            auto = FleetAutoscaler(fleet, config=ScaleConfig())
            plan = auto.plan_placement(
                [model_footprint("default", net)])
            code, rep = _get(fleet.url, "/placement")
            assert code == 200
            assert rep["placement"] == plan.describe()
            # /replicas now carries the AOT-priced HBM block
            code, reps = _get(fleet.url, "/replicas")
            assert code == 200
            for rid in ("r0", "r1"):
                hbm = reps[rid]["hbm"]
                assert hbm["budget_bytes"] > 0
                assert hbm["used_bytes"] > 0
                assert hbm["models"]["default"]["param_bytes"] > 0
                assert hbm["utilization"] == pytest.approx(
                    hbm["used_bytes"] / hbm["budget_bytes"], rel=1e-3)
        finally:
            fleet.stop()

    def test_engine_metrics_hbm_report(self, net):
        from deeplearning4j_tpu.serving import ServingEngine

        eng = ServingEngine(model=net).start()
        try:
            code, m = _get(eng.url, "/metrics")
            assert code == 200
            assert m["hbm"]["used_bytes"] > 0
            assert m["hbm"]["models"]["default"]["kv_bytes"] == 0
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# the goodbye ordering fix (satellite: stale addr can't outlive the board)
# ---------------------------------------------------------------------------


class TestGoodbyeOrdering:
    def test_addr_unlinked_before_deregister(self, tmp_path):
        root = str(tmp_path)
        publish_replica_addr(root, "rX", "http://127.0.0.1:1")
        order = []

        class Board:
            def deregister_worker(self, rid):
                # the addr must ALREADY be gone when the board goodbye
                # lands — the crash window between the two steps now
                # leaves a board entry (expiry reaps it), never a
                # stale addr file (nothing reaps those)
                order.append(("dereg", rid,
                              read_replica_addr(root, "rX")))

        goodbye_replica(Board(), root, "rX")
        assert order == [("dereg", "rX", None)]

    def test_board_failure_still_removed_addr(self, tmp_path):
        root = str(tmp_path)
        publish_replica_addr(root, "rX", "http://127.0.0.1:1")

        class Board:
            def deregister_worker(self, rid):
                raise OSError("board transport died")

        with pytest.raises(OSError):
            goodbye_replica(Board(), root, "rX")
        assert read_replica_addr(root, "rX") is None


# ---------------------------------------------------------------------------
# decision determinism: same snapshots => same decisions, bit-exact
# ---------------------------------------------------------------------------


def _snap(ready, queue, shed=0, p99_ms=None, deadline_s=5.0):
    lat = {}
    if p99_ms is not None:
        lat["default"] = {"p50": p99_ms / 2, "p99": p99_ms, "count": 10}
    return {"ready_replicas": [f"r{i}" for i in range(ready)],
            "queue_depth": queue, "shed_total": shed,
            "slo_classes": [{"name": "default", "deadline_s": deadline_s}],
            "per_class_latency_ms": lat}


class TestDeterministicDecisions:
    CFG = ScaleConfig(min_replicas=1, max_replicas=3, up_queue=8.0,
                      up_p99_frac=0.8, up_shed=1, window=2,
                      down_queue=0.0, cooldown=1)

    def scripted(self):
        return ([_snap(1, 20)] * 2            # queue wave -> up
                + [_snap(2, 0)] * 4           # idle -> (cooldown) down
                + [_snap(1, 0, shed=0)]       # at min: hold
                + [_snap(1, 1, p99_ms=4500)] * 3   # p99 pressure -> up
                + [_snap(1, 0, shed=5), _snap(1, 0, shed=10)])  # sheds

    def test_replay_bit_exact_and_votes(self):
        decs = FleetAutoscaler.replay(self.scripted(), config=self.CFG)
        assert decs == FleetAutoscaler.replay(self.scripted(),
                                              config=self.CFG)
        actions = [d["action"] for d in decs]
        assert actions.count("up") >= 2 and actions.count("down") >= 1
        assert decs[1]["action"] == "up" and decs[1]["votes"] == ["queue"]
        down = next(d for d in decs if d["action"] == "down")
        assert down["victim"] == "r1"  # highest rid among ready
        assert any("p99" in d["votes"] for d in decs)
        assert any("shed" in d["votes"] for d in decs)

    def test_bounds_and_cooldown(self):
        cfg = ScaleConfig(min_replicas=1, max_replicas=1, window=1,
                          cooldown=2)
        decs = FleetAutoscaler.replay(
            [_snap(1, 50)] * 2 + [_snap(1, 0)] * 3, config=cfg)
        assert [d["action"] for d in decs] == ["hold"] * 5
        assert decs[0]["reason"] == "at_max"
        assert decs[1]["reason"] == "cooldown"
        assert any(d["reason"] == "at_min" for d in decs[2:])

    def test_chaos_overlay_is_deterministic_input_corruption(self):
        cc = AutoscaleChaos(AutoscaleChaosConfig(
            load_wave={"at_tick": 1, "ticks": 2, "queue_depth": 40,
                       "sheds_per_tick": 3}))
        base = {"ready_replicas": ["r0"], "queue_depth": 0,
                "shed_total": 0}
        outs = [cc.on_signals(t, dict(base)) for t in range(4)]
        assert outs[0]["queue_depth"] == 0
        assert [o["queue_depth"] for o in outs[1:3]] == [40, 40]
        assert [o["shed_total"] for o in outs[1:3]] == [3, 6]
        assert outs[3]["queue_depth"] == 0
        assert len(cc.log) == 2


# ---------------------------------------------------------------------------
# the headline chaos contract: wave -> scale-up -> scale-down under
# live traffic, zero failed admitted requests, decisions replayable
# ---------------------------------------------------------------------------


class TestScaleChaos:
    def test_wave_up_then_down_under_predict_traffic(self, net):
        cfg = ScaleConfig(min_replicas=1, max_replicas=2, up_queue=10.0,
                          up_shed=0, window=2, down_queue=0.5, cooldown=1)
        fleet = _fleet(net, 1)
        auto = FleetAutoscaler(
            fleet, config=cfg,
            chaos=AutoscaleChaos(AutoscaleChaosConfig(
                load_wave={"at_tick": 0, "ticks": 2, "queue_depth": 50})))
        failures, codes = [], []
        stop = threading.Event()
        rows = [[0.1, 0.2, 0.3, 0.4]]

        def hammer():
            while not stop.is_set():
                try:
                    code, _, _ = _post_raw(fleet.url, "/predict",
                                           {"batch": rows})
                    codes.append(code)
                    if code != 200:
                        failures.append(code)
                except OSError as e:  # connect failure = a lost request
                    failures.append(f"{e}")
                time.sleep(0.005)

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        try:
            _wait_ready(fleet.router, 1)
            for t in threads:
                t.start()
            d0, d1 = auto.tick(), auto.tick()
            assert [d0["action"], d1["action"]] == ["hold", "up"]
            assert d1["enacted"] == "r1"
            _wait_ready(fleet.router, 2)
            down = None
            for _ in range(8):  # quiet ticks walk cooldown+window to down
                d = auto.tick()
                if d["action"] == "down":
                    down = d
                    break
            assert down is not None and down["victim"] == "r1"
            assert down["enacted"] == "r1"
            # the victim drained through the goodbye path: board + addr
            # agree it is gone, and traffic kept flowing the whole time
            assert read_replica_addr(fleet.fleet_dir, "r1") is None
            time.sleep(0.3)  # a last full round of hammer traffic
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
            fleet.stop()
        assert not failures, f"failed admitted requests: {failures[:5]}"
        assert len(codes) > 20  # the hammer actually exercised the window
        # the recorded run replays bit-exact from its own signals_log
        assert _stripped(auto.decisions) == FleetAutoscaler.replay(
            auto.signals_log, config=cfg)
        snap = auto.stats.snapshot()
        assert snap["scale_ups"] == 1 and snap["scale_downs"] == 1
        assert snap["enact_failures"] == 0

    def test_scale_down_races_live_generate_stream(self):
        """Scale-down drains the victim through the goodbye path while
        a /generate stream is mid-flight ON the victim: the stream
        finishes (done record, full token count), nothing 5xxs."""
        # down_queue is generous: live streams keep a small real queue
        # depth, and the contract under test is the drain, not the vote
        cfg = ScaleConfig(min_replicas=1, max_replicas=2, up_queue=20.0,
                          up_shed=0, window=1, down_queue=10.0, cooldown=0)
        lm = tiny_lm()
        fleet = ServingFleet(
            model=lm, replicas=1, heartbeat_s=0.5,
            engine_kwargs={"kv_block": 8, "kv_blocks": 16}).start()
        auto = FleetAutoscaler(
            fleet, config=cfg,
            chaos=AutoscaleChaos(AutoscaleChaosConfig(
                load_wave={"at_tick": 0, "ticks": 1, "queue_depth": 50})))
        results, failures = [], []

        def stream_one():
            try:
                req = urllib.request.Request(
                    fleet.url + "/generate",
                    data=json.dumps({"tokens": [1, 5, 2, 9], "n_new": 12,
                                     "temperature": 0.0,
                                     "stream": True}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=120) as resp:
                    events = [json.loads(ln)
                              for ln in resp.read().splitlines()
                              if ln.strip()]
                done = [e for e in events if e.get("done")]
                if done and len(done[0]["tokens"]) == 12:
                    results.append(done[0]["tokens"])
                else:
                    failures.append(f"incomplete stream: {events[-2:]}")
            except (OSError, urllib.error.HTTPError) as e:
                failures.append(f"{e}")

        try:
            _wait_ready(fleet.router, 1)
            d0 = auto.tick()
            assert d0["action"] == "up" and d0["enacted"] == "r1"
            _wait_ready(fleet.router, 2)
            # streams land on BOTH replicas (round-robin walk), so at
            # least one is mid-flight on the victim when the drain hits
            threads = [threading.Thread(target=stream_one)
                       for _ in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.15)  # let the streams admit + start ticking
            down = auto.tick()
            assert down["action"] == "down" and down["enacted"] == "r1"
            for t in threads:
                t.join(timeout=120)
            assert not failures, f"failed streams: {failures}"
            assert len(results) == 4
            assert all(r == results[0] for r in results)  # greedy, equal
            # new traffic keeps flowing on the survivor
            code, body, _ = _post_raw(
                fleet.url, "/generate",
                {"tokens": [1, 5, 2, 9], "n_new": 4, "temperature": 0.0})
            assert code == 200
        finally:
            fleet.stop()
        assert _stripped(auto.decisions) == FleetAutoscaler.replay(
            auto.signals_log, config=cfg)


# ---------------------------------------------------------------------------
# knob / ledger / bench-leg registration
# ---------------------------------------------------------------------------


class TestRegistration:
    def test_knobs_registered(self):
        from deeplearning4j_tpu.ops import env as envknob

        for name in ("DL4J_TPU_SERVE_SCALE_MIN",
                     "DL4J_TPU_SERVE_SCALE_MAX",
                     "DL4J_TPU_SERVE_SCALE_UP_QUEUE",
                     "DL4J_TPU_SERVE_SCALE_UP_P99_FRAC",
                     "DL4J_TPU_SERVE_SCALE_UP_SHED",
                     "DL4J_TPU_SERVE_SCALE_WINDOW",
                     "DL4J_TPU_SERVE_SCALE_DOWN_QUEUE",
                     "DL4J_TPU_SERVE_SCALE_COOLDOWN",
                     "DL4J_TPU_SERVE_TENANT_QUOTAS"):
            assert envknob.knob(name) is not None

    def test_autoscale_ledger_registered(self):
        from deeplearning4j_tpu import obs

        auto = FleetAutoscaler(config=ScaleConfig())
        ledgers = obs.default_registry().ledgers(auto)
        assert "autoscale_stats" in ledgers
        snap = ledgers["autoscale_stats"].snapshot()
        assert snap["ticks"] == 0 and "scale_ups" in snap

    def test_autoscale_leg_registered(self):
        """ISSUE 20: the autoscale leg is in the expected set AND in
        bench.py's CPU-only set — the control plane is host-side work,
        so its proof must run (and persist) with the tunnel dead."""
        import re

        from scripts.bench_state import EXPECTED, expected_legs

        assert "autoscale" in EXPECTED
        assert "autoscale" in expected_legs()
        src = open(os.path.join(REPO, "bench.py")).read()
        m = re.search(r"_CPU_ONLY_LEGS\s*=\s*\{([^}]*)\}", src)
        assert m and "autoscale" in m.group(1)
